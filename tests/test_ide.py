"""The stdlib web IDE served into dev environments (dstack_tpu/ide.py).

Parity: the reference delivers an IDE backend at dev-env start
(ref server/services/jobs/configurators/dev.py:35); this is the air-gapped
tier of that chain, so it must behave like an editor (tree/read/write) and
refuse to escape the workspace."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from dstack_tpu import ide


@pytest.fixture
def ide_server(tmp_path):
    (tmp_path / "src").mkdir()
    (tmp_path / "src" / "train.py").write_text("import jax\n")
    (tmp_path / "README.md").write_text("hello\n")
    (tmp_path / ".git").mkdir()
    (tmp_path / ".git" / "HEAD").write_text("ref: refs/heads/main\n")
    server = ide.serve(0, str(tmp_path))
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        yield f"http://127.0.0.1:{server.server_address[1]}", tmp_path
    finally:
        server.shutdown()
        server.server_close()


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, dict(resp.headers), resp.read()


def _put(url, body):
    req = urllib.request.Request(url, data=body, method="PUT")
    try:
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class TestIde:
    def test_page_health_and_identity(self, ide_server):
        base, _ = ide_server
        status, headers, body = _get(base + "/")
        assert status == 200 and b"dstack-tpu IDE" in body
        assert headers["X-Dstack-IDE"] == "dstack-tpu"
        status, _, body = _get(base + "/healthcheck")
        assert json.loads(body)["ide"] == "dstack-tpu"

    def test_tree_lists_files_and_skips_dotdirs(self, ide_server):
        base, _ = ide_server
        _, _, body = _get(base + "/api/tree")
        items = json.loads(body)
        paths = [i["path"] for i in items]
        assert "README.md" in paths
        assert "src/train.py" in paths
        assert not any(p.startswith(".git") for p in paths)
        depth = {i["path"]: i["depth"] for i in items}
        assert depth["src/train.py"] == 1

    def test_read_write_roundtrip(self, ide_server):
        base, tmp_path = ide_server
        status, _, body = _get(base + "/api/file?path=src/train.py")
        assert (status, body) == (200, b"import jax\n")
        status, _ = _put(base + "/api/file?path=src/train.py", b"import jax.numpy\n")
        assert status == 200
        assert (tmp_path / "src" / "train.py").read_bytes() == b"import jax.numpy\n"

    def test_create_in_new_directory(self, ide_server):
        base, tmp_path = ide_server
        status, _ = _put(base + "/api/file?path=new/deep/file.txt", b"x")
        assert status == 200
        assert (tmp_path / "new" / "deep" / "file.txt").read_text() == "x"

    def test_traversal_rejected(self, ide_server):
        base, _ = ide_server
        status, _ = _put(base + "/api/file?path=../escape.txt", b"nope")
        assert status == 403
        req = urllib.request.Request(base + "/api/file?path=%2e%2e%2fetc%2fpasswd")
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                status = resp.status
        except urllib.error.HTTPError as e:
            status = e.code
        assert status in (403, 404)

    def test_cross_origin_write_rejected(self, ide_server):
        """CSRF guard: a write carrying a foreign Origin must be refused, and
        POST (which skips CORS preflight cross-site) must not write at all."""
        base, tmp_path = ide_server
        req = urllib.request.Request(
            base + "/api/file?path=evil.py", data=b"pwned", method="PUT",
            headers={"Origin": "http://evil.example"},
        )
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                status = resp.status
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 403
        assert not (tmp_path / "evil.py").exists()

        req = urllib.request.Request(
            base + "/api/file?path=evil.py", data=b"pwned", method="POST"
        )
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                status = resp.status
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 501  # no POST handler at all
        assert not (tmp_path / "evil.py").exists()

    def test_dns_rebinding_host_rejected(self, ide_server):
        """DNS rebinding sends Origin == Host == attacker.example to 127.0.0.1:
        the Host allowlist must refuse it on every route, reads included."""
        import http.client

        base, tmp_path = ide_server
        addr = base[len("http://"):]

        for method, path, body in (
            ("GET", "/api/tree", None),
            ("GET", "/api/file?path=README.md", None),
            ("PUT", "/api/file?path=evil.py", b"pwned"),
        ):
            conn = http.client.HTTPConnection(addr, timeout=5)
            conn.putrequest(method, path, skip_host=True, skip_accept_encoding=True)
            conn.putheader("Host", "attacker.example")
            conn.putheader("Origin", "http://attacker.example")
            if body is not None:
                conn.putheader("Content-Length", str(len(body)))
            conn.endheaders()
            if body is not None:
                conn.send(body)
            assert conn.getresponse().status == 403, f"{method} {path} not rejected"
            conn.close()
        assert not (tmp_path / "evil.py").exists()

        # localhost spellings (any port — the attach tunnel's local forward port
        # differs from the bound port) keep working.
        conn = http.client.HTTPConnection(addr, timeout=5)
        conn.putrequest("GET", "/healthcheck", skip_host=True, skip_accept_encoding=True)
        conn.putheader("Host", "localhost:54321")
        conn.endheaders()
        assert conn.getresponse().status == 200
        conn.close()

    def test_same_origin_write_allowed(self, ide_server):
        base, tmp_path = ide_server
        host = base[len("http://"):]
        req = urllib.request.Request(
            base + "/api/file?path=ok.py", data=b"fine", method="PUT",
            headers={"Origin": f"http://{host}"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 200
        assert (tmp_path / "ok.py").read_text() == "fine"

    def test_chunked_and_bad_content_length_rejected(self, ide_server):
        """Chunked uploads would silently write empty files; negative lengths
        would read to EOF past the size cap — both refused up front."""
        import http.client

        base, tmp_path = ide_server
        host = base[len("http://"):]

        conn = http.client.HTTPConnection(host, timeout=5)
        conn.putrequest("PUT", "/api/file?path=c.txt", skip_accept_encoding=True)
        conn.putheader("Transfer-Encoding", "chunked")
        conn.endheaders()
        conn.send(b"4\r\nbody\r\n0\r\n\r\n")
        assert conn.getresponse().status == 411
        conn.close()
        assert not (tmp_path / "c.txt").exists()

        conn = http.client.HTTPConnection(host, timeout=5)
        conn.putrequest("PUT", "/api/file?path=c.txt", skip_accept_encoding=True)
        conn.putheader("Content-Length", "-1")
        conn.endheaders()
        assert conn.getresponse().status == 411
        conn.close()
        assert not (tmp_path / "c.txt").exists()

    def test_missing_file_404(self, ide_server):
        base, _ = ide_server
        try:
            urllib.request.urlopen(base + "/api/file?path=nope.txt", timeout=5)
            status = 200
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 404
