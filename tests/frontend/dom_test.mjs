// DOM-level test for the admin SPA (server/statics/app.js): executes the real
// app code against a hand-rolled DOM/fetch/WebSocket shim — no browser, no
// npm deps, plain `node dom_test.mjs`. Run in CI; the pytest wrapper
// (tests/test_frontend.py) skips it where node is absent (the TPU image).
//
// Covers: runs list renders + paginates, run detail streams logs over the
// WebSocket (no polling), and the submit view drives parse -> plan -> apply.

import { readFileSync } from "node:fs";
import { dirname, join } from "node:path";
import { fileURLToPath } from "node:url";
import vm from "node:vm";

let failures = 0;
let checks = 0;
function check(cond, msg) {
  checks++;
  if (!cond) { failures++; console.error(`FAIL: ${msg}`); }
}

/* ---------------- DOM shim ---------------- */

class TextNode {
  constructor(data) { this.nodeType = 3; this.data = String(data); }
  get textContent() { return this.data; }
}

class El {
  constructor(tag) {
    this.tagName = String(tag).toUpperCase();
    this.nodeType = 1;
    this.children = [];
    this.attrs = {};
    this.listeners = {};
    this.style = {};
    this.value = "";
    this.checked = true;
    this.scrollTop = 0;
    this.scrollHeight = 0;
    this.innerHTML = "";
  }
  get className() { return this.attrs.class || ""; }
  set className(v) { this.attrs.class = v; }
  setAttribute(k, v) { this.attrs[k] = String(v); }
  removeAttribute(k) { delete this.attrs[k]; }
  getAttribute(k) { return k in this.attrs ? this.attrs[k] : null; }
  addEventListener(t, f) { (this.listeners[t] ||= []).push(f); }
  append(...cs) {
    for (const c of cs) this.children.push(c && c.nodeType ? c : new TextNode(c));
  }
  replaceChildren(...cs) {
    this.children = [];
    this.append(...cs.filter((c) => c !== null && c !== undefined && c !== false));
  }
  get textContent() {
    return this.children.map((c) => c.textContent ?? "").join("");
  }
  set textContent(v) {
    this.children = v === "" ? [] : [new TextNode(v)];
  }
  dispatch(type, ev = {}) {
    ev.preventDefault ||= () => {};
    ev.stopPropagation ||= () => {};
    ev.target ||= this;
    for (const f of this.listeners[type] || []) f(ev);
  }
  click() { this.dispatch("click"); }
  getBoundingClientRect() { return { left: 0, top: 0, width: 300, height: 64 }; }
}

function* walk(el) {
  yield el;
  for (const c of el.children || []) if (c.nodeType === 1) yield* walk(c);
}
const findAll = (root, pred) => [...walk(root)].filter(pred);
const byTag = (root, tag) => findAll(root, (e) => e.tagName === tag.toUpperCase());
const buttonByText = (root, text) =>
  findAll(root, (e) => e.tagName === "BUTTON" && e.textContent.includes(text))[0];

/* ---------------- environment shim ---------------- */

const appRoot = new El("div");
appRoot.attrs.id = "app";

const hashListeners = [];
const loc = { protocol: "http:", host: "testhost", _hash: "#/" };
Object.defineProperty(loc, "hash", {
  get() { return this._hash; },
  set(v) {
    this._hash = v;
    setTimeout(() => hashListeners.forEach((f) => f()), 0);
  },
});

const lsStore = { dstack_tpu_token: "test-token", dstack_tpu_project: "main" };

const fetchCalls = [];
const RUNS = Array.from({ length: 60 }, (_, i) => ({
  run_spec: { run_name: `run-${i}`, configuration: { type: "task" } },
  status: i % 2 ? "done" : "running",
  submitted_at: new Date().toISOString(),
  cost: 0.5,
}));

const ROUTES = {
  "/api/users/get_my_user": () => ({ username: "admin", global_role: "admin" }),
  "/api/projects/list": () => [{ project_name: "main", members: [] }],
  "/api/project/main/runs/list": () => RUNS,
  "/api/project/main/runs/get": () => ({
    run_spec: { run_name: "run-0", configuration: { type: "task" } },
    status: "running", submitted_at: new Date().toISOString(), cost: 0,
    jobs: [],
  }),
  "/api/project/main/metrics/job": () => ({ points: [] }),
  "/api/project/main/logs/poll": () => ({ logs: [] }),
  "/api/project/main/configurations/parse": (body) => {
    if (!body.yaml.includes("type:")) throw { status: 400, detail: "invalid configuration" };
    return { type: "task", commands: ["python train.py"] };
  },
  "/api/project/main/runs/get_plan": (body) => ({
    action: "create",
    effective_run_name: "ui-run",
    run_spec: { run_name: "ui-run", configuration: body.run_spec.configuration },
    total_offers: 1,
    offers: [{ slice_name: "v5litepod-8", backend: "local", region: "local", price: 1.2, availability: "available" }],
  }),
  "/api/project/main/runs/submit": (body) => ({
    run_spec: { run_name: body.run_spec.run_name || "ui-run" },
    status: "submitted",
  }),
};

async function fakeFetch(path, opts = {}) {
  const body = opts.body ? JSON.parse(opts.body) : {};
  fetchCalls.push({ path, body });
  const handler = ROUTES[path];
  if (!handler) return { status: 404, ok: false, text: async () => `{"detail":"no stub for ${path}"}` };
  try {
    const data = handler(body);
    return { status: 200, ok: true, text: async () => JSON.stringify(data) };
  } catch (e) {
    return { status: e.status || 500, ok: false, text: async () => JSON.stringify({ detail: e.detail }) };
  }
}

const wsInstances = [];
class FakeWebSocket {
  constructor(url) { this.url = url; this.closed = false; wsInstances.push(this); }
  close() { this.closed = true; }
}

const sandbox = {
  document: {
    getElementById: () => appRoot,
    createElement: (t) => new El(t),
    createElementNS: (_ns, t) => new El(t),
    createTextNode: (s) => new TextNode(s),
    body: new El("body"),
  },
  window: {
    addEventListener: (t, f) => { if (t === "hashchange") hashListeners.push(f); },
    confirm: () => true,
    prompt: () => "",
    alert: () => {},
    innerWidth: 1280,
  },
  location: loc,
  localStorage: {
    getItem: (k) => (k in lsStore ? lsStore[k] : null),
    setItem: (k, v) => { lsStore[k] = String(v); },
    removeItem: (k) => { delete lsStore[k]; },
  },
  fetch: fakeFetch,
  WebSocket: FakeWebSocket,
  setInterval, clearInterval, setTimeout, clearTimeout,
  Date, JSON, Math, Promise, Object, Array, String, Number, Infinity, NaN,
  encodeURIComponent, decodeURIComponent, console, Error,
};
sandbox.globalThis = sandbox;

const here = dirname(fileURLToPath(import.meta.url));
const src = readFileSync(join(here, "../../dstack_tpu/server/statics/app.js"), "utf8");
vm.createContext(sandbox);
vm.runInContext(src, sandbox, { filename: "app.js" });

const settle = (ms = 30) => new Promise((r) => setTimeout(r, ms));

/* ---------------- the test ---------------- */

await settle(); // initial route(): "#/" -> runs list

// 1. Runs list renders and paginates at 25/page.
{
  const rows = byTag(appRoot, "tbody").flatMap((tb) => tb.children);
  check(rows.length === 25, `runs list shows 25 rows/page (got ${rows.length})`);
  check(appRoot.textContent.includes("page 1 / 3"), "pager shows page 1 / 3");
  check(appRoot.textContent.includes("60 rows"), "pager shows total row count");
  const next = buttonByText(appRoot, "next");
  check(next, "pager has a next button");
  next.click();
  await settle(5);
  check(appRoot.textContent.includes("page 2 / 3"), "next advances to page 2");
  check(appRoot.textContent.includes("run-25"), "page 2 shows the 26th run");
}

// 2. Run detail streams logs over the WebSocket — no polling interval.
{
  loc.hash = "#/p/main/runs/run-0";
  await settle();
  check(wsInstances.length === 1, "run detail opened exactly one WebSocket");
  const ws = wsInstances[0];
  check(ws.url.includes("/api/project/main/logs/ws"), `WS hits the logs endpoint (${ws.url})`);
  check(ws.url.includes("run_name=run-0"), "WS names the run");
  check(ws.url.includes("token=test-token"), "WS carries the token (browsers cannot set headers)");
  ws.onmessage({ data: JSON.stringify({ logs: [{ message: "hello-from-ws\n" }], next_line: 1 }) });
  check(appRoot.textContent.includes("hello-from-ws"), "pushed log line rendered");
  let pollCalls = fetchCalls.filter((c) => c.path.endsWith("/logs/poll"));
  check(pollCalls.length === 0, "no REST log polling while the socket is open");
  // Socket failure falls back to polling — resuming AFTER the pushed lines.
  ws.onerror();
  await settle();
  pollCalls = fetchCalls.filter((c) => c.path.endsWith("/logs/poll"));
  check(pollCalls.length === 1, "WS failure starts the poll fallback");
  check(pollCalls[0].body.start_line === 1, "fallback resumes from the streamed position (no duplicates)");
}

// 3. Submit view: YAML -> parse -> plan -> apply -> lands on the run page.
{
  loc.hash = "#/p/main/submit";
  await settle();
  check(wsInstances[0].closed, "leaving run detail closed its WebSocket");
  const ta = byTag(appRoot, "textarea")[0];
  check(ta, "submit view has a YAML textarea");
  ta.value = "type: task\ncommands:\n  - python train.py";
  buttonByText(appRoot, "Plan").click();
  await settle();
  check(appRoot.textContent.includes("Plan: create"), "plan action rendered");
  check(appRoot.textContent.includes("v5litepod-8"), "plan offers rendered");
  const apply = buttonByText(appRoot, "Apply");
  check(apply && apply.getAttribute("disabled") === null, "apply enabled after a plannable config");
  apply.click();
  await settle();
  check(loc.hash === "#/p/main/runs/ui-run", `apply navigates to the run (${loc.hash})`);
  const submits = fetchCalls.filter((c) => c.path.endsWith("/runs/submit"));
  check(submits.length === 1, "exactly one submit call");
  check(submits[0].body.run_spec.configuration.type === "task", "submit carries the parsed configuration");
}

// 4. Submit view surfaces a parse error instead of applying.
{
  loc.hash = "#/p/main/runs"; // reset
  await settle();
  loc.hash = "#/p/main/submit";
  await settle();
  const ta = byTag(appRoot, "textarea")[0];
  ta.value = "not a config";
  buttonByText(appRoot, "Plan").click();
  await settle();
  check(appRoot.textContent.includes("invalid configuration"), "parse error shown");
  const apply = buttonByText(appRoot, "Apply");
  check(apply.getAttribute("disabled") !== null, "apply stays disabled on error");
}

if (failures) {
  console.error(`FAILED: ${failures} of ${checks} checks`);
  process.exit(1);
}
console.log(`OK: ${checks} DOM checks passed`);
