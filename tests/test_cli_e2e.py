"""CLI end-to-end: real server process + real CLI process + real native runner.

Drives the verify-skill recipe: config -> backend -> apply -f task.dstack.yml (with
code upload) -> attached logs -> ps/logs/fleet/offer/secret surfaces."""

import os
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest
import requests

from dstack_tpu.utils.runner_binary import find_runner_binary

pytestmark = pytest.mark.skipif(
    find_runner_binary() is None, reason="native runner binary unavailable"
)

REPO_ROOT = Path(__file__).resolve().parent.parent
TOKEN = "test-admin-token"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def server(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env.update(
        {
            "DSTACK_TPU_SERVER_ADMIN_TOKEN": TOKEN,
            "DSTACK_TPU_SERVER_DIR": str(tmp_path / "server"),
            "DSTACK_TPU_DB_PATH": str(tmp_path / "server" / "server.db"),
            "DSTACK_TPU_SERVER_PORT": str(port),
            "PYTHONPATH": str(REPO_ROOT),
        }
    )
    proc = subprocess.Popen(
        [sys.executable, "-m", "dstack_tpu.server.app"],
        env=env,
        cwd=str(tmp_path),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )
    base = f"http://127.0.0.1:{port}"
    try:
        for _ in range(100):
            try:
                if requests.get(base + "/healthcheck", timeout=1).status_code == 200:
                    break
            except requests.ConnectionError:
                time.sleep(0.1)
        else:
            out = proc.stdout.read().decode(errors="replace") if proc.stdout else ""
            raise RuntimeError(f"server did not start: {out[:2000]}")
        yield base
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def _cli(args, cwd, tmp_path, check=True, timeout=60):
    env = dict(os.environ)
    env.update(
        {
            "DSTACK_TPU_CLI_CONFIG_DIR": str(tmp_path / "cli-config"),
            "PYTHONPATH": str(REPO_ROOT),
        }
    )
    result = subprocess.run(
        [sys.executable, "-m", "dstack_tpu.cli.main", *args],
        cwd=str(cwd),
        env=env,
        stdin=subprocess.DEVNULL,  # pin non-TTY behavior even under pytest -s
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    if check and result.returncode != 0:
        raise AssertionError(
            f"cli {' '.join(args)} failed ({result.returncode}):\n{result.stdout}\n{result.stderr}"
        )
    return result


class TestCliE2E:
    def test_full_apply_flow(self, server, tmp_path):
        work = tmp_path / "myproject"
        work.mkdir()
        (work / "hello.txt").write_text("payload-from-repo\n")
        (work / "task.dstack.yml").write_text(
            "type: task\n"
            "commands:\n"
            "  - echo cli-e2e-$((21*2))\n"
            "  - cat hello.txt\n"
        )

        _cli(["config", "--url", server, "--token", TOKEN], work, tmp_path)
        _cli(["init"], work, tmp_path)

        result = _cli(["apply", "-f", "task.dstack.yml", "-y"], work, tmp_path, timeout=120)
        assert "cli-e2e-42" in result.stdout, result.stdout + result.stderr
        assert "payload-from-repo" in result.stdout  # code upload + extraction worked
        assert "finished: done" in result.stderr

        ps = _cli(["ps", "-a"], work, tmp_path)
        assert "task" in ps.stdout and "done" in ps.stdout

        logs = _cli(["logs", "task"], work, tmp_path, check=False)
        run_name = [l for l in ps.stdout.splitlines()[1:] if l.strip()][0].split()[0]
        logs = _cli(["logs", run_name], work, tmp_path)
        assert "cli-e2e-42" in logs.stdout

        fleets = _cli(["fleet", "list"], work, tmp_path)
        assert run_name in fleets.stdout  # auto-created run fleet

        # stop/delete prompt unless -y (reference parity); non-interactive
        # without -y refuses rather than acting silently.
        refused = _cli(["delete", run_name], work, tmp_path, check=False)
        assert refused.returncode != 0 and "pass -y" in refused.stderr
        ps = _cli(["ps", "-a"], work, tmp_path)
        assert run_name in ps.stdout  # still there
        _cli(["delete", run_name, "-y"], work, tmp_path)
        ps = _cli(["ps", "-a"], work, tmp_path)
        assert run_name not in ps.stdout

    def test_offers_and_secrets(self, server, tmp_path):
        work = tmp_path / "w2"
        work.mkdir()
        _cli(["config", "--url", server, "--token", TOKEN], work, tmp_path)
        _cli(["backend", "create", "mock"], work, tmp_path)

        offers = _cli(["offer", "--tpu", "v5p-16"], work, tmp_path)
        assert "v5p-16" in offers.stdout
        assert "$" in offers.stdout

        _cli(["secret", "set", "API_KEY", "s3cret"], work, tmp_path)
        listed = _cli(["secret", "list"], work, tmp_path)
        assert "API_KEY" in listed.stdout
        _cli(["secret", "delete", "API_KEY"], work, tmp_path)

    def test_failed_run_exit_code(self, server, tmp_path):
        work = tmp_path / "w3"
        work.mkdir()
        (work / "bad.dstack.yml").write_text("type: task\ncommands: [\"exit 3\"]\n")
        _cli(["config", "--url", server, "--token", TOKEN], work, tmp_path)
        result = _cli(
            ["apply", "-f", "bad.dstack.yml", "-y", "--no-repo"],
            work,
            tmp_path,
            check=False,
            timeout=120,
        )
        assert result.returncode == 1
        assert "failed" in result.stderr


class TestProjectCommand:
    def test_project_list_create_delete(self, server, tmp_path):
        base = server
        _cli(["config", "--url", base, "--token", TOKEN, "--project", "main"], tmp_path, tmp_path)
        out = _cli(["project", "list"], tmp_path, tmp_path)
        assert "main" in out.stdout and "admin" in out.stdout
        _cli(["project", "create", "research"], tmp_path, tmp_path)
        out = _cli(["project", "list"], tmp_path, tmp_path)
        assert "research" in out.stdout
        _cli(["project", "delete", "research"], tmp_path, tmp_path)
        out = _cli(["project", "list"], tmp_path, tmp_path)
        assert "research" not in out.stdout
