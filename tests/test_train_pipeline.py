"""Overlapped training pipeline: gradient accumulation, input prefetch,
per-host sharded batches, and the comm/compute-overlap env defaults.

Numerics run on the virtual 8-device CPU mesh (conftest); the orchestrator
side (env injection) runs through the real server + scripted runner."""

import dataclasses
import itertools
import os
import threading
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# Pin eager computation to CPU (same pattern as tests/test_workloads.py).
jax.config.update("jax_default_device", jax.devices("cpu")[0])

import optax

from dstack_tpu.workloads import data as data_lib
from dstack_tpu.workloads import model as model_lib
from dstack_tpu.workloads import moe as moe_lib
from dstack_tpu.workloads import train as train_lib
from dstack_tpu.workloads import xla_flags
from dstack_tpu.workloads.config import get_config
from dstack_tpu.workloads.sharding import BATCH_SPEC, batch_sharding, make_mesh

REPO = Path(__file__).parent.parent


def fp32_cfg(**over):
    over.setdefault("dtype", "float32")
    over.setdefault("param_dtype", "float32")
    over.setdefault("remat", False)
    over.setdefault("max_seq_len", 64)
    return get_config("test", **over)


def cpu_devices(n=8):
    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip(f"need {n} cpu devices, have {len(devs)}")
    return devs[:n]


class TestGradAccum:
    def test_accum4_matches_full_batch_step(self):
        """One accum=4 update over 4 microbatches == one full-batch update,
        within fp32 tolerance (the acceptance-bar equivalence)."""
        cfg = fp32_cfg()
        opt = optax.sgd(0.1)  # linear in grads: equivalence is exact up to fp
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size)
        targets = jax.random.randint(jax.random.PRNGKey(2), (8, 32), 0, cfg.vocab_size)

        results = {}
        for accum in (1, 4):
            state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), opt)
            step = train_lib.make_train_step(cfg, opt, grad_accum=accum)
            state, metrics = step(state, tokens, targets)
            results[accum] = (state, metrics)

        full, acc = results[1], results[4]
        np.testing.assert_allclose(
            float(acc[1]["loss"]), float(full[1]["loss"]), rtol=1e-5
        )
        np.testing.assert_allclose(
            float(acc[1]["grad_norm"]), float(full[1]["grad_norm"]), rtol=1e-4
        )
        for key in full[0].params:
            np.testing.assert_allclose(
                np.asarray(acc[0].params[key]), np.asarray(full[0].params[key]),
                rtol=1e-4, atol=1e-5, err_msg=key,
            )

    def test_accum_on_mesh_matches_unaccumulated(self):
        devs = cpu_devices(8)
        mesh = make_mesh(dp=2, fsdp=4, tp=1, sp=1, devices=devs)
        cfg = fp32_cfg()
        opt = optax.sgd(0.1)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (16, 32), 0, cfg.vocab_size)
        results = {}
        with mesh:
            bspec = batch_sharding(mesh)
            tok = jax.device_put(tokens, bspec)
            for accum in (1, 2):
                state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), opt, mesh)
                step = train_lib.make_train_step(cfg, opt, mesh, grad_accum=accum)
                state, metrics = step(state, tok, tok)
                results[accum] = (
                    {k: np.asarray(v) for k, v in state.params.items()},
                    float(metrics["loss"]),
                )
        np.testing.assert_allclose(results[2][1], results[1][1], rtol=1e-5)
        for key in results[1][0]:
            np.testing.assert_allclose(
                results[2][0][key], results[1][0][key], rtol=1e-4, atol=1e-5,
                err_msg=key,
            )

    def test_moe_accum_trains(self):
        cfg = dataclasses.replace(moe_lib.MOE_PRESETS["moe_test"], max_seq_len=64)
        opt = optax.adamw(1e-3)
        params = moe_lib.init_moe_params(cfg, jax.random.PRNGKey(0))
        opt_state = opt.init(params)
        step = moe_lib.make_moe_train_step(cfg, opt, grad_accum=2)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)
        losses = []
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, tokens, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_indivisible_batch_rejected(self):
        cfg = fp32_cfg()
        opt = optax.sgd(0.1)
        state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), opt)
        step = train_lib.make_train_step(cfg, opt, grad_accum=3)
        tokens = jnp.zeros((8, 32), jnp.int32)
        with pytest.raises(ValueError, match="not divisible"):
            step(state, tokens, tokens)

    def test_microbatch_smaller_than_data_shards_rejected(self):
        devs = cpu_devices(8)
        mesh = make_mesh(dp=2, fsdp=4, tp=1, sp=1, devices=devs)
        cfg = fp32_cfg()
        opt = optax.sgd(0.1)
        with mesh:
            state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), opt, mesh)
            step = train_lib.make_train_step(cfg, opt, mesh, grad_accum=2)
            tokens = jax.device_put(
                jnp.zeros((8, 32), jnp.int32), batch_sharding(mesh)
            )  # microbatch 4 < 8 data shards
            with pytest.raises(ValueError, match="data shards"):
                step(state, tokens, tokens)

    def test_bad_grad_accum_rejected(self):
        cfg = fp32_cfg()
        with pytest.raises(ValueError, match="grad_accum"):
            train_lib.make_train_step(cfg, optax.sgd(0.1), grad_accum=0)


class TestPrefetcher:
    def test_order_preserved(self):
        with data_lib.Prefetcher(iter(range(20)), depth=3) as p:
            assert list(p) == list(range(20))

    def test_depth_bounds_readahead(self):
        produced = []

        def source():
            for i in itertools.count():
                produced.append(i)
                yield i

        p = data_lib.Prefetcher(source(), depth=3)
        try:
            assert next(p) == 0
            deadline = time.time() + 2.0
            # It prefetches AHEAD of demand (that's the point)...
            while len(produced) < 3 and time.time() < deadline:
                time.sleep(0.01)
            assert len(produced) >= 3
            time.sleep(0.2)
            # ...but never more than consumed + depth + 1 in-hand item.
            assert len(produced) <= 1 + 3 + 1, produced
        finally:
            p.close()

    def test_depth_zero_is_synchronous_passthrough(self):
        pulled = []

        def source():
            for i in range(5):
                pulled.append(i)
                yield i

        p = data_lib.Prefetcher(source(), depth=0)
        assert p._thread is None
        assert next(p) == 0
        assert pulled == [0]  # nothing pulled ahead
        assert list(p) == [1, 2, 3, 4]

    def test_source_exception_propagates(self):
        def source():
            yield 1
            yield 2
            raise RuntimeError("corrupt shard")

        p = data_lib.Prefetcher(source(), depth=2)
        try:
            assert next(p) == 1
            assert next(p) == 2
            with pytest.raises(RuntimeError, match="corrupt shard"):
                next(p)
        finally:
            p.close()

    def test_exhaustion_stops_iteration(self):
        p = data_lib.Prefetcher(iter([1]), depth=2)
        assert next(p) == 1
        with pytest.raises(StopIteration):
            next(p)
        with pytest.raises(StopIteration):
            next(p)  # stays closed

    def test_close_stops_fill_thread(self):
        p = data_lib.Prefetcher(itertools.count(), depth=2)
        assert next(p) == 0
        p.close()
        assert not p._thread.is_alive()

    def test_negative_depth_rejected(self):
        with pytest.raises(ValueError, match="depth"):
            data_lib.Prefetcher(iter([]), depth=-1)


class TestHostShardedBatches:
    def test_host_shard_partition(self):
        seen = []
        for pi in range(4):
            off, rows = data_lib.host_shard(16, pi, 4)
            assert rows == 4
            seen.extend(range(off, off + rows))
        assert sorted(seen) == list(range(16))  # disjoint cover

    def test_host_shard_indivisible_rejected(self):
        with pytest.raises(ValueError, match="not divisible"):
            data_lib.host_shard(10, 0, 3)

    def test_synthetic_per_host_distinct_and_reproducible(self):
        a0 = next(data_lib.synthetic_batches(100, 8, 16, process_index=0, process_count=2))
        a0_again = next(
            data_lib.synthetic_batches(100, 8, 16, process_index=0, process_count=2)
        )
        a1 = next(data_lib.synthetic_batches(100, 8, 16, process_index=1, process_count=2))
        assert a0[0].shape == (4, 16)  # local rows = global / hosts
        np.testing.assert_array_equal(a0[0], a0_again[0])
        assert not np.array_equal(a0[0], a1[0])

    def test_token_file_windows_and_targets(self, tmp_path):
        path = tmp_path / "tokens.bin"
        np.arange(4 * 9, dtype=np.uint16).tofile(path)  # 4 windows of seq+1=9
        it = data_lib.token_file_batches(
            str(path), global_batch=2, seq=8, loop=False,
            process_index=0, process_count=1,
        )
        tokens, targets = next(it)
        assert tokens.shape == (2, 8)
        np.testing.assert_array_equal(tokens[0], np.arange(8))
        np.testing.assert_array_equal(targets[0], np.arange(1, 9))  # next-token
        np.testing.assert_array_equal(tokens[1], np.arange(9, 17))
        next(it)  # windows 2..3
        with pytest.raises(StopIteration):
            next(it)

    def test_token_file_hosts_are_disjoint(self, tmp_path):
        path = tmp_path / "tokens.bin"
        np.arange(4 * 9, dtype=np.uint16).tofile(path)
        host_rows = [
            next(data_lib.token_file_batches(
                str(path), global_batch=4, seq=8,
                process_index=pi, process_count=2,
            ))[0]
            for pi in range(2)
        ]
        combined = np.concatenate(host_rows)  # hosts cover the global batch
        full = next(data_lib.token_file_batches(
            str(path), global_batch=4, seq=8, process_index=0, process_count=1
        ))[0]
        np.testing.assert_array_equal(combined, full)

    def test_token_file_too_small_rejected(self, tmp_path):
        path = tmp_path / "tokens.bin"
        np.arange(10, dtype=np.uint16).tofile(path)
        with pytest.raises(ValueError, match="need at least"):
            next(data_lib.token_file_batches(str(path), global_batch=4, seq=8))

    def test_sharded_batches_on_8_device_mesh(self):
        """The multihost batch-construction path on the fake-device harness:
        the assembled global array carries the batch sharding and exactly the
        source's content."""
        devs = cpu_devices(8)
        mesh = make_mesh(dp=2, fsdp=2, tp=1, sp=2, devices=devs)
        src_np = next(data_lib.synthetic_batches(100, 16, 32, process_index=0,
                                                 process_count=1))
        with mesh:
            tokens, targets = next(data_lib.sharded_batches(
                iter([src_np]), mesh, BATCH_SPEC, global_batch=16
            ))
        assert tokens.shape == (16, 32)
        assert tokens.sharding == jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(("dp", "fsdp"), "sp")
        )
        np.testing.assert_array_equal(np.asarray(tokens), src_np[0])
        # Each device holds exactly its [4, 16] tile.
        shard_shape = tokens.sharding.shard_shape(tokens.shape)
        assert shard_shape == (4, 16)

    def test_input_pipeline_feeds_train_step(self):
        devs = cpu_devices(8)
        mesh = make_mesh(dp=2, fsdp=4, tp=1, sp=1, devices=devs)
        cfg = fp32_cfg()
        opt = optax.sgd(0.1)
        with mesh:
            state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), opt, mesh)
            step = train_lib.make_train_step(cfg, opt, mesh, grad_accum=2)
            with data_lib.input_pipeline(
                mesh, BATCH_SPEC, global_batch=16, seq=32,
                vocab_size=cfg.vocab_size, prefetch=2,
            ) as feed:
                for _ in range(2):
                    tokens, targets = next(feed)
                    state, metrics = step(state, tokens, targets)
        assert np.isfinite(float(metrics["loss"]))


class TestXlaFlags:
    def test_defaults_compose(self):
        flags = xla_flags.compose("")
        for name in xla_flags.OVERLAP_XLA_FLAGS:
            assert f"{name}=" in flags
        assert "--xla_tpu_enable_latency_hiding_scheduler=true" in flags

    def test_user_flag_wins_by_name(self):
        flags = xla_flags.compose("--xla_tpu_enable_latency_hiding_scheduler=false")
        assert flags.count("--xla_tpu_enable_latency_hiding_scheduler") == 1
        assert "--xla_tpu_enable_latency_hiding_scheduler=false" in flags
        assert "--xla_enable_async_all_gather=true" in flags  # rest still added

    def test_unrelated_user_flags_preserved(self):
        env = xla_flags.overlap_env({"XLA_FLAGS": "--xla_dump_to=/tmp/hlo"})
        assert env["XLA_FLAGS"].startswith("--xla_dump_to=/tmp/hlo")
        assert "--xla_tpu_enable_async_collective_fusion=true" in env["XLA_FLAGS"]
        assert "--xla_tpu_enable_megascale_barrier=true" in env["LIBTPU_INIT_ARGS"]

    def test_opt_out(self):
        assert xla_flags.overlap_env({xla_flags.ENV_DISABLE: "0"}) == {}

    def test_apply_noops_off_tpu(self, monkeypatch):
        monkeypatch.delenv("PJRT_DEVICE", raising=False)
        sentinel = os.environ.get("XLA_FLAGS")
        assert xla_flags.apply() == {}
        assert os.environ.get("XLA_FLAGS") == sentinel  # untouched

    def test_apply_sets_env_on_tpu(self, monkeypatch):
        monkeypatch.setenv("PJRT_DEVICE", "TPU")
        monkeypatch.setenv("XLA_FLAGS", "--xla_dump_to=/tmp/hlo")
        monkeypatch.setenv("LIBTPU_INIT_ARGS", "")
        applied = xla_flags.apply()
        assert os.environ["XLA_FLAGS"] == applied["XLA_FLAGS"]
        assert applied["XLA_FLAGS"].startswith("--xla_dump_to=/tmp/hlo")
        assert "--xla_tpu_enable_latency_hiding_scheduler=true" in applied["XLA_FLAGS"]

    def test_chip_generation_from_env(self):
        gen = xla_flags.chip_generation_from_env
        assert gen({"TPU_ACCELERATOR_TYPE": "v5p-16"}) == "v5p"
        assert gen({"TPU_ACCELERATOR_TYPE": "v5litepod-8"}) == "v5e"
        assert gen({"TPU_ACCELERATOR_TYPE": "v6e-8"}) == "v6e"
        assert gen({"TPU_ACCELERATOR_TYPE": "weird-999"}) == ""
        assert gen({}) == ""

    def test_generation_flags_merge_over_base(self):
        v5p = xla_flags.generation_flags("v5p")
        # Base set intact, plus the generation branch.
        for name, val in xla_flags.OVERLAP_XLA_FLAGS.items():
            assert v5p[name] == val
        assert v5p["--xla_tpu_scoped_vmem_limit_kib"] == "81920"
        v6e = xla_flags.generation_flags("v6e")
        assert v6e["--xla_tpu_scoped_vmem_limit_kib"] == "98304"
        assert (v6e["--xla_tpu_enable_sparse_core_collective_offload_all_gather"]
                == "true")
        # Unknown generation = exactly the base set (pre-branch behavior).
        assert xla_flags.generation_flags("") == dict(xla_flags.OVERLAP_XLA_FLAGS)
        assert xla_flags.generation_flags("v4") == dict(xla_flags.OVERLAP_XLA_FLAGS)

    def test_overlap_env_branches_on_accelerator_type(self):
        env = xla_flags.overlap_env({"TPU_ACCELERATOR_TYPE": "v5p-16"})
        assert "--xla_tpu_scoped_vmem_limit_kib=81920" in env["XLA_FLAGS"]
        env = xla_flags.overlap_env({"TPU_ACCELERATOR_TYPE": "v6e-8"})
        assert ("--xla_tpu_enable_sparse_core_collective_offload_all_reduce"
                "=true") in env["XLA_FLAGS"]
        # No generation info: base-only, no vmem override.
        env = xla_flags.overlap_env({})
        assert "--xla_tpu_scoped_vmem_limit_kib" not in env["XLA_FLAGS"]

    def test_user_flag_beats_generation_default(self):
        env = xla_flags.overlap_env({
            "TPU_ACCELERATOR_TYPE": "v5p-16",
            "XLA_FLAGS": "--xla_tpu_scoped_vmem_limit_kib=65536",
        })
        assert env["XLA_FLAGS"].count("--xla_tpu_scoped_vmem_limit_kib") == 1
        assert "--xla_tpu_scoped_vmem_limit_kib=65536" in env["XLA_FLAGS"]

    def test_docker_image_env_matches_module(self):
        """docker/tpu bakes the same defaults the module composes — the image
        and the configurator must never drift apart. The generation branches
        are deliberately NOT baked: the image doesn't know the chip; the
        configurator/entrypoint add them at env-compose time."""
        text = (REPO / "docker" / "tpu" / "Dockerfile").read_text()
        baked = {}
        for var in ("XLA_FLAGS", "LIBTPU_INIT_ARGS"):
            m = [ln for ln in text.splitlines() if f'{var}="' in ln]
            assert m, f"docker/tpu/Dockerfile does not bake {var}"
            baked[var] = m[0].split('"')[1]
        assert xla_flags._parse(baked["XLA_FLAGS"]) == dict(xla_flags.OVERLAP_XLA_FLAGS)
        assert xla_flags._parse(baked["LIBTPU_INIT_ARGS"]) == dict(
            xla_flags.OVERLAP_LIBTPU_ARGS
        )


class TestTimedLoop:
    def test_reports_compile_separately_and_percentiles(self, capsys):
        calls = []

        def do_step():
            calls.append(1)
            time.sleep(0.05 if len(calls) == 1 else 0.01)
            return jnp.float32(1.0)

        stats = train_lib._timed_loop(12, batch=4, seq=8, do_step=do_step)
        assert stats["compile_s"] >= 0.05
        assert 0 < stats["p50_s"] <= stats["p90_s"]
        # Steady-state throughput excludes the slow first step entirely.
        assert stats["tokens_per_sec"] > 4 * 8 / 0.05
        out = capsys.readouterr().out
        assert "compile+first-step" in out
        assert "p50" in out and "p90" in out


class TestOverlapEnvInjection:
    """Orchestrated runs receive the overlap env defaults (acceptance bar:
    server-side coverage of the job-configurator path)."""

    @pytest.fixture(autouse=True)
    def _fake_runner(self, monkeypatch):
        from dstack_tpu.server.background import tasks
        from dstack_tpu.server.services import backends as backends_service
        from tests.common import FakeRunnerClient

        FakeRunnerClient.reset()
        backends_service.reset_compute_cache()
        monkeypatch.setattr(tasks, "get_runner_client", FakeRunnerClient.for_jpd)
        yield

    async def test_tpu_job_env_gets_overlap_defaults(self):
        from tests.common import FakeRunnerClient, api_server, drive, setup_mock_backend, tpu_task_spec

        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post(
                "/api/project/main/runs/submit", tpu_task_spec("overlap", "v5e-8")
            )
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "overlap"})
            assert run["status"] == "done"
            fakes = list(FakeRunnerClient.registry.values())
            assert fakes
            for fake in fakes:
                env = fake.submitted.env
                assert "--xla_tpu_enable_latency_hiding_scheduler=true" in env["XLA_FLAGS"]
                assert "--xla_enable_async_all_gather=true" in env["XLA_FLAGS"]
                assert "--xla_tpu_enable_megascale_barrier=true" in env["LIBTPU_INIT_ARGS"]

    async def test_user_env_wins_flag_by_flag(self):
        from tests.common import FakeRunnerClient, api_server, drive, setup_mock_backend, tpu_task_spec

        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post(
                "/api/project/main/runs/submit",
                tpu_task_spec(
                    "overlap-ov", "v5e-8",
                    env={"XLA_FLAGS": "--xla_tpu_enable_latency_hiding_scheduler=false"},
                ),
            )
            await drive(api.db)
            env = list(FakeRunnerClient.registry.values())[0].submitted.env
            assert "--xla_tpu_enable_latency_hiding_scheduler=false" in env["XLA_FLAGS"]
            assert "--xla_tpu_enable_latency_hiding_scheduler=true" not in env["XLA_FLAGS"]
            assert "--xla_enable_async_all_gather=true" in env["XLA_FLAGS"]

    async def test_opt_out_env(self):
        from tests.common import FakeRunnerClient, api_server, drive, setup_mock_backend, tpu_task_spec

        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post(
                "/api/project/main/runs/submit",
                tpu_task_spec("overlap-off", "v5e-8",
                              env={"DSTACK_TPU_OVERLAP_FLAGS": "0"}),
            )
            await drive(api.db)
            env = list(FakeRunnerClient.registry.values())[0].submitted.env
            # Pinned EMPTY (not merely absent): the container-level value must
            # override the default image's baked ENV so the opt-out is real.
            assert env["XLA_FLAGS"] == ""
            assert env["LIBTPU_INIT_ARGS"] == ""

    def test_non_tpu_job_on_default_image_neutralizes_baked_flags(self):
        """The default image bakes TPU-only XLA_FLAGS; a non-TPU job on it
        must have them pinned empty or CPU-backed XLA aborts at init."""
        from dstack_tpu.core.models.runs import RunSpec
        from dstack_tpu.server.services.jobs.configurators import get_job_specs

        spec = RunSpec.model_validate({
            "run_name": "cpu-task",
            "configuration": {"type": "task", "commands": ["echo hi"]},
        })
        (job,) = get_job_specs(spec)
        assert job.env["XLA_FLAGS"] == ""
        assert job.env["LIBTPU_INIT_ARGS"] == ""

    def test_non_tpu_job_on_custom_image_untouched(self):
        from dstack_tpu.core.models.runs import RunSpec
        from dstack_tpu.server.services.jobs.configurators import get_job_specs

        spec = RunSpec.model_validate({
            "run_name": "cpu-task-img",
            "configuration": {
                "type": "task",
                "commands": ["echo hi"],
                "image": "python:3.11",
            },
        })
        (job,) = get_job_specs(spec)
        assert "XLA_FLAGS" not in job.env
        assert "LIBTPU_INIT_ARGS" not in job.env

    def test_opt_out_on_custom_image_leaves_image_env_alone(self):
        """Opting out on a CUSTOM image must not pin XLA_FLAGS="" — that
        would wipe flags the user baked into their own image's ENV."""
        from dstack_tpu.core.models.runs import RunSpec
        from dstack_tpu.server.services.jobs.configurators import get_job_specs

        spec = RunSpec.model_validate({
            "run_name": "custom-img",
            "configuration": {
                "type": "task",
                "commands": ["python train.py"],
                "image": "ghcr.io/me/my-tpu-image:1",
                "resources": {"tpu": "v5e-8"},
                "env": {"DSTACK_TPU_OVERLAP_FLAGS": "0"},
            },
        })
        jobs = get_job_specs(spec)
        for job in jobs:
            assert "XLA_FLAGS" not in job.env
            assert "LIBTPU_INIT_ARGS" not in job.env


class TestDraftDistill:
    """Draft-head distillation (serve speculation's model-based proposer):
    the loop must actually fit the frozen target's argmax, leave the target
    untouched, and round-trip the head through the ``.draft`` subtree the
    serve engine restores from."""

    def test_distill_improves_and_freezes_target(self):
        cfg = fp32_cfg()
        params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
        draft = model_lib.init_draft_params(cfg, jax.random.PRNGKey(1))
        opt = train_lib.make_optimizer(learning_rate=1e-2)
        state = train_lib.DraftTrainState(
            params=params, draft=draft, opt_state=opt.init(draft),
            step=jnp.zeros((), jnp.int32),
        )
        step = train_lib.make_draft_distill_step(cfg, opt)
        tokens = jax.random.randint(
            jax.random.PRNGKey(2), (4, 32), 0, cfg.vocab_size
        )
        target_before = {k: np.asarray(v) for k, v in params.items()}
        losses = []
        for _ in range(12):
            state, loss = step(state, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
        assert int(state.step) == 12
        for k, v in state.params.items():
            np.testing.assert_array_equal(np.asarray(v), target_before[k])

    def test_draft_subtree_roundtrips_into_serve(self, tmp_path):
        from dstack_tpu.workloads import serve as serve_lib
        from dstack_tpu.workloads.checkpoint import CheckpointManager

        cfg = fp32_cfg()
        params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
        draft = model_lib.init_draft_params(cfg, jax.random.PRNGKey(1))
        opt = train_lib.make_optimizer(learning_rate=1e-2)
        state = train_lib.DraftTrainState(
            params=params, draft=draft, opt_state=opt.init(draft),
            step=jnp.asarray(3, jnp.int32),
        )
        CheckpointManager(str(tmp_path)).save(3, state, block=True)
        restored, manifest = serve_lib.load_draft_params(str(tmp_path), cfg)
        assert manifest["step"] == 3
        assert set(restored) == set(draft)
        for k in draft:
            np.testing.assert_array_equal(
                np.asarray(restored[k]), np.asarray(draft[k]), err_msg=k
            )
        # The same checkpoint also serves the TARGET weights (.params): one
        # artifact, both restore paths.
        served, _ = serve_lib.load_serve_params(str(tmp_path), cfg)
        np.testing.assert_array_equal(
            np.asarray(served["embed"]), np.asarray(params["embed"])
        )

    def test_wrong_width_head_rejected(self, tmp_path):
        from dstack_tpu.workloads import serve as serve_lib
        from dstack_tpu.workloads.checkpoint import CheckpointManager

        cfg = fp32_cfg()
        narrow = fp32_cfg(d_model=64, n_heads=4, n_kv_heads=4)
        opt = train_lib.make_optimizer(learning_rate=1e-2)
        draft = model_lib.init_draft_params(narrow, jax.random.PRNGKey(1))
        state = train_lib.DraftTrainState(
            params=model_lib.init_params(narrow, jax.random.PRNGKey(0)),
            draft=draft, opt_state=opt.init(draft),
            step=jnp.zeros((), jnp.int32),
        )
        CheckpointManager(str(tmp_path)).save(1, state, block=True)
        with pytest.raises(ValueError, match="d_model"):
            serve_lib.load_draft_params(str(tmp_path), cfg)

    def test_params_only_checkpoint_rejected(self, tmp_path):
        from dstack_tpu.workloads import serve as serve_lib
        from dstack_tpu.workloads.checkpoint import CheckpointManager

        cfg = fp32_cfg()
        params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
        CheckpointManager(str(tmp_path)).save(1, params, block=True)
        with pytest.raises(ValueError, match="--draft-head"):
            serve_lib.load_draft_params(str(tmp_path), cfg)


class TestEntrypointDefaults:
    def test_default_batch_scales_with_grad_accum(self, monkeypatch, capsys):
        """The shipped examples pass --grad-accum with no --batch: the default
        batch must keep each MICROBATCH at 2 rows per data shard, or main()
        dies in check_microbatch at the first step (regression)."""
        import sys

        monkeypatch.setattr(sys, "argv", [
            "train", "--config", "test", "--steps", "1", "--seq", "32",
            "--grad-accum", "4", "--prefetch", "1",
        ])
        train_lib.main()
        out = capsys.readouterr().out
        n = len(jax.devices())
        assert f"batch={2 * n * 4} " in out  # scaled by accum
        assert "compile+first-step" in out
