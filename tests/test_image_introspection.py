"""Plan-time registry image introspection (reference services/docker.py:34-70).

A fake OCI registry (aiohttp) drives the full protocol: bearer-token dance,
manifest list -> platform manifest -> config blob. A bad image or credential
must fail at PLAN time with a clear error; an unreachable registry must degrade
to "unverified" (the server may be air-gapped while TPU hosts are not)."""

import hashlib
import json

import pytest
from aiohttp import web
from aiohttp.test_utils import TestServer

from dstack_tpu.core.errors import ServerClientError
from dstack_tpu.core.services import docker_registry
from dstack_tpu.core.services.docker_registry import parse_image_ref
from tests.common import api_server


class FakeRegistry:
    """Minimal Docker Registry v2: one repo, optional token auth."""

    def __init__(self, require_auth=False, username="bot", password="hunter2"):
        self.require_auth = require_auth
        self.username, self.password = username, password
        self.empty_token = False  # 200 from /token with no token field
        config = {
            "os": "linux",
            "architecture": "amd64",
            "config": {"User": "appuser", "Entrypoint": ["/entry.sh"], "Cmd": ["serve"]},
        }
        self.config_blob = json.dumps(config).encode()
        self.config_digest = "sha256:" + hashlib.sha256(self.config_blob).hexdigest()
        manifest = {"config": {"digest": self.config_digest}}
        self.manifest_blob = json.dumps(manifest).encode()
        self.manifest_digest = "sha256:" + hashlib.sha256(self.manifest_blob).hexdigest()
        self.index = json.dumps({
            "manifests": [
                {"digest": "sha256:armarm", "platform": {"os": "linux", "architecture": "arm64"}},
                {"digest": self.manifest_digest, "platform": {"os": "linux", "architecture": "amd64"}},
            ]
        }).encode()
        self.token_requests = []

    def app(self):
        app = web.Application()
        self.base_url = ""  # set after the server binds; realm is read per-request

        def authed(request):
            if not self.require_auth:
                return True
            return request.headers.get("Authorization") == "Bearer tok-ok"

        async def token(request):
            self.token_requests.append(request.headers.get("Authorization"))
            import base64

            expect = "Basic " + base64.b64encode(
                f"{self.username}:{self.password}".encode()
            ).decode()
            if request.headers.get("Authorization") != expect:
                return web.json_response({}, status=401)
            if self.empty_token:
                return web.json_response({})
            return web.json_response({"token": "tok-ok"})

        async def manifests(request):
            if not authed(request):
                return web.json_response(
                    {}, status=401,
                    headers={"WWW-Authenticate": f'Bearer realm="{self.base_url}/token",service="fake"'},
                )
            ref = request.match_info["ref"]
            if request.match_info["repo"] != "team/app":
                return web.json_response({}, status=404)
            if ref == "good":
                return web.Response(body=self.index, content_type="application/vnd.oci.image.index.v1+json")
            if ref == self.manifest_digest:
                return web.Response(body=self.manifest_blob, content_type="application/vnd.oci.image.manifest.v1+json")
            return web.json_response({}, status=404)

        async def blobs(request):
            if not authed(request):
                return web.json_response({}, status=401)
            if request.match_info["digest"] == self.config_digest:
                return web.Response(body=self.config_blob)
            return web.json_response({}, status=404)

        app.router.add_get("/token", token)
        app.router.add_get("/v2/{repo:.+}/manifests/{ref}", manifests)
        app.router.add_get("/v2/{repo:.+}/blobs/{digest}", blobs)
        return app


async def start_fake_registry(require_auth=False):
    """(registry, server, host) with the token realm pointing at the live port."""
    reg = FakeRegistry(require_auth=require_auth)
    server = TestServer(reg.app())
    await server.start_server()
    reg.base_url = f"http://127.0.0.1:{server.port}"
    return reg, server, f"127.0.0.1:{server.port}"


class TestParseImageRef:
    def test_docker_hub_defaults(self):
        assert parse_image_ref("ubuntu") == ("registry-1.docker.io", "library/ubuntu", "latest")
        assert parse_image_ref("nvidia/cuda:12.1") == ("registry-1.docker.io", "nvidia/cuda", "12.1")

    def test_explicit_registry_port_digest(self):
        assert parse_image_ref("ghcr.io/org/app:v1") == ("ghcr.io", "org/app", "v1")
        assert parse_image_ref("localhost:5000/x/y@sha256:abc") == ("localhost:5000", "x/y", "sha256:abc")

    def test_invalid(self):
        with pytest.raises(ServerClientError):
            parse_image_ref("bad image!!")


class TestIntrospection:
    async def _with_registry(self, require_auth=False):
        return await start_fake_registry(require_auth)

    async def test_resolves_config_via_manifest_list(self):
        docker_registry.clear_cache()
        reg, server, host = await self._with_registry()
        try:
            cfg = await docker_registry.get_image_config(f"{host}/team/app:good")
            assert cfg.verified
            assert cfg.user == "appuser"
            assert cfg.entrypoint == ["/entry.sh"]
            assert cfg.architecture == "amd64"  # picked the amd64 entry, not arm
        finally:
            await server.close()

    async def test_missing_image_is_definitive_error(self):
        docker_registry.clear_cache()
        reg, server, host = await self._with_registry()
        try:
            with pytest.raises(ServerClientError, match="not found"):
                await docker_registry.get_image_config(f"{host}/team/app:nope")
            with pytest.raises(ServerClientError, match="not found"):
                await docker_registry.get_image_config(f"{host}/other/repo:good")
        finally:
            await server.close()

    async def test_token_dance_with_credentials(self):
        docker_registry.clear_cache()
        reg, server, host = await self._with_registry(require_auth=True)
        try:
            cfg = await docker_registry.get_image_config(
                f"{host}/team/app:good", username="bot", password="hunter2"
            )
            assert cfg.user == "appuser"
            assert reg.token_requests  # the bearer dance actually ran
            with pytest.raises(ServerClientError, match="auth"):
                await docker_registry.get_image_config(
                    f"{host}/team/app:good", username="bot", password="wrong"
                )
        finally:
            await server.close()

    async def test_unreachable_registry_degrades_to_unverified(self):
        docker_registry.clear_cache()
        cfg = await docker_registry.get_image_config("127.0.0.1:1/team/app:good")
        assert cfg.verified is False
        assert "unreachable" in (cfg.note or "")

    async def test_network_failure_mid_introspection_degrades(self, monkeypatch):
        """The config blob often lives on a different (CDN) host than the
        registry: a network failure on ANY hop must degrade to unverified,
        not error the plan (ADVICE r4)."""
        docker_registry.clear_cache()
        reg, server, host = await self._with_registry()
        real_request = docker_registry._request

        def flaky_request(url, headers, timeout=10.0):
            if "/blobs/" in url:
                raise OSError("blob CDN unreachable")
            return real_request(url, headers, timeout)

        monkeypatch.setattr(docker_registry, "_request", flaky_request)
        try:
            cfg = await docker_registry.get_image_config(f"{host}/team/app:good")
            assert cfg.verified is False
            assert "unreachable" in (cfg.note or "")
        finally:
            await server.close()

    async def test_tokenless_token_endpoint_is_clear_error(self):
        """A 200 from the token endpoint with no token is a malformed-endpoint
        error, not a 'Bearer None' credential failure (ADVICE r4)."""
        docker_registry.clear_cache()
        reg, server, host = await start_fake_registry(require_auth=True)
        reg.empty_token = True
        try:
            with pytest.raises(ServerClientError, match="no token"):
                await docker_registry.get_image_config(
                    f"{host}/team/app:good", username="bot", password="hunter2"
                )
        finally:
            await server.close()

    async def test_fixed_password_bypasses_cached_auth_failure(self):
        """The introspection cache keys on the credential, so correcting a
        password takes effect immediately instead of replaying the cached
        auth error for the TTL (ADVICE r4)."""
        docker_registry.clear_cache()
        reg, server, host = await start_fake_registry(require_auth=True)
        try:
            with pytest.raises(ServerClientError, match="auth"):
                await docker_registry.get_image_config_cached(
                    f"{host}/team/app:good", username="bot", password="wrong"
                )
            cfg = await docker_registry.get_image_config_cached(
                f"{host}/team/app:good", username="bot", password="hunter2"
            )
            assert cfg.user == "appuser"
        finally:
            await server.close()
            docker_registry.clear_cache()


class TestPlanIntegration:
    async def test_plan_surfaces_image_config(self):
        docker_registry.clear_cache()
        reg, server, host = await start_fake_registry()
        try:
            async with api_server() as api:
                plan = await api.post(
                    "/api/project/main/runs/get_plan",
                    {"run_spec": {"configuration": {
                        "type": "task", "commands": ["true"], "image": f"{host}/team/app:good",
                    }}},
                )
                assert plan["image_config"]["user"] == "appuser"
                assert plan["image_config"]["entrypoint"] == ["/entry.sh"]
        finally:
            await server.close()

    async def test_plan_rejects_missing_image_with_clear_error(self):
        docker_registry.clear_cache()
        reg, server, host = await start_fake_registry()
        try:
            async with api_server() as api:
                raw = await api.client.post(
                    "/api/project/main/runs/get_plan",
                    json={"run_spec": {"configuration": {
                        "type": "task", "commands": ["true"], "image": f"{host}/team/app:missing",
                    }}},
                    headers={"Authorization": f"Bearer {api.token}"},
                )
                assert raw.status == 400
                body = await raw.json()
                assert "not found" in json.dumps(body)
        finally:
            await server.close()
