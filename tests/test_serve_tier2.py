"""Tier-2 serving engine: chunked prefill, cross-request prefix caching, and
speculative decode (PR 9).

Every feature here is a THROUGHPUT/LATENCY optimization, never a semantic
one — the invariant all three share is that the emitted token stream must be
bit-identical to the tier-1 engine's. test_serve_engine.py pins the tier-1
engine against a full-context reference decode, so most tests here compare
against a plain tier-1 engine (jitted + batched = fast) and one anchor test
compares chunked prefill directly against ``greedy_reference_decode``.
Equivalence runs in fp32 on CPU so argmax ties can't blur the comparison.

The page-accounting tests additionally pin the allocator invariant: free
list, cached blocks, and private slot pages PARTITION the pool at every
step — eviction can never free a live page.

Engine geometries are deliberately reused across tests (and shared with
test_serve_engine.py): every distinct (page_size, num_pages, max_batch,
max_seq) is a fresh set of XLA compilations.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dstack_tpu.workloads import model as model_lib
from dstack_tpu.workloads import serve as serve_lib
from dstack_tpu.workloads.attention import paged_chunk_attention
from dstack_tpu.workloads.config import get_config
from dstack_tpu.workloads.kernels.paged import paged_chunk_attention_pallas

TINY = get_config(
    "test", n_layers=2, d_model=64, n_heads=4, n_kv_heads=2, d_ff=128,
    vocab_size=251, max_seq_len=128, dtype="float32", param_dtype="float32",
    remat=False,
)

PROMPTS = [[1, 2, 3, 4, 5], [7, 8, 9], [10, 11, 12, 13, 14, 15, 16]]

# 18 tokens = 2 full pages of 8 + a 2-token tail: long enough that prefix
# matching covers whole blocks, short enough to stay fast.
SHARED_PREFIX = [5, 9, 13, 2, 44, 17, 81, 3, 7, 7, 101, 55, 13, 24, 9, 16,
                 31, 8]

# The preemption geometry test_serve_engine.py uses: pool sized so decode
# growth forces preemption of the youngest request.
PREEMPT_POOL = dict(page_size=4, num_pages=7, max_batch=3, max_seq=96)
PREEMPT_PROMPTS = [[i + 1, i + 2, i + 3, i + 4, i + 5] for i in (0, 10, 20)]

# One tight-pool geometry shared by every eviction/rollback test.
EVICT_POOL = dict(page_size=4, num_pages=12, max_batch=2, max_seq=64)


@pytest.fixture(scope="module")
def params():
    return model_lib.init_params(TINY, jax.random.PRNGKey(0))


def make_engine(params, **overrides) -> serve_lib.ServeEngine:
    kwargs = dict(page_size=8, num_pages=32, max_batch=4, max_seq=128)
    kwargs.update(overrides)
    return serve_lib.ServeEngine(
        TINY, serve_lib.EngineConfig(**kwargs), params=params
    )


def drain(engine, limit=3000, per_step=None):
    steps = 0
    while engine.has_work():
        engine.step()
        if per_step is not None:
            per_step(engine)
        steps += 1
        assert steps < limit, "engine never drained"
    return steps


_REF_MEMO = {}


def tier1_decode(params, prompts, max_new) -> list:
    """Expected token streams from a plain tier-1 engine at the default
    roomy geometry (no preemption, no tier-2 features) — itself proven
    token-identical to the full-context reference by test_serve_engine.py.
    Memoized: preemption/eviction tests reuse the same prompt sets."""
    key = (id(params), tuple(tuple(p) for p in prompts), max_new)
    if key not in _REF_MEMO:
        engine = make_engine(params)
        out = []
        for batch_start in range(0, len(prompts), engine.ecfg.max_batch):
            batch = prompts[batch_start:batch_start + engine.ecfg.max_batch]
            reqs = [engine.submit(p, max_new_tokens=max_new) for p in batch]
            drain(engine)
            out.extend(r.tokens for r in reqs)
        _REF_MEMO[key] = out
    return _REF_MEMO[key]


def check_page_partition(engine) -> None:
    """free list + cached blocks + private slot pages partition the pool:
    no page is ever in two of them, and none is lost."""
    free = set(engine._free)
    assert len(free) == len(engine._free), "free list duplicate"
    cached = (
        {blk.page for blk in engine._cache.blocks.values()}
        if engine._cache is not None else set()
    )
    in_slots = set()
    for pages in engine.slot_pages:
        in_slots.update(pages)
    private = in_slots - cached
    assert not free & cached, "cached page on the free list"
    assert not free & private, "page both free and owned by a slot"
    assert len(free) + len(cached) + len(private) == engine.ecfg.num_pages


class TestChunkedPrefill:
    def test_token_identical_to_full_reference(self, params):
        """The anchor: chunked prefill against the O(T^2) full-context
        reference directly (not via the tier-1 engine). Chunk 4 over 3/5/7
        token prompts exercises unaligned chunk boundaries."""
        engine = make_engine(params, prefill_chunk=4)
        reqs = [engine.submit(p, max_new_tokens=6) for p in PROMPTS]
        drain(engine)
        for prompt, req in zip(PROMPTS, reqs):
            assert req.tokens == serve_lib.greedy_reference_decode(
                params, TINY, prompt, 6
            ), f"chunked prefill diverged for {prompt}"

    def test_token_identical_under_preemption(self, params):
        ref = tier1_decode(params, PREEMPT_PROMPTS, 20)
        engine = make_engine(params, prefill_chunk=4, **PREEMPT_POOL)
        reqs = [engine.submit(p, max_new_tokens=20) for p in PREEMPT_PROMPTS]
        drain(engine)
        assert max(r.preemptions for r in reqs) >= 1, (
            "pool was sized to force preemption"
        )
        assert [r.tokens for r in reqs] == ref

    def test_long_prompt_does_not_stall_running_decode(self, params):
        """THE chunking guarantee: while a long prompt prefills chunk by
        chunk, an already-decoding request keeps emitting one token EVERY
        step — with whole-prompt prefill those steps would all be one
        monolithic stall."""
        engine = make_engine(params, prefill_chunk=4)
        a = engine.submit(PROMPTS[0], max_new_tokens=16)
        for _ in range(3):
            engine.step()
        long_prompt = list(range(1, 33))  # 8 chunks of 4
        b = engine.submit(long_prompt, max_new_tokens=4)
        chunk_steps = 0
        while not b.tokens and not b.done:
            before = len(a.tokens)
            engine.step()
            chunk_steps += 1
            assert len(a.tokens) == before + 1, (
                "decode stalled during a prefill chunk"
            )
            assert chunk_steps < 32
        assert chunk_steps >= 32 // 4, "prompt was not actually chunked"
        drain(engine)
        assert [a.tokens] == tier1_decode(params, [PROMPTS[0]], 16)
        assert [b.tokens] == tier1_decode(params, [long_prompt], 4)


class TestPrefixCache:
    def test_hit_path_equals_cold_path(self, params):
        """The second identical-prefix request reuses cached pages and still
        emits exactly the cold path's tokens."""
        engine = make_engine(params, prefix_cache=True)
        prompts = [SHARED_PREFIX + [50 + i, 60 + i] for i in range(3)]
        outs = []
        for p in prompts:
            r = engine.submit(p, max_new_tokens=6)
            drain(engine)
            outs.append(r.tokens)
        assert engine.total_prefix_hit_tokens > 0, engine.stats()
        assert engine.stats()["prefix_hit_rate"] > 0.3
        assert outs == tier1_decode(params, prompts, 6), (
            "cache-hit path diverged from cold path"
        )

    def test_concurrent_requests_share_pages_with_refcounts(self, params):
        engine = make_engine(params, prefix_cache=True)
        warm = engine.submit(SHARED_PREFIX + [99], max_new_tokens=2)
        drain(engine)
        assert warm.done
        n_shared = len(SHARED_PREFIX) // engine.ecfg.page_size  # 2 blocks
        a = engine.submit(SHARED_PREFIX + [70, 71], max_new_tokens=8)
        b = engine.submit(SHARED_PREFIX + [80, 81], max_new_tokens=8)
        engine.step()
        # Both slots' tables open with the SAME cached pages...
        slot_a = engine.slots.index(a)
        slot_b = engine.slots.index(b)
        pages_a = engine.page_tables[slot_a][:n_shared].tolist()
        pages_b = engine.page_tables[slot_b][:n_shared].tolist()
        assert pages_a == pages_b
        # ...each holding one reference per user.
        for page in pages_a:
            assert engine._cache._page_block[page].refs == 2
        check_page_partition(engine)
        drain(engine)
        for page in pages_a:
            assert engine._cache._page_block[page].refs == 0  # released
        assert [a.tokens, b.tokens] == tier1_decode(
            params, [a.prompt, b.prompt], 8
        )

    def test_fully_cached_prompt_still_prefills_last_block(self, params):
        """A prompt that is exactly its cached blocks must keep >= 1 token
        to prefill — the first output token comes from the last position's
        logits, which a pure cache hit would never compute."""
        engine = make_engine(params, prefix_cache=True)
        prompt = SHARED_PREFIX[:16]  # exactly 2 full pages
        first = engine.submit(prompt, max_new_tokens=4)
        drain(engine)
        again = engine.submit(prompt, max_new_tokens=4)
        drain(engine)
        assert again.cached_tokens == 8  # one block matched, one recomputed
        assert [first.tokens] == tier1_decode(params, [prompt], 4)
        assert again.tokens == first.tokens

    def test_eviction_never_frees_a_live_page(self, params):
        """Churn through more distinct prefixes than the pool holds: blocks
        must evict (the counter moves), the partition invariant must hold at
        every step, and every output must still match the tier-1 engine."""
        import random

        rng = random.Random(3)
        engine = make_engine(params, prefix_cache=True, **EVICT_POOL)
        prompts = [
            [rng.randrange(1, 250) for _ in range(rng.randint(6, 14))]
            for _ in range(8)
        ]
        reqs = [engine.submit(p, max_new_tokens=8) for p in prompts]
        drain(engine, per_step=check_page_partition)
        assert engine._cache.evictions > 0, "pool was sized to force eviction"
        assert [r.tokens for r in reqs] == tier1_decode(params, prompts, 8), (
            "eviction corrupted a stream"
        )

    def test_admission_rollback_when_pages_short(self, params):
        """A cache-hit request that still can't fit its suffix stays queued
        — and the match's references are rolled back, so the blocks remain
        evictable rather than pinned by a request that never ran."""
        engine = make_engine(params, prefix_cache=True, **EVICT_POOL)
        prefix = SHARED_PREFIX[:8]  # 2 blocks of 4
        warm = engine.submit(prefix + [60], max_new_tokens=2)
        drain(engine)
        assert warm.done
        prefix_pages = [
            blk.page for blk in engine._cache.blocks.values()
        ]
        assert len(prefix_pages) == 2
        # Hog the rest of the pool so the next request's suffix can't fit.
        hog = engine.submit([100 + (i % 90) for i in range(37)],
                            max_new_tokens=8)
        engine.step()
        queued = engine.submit(prefix + [71, 72, 73, 74, 75], max_new_tokens=4)
        engine.step()
        assert engine.queue_depth == 1 and not queued.tokens
        # The failed admission rolled its matched references back (the hog's
        # own registered blocks legitimately keep refs while it decodes).
        for page in prefix_pages:
            blk = engine._cache._page_block.get(page)
            assert blk is None or blk.refs == 0, (
                "failed admission left refs behind"
            )
        drain(engine, per_step=check_page_partition)
        assert hog.done and queued.done
        assert [queued.tokens] == tier1_decode(params, [queued.prompt], 4)

    def test_failed_allocation_does_not_evict_cache(self, params):
        """An allocation the pool can't satisfy even by evicting everything
        must evict NOTHING: the requester stays blocked either way, and
        destroying cached prefixes for it would cost every later sharer a
        re-prefill for zero gain."""
        engine = make_engine(params, prefix_cache=True, **EVICT_POOL)
        prefix = SHARED_PREFIX[:8]  # 2 blocks of 4
        warm = engine.submit(prefix + [60], max_new_tokens=2)
        drain(engine)
        assert warm.done and len(engine._cache) == 2
        warm_keys = set(engine._cache.blocks)
        # Hog 9 of the 10 remaining pages (33 + 1 headroom) for several
        # steps (prefill + decode emit 2 tokens the first step, then one
        # per step; 33 + 6 = 39 tokens never outgrows 10 pages), so the
        # pool is free<=1 / evictable=2 while the hog runs (the hog's own
        # prompt blocks get registered too, but at refs=1 — not evictable).
        hog = engine.submit([100 + (i % 90) for i in range(33)],
                            max_new_tokens=6)
        engine.step()
        # 8 pages needed, at most 3 obtainable: must fail WITHOUT touching
        # the cache.
        big = engine.submit([200 + (i % 50) for i in range(30)],
                            max_new_tokens=2)
        engine.step()
        assert engine.queue_depth == 1 and not big.tokens
        assert engine._cache.evictions == 0, (
            "failed allocation destroyed cached prefixes"
        )
        assert warm_keys <= set(engine._cache.blocks)
        drain(engine, per_step=check_page_partition)
        assert hog.done and big.done

    def test_resume_after_preemption_not_counted_as_hit(self, params):
        """Preemption resumes re-match their OWN sealed blocks — correct for
        page reuse, but not cross-request sharing: the exported hit ratio
        must stay 0 on a no-sharing workload however much preemption churn
        the pool forces."""
        ref = tier1_decode(params, PREEMPT_PROMPTS, 20)
        engine = make_engine(params, prefix_cache=True, **PREEMPT_POOL)
        reqs = [engine.submit(p, max_new_tokens=20) for p in PREEMPT_PROMPTS]
        drain(engine, per_step=check_page_partition)
        assert max(r.preemptions for r in reqs) >= 1
        assert [r.tokens for r in reqs] == ref
        assert engine.total_prefix_hit_tokens == 0, (
            "self-matches on resume inflated the hit counter"
        )
        # Lookups: each prompt counted once, resumes excluded.
        assert engine.total_prefix_lookup_tokens == sum(
            len(p) for p in PREEMPT_PROMPTS
        )


class TestSpeculativeDecode:
    def test_token_identical_to_plain_engine(self, params):
        # Repetitive prompts feed the n-gram proposer, so acceptance > 0 and
        # the equivalence is exercised on real accepted drafts.
        base = [3, 17, 9, 3, 17, 9, 3, 17]
        prompts = [base + [40 + i] for i in range(3)]
        plain = make_engine(params)
        p_reqs = [plain.submit(p, max_new_tokens=16) for p in prompts]
        drain(plain)
        spec = make_engine(params, spec_tokens=3)
        s_reqs = [spec.submit(p, max_new_tokens=16) for p in prompts]
        drain(spec)
        for pr, sr in zip(p_reqs, s_reqs):
            assert sr.tokens == pr.tokens, "speculation changed the output"
        assert spec.total_spec_proposed > 0
        assert spec.total_steps <= plain.total_steps

    def test_token_identical_under_preemption(self, params):
        ref = tier1_decode(params, PREEMPT_PROMPTS, 20)
        engine = make_engine(params, spec_tokens=3, **PREEMPT_POOL)
        reqs = [engine.submit(p, max_new_tokens=20) for p in PREEMPT_PROMPTS]
        drain(engine)
        assert max(r.preemptions for r in reqs) >= 1
        assert [r.tokens for r in reqs] == ref

    def test_max_new_exact_and_eos_stop(self, params):
        """A spec burst can propose past the request's budget or its EOS:
        emission must clip to exactly max_new, and stop AT the eos token."""
        [ref] = tier1_decode(params, [PROMPTS[0]], 6)
        engine = make_engine(params, spec_tokens=3)
        exact = engine.submit(PROMPTS[0], max_new_tokens=6)
        drain(engine)
        assert exact.tokens == ref and len(exact.tokens) == 6

        eos = ref[2]
        stopped = engine.submit(PROMPTS[0], max_new_tokens=6, eos_id=eos)
        drain(engine)
        assert stopped.tokens == ref[:3]  # eos included, nothing after
        assert stopped.done

    def test_ngram_proposer(self):
        # The trailing bigram (5, 6) occurred earlier; drafts replay what
        # followed it.
        ctx = [1, 5, 6, 9, 4, 2, 5, 6]
        assert serve_lib.propose_ngram_drafts(ctx, 3) == [9, 4, 2]
        # Shorter continuation than k: pad with the last token.
        assert serve_lib.propose_ngram_drafts([1, 5, 6, 9, 5, 6], 3) == [9, 5, 6]
        # No recurrence at all: fall back to repeating the last token.
        assert serve_lib.propose_ngram_drafts([1, 2, 3], 2) == [3, 3]
        assert serve_lib.propose_ngram_drafts([], 2) == []
        assert serve_lib.propose_ngram_drafts([1, 2], 0) == []

    def test_index_proposer_matches_scan(self):
        """The engine's O(1) continuation-index proposer is a drop-in for
        the reference backward scan: identical drafts on random (and highly
        repetitive, so n-grams actually recur) sequences, both when the
        index is built whole and when it is grown token by token the way
        ``_emit`` maintains it."""
        import random

        rng = random.Random(11)
        for trial in range(200):
            n = rng.randint(1, 40)
            ctx = [rng.randrange(1, 5) for _ in range(n)]
            k = rng.randint(1, 5)
            index = serve_lib._ngram_index(ctx)
            assert serve_lib.propose_from_index(ctx, index, k) == (
                serve_lib.propose_ngram_drafts(ctx, k)
            ), (ctx, k)
            # Incremental maintenance reaches the same index state.
            grown: dict = {}
            for i in range(1, len(ctx)):
                serve_lib._ngram_record(ctx, i, grown)
            assert grown == index, ctx


@pytest.fixture(scope="module")
def draft(params):
    # A RANDOM head: its proposals are near-worthless, which is exactly the
    # point — token identity must hold for any head, because drafts are only
    # a throughput bet the verify forward scores. Accept-rate quality is
    # bench_serve's concern (distilled heads), not correctness's.
    return model_lib.init_draft_params(TINY, jax.random.PRNGKey(7))


def make_draft_engine(params, draft, **overrides) -> serve_lib.ServeEngine:
    kwargs = dict(page_size=8, num_pages=32, max_batch=4, max_seq=128,
                  spec_tokens=3, spec_fallback_threshold=0.0)
    kwargs.update(overrides)
    return serve_lib.ServeEngine(
        TINY, serve_lib.EngineConfig(**kwargs), params=params,
        draft_params=draft,
    )


class TestDraftHead:
    def test_token_identical_to_plain_engine(self, params, draft):
        ref = tier1_decode(params, PROMPTS, 16)
        engine = make_draft_engine(params, draft)
        reqs = [engine.submit(p, max_new_tokens=16) for p in PROMPTS]
        drain(engine)
        assert [r.tokens for r in reqs] == ref
        assert engine.total_spec_proposed > 0
        assert engine.stats()["spec_proposer"] == "draft"

    def test_token_identical_under_preemption(self, params, draft):
        """Preemption + re-prefill with a draft head: the refolded prompt's
        prefill must rebuild last_hidden so post-resume proposals condition
        on the right state — and the stream stays exactly greedy."""
        ref = tier1_decode(params, PREEMPT_PROMPTS, 20)
        engine = make_draft_engine(params, draft, **PREEMPT_POOL)
        reqs = [engine.submit(p, max_new_tokens=20) for p in PREEMPT_PROMPTS]
        drain(engine)
        assert max(r.preemptions for r in reqs) >= 1
        assert [r.tokens for r in reqs] == ref

    def test_chunked_prefill_and_prefix_cache_compose(self, params, draft):
        """Tier-2 prefill paths must hand back the same conditioning hidden
        the whole-prompt path does (last chunk's final valid position)."""
        engine = make_draft_engine(params, draft, prefix_cache=True,
                                   prefill_chunk=4)
        warm = engine.submit(SHARED_PREFIX + [50], max_new_tokens=2)
        drain(engine)
        assert warm.done
        prompts = [SHARED_PREFIX + [60], SHARED_PREFIX + [61]]
        reqs = [engine.submit(p, max_new_tokens=6) for p in prompts]
        drain(engine)
        assert engine.total_prefix_hit_tokens > 0
        assert [r.tokens for r in reqs] == tier1_decode(params, prompts, 6)

    def test_int8_matches_plain_int8(self, params, draft):
        """Weight-only quant changes numerics (no fp reference), but the
        draft head must be a pure scheduling change WITHIN the int8 world —
        the proposer conditions on the quantized target's own hidden and
        verifies through the quantized target's own logits."""
        plain = make_engine(params, quant="int8")
        p_reqs = [plain.submit(p, max_new_tokens=6) for p in PROMPTS]
        drain(plain)
        spec = make_draft_engine(params, draft, quant="int8")
        s_reqs = [spec.submit(p, max_new_tokens=6) for p in PROMPTS]
        drain(spec)
        assert [r.tokens for r in s_reqs] == [r.tokens for r in p_reqs]

    def test_fallback_trigger(self, params, draft):
        """A random head accepts ~nothing, so a full window at a demanding
        threshold must flip the slot to the n-gram proposer — permanently,
        with the stream still exactly greedy."""
        ref = tier1_decode(params, [PROMPTS[0]], 20)
        engine = make_draft_engine(params, draft, spec_fallback_window=4,
                                   spec_fallback_threshold=0.9)
        req = engine.submit(PROMPTS[0], max_new_tokens=20)
        drain(engine)
        assert req.tokens == ref[0]
        assert not req.draft_ok
        assert engine.total_spec_fallbacks == 1
        assert engine.stats()["spec_fallbacks"] == 1

    def test_fallback_needs_full_window(self, params, draft):
        # 6 spec steps max (one emitted token each at ~0 accept) can never
        # fill a 50-step window — the head keeps proposing to the end.
        engine = make_draft_engine(params, draft, spec_fallback_window=50,
                                   spec_fallback_threshold=0.9)
        req = engine.submit(PROMPTS[0], max_new_tokens=6)
        drain(engine)
        assert req.draft_ok
        assert engine.total_spec_fallbacks == 0

    def test_draft_requires_spec_tokens(self, params, draft):
        with pytest.raises(ValueError, match="spec_tokens"):
            make_draft_engine(params, draft, spec_tokens=0)

    def test_propose_shape_dtype_contract(self, params, draft):
        """The jitted proposer's contract the engine builds rows from:
        [S, k] int32 for any slot count, matching the pure-model reference."""
        fn = serve_lib.make_draft_fn(TINY, 4)
        hidden = jnp.zeros((3, TINY.d_model), jnp.float32)
        last = jnp.array([5, 9, 200], jnp.int32)
        out = fn(params, draft, hidden, last)
        assert out.shape == (3, 4) and out.dtype == jnp.int32
        ref = model_lib.draft_propose(params, draft, hidden, last, 4, TINY)
        assert np.array_equal(np.asarray(out), np.asarray(ref))

    def test_windowed_accept_rate(self, params, draft):
        engine = make_draft_engine(params, draft, spec_window=4)
        assert engine.spec_accept_rate_windowed == 0.0  # renders pre-traffic
        for sample in [(3, 3), (3, 3), (3, 0), (3, 0)]:
            engine._spec_recent.append(sample)
        assert engine.spec_accept_rate_windowed == pytest.approx(0.5)
        # The window slides: two perfect steps push out two perfect steps.
        engine._spec_recent.append((3, 3))
        engine._spec_recent.append((3, 3))
        assert engine.spec_accept_rate_windowed == pytest.approx(0.5)
        assert engine.stats()["spec_accept_rate_windowed"] == 0.5


class TestCombined:
    def test_all_three_with_pallas_decode(self, params):
        """Chunked prefill + prefix cache + speculation, decode_impl=pallas:
        the in-repo chunk kernel (interpret mode on CPU) runs both the
        prefill chunks and the verify step, token-identically."""
        engine = make_engine(params, prefix_cache=True, prefill_chunk=4,
                             spec_tokens=3, decode_impl="pallas")
        warm = engine.submit(SHARED_PREFIX + [50], max_new_tokens=2)
        drain(engine)
        assert warm.done
        prompts = [SHARED_PREFIX + [60], SHARED_PREFIX + [61]]
        reqs = [engine.submit(p, max_new_tokens=4) for p in prompts]
        drain(engine, per_step=check_page_partition)
        assert engine.total_prefix_hit_tokens > 0
        assert [r.tokens for r in reqs] == tier1_decode(params, prompts, 4)

    def test_tier2_with_int8_matches_plain_int8(self, params):
        """quant changes numerics (so no fp reference) — but tier-2 must
        still be a pure scheduling change WITHIN the int8 world."""
        plain = make_engine(params, quant="int8")
        p_reqs = [plain.submit(p, max_new_tokens=6) for p in PROMPTS]
        drain(plain)
        tier2 = make_engine(params, quant="int8", prefix_cache=True,
                            prefill_chunk=4, spec_tokens=3)
        t_reqs = [tier2.submit(p, max_new_tokens=6) for p in PROMPTS]
        drain(tier2)
        for pr, tr in zip(p_reqs, t_reqs):
            assert tr.tokens == pr.tokens


class TestChunkKernelParity:
    def test_pallas_matches_xla_on_valid_queries(self):
        ks = jax.random.split(jax.random.PRNGKey(2), 5)
        q = jax.random.normal(ks[0], (4, 4, 4, 16))
        kp = jax.random.normal(ks[1], (12, 8, 2, 16))
        vp = jax.random.normal(ks[2], (12, 8, 2, 16))
        pt = jax.random.randint(ks[3], (4, 6), 0, 12)
        starts = jnp.array([0, 5, 17, 40], jnp.int32)
        valid = jnp.array([4, 4, 2, 4], jnp.int32)
        got = paged_chunk_attention_pallas(q, kp, vp, pt, starts,
                                           starts + valid)
        ref = paged_chunk_attention(q, kp, vp, pt, starts)
        for s in range(4):
            np.testing.assert_allclose(
                np.asarray(got[s, :int(valid[s])]),
                np.asarray(ref[s, :int(valid[s])]),
                atol=1e-4,
            )
        assert bool(jnp.isfinite(got).all())
        # kv_len == 0 slots (inactive) emit finite zeros, never NaN.
        out0 = paged_chunk_attention_pallas(
            q, kp, vp, pt, jnp.zeros(4, jnp.int32), jnp.zeros(4, jnp.int32)
        )
        assert bool(jnp.isfinite(out0).all())

    def test_decode_is_the_c1_special_case(self):
        """chunk attention with C=1 and starts = kv_lens - 1 must equal the
        single-query decode path — the relationship the engine relies on."""
        from dstack_tpu.workloads.attention import paged_decode_attention

        ks = jax.random.split(jax.random.PRNGKey(4), 4)
        q = jax.random.normal(ks[0], (3, 4, 16))
        kp = jax.random.normal(ks[1], (8, 8, 2, 16))
        vp = jax.random.normal(ks[2], (8, 8, 2, 16))
        pt = jax.random.randint(ks[3], (3, 4), 0, 8)
        kv_lens = jnp.array([1, 9, 30], jnp.int32)
        dec = paged_decode_attention(q, kp, vp, pt, kv_lens)
        chunk = paged_chunk_attention(q[:, None], kp, vp, pt, kv_lens - 1)
        np.testing.assert_allclose(
            np.asarray(dec), np.asarray(chunk[:, 0]), atol=1e-5
        )


class TestConfigValidation:
    def test_negative_knobs_rejected(self, params):
        with pytest.raises(ValueError, match="prefill_chunk"):
            make_engine(params, prefill_chunk=-1)
        with pytest.raises(ValueError, match="spec_tokens"):
            make_engine(params, spec_tokens=-2)
        with pytest.raises(ValueError, match="prefix_cache"):
            make_engine(params, prefix_cache=True, num_pages=1, max_seq=8)

    def test_stats_surface(self, params):
        engine = make_engine(params, prefix_cache=True, prefill_chunk=8,
                             spec_tokens=2)
        stats = engine.stats()
        for key in ("prefill_chunk", "prefix_cache", "spec_tokens",
                    "prefix_hit_rate", "spec_accept_rate", "cached_pages",
                    "prefix_evictions"):
            assert key in stats, key
        assert stats["prefill_chunk"] == 8
        assert stats["spec_tokens"] == 2
        assert stats["prefix_cache"] == 1


class TestEngineGaugesThroughProxy:
    async def test_headers_emitted_and_recorded(self, params):
        """The engine app reports tier-2 gauges on every response; the proxy
        records them for /metrics exactly like the queue depth."""
        from aiohttp.test_utils import TestClient, TestServer

        from dstack_tpu.server.services import proxy as proxy_service

        runner = serve_lib.EngineRunner(
            make_engine(params, prefix_cache=True, spec_tokens=3)
        )
        runner.start()
        try:
            client = TestClient(TestServer(serve_lib.create_serve_app(runner)))
            await client.start_server()
            try:
                resp = await client.post(
                    "/generate",
                    json={"prompt_tokens": SHARED_PREFIX + [61],
                          "max_tokens": 3, "stream": False},
                )
                assert resp.status == 200
                assert "X-Dstack-Prefix-Hit-Rate" in resp.headers
                assert "X-Dstack-Spec-Accept-Rate" in resp.headers
                # The proxy-side recording path (unit: feed the headers in).
                stats = proxy_service.ServiceStats()
                saved, proxy_service.stats = proxy_service.stats, stats
                try:
                    proxy_service._record_queue_depth("r1", resp.headers)
                finally:
                    proxy_service.stats = saved
                gauges = stats.engine_gauges("r1")
                assert set(gauges) == {
                    "prefix_cache_hit_ratio", "spec_accept_ratio"
                }
                assert stats.queue_depth("r1") is not None
            finally:
                await client.close()
        finally:
            runner.shutdown()

    async def test_gauges_absent_when_features_off(self, params):
        """A tier-1 engine must not advertise ratios it doesn't compute."""
        from aiohttp.test_utils import TestClient, TestServer

        runner = serve_lib.EngineRunner(make_engine(params))
        runner.start()
        try:
            client = TestClient(TestServer(serve_lib.create_serve_app(runner)))
            await client.start_server()
            try:
                resp = await client.get("/health")
                assert resp.status == 200
                assert "X-Dstack-Queue-Depth" in resp.headers
                assert "X-Dstack-Prefix-Hit-Rate" not in resp.headers
                assert "X-Dstack-Spec-Accept-Rate" not in resp.headers
            finally:
                await client.close()
        finally:
            runner.shutdown()
