"""Runs the C++ agent's native unit tests (runner/tests/test_runner.cpp) through
pytest so the whole suite stays one command. `make -C runner test` also works
standalone."""

import shutil
import subprocess
from pathlib import Path

import pytest

RUNNER_DIR = Path(__file__).resolve().parent.parent / "runner"


@pytest.mark.skipif(shutil.which("make") is None or shutil.which("g++") is None,
                    reason="native toolchain unavailable")
def test_native_runner_unit_tests():
    result = subprocess.run(
        ["make", "-C", str(RUNNER_DIR), "test"],
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert result.returncode == 0, f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert "OK:" in result.stdout
