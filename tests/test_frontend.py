"""Admin SPA (server/statics): serving + API-contract parity.

The SPA is build-less ES modules (no node toolchain in CI), so these tests pin
the contract statically: every endpoint the JS calls must be a registered
route, and the shell/assets must serve. Parity: the reference serves its React
SPA from server statics (ref: src/dstack/_internal/server/app.py:292-295)."""

import re
from pathlib import Path

from tests.common import api_server

STATICS = Path(__file__).parent.parent / "dstack_tpu" / "server" / "statics"


def spa_api_paths():
    src = (STATICS / "app.js").read_text()
    # api("/api/...") and api(`/api/...${...}`) call sites.
    paths = set()
    for m in re.finditer(r"""api\((?:"([^"]+)"|`([^`]+)`)""", src):
        path = m.group(1) or m.group(2)
        path = path.replace("${P()}", "{project_name}")
        if "${" in path:  # run-name etc. interpolations aren't route segments
            path = re.sub(r"\$\{[^}]+\}", "X", path)
        paths.add(path)
    return paths


class TestSpaContract:
    def test_spa_calls_only_registered_routes(self):
        from dstack_tpu.server.app import create_app

        app = create_app(db_path=":memory:", run_background_tasks=False)
        registered = {r.resource.canonical for r in app.router.routes() if r.resource}
        paths = spa_api_paths()
        assert len(paths) >= 20, f"path extraction broke: {sorted(paths)}"
        missing = sorted(p for p in paths if p not in registered)
        assert not missing, f"SPA calls unregistered endpoints: {missing}"

    def test_assets_exist_and_reference_each_other(self):
        html = (STATICS / "index.html").read_text()
        assert "/statics/app.js" in html and "/statics/style.css" in html
        js = (STATICS / "app.js").read_text()
        # Every resource surface has a view (VERDICT: "every REST resource a page").
        for view in ("viewRuns", "viewRunDetail", "viewFleets", "viewFleetDetail",
                     "viewInstances", "viewVolumes", "viewGateways", "viewOffers",
                     "viewSecrets", "viewProjects", "viewUsers", "viewLogin"):
            assert f"async function {view}" in js, f"missing {view}"
        # Live log tail + metrics sparklines are wired.
        assert "logs/poll" in js and "metrics/job" in js and "sparkline" in js

    async def test_shell_and_assets_served(self):
        async with api_server() as api:
            resp = await api.client.get("/")
            assert resp.status == 200
            assert "app.js" in await resp.text()
            resp = await api.client.get("/statics/app.js")
            assert resp.status == 200
            assert "javascript" in resp.content_type
            resp = await api.client.get("/statics/style.css")
            assert resp.status == 200

    def test_js_brackets_balanced(self):
        """No JS runtime ships in this image; a string/comment-aware bracket
        balance check catches the truncation/paste class of syntax errors."""
        src = (STATICS / "app.js").read_text()
        stack = []
        pairs = {")": "(", "]": "[", "}": "{"}
        i, n, mode = 0, len(src), None
        while i < n:
            c = src[i]
            if c == "\n" and mode == "//":
                mode = None
            if mode is None:
                if c in "'\"`":
                    mode = c
                elif src[i : i + 2] == "//":
                    mode, i = "//", i + 1
                elif src[i : i + 2] == "/*":
                    mode, i = "/*", i + 1
                elif c in "([{":
                    stack.append(c)
                elif c in ")]}":
                    assert stack and stack[-1] == pairs[c], f"bracket mismatch at byte {i}"
                    stack.pop()
            elif mode in "'\"`":
                if c == "\\":
                    i += 1
                elif c == mode:
                    mode = None
                elif mode == "`" and src[i : i + 2] == "${":
                    depth, i = 1, i + 2
                    while i < n and depth:
                        depth += {"{": 1, "}": -1}.get(src[i], 0)
                        i += 1
                    continue
            elif mode == "/*" and src[i : i + 2] == "*/":
                mode, i = None, i + 1
            i += 1
        assert not stack and mode is None
