"""Admin SPA (server/statics): serving + API-contract parity.

The SPA is build-less ES modules (no node toolchain in CI), so these tests pin
the contract statically: every endpoint the JS calls must be a registered
route, and the shell/assets must serve. Parity: the reference serves its React
SPA from server statics (ref: src/dstack/_internal/server/app.py:292-295)."""

import asyncio
import re
import time
from pathlib import Path

from dstack_tpu.server.services import logs as logs_service
from tests.common import api_server

STATICS = Path(__file__).parent.parent / "dstack_tpu" / "server" / "statics"


def spa_api_paths():
    src = (STATICS / "app.js").read_text()
    # api("/api/...") and api(`/api/...${...}`) call sites.
    paths = set()
    for m in re.finditer(r"""api\((?:"([^"]+)"|`([^`]+)`)""", src):
        path = m.group(1) or m.group(2)
        path = path.replace("${P()}", "{project_name}")
        if "${" in path:  # run-name etc. interpolations aren't route segments
            path = re.sub(r"\$\{[^}]+\}", "X", path)
        paths.add(path)
    return paths


class TestSpaContract:
    def test_spa_calls_only_registered_routes(self):
        from dstack_tpu.server.app import create_app

        app = create_app(db_path=":memory:", run_background_tasks=False)
        registered = {r.resource.canonical for r in app.router.routes() if r.resource}
        paths = spa_api_paths()
        assert len(paths) >= 20, f"path extraction broke: {sorted(paths)}"
        missing = sorted(p for p in paths if p not in registered)
        assert not missing, f"SPA calls unregistered endpoints: {missing}"

    def test_assets_exist_and_reference_each_other(self):
        html = (STATICS / "index.html").read_text()
        assert "/statics/app.js" in html and "/statics/style.css" in html
        js = (STATICS / "app.js").read_text()
        # Every resource surface has a view (VERDICT: "every REST resource a page").
        for view in ("viewRuns", "viewRunDetail", "viewFleets", "viewFleetDetail",
                     "viewInstances", "viewVolumes", "viewGateways", "viewOffers",
                     "viewSecrets", "viewProjects", "viewUsers", "viewLogin"):
            assert f"async function {view}" in js, f"missing {view}"
        # Live log tail (WS push, REST only as fallback), metrics sparklines,
        # pagination, and UI run submission are wired.
        assert "viewSubmit" in js and "configurations/parse" in js
        assert "logs/ws" in js and "metrics/job" in js and "sparkline" in js
        assert "paginated(" in js
        # logs/poll remains only as the WS-failure fallback (gated on onerror).
        assert "ws.onerror" in js
        assert "setInterval(pollLogs" not in js

    async def test_shell_and_assets_served(self):
        async with api_server() as api:
            resp = await api.client.get("/")
            assert resp.status == 200
            assert "app.js" in await resp.text()
            resp = await api.client.get("/statics/app.js")
            assert resp.status == 200
            assert "javascript" in resp.content_type
            resp = await api.client.get("/statics/style.css")
            assert resp.status == 200

    def test_dom_level_behavior_under_node(self):
        """Execute the real app.js against a DOM/fetch/WebSocket shim
        (tests/frontend/dom_test.mjs): list pagination, WS log push, and the
        parse->plan->apply submit flow. Needs node (present in CI, absent in
        the TPU image — skipped there)."""
        import shutil
        import subprocess

        import pytest

        node = shutil.which("node")
        if node is None:
            pytest.skip("node is not installed in this image; runs in CI")
        proc = subprocess.run(
            [node, str(Path(__file__).parent / "frontend" / "dom_test.mjs")],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, f"stdout={proc.stdout}\nstderr={proc.stderr}"
        assert "OK:" in proc.stdout

    def test_js_brackets_balanced(self):
        """No JS runtime ships in this image; a string/comment-aware bracket
        balance check catches the truncation/paste class of syntax errors."""
        src = (STATICS / "app.js").read_text()
        stack = []
        pairs = {")": "(", "]": "[", "}": "{"}
        i, n, mode = 0, len(src), None
        while i < n:
            c = src[i]
            if c == "\n" and mode == "//":
                mode = None
            if mode is None:
                if c in "'\"`":
                    mode = c
                elif src[i : i + 2] == "//":
                    mode, i = "//", i + 1
                elif src[i : i + 2] == "/*":
                    mode, i = "/*", i + 1
                elif c in "([{":
                    stack.append(c)
                elif c in ")]}":
                    assert stack and stack[-1] == pairs[c], f"bracket mismatch at byte {i}"
                    stack.pop()
            elif mode in "'\"`":
                if c == "\\":
                    i += 1
                elif c == mode:
                    mode = None
                elif mode == "`" and src[i : i + 2] == "${":
                    depth, i = 1, i + 2
                    while i < n and depth:
                        depth += {"{": 1, "}": -1}.get(src[i], 0)
                        i += 1
                    continue
            elif mode == "/*" and src[i : i + 2] == "*/":
                mode, i = None, i + 1
            i += 1
        assert not stack and mode is None


class TestSpaEndpoints:
    """The two endpoints added for the SPA: YAML parse and the WS log stream."""

    async def test_configurations_parse(self):
        async with api_server() as api:
            conf = await api.post(
                "/api/project/main/configurations/parse",
                {"yaml": "type: task\ncommands:\n  - echo hi\n"},
            )
            assert conf["type"] == "task"
            assert conf["commands"] == ["echo hi"]

            headers = {"Authorization": f"Bearer {api.token}"}
            resp = await api.client.post(
                "/api/project/main/configurations/parse",
                json={"yaml": "type: no-such-type"}, headers=headers,
            )
            assert resp.status == 400
            body = await resp.json()
            assert "invalid configuration" in str(body)

            resp = await api.client.post(
                "/api/project/main/configurations/parse",
                json={"yaml": ": ["}, headers=headers,
            )
            assert resp.status == 400
            assert "invalid YAML" in str(await resp.json())

            resp = await api.client.post(
                "/api/project/main/configurations/parse",
                json={"yaml": ""}, headers=headers,
            )
            assert resp.status == 400

    async def test_logs_ws_pushes_log_events(self, tmp_path):
        from tests.test_services import _drive

        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        try:
            async with api_server() as api:
                await api.post(
                    "/api/project/main/runs/submit",
                    {"run_spec": {"run_name": "wslog", "configuration": {
                        "type": "task", "commands": ["echo ws-log-line"]}}},
                )
                deadline = time.time() + 30
                while time.time() < deadline:
                    await _drive(api)
                    run = await api.post(
                        "/api/project/main/runs/get", {"run_name": "wslog"}
                    )
                    if run["status"] in ("done", "failed", "terminated"):
                        break
                    await asyncio.sleep(0.05)
                assert run["status"] == "done", run

                # Browser-style connect: token in the query, no auth header.
                ws = await api.client.ws_connect(
                    f"/api/project/main/logs/ws?run_name=wslog&token={api.token}"
                )
                msg = await ws.receive_json(timeout=10)
                text = "".join(e["message"] for e in msg["logs"])
                assert "ws-log-line" in text
                assert msg["next_line"] >= 1
                await ws.close()

                # A bad token is rejected before the upgrade completes.
                resp = await api.client.get(
                    "/api/project/main/logs/ws?run_name=wslog&token=wrong",
                    headers={"Upgrade": "websocket", "Connection": "Upgrade"},
                )
                assert resp.status in (401, 403)
        finally:
            logs_service.set_log_storage(None)
