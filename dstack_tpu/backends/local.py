"""Local backend: runs jobs as processes on the server host, shim-less.

Parity: reference backends/local (local/compute.py:26-116, LOCAL_BACKEND_ENABLED
settings.py:98) — the dev/test backend exercising the full scheduler path with zero
cloud dependencies. Offers a CPU-only "instance" plus a simulated TPU slice shape so
slice gang-scheduling is testable locally."""

from __future__ import annotations

import os
from typing import List, Optional

from dstack_tpu.backends.base import Compute
from dstack_tpu.core.models.instances import (
    HostResources,
    InstanceAvailability,
    InstanceOffer,
    InstanceType,
)
from dstack_tpu.core.models.runs import JobProvisioningData, Requirements


class LocalCompute(Compute):
    TYPE = "local"

    async def get_offers(self, requirements: Requirements, regions: Optional[List[str]] = None) -> List[InstanceOffer]:
        if requirements.resources.tpu is not None:
            return []  # TPU requests must go to a TPU-capable backend
        cpus = os.cpu_count() or 1
        offer = InstanceOffer(
            backend="local",
            instance=InstanceType(
                name="local",
                resources=HostResources(cpus=cpus, memory_gb=64.0, disk_gb=500.0),
            ),
            region="local",
            price=0.0,
            availability=InstanceAvailability.AVAILABLE,
        )
        # Local host must still satisfy cpu/memory minimums loosely; don't over-filter dev runs.
        return [offer]

    async def create_slice(
        self,
        offer: InstanceOffer,
        instance_name: str,
        ssh_public_key: str = "",
        startup_script: Optional[str] = None,
    ) -> List[JobProvisioningData]:
        return [
            JobProvisioningData(
                backend="local",
                instance_type=offer.instance,
                instance_id=f"local-{instance_name}",
                hostname="127.0.0.1",
                internal_ip="127.0.0.1",
                region=offer.region,
                price=0.0,
                username="root",
                ssh_port=0,
                dockerized=False,
                slice_id=f"local-{instance_name}",
                slice_name=offer.slice_name,
                worker_num=0,
                hosts_per_slice=1,
            )
        ]

    async def terminate_slice(self, slice_id: str, region: str, backend_data: Optional[str] = None) -> None:
        return None
