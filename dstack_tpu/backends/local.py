"""Local backend: runs jobs as host processes via the native runner agent, shim-less.

Parity: reference backends/local (local/compute.py:26-116, LOCAL_BACKEND_ENABLED
settings.py:98) — the dev/test backend exercising the full scheduler path with zero
cloud dependencies. `create_slice` spawns a real dstack-tpu-runner process on an
ephemeral port, so the control plane drives the exact same HTTP protocol it uses
against cloud instances."""

from __future__ import annotations

import asyncio
import json
import logging
import os
import re
import signal
import subprocess
import tempfile
import threading
from typing import List, Optional

from dstack_tpu.backends.base import Compute
from dstack_tpu.core.errors import ComputeError
from dstack_tpu.core.models.instances import (
    HostResources,
    InstanceAvailability,
    InstanceOffer,
    InstanceType,
)
from dstack_tpu.core.models.runs import JobProvisioningData, Requirements
from dstack_tpu.utils.runner_binary import find_runner_binary

logger = logging.getLogger(__name__)

_LISTEN_RE = re.compile(r"listening on [\d.]+:(\d+)")


def _pid_is_runner(pid: int, base_dir: Optional[str] = None) -> bool:
    """True if pid is (still) one of our runner agents. The per-slice tempdir passed as
    --base-dir is the discriminator — it survives custom binary names
    (DSTACK_TPU_RUNNER_BINARY) and is unique per spawn."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            argv = f.read().split(b"\0")
    except OSError:
        return False
    if base_dir is not None:
        return base_dir.encode() in argv
    return any(b"dstack-tpu-runner" in a for a in argv)


class LocalCompute(Compute):
    TYPE = "local"

    def __init__(self) -> None:
        # Live runner processes by slice_id, so terminate can reap them (otherwise the
        # children linger as zombies of the server process).
        self._procs: dict = {}

    async def get_offers(self, requirements: Requirements, regions: Optional[List[str]] = None) -> List[InstanceOffer]:
        if requirements.resources.tpu is not None:
            return []  # TPU requests must go to a TPU-capable backend
        cpus = os.cpu_count() or 1
        try:
            memory_gb = (
                os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE") / (1024**3)
            )
        except (ValueError, OSError):
            memory_gb = 8.0
        # The host must plausibly satisfy the request (round-1 finding: a 128-CPU
        # ask must not land on a 4-CPU dev box). CPU overcommits up to a small
        # floor — local jobs timeshare, and the default cpu>=2 ask must still run
        # on a 1-CPU dev container; memory is filtered for real.
        res = requirements.resources
        if res.cpu.count.min and res.cpu.count.min > max(cpus, 4):
            return []
        if res.memory.min and res.memory.min > memory_gb:
            return []
        offer = InstanceOffer(
            backend="local",
            instance=InstanceType(
                name="local",
                resources=HostResources(
                    cpus=cpus, memory_gb=round(memory_gb, 1), disk_gb=500.0
                ),
            ),
            region="local",
            price=0.0,
            availability=InstanceAvailability.AVAILABLE,
        )
        return [offer]

    async def create_slice(
        self,
        offer: InstanceOffer,
        instance_name: str,
        ssh_public_key: str = "",
        startup_script: Optional[str] = None,
        volumes=None,
    ) -> List[JobProvisioningData]:
        loop = asyncio.get_running_loop()

        from dstack_tpu.server import settings

        docker_mode = settings.LOCAL_DOCKER_MODE

        def _spawn():
            # Off the event loop: find_runner_binary may compile the agent (slow) and
            # Popen/mkdtemp do blocking IO.
            binary = find_runner_binary()
            if binary is None:
                raise ComputeError("dstack-tpu-runner binary not found and could not be built")
            base_dir = tempfile.mkdtemp(prefix=f"dstack-tpu-{instance_name}-")
            return base_dir, subprocess.Popen(
                [
                    binary,
                    "--host", "127.0.0.1",
                    "--port", "0",
                    "--base-dir", base_dir,
                    "--docker", docker_mode,
                ],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )

        base_dir, proc = await loop.run_in_executor(None, _spawn)
        port = await self._read_port(proc)
        logger.info("local runner %s: pid=%s port=%s", instance_name, proc.pid, port)
        self._procs[f"local-{instance_name}"] = proc
        return [
            JobProvisioningData(
                backend="local",
                instance_type=offer.instance,
                instance_id=f"local-{instance_name}",
                hostname="127.0.0.1",
                internal_ip="127.0.0.1",
                region=offer.region,
                price=0.0,
                username="root",
                ssh_port=0,  # direct HTTP, no tunnel
                dockerized=docker_mode != "never",
                backend_data=json.dumps({"runner_port": port, "runner_pid": proc.pid, "base_dir": base_dir}),
                slice_id=f"local-{instance_name}",
                slice_name=offer.slice_name,
                worker_num=0,
                hosts_per_slice=1,
            )
        ]

    async def _read_port(self, proc: subprocess.Popen) -> int:
        loop = asyncio.get_running_loop()

        def _read() -> int:
            assert proc.stdout is not None
            # Tolerate loader/env warnings before the listen line.
            for _ in range(20):
                line = proc.stdout.readline().decode(errors="replace")
                if not line:
                    break
                m = _LISTEN_RE.search(line)
                if m:
                    return int(m.group(1))
            raise ComputeError("runner did not report a listen port")

        try:
            return await asyncio.wait_for(loop.run_in_executor(None, _read), timeout=10)
        except (asyncio.TimeoutError, ComputeError):
            # Don't leak a half-born agent: kill and reap before propagating.
            try:
                proc.kill()
                await loop.run_in_executor(None, proc.wait)
            except Exception:
                pass
            raise ComputeError("runner failed to start")

    async def terminate_slice(self, slice_id: str, region: str, backend_data: Optional[str] = None) -> None:
        proc = self._procs.pop(slice_id, None)
        pid = proc.pid if proc is not None else None
        if pid is None and backend_data:
            try:
                data = json.loads(backend_data)
                pid = data.get("runner_pid")
                base_dir = data.get("base_dir")
            except ValueError:
                pid, base_dir = None, None
            # After a server restart the persisted pid may have been recycled by an
            # unrelated process: only signal if it is still our runner agent.
            if pid is not None and not _pid_is_runner(pid, base_dir):
                pid = None
        if pid:
            try:
                os.killpg(pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        if proc is not None:
            loop = asyncio.get_running_loop()

            def _reap() -> None:
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5)

            await loop.run_in_executor(None, _reap)
        # Don't leave per-slice workdirs accreting in /tmp across dev runs.
        if backend_data:
            try:
                base_dir = json.loads(backend_data).get("base_dir")
            except ValueError:
                base_dir = None
            if base_dir and base_dir.startswith(tempfile.gettempdir()):
                import shutil

                await asyncio.get_running_loop().run_in_executor(
                    None, lambda: shutil.rmtree(base_dir, ignore_errors=True)
                )

    # -- volumes: a "disk" is a host directory (dev parity for the data-disk path) ----

    async def create_volume(self, volume):
        import json as _json

        from dstack_tpu.core.models.volumes import VolumeProvisioningData

        host_dir = tempfile.mkdtemp(prefix=f"dstack-tpu-vol-{volume.name}-")
        return VolumeProvisioningData(
            backend="local",
            volume_id=host_dir,
            size_gb=float(volume.configuration.size or 1),
            availability_zone="local",
            price=0.0,
            backend_data=_json.dumps({"host_dir": host_dir}),
        )

    async def delete_volume(self, volume) -> None:
        import shutil

        pd = volume.provisioning_data
        if pd is not None and pd.volume_id and os.path.isdir(pd.volume_id):
            shutil.rmtree(pd.volume_id, ignore_errors=True)

    # -- gateway: the appliance runs as a local subprocess (dev parity) ----------------

    async def create_gateway(self, configuration, token: str):
        import sys

        from dstack_tpu.core.models.gateways import GatewayProvisioningData

        proc = subprocess.Popen(
            [sys.executable, "-m", "dstack_tpu.gateway",
             "--host", "127.0.0.1", "--port", "0", "--token", token],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        loop = asyncio.get_running_loop()

        def _read_port() -> int:
            assert proc.stdout is not None
            for _ in range(40):
                line = proc.stdout.readline().decode(errors="replace")
                if not line:
                    break
                m = re.search(r"listening on [\d.]+:(\d+)", line)
                if m:
                    return int(m.group(1))
            raise ComputeError("gateway appliance did not report a port")

        try:
            port = await asyncio.wait_for(loop.run_in_executor(None, _read_port), timeout=20)
        except (asyncio.TimeoutError, ComputeError):
            proc.kill()
            raise ComputeError("gateway appliance failed to start")
        # Keep draining the pipe for the gateway's lifetime: aiohttp access/INFO
        # logging would otherwise fill the 64KiB pipe buffer and block the
        # appliance the first time it takes sustained traffic.
        def _drain(stream=proc.stdout):
            for _ in iter(stream.readline, b""):
                pass

        threading.Thread(target=_drain, name=f"gw-drain-{proc.pid}", daemon=True).start()
        self._procs[f"local-gw-{proc.pid}"] = proc
        return GatewayProvisioningData(
            instance_id=f"local-gw-{proc.pid}",
            ip_address="127.0.0.1",
            region="local",
            backend_data=json.dumps({"pid": proc.pid, "port": port}),
        )

    async def terminate_gateway(self, instance_id: str, region: str, backend_data=None) -> None:
        proc = self._procs.pop(instance_id, None)
        if proc is not None:
            try:
                os.killpg(proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            await asyncio.get_running_loop().run_in_executor(None, proc.wait)
        elif backend_data:
            try:
                pid = json.loads(backend_data).get("pid")
                if pid:
                    os.killpg(pid, signal.SIGTERM)
            except (ValueError, ProcessLookupError, PermissionError):
                pass
