"""Backend Compute ABC.

Parity: reference core/backends/base/compute.py:52-367 (Compute ABC + capability
mixins). TPU twist: `create_slice` provisions an entire pod slice atomically (N hosts =
one cloud resource) and returns per-worker provisioning data — the reference's
create_instance assumes 1 VM = 1 instance."""

from __future__ import annotations

import abc
from typing import List, Optional

from dstack_tpu.core.models.instances import InstanceOffer
from dstack_tpu.core.models.runs import JobProvisioningData, Requirements
from dstack_tpu.core.models.volumes import Volume, VolumeProvisioningData


class Compute(abc.ABC):
    """One instance per configured backend per project."""

    TYPE: str = ""

    @abc.abstractmethod
    async def get_offers(self, requirements: Requirements, regions: Optional[List[str]] = None) -> List[InstanceOffer]:
        ...

    @abc.abstractmethod
    async def create_slice(
        self,
        offer: InstanceOffer,
        instance_name: str,
        ssh_public_key: str = "",
        startup_script: Optional[str] = None,
        volumes: Optional[List[Volume]] = None,
    ) -> List[JobProvisioningData]:
        """Provision the slice behind `offer`; one JobProvisioningData per worker host.
        `volumes` (when the backend supports them) attach to every host of the slice
        at create time (TPU data disks, reference gcp/compute.py:1003-1016)."""

    @abc.abstractmethod
    async def terminate_slice(self, slice_id: str, region: str, backend_data: Optional[str] = None) -> None:
        ...

    async def update_provisioning_data(self, jpd: JobProvisioningData) -> JobProvisioningData:
        """Poll the cloud until hostname/IP are known; default: already known."""
        return jpd


class ComputeWithVolumeSupport(abc.ABC):
    async def create_volume(self, volume: Volume) -> VolumeProvisioningData:
        raise NotImplementedError

    async def register_volume(self, volume: Volume) -> VolumeProvisioningData:
        raise NotImplementedError

    async def delete_volume(self, volume: Volume) -> None:
        raise NotImplementedError

    async def attach_volume(self, volume: Volume, provisioning_data: JobProvisioningData) -> str:
        """Returns the device name on the host."""
        raise NotImplementedError

    async def detach_volume(self, volume: Volume, provisioning_data: JobProvisioningData) -> None:
        raise NotImplementedError


class ComputeWithGatewaySupport(abc.ABC):
    async def create_gateway(self, configuration) -> "object":
        raise NotImplementedError

    async def terminate_gateway(self, instance_id: str, region: str) -> None:
        raise NotImplementedError
