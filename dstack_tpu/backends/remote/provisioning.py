"""SSH fleets: provision user-supplied hosts into the slice pool over SSH.

Parity: reference remote/provisioning.py (paramiko: arch detect :40, shim upload +
systemd :116, host_info -> InstanceType :246). TPU-native differences: host probing
counts TPU accelerator devices (/dev/accel*, /dev/vfio) and libtpu presence instead of
running nvidia-smi, the agent uploaded is the C++ runner, and upload rides stdin over
the OpenSSH client (``cat > bin``) — no paramiko/SFTP dependency.
"""

from __future__ import annotations

import json
import logging
import shlex
from typing import Optional, Tuple

from dstack_tpu.backends.gcp.startup import RUNNER_PORT
from dstack_tpu.core.errors import SSHError
from dstack_tpu.core.models.configurations import SSHHostParams
from dstack_tpu.core.models.instances import (
    HostResources,
    InstanceType,
    SSHConnectionParams,
    TpuResources,
)
from dstack_tpu.core.models.runs import JobProvisioningData
from dstack_tpu.core.services.ssh import tunnel as ssh_tunnel

logger = logging.getLogger(__name__)

# Overridable seam for tests (fake SSH executor).
ssh_exec = ssh_tunnel.ssh_exec

_HOST_INFO_CMD = (
    "echo cpus=$(nproc);"
    " echo mem_mb=$(awk '/MemTotal/{print int($2/1024)}' /proc/meminfo);"
    " echo disk_gb=$(df -BG --output=avail / 2>/dev/null | tail -1 | tr -dc 0-9);"
    " echo accel=$(ls /dev/accel* 2>/dev/null | wc -l);"
    " echo vfio=$(ls /dev/vfio/* 2>/dev/null | grep -cv vfio$ || true);"
    " echo libtpu=$(ls /usr/lib/libtpu.so /lib/libtpu.so /usr/local/lib/libtpu.so 2>/dev/null | head -1);"
    " echo arch=$(uname -m)"
)

_INSTALL_RUNNER_CMD = (
    "mkdir -p /usr/local/bin /var/lib/dstack-tpu"
    " && cat > /usr/local/bin/dstack-tpu-runner"
    " && chmod +x /usr/local/bin/dstack-tpu-runner"
)

# Appends keys arriving on stdin (one per line) to ~/.ssh/authorized_keys,
# idempotently. The server's tunnel identity differs from the fleet's
# provisioning identity (reference installs the project key the same way,
# remote/provisioning.py:266-267); without this the healthcheck tunnels can
# never authenticate and the host is torn down at PROVISIONING_TIMEOUT.
_AUTHORIZE_KEYS_CMD = (
    'mkdir -p "$HOME/.ssh" && chmod 700 "$HOME/.ssh"'
    ' && touch "$HOME/.ssh/authorized_keys" && chmod 600 "$HOME/.ssh/authorized_keys"'
    ' && while IFS= read -r k; do'
    ' if [ -n "$k" ] && ! grep -qxF "$k" "$HOME/.ssh/authorized_keys"; then'
    ' echo "$k" >> "$HOME/.ssh/authorized_keys"; fi; done'
)


def _start_runner_cmd(port: int) -> str:
    # --docker auto: image-based jobs go to the engine when one is installed on the
    # fleet host; bare hosts keep the pty-exec path.
    unit = f"""[Unit]
Description=dstack-tpu runner agent
After=network-online.target
[Service]
Environment=PJRT_DEVICE=TPU
ExecStart=/usr/local/bin/dstack-tpu-runner --port {port} --base-dir /var/lib/dstack-tpu --docker auto
Restart=always
RestartSec=2
[Install]
WantedBy=multi-user.target
"""
    # systemd when available; nohup fallback for containers/minimal hosts.
    return (
        "if command -v systemctl >/dev/null 2>&1 && [ -d /run/systemd/system ]; then"
        f" printf %s {shlex.quote(unit)} > /etc/systemd/system/dstack-tpu-runner.service"
        " && systemctl daemon-reload && systemctl enable --now dstack-tpu-runner.service;"
        " else"
        " pkill -f 'dstack-tpu-runner --port' 2>/dev/null;"
        f" nohup /usr/local/bin/dstack-tpu-runner --port {port}"
        " --base-dir /var/lib/dstack-tpu --docker auto >/var/lib/dstack-tpu/runner.log 2>&1 &"
        " fi"
    )


def parse_host_info(output: str) -> dict:
    info = {}
    for line in output.splitlines():
        if "=" in line:
            k, _, v = line.strip().partition("=")
            info[k] = v
    return info


def host_info_to_instance_type(info: dict) -> InstanceType:
    """Reference :246 host_info_to_instance_type, with a TPU branch instead of GPUs.

    Accelerator count comes from /dev/accel* (PJRT device nodes); the generation is
    unknown from the device node alone, so it stays None — requirements matching for
    SSH fleets is by chip count.
    """
    chips = int(info.get("accel") or 0) or int(info.get("vfio") or 0)
    tpu = None
    if chips > 0:
        tpu = TpuResources(chips=chips, hosts=1)
    return InstanceType(
        name=info.get("arch", "ssh-host"),
        resources=HostResources(
            cpus=int(info.get("cpus") or 0),
            memory_gb=float(info.get("mem_mb") or 0) / 1024.0,
            disk_gb=float(info.get("disk_gb") or 0),
            tpu=tpu,
        ),
    )


def _proxy_params(host: SSHHostParams) -> Optional[SSHConnectionParams]:
    if not host.proxy_jump:
        return None
    user, _, hostport = host.proxy_jump.rpartition("@")
    hostname, _, port = hostport.partition(":")
    return SSHConnectionParams(
        hostname=hostname, username=user or "root", port=int(port or 22)
    )


async def provision_ssh_host(
    host: SSHHostParams,
    runner_binary: bytes,
    *,
    default_user: Optional[str] = None,
    default_identity_file: Optional[str] = None,
    runner_port: int = RUNNER_PORT,
    authorize_keys: Optional[list] = None,
) -> Tuple[JobProvisioningData, dict]:
    """Probe, install the runner, start it, and install `authorize_keys` (the
    server's tunnel public key) into the host's authorized_keys. Returns
    (jpd, host_info).

    Raises SSHError when the host is unreachable or any step fails.
    """
    user = host.user or default_user or "root"
    identity = host.identity_file or default_identity_file
    proxy = _proxy_params(host)
    kwargs = dict(
        username=user, port=host.port, identity_file=identity, proxy=proxy
    )

    rc, out, err = await ssh_exec(host.hostname, _HOST_INFO_CMD, **kwargs)
    if rc != 0:
        raise SSHError(f"host probe failed on {host.hostname}: {err.decode(errors='replace')[:300]}")
    info = parse_host_info(out.decode(errors="replace"))

    keys = "\n".join(k.strip() for k in (authorize_keys or []) if k and k.strip())
    if keys:
        rc, _, err = await ssh_exec(
            host.hostname, _AUTHORIZE_KEYS_CMD, input_data=(keys + "\n").encode(), **kwargs
        )
        if rc != 0:
            raise SSHError(
                f"installing server key on {host.hostname} failed: "
                f"{err.decode(errors='replace')[:300]}"
            )

    rc, _, err = await ssh_exec(
        host.hostname, _INSTALL_RUNNER_CMD, input_data=runner_binary, timeout=180, **kwargs
    )
    if rc != 0:
        raise SSHError(f"runner upload failed on {host.hostname}: {err.decode(errors='replace')[:300]}")

    rc, _, err = await ssh_exec(host.hostname, _start_runner_cmd(runner_port), **kwargs)
    if rc != 0:
        raise SSHError(f"runner start failed on {host.hostname}: {err.decode(errors='replace')[:300]}")

    instance_type = host_info_to_instance_type(info)
    jpd = JobProvisioningData(
        backend="ssh",
        instance_type=instance_type,
        instance_id=f"ssh-{host.hostname}",
        hostname=host.hostname,
        internal_ip=host.hostname,
        region="remote",
        price=0.0,
        username=user,
        ssh_port=host.port,
        ssh_proxy=proxy,
        dockerized=False,
        backend_data=json.dumps({"runner_port": runner_port, "host_info": info}),
    )
    return jpd, info
