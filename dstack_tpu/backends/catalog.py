"""TPU pod-slice offer catalog: generation x chip-count x region -> priced offer.

Parity: the reference's external `gpuhunt` catalog + adapter (base/offers.py:26-190,
KNOWN_TPUS); here the catalog is built in, TPU-only, and slice-topology-aware (the
reference prices single VMs; a TPU offer prices a whole slice and knows its host count).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from dstack_tpu.core.models.instances import (
    HostResources,
    InstanceAvailability,
    InstanceOffer,
    InstanceType,
    TpuResources,
)
from dstack_tpu.core.models.resources import (
    TPU_GENERATIONS,
    TpuSliceSpec,
    default_topology,
)
from dstack_tpu.core.models.runs import Requirements

# $/chip/hour on-demand (public GCP list prices, us-central region family).
ON_DEMAND_PRICE_PER_CHIP: Dict[str, float] = {
    "v4": 3.22,
    "v5e": 1.20,
    "v5p": 4.20,
    "v6e": 2.70,
}
SPOT_DISCOUNT = 0.6  # spot ~40% of on-demand

# Host VM shape paired with each generation's TPU VM (vCPUs, RAM GB per host).
HOST_SHAPES: Dict[str, tuple] = {
    "v4": (240, 400.0),
    "v5e": (224, 384.0),
    "v5p": (208, 448.0),
    "v6e": (180, 720.0),
}

REGIONS: Dict[str, List[str]] = {
    "v4": ["us-central2"],
    "v5e": ["us-central1", "us-west4", "europe-west4", "asia-southeast1"],
    "v5p": ["us-central1", "us-east5", "europe-west4"],
    "v6e": ["us-central2", "us-east1", "europe-west4", "asia-northeast1"],
}


def slice_offer(
    generation: str,
    chips: int,
    region: str,
    spot: bool,
    backend: str = "gcp",
) -> InstanceOffer:
    gen = TPU_GENERATIONS[generation]
    spec = TpuSliceSpec(generation=generation, chips=chips)
    cpus, mem = HOST_SHAPES[generation]
    # Sub-host slices get a proportional share of the host VM.
    frac = min(1.0, chips / gen.chips_per_host)
    price = chips * ON_DEMAND_PRICE_PER_CHIP[generation] * (SPOT_DISCOUNT if spot else 1.0)
    topology = default_topology(generation, chips)
    return InstanceOffer(
        backend=backend,
        instance=InstanceType(
            name=spec.accelerator_type,
            resources=HostResources(
                cpus=int(cpus * frac),
                memory_gb=mem * frac,
                disk_gb=100.0,
                spot=spot,
                tpu=TpuResources.from_slice(spec, topology=topology),
            ),
        ),
        region=region,
        price=round(price, 4),
        # Honest UNKNOWN, not AVAILABLE: the TPU API exposes no capacity/quota
        # read, so plans must not promise capacity the provision-time zone
        # fall-through may fail to find (VERDICT r2 "offer availability is
        # fiction"). is_available() admits UNKNOWN, so scheduling is unchanged.
        availability=InstanceAvailability.UNKNOWN,
        slice_name=spec.slice_name,
        hosts_per_slice=spec.hosts,
        spot=spot,
    )


def get_catalog_offers(
    backend: str = "gcp",
    regions: Optional[List[str]] = None,
    requirements: Optional[Requirements] = None,
) -> List[InstanceOffer]:
    offers: List[InstanceOffer] = []
    for gen_name, gen in TPU_GENERATIONS.items():
        for chips in gen.valid_chip_counts:
            for region in REGIONS[gen_name]:
                if regions and region not in regions:
                    continue
                for spot in (False, True):
                    offers.append(slice_offer(gen_name, chips, region, spot, backend=backend))
    if requirements is not None:
        offers = [o for o in offers if offer_matches(o, requirements)]
    return sorted(offers, key=lambda o: o.price)


def offer_matches(offer: InstanceOffer, req: Requirements) -> bool:
    res = req.resources
    host = offer.instance.resources
    if res.tpu is not None:
        tpu = host.tpu
        if tpu is None or tpu.generation != res.tpu.generation or tpu.chips != res.tpu.chips:
            return False
    elif host.tpu is not None and host.tpu.chips > 0:
        # CPU-only request should not pay for a slice.
        return False
    if res.cpu.count.min is not None and host.cpus < res.cpu.count.min:
        return False
    if res.memory.min is not None and host.memory_gb < res.memory.min:
        return False
    if res.disk is not None and res.disk.size.min is not None and host.disk_gb < res.disk.size.min:
        return False
    if req.spot is not None and offer.spot != req.spot:
        return False
    if req.max_price is not None and offer.price > req.max_price:
        return False
    return True
