"""Mock TPU backend for tests: serves the real TPU catalog, 'provisions' instantly.

Parity: reference testing ComputeMockSpec (server/testing/common.py:985) — but as a real
Compute subclass so scheduler tests run the production code path (SURVEY §4: fake
Compute + real loops)."""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional

from dstack_tpu.backends import catalog
from dstack_tpu.backends.base import Compute
from dstack_tpu.core.models.instances import InstanceOffer
from dstack_tpu.core.models.runs import JobProvisioningData, Requirements

_counter = itertools.count(1)


class MockTpuCompute(Compute):
    TYPE = "mock"

    def __init__(self, fail_provision: bool = False, regions: Optional[List[str]] = None):
        self.fail_provision = fail_provision
        self.regions = regions
        self.created: List[str] = []
        self.terminated: List[str] = []
        self.created_volumes: List[str] = []
        self.deleted_volumes: List[str] = []
        self.slice_volumes: Dict[str, List[str]] = {}  # slice_id -> volume names

    async def get_offers(self, requirements: Requirements, regions: Optional[List[str]] = None) -> List[InstanceOffer]:
        return catalog.get_catalog_offers(
            backend="mock", regions=regions or self.regions, requirements=requirements
        )

    async def create_slice(
        self,
        offer: InstanceOffer,
        instance_name: str,
        ssh_public_key: str = "",
        startup_script: Optional[str] = None,
        volumes=None,
    ) -> List[JobProvisioningData]:
        if self.fail_provision:
            from dstack_tpu.core.errors import NoCapacityError

            raise NoCapacityError(f"mock: no capacity for {offer.instance.name}")
        n = next(_counter)
        slice_id = f"mock-slice-{n}"
        self.created.append(slice_id)
        if volumes:
            self.slice_volumes[slice_id] = [v.name for v in volumes]
        return [
            JobProvisioningData(
                backend="mock",
                instance_type=offer.instance,
                instance_id=f"{slice_id}-w{w}",
                hostname=f"10.130.0.{n % 250 + 1}" if w == 0 else f"10.130.{w}.{n % 250 + 1}",
                internal_ip=f"10.130.{w}.{n % 250 + 1}",
                region=offer.region,
                price=offer.price,
                username="root",
                ssh_port=22,
                dockerized=True,
                slice_id=slice_id,
                slice_name=offer.slice_name,
                worker_num=w,
                hosts_per_slice=offer.hosts_per_slice,
            )
            for w in range(offer.hosts_per_slice)
        ]

    async def terminate_slice(self, slice_id: str, region: str, backend_data: Optional[str] = None) -> None:
        self.terminated.append(slice_id)

    # -- volumes (instant-provision fakes for scheduler tests) ------------------------

    async def create_volume(self, volume):
        from dstack_tpu.core.models.volumes import VolumeProvisioningData

        self.created_volumes.append(volume.name)
        return VolumeProvisioningData(
            backend="mock",
            volume_id=f"mock-disk-{volume.name}",
            size_gb=float(volume.configuration.size or 100),
            availability_zone=f"{volume.configuration.region}-a",
            price=0.0,
        )

    async def register_volume(self, volume):
        from dstack_tpu.core.models.volumes import VolumeProvisioningData

        return VolumeProvisioningData(
            backend="mock",
            volume_id=volume.configuration.volume_id,
            size_gb=100,
            availability_zone=f"{volume.configuration.region}-a",
            price=0.0,
        )

    async def delete_volume(self, volume) -> None:
        self.deleted_volumes.append(volume.name)
