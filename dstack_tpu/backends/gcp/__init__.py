"""GCP TPU backend (queued-resource slice provisioning)."""

from dstack_tpu.backends.gcp.compute import GcpTpuCompute, ProvisioningError

__all__ = ["GcpTpuCompute", "ProvisioningError"]
