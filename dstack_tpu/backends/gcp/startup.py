"""Startup script for freshly provisioned TPU VM workers.

Parity: reference ``_get_tpu_startup_script`` (gcp/compute.py:952-958) + shim install
commands (base/compute.py:508-581): cloud-init installs the host agent as a systemd
unit with ``PJRT_DEVICE=TPU``. TPU-native differences: the agent is the C++
dstack-tpu-runner acting as both runner and shim — it drives job containers through
the docker engine socket (``--docker auto``: container when the job names an image,
host exec otherwise), and the script probes TPU devices (/dev/accel*, /dev/vfio) +
libtpu so the control plane can verify accelerator health from the first heartbeat.
"""

from __future__ import annotations

from typing import List, Optional

RUNNER_PORT = 10999


def build_startup_script(
    runner_url: str,
    authorized_keys: Optional[List[str]] = None,
    runner_port: int = RUNNER_PORT,
    extra_env: Optional[dict] = None,
    login_user: str = "ubuntu",
    docker_mode: str = "auto",
) -> str:
    """A bash cloud-init script: SSH keys -> runner install -> systemd unit -> start.

    Keys are installed for `login_user` (GCP TPU VM images ship sshd with root
    login disabled — the reference connects as "ubuntu", gcp/compute.py:278,342)
    and for root as a fallback for images that do allow it.
    """
    env_lines = {"PJRT_DEVICE": "TPU", "TPU_RUNTIME": "pjrt"}
    if extra_env:
        env_lines.update({str(k): str(v) for k, v in extra_env.items()})
    env_block = "\n".join(f"Environment={k}={v}" for k, v in sorted(env_lines.items()))

    keys_block = ""
    if authorized_keys:
        joined = "\n".join(k.strip() for k in authorized_keys if k.strip())
        keys_block = f"""
install_keys() {{
  local home_dir="$1" owner="$2"
  mkdir -p "$home_dir/.ssh" && chmod 700 "$home_dir/.ssh"
  cat >> "$home_dir/.ssh/authorized_keys" <<'DSTACK_KEYS'
{joined}
DSTACK_KEYS
  chmod 600 "$home_dir/.ssh/authorized_keys"
  chown -R "$owner:" "$home_dir/.ssh" 2>/dev/null || true
}}
install_keys /root root
if id -u {login_user} >/dev/null 2>&1; then
  install_keys "$(getent passwd {login_user} | cut -d: -f6)" {login_user}
fi
"""

    return f"""#!/bin/bash
set -x
{keys_block}
# TPU device + libtpu discovery, recorded for the control plane (host-info contract;
# replaces the reference's nvidia-smi probe, shim/host/gpu.go:44-58).
mkdir -p /var/lib/dstack-tpu
{{
  echo "accel_devices=$(ls /dev/accel* 2>/dev/null | wc -l)"
  echo "vfio_devices=$(ls /dev/vfio/* 2>/dev/null | wc -l)"
  echo "libtpu=$(ls /usr/lib/libtpu.so /lib/libtpu.so 2>/dev/null | head -1)"
  echo "worker_id=$(curl -s -H 'Metadata-Flavor: Google' 'http://metadata.google.internal/computeMetadata/v1/instance/attributes/agent-worker-number' 2>/dev/null)"
}} > /var/lib/dstack-tpu/host-info

# Container runtime for image-based jobs (TPU VM images usually ship docker;
# install it when absent — the docker/tpu base image is the default job image).
if ! command -v docker >/dev/null 2>&1; then
  apt-get update -qq && apt-get install -y -qq docker.io || true
fi
systemctl enable --now docker 2>/dev/null || true

# Install the runner agent.
mkdir -p /usr/local/bin
curl -fsSL -o /usr/local/bin/dstack-tpu-runner '{runner_url}'
chmod +x /usr/local/bin/dstack-tpu-runner

cat > /etc/systemd/system/dstack-tpu-runner.service <<'DSTACK_UNIT'
[Unit]
Description=dstack-tpu runner agent
After=network-online.target docker.service
[Service]
{env_block}
ExecStart=/usr/local/bin/dstack-tpu-runner --port {runner_port} --base-dir /var/lib/dstack-tpu --docker {docker_mode}
Restart=always
RestartSec=2
[Install]
WantedBy=multi-user.target
DSTACK_UNIT

systemctl daemon-reload
systemctl enable --now dstack-tpu-runner.service
"""
