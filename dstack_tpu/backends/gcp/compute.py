"""GCP TPU backend: provisions pod slices via Cloud TPU v2 **queued resources**.

Parity + extension: reference gcp/compute.py provisions TPUs with ``nodes.create`` and
explicitly refuses multi-host slices (``_is_single_host_tpu`` gcp/compute.py:983-999).
This backend provisions EVERY slice — single- or multi-host — through a queued
resource wrapping one node: the TPU-native provisioning primitive (atomic for all
hosts of a slice, native spot semantics, no 30s blocking wait on create). Runtime
version selection mirrors gcp/compute.py:970-976; the startup script mirrors
:952-958 (PJRT_DEVICE=TPU) but installs the C++ runner agent directly.

The slice is the instance atom: ``create_slice`` returns one JobProvisioningData per
worker host with ``hostname=None``; the scheduler polls ``update_provisioning_data``
until the node is READY and the per-worker network endpoints are known.
"""

from __future__ import annotations

import json
import logging
import uuid
from typing import Dict, List, Optional

from dstack_tpu.backends import catalog
from dstack_tpu.backends.base import Compute, ComputeWithVolumeSupport
from dstack_tpu.backends.gcp.auth import token_provider_from_creds
from dstack_tpu.backends.gcp.client import AiohttpTransport, GcpApiError, TpuV2Client, Transport
from dstack_tpu.backends.gcp.startup import build_startup_script
from dstack_tpu.core.errors import ComputeError, NoCapacityError, ServerClientError
from dstack_tpu.core.models.instances import InstanceOffer
from dstack_tpu.core.models.resources import TPU_GENERATIONS, TpuSliceSpec
from dstack_tpu.core.models.runs import JobProvisioningData, Requirements
from dstack_tpu.core.models.volumes import Volume, VolumeProvisioningData

logger = logging.getLogger(__name__)

# TPU zones per (generation, region); the TPU API is zonal while offers are regional
# (reference resolves zones via gpuhunt's catalog rows; this build keeps a curated map
# aligned with backends/catalog.REGIONS).
TPU_ZONES: Dict[str, Dict[str, List[str]]] = {
    "v4": {"us-central2": ["us-central2-b"]},
    "v5e": {
        "us-central1": ["us-central1-a"],
        "us-west4": ["us-west4-a"],
        "europe-west4": ["europe-west4-b"],
        "asia-southeast1": ["asia-southeast1-b"],
    },
    "v5p": {
        "us-central1": ["us-central1-a"],
        "us-east5": ["us-east5-a", "us-east5-c"],
        "europe-west4": ["europe-west4-b"],
    },
    "v6e": {
        "us-central2": ["us-central2-b"],
        "us-east1": ["us-east1-d"],
        "europe-west4": ["europe-west4-a"],
        "asia-northeast1": ["asia-northeast1-b"],
    },
}

# Queued-resource states, cloud.google.com/tpu/docs/queued-resources.
_QR_PENDING = {"CREATING", "ACCEPTED", "PROVISIONING", "WAITING_FOR_RESOURCES"}
_QR_FAILED = {"FAILED", "SUSPENDING", "SUSPENDED"}

_CAPACITY_API_REASONS = {"RESOURCE_EXHAUSTED", "QUOTA_EXCEEDED", "UNAVAILABLE", "NOT_FOUND"}


class ProvisioningError(ComputeError):
    """Slice cannot reach READY (stockout after queueing, preemption mid-provision)."""


class GcpTpuCompute(Compute, ComputeWithVolumeSupport):
    TYPE = "gcp"

    def __init__(self, config: Optional[dict] = None, transport: Optional[Transport] = None):
        config = config or {}
        self.project_id = config.get("project_id")
        if not self.project_id:
            raise ServerClientError("gcp backend requires project_id")
        self.regions = config.get("regions")
        self.allocate_public_ips = bool(config.get("allocate_public_ips", True))
        self.network = config.get("network")
        self.subnetwork = config.get("subnetwork")
        self.service_account = config.get("vm_service_account")
        self.runner_url = config.get(
            "runner_url",
            "https://storage.googleapis.com/dstack-tpu-artifacts/dstack-tpu-runner",
        )
        self.gateway_wheel_url = config.get(
            "gateway_wheel_url",
            "https://storage.googleapis.com/dstack-tpu-artifacts/dstack_tpu-latest-py3-none-any.whl",
        )
        # TPU VM images ship sshd with root login disabled; "ubuntu" is the
        # stock login user (reference gcp/compute.py:278,342).
        self.vm_username = config.get("vm_username", "ubuntu")
        if transport is None:
            transport = AiohttpTransport(token_provider_from_creds(config.get("creds")))
        self.client = TpuV2Client(self.project_id, transport)

    # -- offers -----------------------------------------------------------------------

    async def get_offers(
        self, requirements: Requirements, regions: Optional[List[str]] = None
    ) -> List[InstanceOffer]:
        if requirements.resources.tpu is None:
            return []  # this backend provisions TPU slices only
        if regions is not None:
            if self.regions:
                regions = [r for r in regions if r in self.regions]
            if not regions:
                return []  # requested regions are all outside this backend's scope
        else:
            regions = self.regions
        offers = catalog.get_catalog_offers(
            backend="gcp", regions=regions, requirements=requirements
        )
        # Only regions with a known TPU zone for the generation are provisionable.
        out = []
        for offer in offers:
            gen = (offer.instance.resources.tpu or None) and offer.instance.resources.tpu.generation
            zones = TPU_ZONES.get(gen or "", {}).get(offer.region)
            if zones:
                offer = offer.model_copy(update={"availability_zones": zones})
                out.append(offer)
        return out

    # -- provisioning -----------------------------------------------------------------

    async def create_slice(
        self,
        offer: InstanceOffer,
        instance_name: str,
        ssh_public_key: str = "",
        startup_script: Optional[str] = None,
        volumes: Optional[List[Volume]] = None,
    ) -> List[JobProvisioningData]:
        spec = self._slice_spec(offer)
        zones = offer.availability_zones or TPU_ZONES.get(spec.generation, {}).get(
            offer.region, []
        )
        if not zones:
            raise NoCapacityError(f"no TPU zone known for {spec.generation} in {offer.region}")
        if volumes:
            # A data disk is zonal: the slice must land in the disks' zone.
            vzones = {
                v.provisioning_data.availability_zone
                for v in volumes
                if v.provisioning_data is not None
            }
            if len(vzones) > 1:
                raise ServerClientError(
                    f"volumes span multiple zones ({sorted(vzones)}); one slice cannot attach them all"
                )
            if vzones:
                zones = [z for z in zones if z in vzones] or sorted(vzones)
        if startup_script is None:
            startup_script = build_startup_script(
                self.runner_url,
                authorized_keys=[ssh_public_key] if ssh_public_key else None,
                login_user=self.vm_username,
            )
        node = {
            "acceleratorType": spec.accelerator_type,
            "runtimeVersion": TPU_GENERATIONS[spec.generation].default_runtime_version,
            "networkConfig": {
                "enableExternalIps": self.allocate_public_ips,
                **({"network": self.network} if self.network else {}),
                **({"subnetwork": self.subnetwork} if self.subnetwork else {}),
            },
            "metadata": {"startup-script": startup_script},
            "labels": {"owner": "dstack-tpu", "dstack_name": instance_name},
            # TPU data disks attach at node-create time and reach every host of
            # the slice (reference gcp/compute.py:1003-1016 AttachedDisk).
            **(
                {
                    "dataDisks": [
                        {
                            "sourceDisk": (
                                f"projects/{self.project_id}/zones/"
                                f"{(v.provisioning_data.availability_zone if v.provisioning_data else '')}"
                                f"/disks/{v.provisioning_data.volume_id if v.provisioning_data else v.name}"
                            ),
                            "mode": "READ_WRITE",
                        }
                        for v in volumes
                    ]
                }
                if volumes
                else {}
            ),
            **(
                {"serviceAccount": {"email": self.service_account}}
                if self.service_account
                else {}
            ),
        }
        for zone in zones:
            body = {
                "tpu": {
                    "nodeSpec": [
                        {
                            "parent": f"projects/{self.project_id}/locations/{zone}",
                            "nodeId": instance_name,
                            "node": node,
                        }
                    ]
                },
                # Native QR tiering: spot slices are preemptible; on-demand is
                # guaranteed-start (fail fast over queue-forever for the scheduler's
                # offer-retry loop to move on quickly).
                **({"spot": {}} if offer.spot else {"guaranteed": {}}),
            }
            try:
                await self.client.create_queued_resource(zone, instance_name, body)
            except GcpApiError as e:
                # 403 is a capacity signal only when the API names a quota/rate
                # reason; a bare 403 is an IAM misconfiguration and must surface
                # as a hard error, not dissolve into "all zones rejected".
                quota_403 = e.status == 403 and e.reason in _CAPACITY_API_REASONS
                if e.status == 429 or quota_403 or (
                    e.status != 403 and e.reason in _CAPACITY_API_REASONS
                ):
                    logger.warning("gcp: zone %s rejected %s: %s", zone, instance_name, e)
                    continue
                raise ComputeError(str(e)) from e
            backend_data = json.dumps({"zone": zone, "qr_id": instance_name, "is_tpu": True})
            return [
                JobProvisioningData(
                    backend="gcp",
                    instance_type=offer.instance,
                    instance_id=instance_name,
                    hostname=None,  # filled by update_provisioning_data once READY
                    internal_ip=None,
                    region=offer.region,
                    availability_zone=zone,
                    price=offer.price,
                    username=self.vm_username,
                    ssh_port=22,
                    # Startup script boots the engine and starts the agent with
                    # --docker auto: image-based jobs run in containers.
                    dockerized=True,
                    backend_data=backend_data,
                    slice_id=instance_name,
                    slice_name=offer.slice_name,
                    worker_num=w,
                    hosts_per_slice=offer.hosts_per_slice,
                )
                for w in range(offer.hosts_per_slice)
            ]
        raise NoCapacityError(f"all zones rejected {spec.accelerator_type} in {offer.region}")

    async def update_provisioning_data(self, jpd: JobProvisioningData) -> JobProvisioningData:
        data = json.loads(jpd.backend_data or "{}")
        zone, qr_id = data.get("zone"), data.get("qr_id", jpd.instance_id)
        if not zone:
            return jpd
        try:
            qr = await self.client.get_queued_resource(zone, qr_id)
        except GcpApiError as e:
            if e.status == 404:
                raise ProvisioningError(f"queued resource {qr_id} disappeared") from e
            return jpd  # transient API error; retry next pass
        state = (qr.get("state") or {}).get("state", "")
        if state in _QR_FAILED:
            detail = json.dumps((qr.get("state") or {}).get("stateInitiator", ""))
            raise NoCapacityError(f"queued resource {qr_id} state={state} {detail}")
        if state in _QR_PENDING:
            return jpd
        # ACTIVE: the node exists; resolve this worker's endpoint.
        try:
            node = await self.client.get_node(zone, qr_id)
        except GcpApiError:
            return jpd
        if node.get("state") == "PREEMPTED":
            raise ProvisioningError(f"slice {qr_id} was preempted")
        if node.get("state") != "READY":
            return jpd
        endpoints = node.get("networkEndpoints", [])
        if jpd.worker_num >= len(endpoints):
            raise ProvisioningError(
                f"slice {qr_id}: worker {jpd.worker_num} missing from "
                f"{len(endpoints)} network endpoints"
            )
        ep = endpoints[jpd.worker_num]
        internal = ep.get("ipAddress")
        external = (ep.get("accessConfig") or {}).get("externalIp")
        hostname = external if (self.allocate_public_ips and external) else internal
        return jpd.model_copy(update={"hostname": hostname, "internal_ip": internal})

    async def terminate_slice(
        self, slice_id: str, region: str, backend_data: Optional[str] = None
    ) -> None:
        data = json.loads(backend_data or "{}")
        qr_id = data.get("qr_id", slice_id)
        zone = data.get("zone")
        if zone:
            zones = [zone]
        else:
            # backend_data lost: sweep every zone of the region across all
            # generations — guessing one zone and treating its 404 as "already
            # gone" would leak a billed slice sitting in another zone forever.
            zones = sorted(
                {
                    z
                    for regions in TPU_ZONES.values()
                    for z in regions.get(region, [])
                }
            )
        if not zones:
            logger.warning("gcp: cannot resolve zone to terminate %s in %s", slice_id, region)
            return
        not_found = 0
        for z in zones:
            try:
                # force=True tears the node down with the queued resource in one call.
                await self.client.delete_queued_resource(z, qr_id, force=True)
            except GcpApiError as e:
                if e.status == 404:
                    not_found += 1
                    continue
                raise ComputeError(str(e)) from e
        if not_found == len(zones) and len(zones) > 1:
            logger.info("gcp: %s not found in any zone of %s (already gone)", qr_id, region)

    # -- volumes (TPU data disks; reference gcp/compute.py:1003-1016) -----------------

    def _volume_zone(self, volume: Volume) -> str:
        conf = volume.configuration
        if conf.availability_zone:
            return conf.availability_zone
        zones = sorted(
            {z for regions in TPU_ZONES.values() for z in regions.get(conf.region, [])}
        )
        if not zones:
            raise ComputeError(f"no TPU zone known for region {conf.region}")
        return zones[0]

    async def create_volume(self, volume: Volume) -> VolumeProvisioningData:
        zone = self._volume_zone(volume)
        size_gb = int(volume.configuration.size or 100)
        try:
            await self.client.create_disk(zone, volume.name, size_gb)
        except GcpApiError as e:
            raise ComputeError(f"creating disk {volume.name}: {e}") from e
        return VolumeProvisioningData(
            backend="gcp",
            volume_id=volume.name,
            size_gb=size_gb,
            availability_zone=zone,
            # pd-balanced list price; the control plane only needs an estimate.
            price=size_gb * 0.1 / 730.0,
            backend_data=json.dumps({"zone": zone}),
        )

    async def register_volume(self, volume: Volume) -> VolumeProvisioningData:
        zone = self._volume_zone(volume)
        try:
            disk = await self.client.get_disk(zone, volume.configuration.volume_id)
        except GcpApiError as e:
            raise ComputeError(f"disk {volume.configuration.volume_id} not found: {e}") from e
        size_gb = int(disk.get("sizeGb") or 0)
        return VolumeProvisioningData(
            backend="gcp",
            volume_id=volume.configuration.volume_id,
            size_gb=size_gb,
            availability_zone=zone,
            price=size_gb * 0.1 / 730.0,
            backend_data=json.dumps({"zone": zone}),
        )

    async def delete_volume(self, volume: Volume) -> None:
        pd = volume.provisioning_data
        zone = pd.availability_zone if pd else self._volume_zone(volume)
        try:
            await self.client.delete_disk(zone, pd.volume_id if pd else volume.name)
        except GcpApiError as e:
            if e.status != 404:
                raise ComputeError(f"deleting disk {volume.name}: {e}") from e

    # -- gateway (ingress appliance VM; reference gateways run on e2-medium VMs) ------

    GATEWAY_PORT = 8000

    async def create_gateway(self, configuration, token: str):
        from dstack_tpu.core.models.gateways import GatewayProvisioningData

        conf = configuration
        zone = self._region_zone(conf.region)
        name = f"dstack-gw-{uuid.uuid4().hex[:8]}"
        # The appliance is pure python+aiohttp: install the wheel and run the
        # module (gateway/app.py). gateway_wheel_url mirrors runner_url.
        startup = f"""#!/bin/bash
set -x
apt-get update -qq && apt-get install -y -qq python3-pip || true
pip3 install --no-input '{self.gateway_wheel_url}' aiohttp pydantic || true
cat > /etc/systemd/system/dstack-tpu-gateway.service <<'UNIT'
[Unit]
Description=dstack-tpu gateway appliance
After=network-online.target
[Service]
ExecStart=/usr/bin/python3 -m dstack_tpu.gateway --port {self.GATEWAY_PORT} --token {token}
Restart=always
RestartSec=2
[Install]
WantedBy=multi-user.target
UNIT
systemctl daemon-reload
systemctl enable --now dstack-tpu-gateway.service
"""
        body = {
            "name": name,
            "machineType": f"zones/{zone}/machineTypes/e2-small",
            "disks": [
                {
                    "boot": True,
                    "autoDelete": True,
                    "initializeParams": {
                        "sourceImage": "projects/debian-cloud/global/images/family/debian-12",
                        "diskSizeGb": "20",
                    },
                }
            ],
            "networkInterfaces": [
                {
                    **({"network": self.network} if self.network else {"network": "global/networks/default"}),
                    **({"subnetwork": self.subnetwork} if self.subnetwork else {}),
                    **(
                        {"accessConfigs": [{"type": "ONE_TO_ONE_NAT", "name": "External NAT"}]}
                        if conf.public_ip
                        else {}
                    ),
                }
            ],
            "metadata": {"items": [{"key": "startup-script", "value": startup}]},
            "labels": {"owner": "dstack-tpu", "dstack_gateway": "true"},
        }
        try:
            await self.client.insert_instance(zone, body)
            info = await self.client.get_instance(zone, name)
        except GcpApiError as e:
            raise ComputeError(f"creating gateway VM: {e}") from e
        nic = (info.get("networkInterfaces") or [{}])[0]
        access = (nic.get("accessConfigs") or [{}])[0]
        ip = access.get("natIP") or nic.get("networkIP")
        return GatewayProvisioningData(
            instance_id=name,
            ip_address=ip,
            region=conf.region,
            availability_zone=zone,
            backend_data=json.dumps({"zone": zone, "port": self.GATEWAY_PORT}),
        )

    async def terminate_gateway(self, instance_id: str, region: str, backend_data=None) -> None:
        zone = None
        if backend_data:
            try:
                zone = json.loads(backend_data).get("zone")
            except ValueError:
                pass
        zone = zone or self._region_zone(region)
        try:
            await self.client.delete_instance(zone, instance_id)
        except GcpApiError as e:
            if e.status != 404:
                raise ComputeError(str(e)) from e

    def _region_zone(self, region: str) -> str:
        zones = sorted(
            {z for regions in TPU_ZONES.values() for z in regions.get(region, [])}
        )
        return zones[0] if zones else f"{region}-a"

    @staticmethod
    def _slice_spec(offer: InstanceOffer) -> TpuSliceSpec:
        tpu = offer.instance.resources.tpu
        if tpu is None or not tpu.generation:
            raise ServerClientError(f"offer {offer.instance.name} carries no TPU slice")
        return TpuSliceSpec(generation=tpu.generation, chips=tpu.chips)
