"""Thin async REST client for the Cloud TPU v2 API (tpu.googleapis.com).

Parity: the reference drives ``google.cloud.tpu_v2.TpuClient`` (gcp/compute.py:98) but
only ``nodes.create`` (single-host slices, ``_is_single_host_tpu`` gcp/compute.py:983-999).
This client speaks to BOTH surfaces and is built around **queued resources**, the API
that provisions multi-host slices atomically — the headline extension over the
reference (SURVEY §7.5).

Transport is injectable: production uses aiohttp with a TokenProvider; tests inject a
``FakeTransport`` that scripts responses, so the full provisioning FSM is exercised
with zero network (SURVEY §4 fake-Compute strategy, applied one level deeper).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

from dstack_tpu.backends.gcp.auth import TokenProvider
from dstack_tpu.core.errors import BackendError

API_ROOT = "https://tpu.googleapis.com/v2"
COMPUTE_ROOT = "https://compute.googleapis.com/compute/v1"


class GcpApiError(BackendError):
    def __init__(self, status: int, message: str, reason: str = ""):
        super().__init__(f"TPU API {status}: {message}")
        self.status = status
        self.message = message
        self.reason = reason


class Transport:
    """request() returns the decoded JSON body or raises GcpApiError."""

    async def request(
        self, method: str, url: str, body: Optional[dict] = None, params: Optional[dict] = None
    ) -> Any:
        raise NotImplementedError


class AiohttpTransport(Transport):
    def __init__(self, token_provider: TokenProvider):
        self._tokens = token_provider

    async def request(self, method, url, body=None, params=None):
        import aiohttp

        token = await self._tokens.get_token()
        try:
            async with aiohttp.ClientSession() as session:
                async with session.request(
                    method,
                    url,
                    json=body,
                    params=params,
                    headers={"Authorization": f"Bearer {token}"},
                    timeout=aiohttp.ClientTimeout(total=30),
                ) as resp:
                    text = await resp.text()
                    data = json.loads(text) if text else {}
                    if resp.status >= 400:
                        err = data.get("error", {}) if isinstance(data, dict) else {}
                        raise GcpApiError(
                            resp.status,
                            err.get("message", text[:300]),
                            err.get("status", ""),
                        )
                    return data
        except aiohttp.ClientError as e:
            raise GcpApiError(0, f"transport error: {e}") from e


class TpuV2Client:
    """Queued-resource and node operations scoped to one project."""

    def __init__(self, project_id: str, transport: Transport):
        self.project_id = project_id
        self._t = transport

    def _parent(self, zone: str) -> str:
        return f"projects/{self.project_id}/locations/{zone}"

    # -- queued resources (multi-host-capable provisioning; reference lacks these) ----

    async def create_queued_resource(
        self, zone: str, qr_id: str, body: Dict[str, Any]
    ) -> dict:
        return await self._t.request(
            "POST",
            f"{API_ROOT}/{self._parent(zone)}/queuedResources",
            body=body,
            params={"queuedResourceId": qr_id},
        )

    async def get_queued_resource(self, zone: str, qr_id: str) -> dict:
        return await self._t.request(
            "GET", f"{API_ROOT}/{self._parent(zone)}/queuedResources/{qr_id}"
        )

    async def delete_queued_resource(self, zone: str, qr_id: str, force: bool = True) -> dict:
        return await self._t.request(
            "DELETE",
            f"{API_ROOT}/{self._parent(zone)}/queuedResources/{qr_id}",
            params={"force": "true"} if force else None,
        )

    # -- nodes ------------------------------------------------------------------------

    async def get_node(self, zone: str, node_id: str) -> dict:
        return await self._t.request(
            "GET", f"{API_ROOT}/{self._parent(zone)}/nodes/{node_id}"
        )

    async def delete_node(self, zone: str, node_id: str) -> dict:
        return await self._t.request(
            "DELETE", f"{API_ROOT}/{self._parent(zone)}/nodes/{node_id}"
        )

    async def list_accelerator_types(self, zone: str) -> dict:
        return await self._t.request(
            "GET", f"{API_ROOT}/{self._parent(zone)}/acceleratorTypes"
        )

    # -- persistent disks (TPU data volumes; compute API, not the TPU API) ------------

    def _disk_url(self, zone: str, name: str = "") -> str:
        base = f"{COMPUTE_ROOT}/projects/{self.project_id}/zones/{zone}/disks"
        return f"{base}/{name}" if name else base

    # -- GCE instances (gateway appliance VMs) ----------------------------------------

    def _instance_url(self, zone: str, name: str = "") -> str:
        base = f"{COMPUTE_ROOT}/projects/{self.project_id}/zones/{zone}/instances"
        return f"{base}/{name}" if name else base

    async def insert_instance(self, zone: str, body: Dict[str, Any]) -> dict:
        return await self._t.request("POST", self._instance_url(zone), body=body)

    async def get_instance(self, zone: str, name: str) -> dict:
        return await self._t.request("GET", self._instance_url(zone, name))

    async def delete_instance(self, zone: str, name: str) -> dict:
        return await self._t.request("DELETE", self._instance_url(zone, name))

    async def create_disk(
        self, zone: str, name: str, size_gb: int, disk_type: str = "pd-balanced"
    ) -> dict:
        return await self._t.request(
            "POST",
            self._disk_url(zone),
            body={
                "name": name,
                "sizeGb": str(size_gb),
                "type": f"projects/{self.project_id}/zones/{zone}/diskTypes/{disk_type}",
                "labels": {"owner": "dstack-tpu"},
            },
        )

    async def get_disk(self, zone: str, name: str) -> dict:
        return await self._t.request("GET", self._disk_url(zone, name))

    async def delete_disk(self, zone: str, name: str) -> dict:
        return await self._t.request("DELETE", self._disk_url(zone, name))
