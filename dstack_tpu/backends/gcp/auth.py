"""GCP OAuth2 access-token providers, SDK-free.

Parity: the reference authenticates via google-cloud-* client libraries
(core/backends/gcp/auth.py); this build talks REST directly, so auth is a small
token-provider hierarchy:

- ``StaticTokenProvider`` — user-supplied OAuth token (also what tests inject).
- ``MetadataTokenProvider`` — GCE/TPU-VM metadata server (the zero-config path when the
  control plane itself runs on GCP).
- ``ServiceAccountTokenProvider`` — service-account JSON key: RS256-signed JWT grant
  against the oauth2 token endpoint (RFC 7523), signed via the openssl-CLI shim
  (gateway/minicrypto.py — no ``cryptography`` wheel needed).
"""

from __future__ import annotations

import base64
import json
import time
from typing import Optional

from dstack_tpu.core.errors import BackendError

SCOPE = "https://www.googleapis.com/auth/cloud-platform"
TOKEN_URL = "https://oauth2.googleapis.com/token"
METADATA_TOKEN_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/service-accounts/default/token"
)


class AuthError(BackendError):
    """Credential failure; a BackendError so the scheduler's per-offer handling treats
    it as that backend failing, not as a crash of the whole scheduling pass."""


class TokenProvider:
    async def get_token(self) -> str:
        raise NotImplementedError


class StaticTokenProvider(TokenProvider):
    def __init__(self, token: str):
        self._token = token

    async def get_token(self) -> str:
        return self._token


class MetadataTokenProvider(TokenProvider):
    """Fetch tokens from the GCE metadata server (cached until near expiry)."""

    def __init__(self) -> None:
        self._token: Optional[str] = None
        self._expires_at: float = 0.0

    async def get_token(self) -> str:
        if self._token is not None and time.time() < self._expires_at - 60:
            return self._token
        import aiohttp

        try:
            async with aiohttp.ClientSession() as session:
                async with session.get(
                    METADATA_TOKEN_URL,
                    headers={"Metadata-Flavor": "Google"},
                    timeout=aiohttp.ClientTimeout(total=5),
                ) as resp:
                    if resp.status != 200:
                        raise AuthError(f"metadata server returned {resp.status}")
                    data = await resp.json()
        except aiohttp.ClientError as e:
            raise AuthError(f"metadata server unreachable: {e}") from e
        self._token = data["access_token"]
        self._expires_at = time.time() + float(data.get("expires_in", 3600))
        return self._token


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def sign_jwt_rs256(claims: dict, private_key_pem: str) -> str:
    """Build a compact RS256 JWT (header.claims.signature) for the OAuth JWT
    grant. Signing goes through the gateway's openssl-CLI crypto shim
    (gateway/minicrypto.py) — the same zero-python-dependency replacement for
    the ``cryptography`` wheel the TLS stack uses."""
    from dstack_tpu.gateway import minicrypto

    header = {"alg": "RS256", "typ": "JWT"}
    signing_input = (
        _b64url(json.dumps(header, separators=(",", ":")).encode())
        + "."
        + _b64url(json.dumps(claims, separators=(",", ":")).encode())
    )
    signature = minicrypto.rsa_sign_sha256(private_key_pem, signing_input.encode())
    return signing_input + "." + _b64url(signature)


class ServiceAccountTokenProvider(TokenProvider):
    """OAuth2 JWT-bearer grant from a service-account JSON key dict."""

    def __init__(self, sa_key: dict):
        if "client_email" not in sa_key or "private_key" not in sa_key:
            raise AuthError("service account key must contain client_email and private_key")
        self._key = sa_key
        self._token: Optional[str] = None
        self._expires_at: float = 0.0

    async def get_token(self) -> str:
        if self._token is not None and time.time() < self._expires_at - 60:
            return self._token
        now = int(time.time())
        assertion = sign_jwt_rs256(
            {
                "iss": self._key["client_email"],
                "scope": SCOPE,
                "aud": self._key.get("token_uri", TOKEN_URL),
                "iat": now,
                "exp": now + 3600,
            },
            self._key["private_key"],
        )
        import aiohttp

        try:
            async with aiohttp.ClientSession() as session:
                async with session.post(
                    self._key.get("token_uri", TOKEN_URL),
                    data={
                        "grant_type": "urn:ietf:params:oauth:grant-type:jwt-bearer",
                        "assertion": assertion,
                    },
                    timeout=aiohttp.ClientTimeout(total=10),
                ) as resp:
                    data = await resp.json()
                    if resp.status != 200:
                        raise AuthError(f"token exchange failed: {resp.status} {data}")
        except aiohttp.ClientError as e:
            raise AuthError(f"token endpoint unreachable: {e}") from e
        self._token = data["access_token"]
        self._expires_at = time.time() + float(data.get("expires_in", 3600))
        return self._token


def token_provider_from_creds(creds: Optional[dict]) -> TokenProvider:
    """creds: {"token": ...} | {"type": "service_account", ...key...} | None (metadata)."""
    if creds:
        if "token" in creds:
            return StaticTokenProvider(creds["token"])
        if creds.get("type") == "service_account" or "private_key" in creds:
            return ServiceAccountTokenProvider(creds)
        if "data" in creds:  # inline key file content as a JSON string
            return ServiceAccountTokenProvider(json.loads(creds["data"]))
        raise AuthError(f"unrecognized GCP creds shape: keys={sorted(creds)}")
    return MetadataTokenProvider()
