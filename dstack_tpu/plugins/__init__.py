"""Public plugin API: policy hooks applied on `apply` (parity: reference
dstack/plugins/_base.py Plugin/ApplyPolicy).

A plugin ships as an importable class; the server loads it from
``plugins:`` in config.yml (or DSTACK_TPU_PLUGINS, comma-separated) as
``package.module:ClassName`` entries — no packaging-entrypoint machinery
required, which also keeps plugin loading explicit and auditable.
"""

from __future__ import annotations

from typing import List

from dstack_tpu.core.models.fleets import FleetSpec
from dstack_tpu.core.models.runs import RunSpec


class ApplyPolicy:
    """Modify or reject specs on apply. Raise ValueError to reject; mutate and
    return the spec to change it. Called for both the plan and the final apply
    (always with the original spec)."""

    def on_apply(self, user: str, project: str, spec):
        if isinstance(spec, RunSpec):
            return self.on_run_apply(user=user, project=project, spec=spec)
        if isinstance(spec, FleetSpec):
            return self.on_fleet_apply(user=user, project=project, spec=spec)
        return spec

    def on_run_apply(self, user: str, project: str, spec: RunSpec) -> RunSpec:
        return spec

    def on_fleet_apply(self, user: str, project: str, spec: FleetSpec) -> FleetSpec:
        return spec


class Plugin:
    """Subclass and expose policies via get_apply_policies()."""

    def get_apply_policies(self) -> List[ApplyPolicy]:
        return []
