"""A self-contained, stdlib-only web IDE for dev environments.

Parity: the reference installs an IDE backend at dev-env start
(ref server/services/jobs/configurators/dev.py:35 `ide.get_install_commands()`
downloads openvscode-server). That needs egress at job start; TPU pods are
often air-gapped, so this module is the always-available tier of the IDE
chain the dev-env configurator builds (code-server -> openvscode-server ->
THIS -> bare file listing): a real editor — file tree, open, edit, save,
create — served by ``python3 -m dstack_tpu.ide`` with zero dependencies
beyond the interpreter that is already in every supported image.

Binds 127.0.0.1 only: it is reached through the attach bridge / SSH tunnel,
the same trust model as the reference's `code-server --auth none`.
"""

from __future__ import annotations

import argparse
import json
import os
import posixpath
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

MAX_FILE_BYTES = 2 * 1024 * 1024  # editor is for source files, not datasets
SKIP_DIRS = {".git", "__pycache__", ".venv", "node_modules", ".pytest_cache"}

PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>dstack-tpu IDE</title>
<style>
  :root { --bg:#1e1e24; --panel:#26262e; --fg:#d8d8e0; --accent:#7aa2f7; --dim:#8a8a96; }
  * { box-sizing: border-box; }
  body { margin:0; display:flex; height:100vh; font:13px/1.5 ui-monospace,monospace;
         background:var(--bg); color:var(--fg); }
  #tree { width:260px; overflow:auto; background:var(--panel); padding:8px;
          border-right:1px solid #000; flex-shrink:0; }
  #tree .f { cursor:pointer; padding:1px 4px; border-radius:3px; white-space:nowrap; }
  #tree .f:hover { background:#34343e; }
  #tree .f.open { color:var(--accent); }
  #tree .d { color:var(--dim); padding:1px 4px; white-space:nowrap; }
  #main { flex:1; display:flex; flex-direction:column; min-width:0; }
  #bar { display:flex; gap:8px; align-items:center; padding:6px 10px;
         background:var(--panel); border-bottom:1px solid #000; }
  #path { color:var(--accent); flex:1; overflow:hidden; text-overflow:ellipsis; }
  button { background:#3a3a46; color:var(--fg); border:1px solid #000;
           border-radius:4px; padding:3px 10px; cursor:pointer; font:inherit; }
  button:hover { background:#444452; }
  #ed { flex:1; width:100%; resize:none; border:0; outline:0; padding:10px;
        background:var(--bg); color:var(--fg); font:13px/1.5 ui-monospace,monospace;
        tab-size:4; }
  #status { padding:3px 10px; background:var(--panel); color:var(--dim);
            border-top:1px solid #000; min-height:22px; }
</style></head><body>
<div id="tree"></div>
<div id="main">
  <div id="bar">
    <span id="path">(no file)</span>
    <button id="new">new file</button>
    <button id="save">save</button>
  </div>
  <textarea id="ed" spellcheck="false" placeholder="open a file from the tree"></textarea>
  <div id="status">dstack-tpu IDE</div>
</div>
<script>
let cur = null;
const $ = id => document.getElementById(id);
const status = m => { $("status").textContent = m; };
async function tree() {
  const r = await fetch("api/tree"); const items = await r.json();
  const t = $("tree"); t.innerHTML = "";
  for (const it of items) {
    const div = document.createElement("div");
    div.className = it.dir ? "d" : "f";
    div.style.paddingLeft = (6 + it.depth * 14) + "px";
    div.textContent = (it.dir ? "\\u25b8 " : "") + it.name;
    if (!it.dir) {
      div.dataset.path = it.path;
      div.onclick = () => open(it.path);
    }
    t.appendChild(div);
  }
}
async function open(p) {
  const r = await fetch("api/file?path=" + encodeURIComponent(p));
  if (!r.ok) { status("open failed: " + (await r.text())); return; }
  $("ed").value = await r.text();
  cur = p; $("path").textContent = p;
  document.querySelectorAll("#tree .f").forEach(e =>
    e.classList.toggle("open", e.dataset.path === p));
  status("opened " + p);
}
async function save() {
  if (cur === null) { status("no file open"); return; }
  const r = await fetch("api/file?path=" + encodeURIComponent(cur),
                        { method: "PUT", body: $("ed").value });
  status(r.ok ? "saved " + cur : "save failed: " + (await r.text()));
}
$("save").onclick = save;
$("new").onclick = async () => {
  const p = prompt("new file path (relative to workspace):");
  if (!p) return;
  const r = await fetch("api/file?path=" + encodeURIComponent(p),
                        { method: "PUT", body: "" });
  if (r.ok) { await tree(); await open(p); } else status(await r.text());
};
document.addEventListener("keydown", e => {
  if ((e.ctrlKey || e.metaKey) && e.key === "s") { e.preventDefault(); save(); }
});
tree();
</script></body></html>"""


class IdeHandler(BaseHTTPRequestHandler):
    root: str = "."
    server_version = "dstack-tpu-ide"
    # Host-header allowlist (ADVICE r5): DNS rebinding defeats the Origin==Host
    # CSRF check — a site rebound to 127.0.0.1:<port> sends its own domain in
    # BOTH headers, so they match. The IDE is only ever addressed as localhost
    # through the attach tunnel (the forwarded local port may differ from the
    # bound port, so only the hostname is pinned); any other Host value means a
    # browser was tricked into sending the request here. serve() extends this
    # with a custom --host binding.
    allowed_hosts = frozenset({"127.0.0.1", "localhost", "::1"})

    # -- helpers ----------------------------------------------------------
    def _host_allowed(self) -> bool:
        host = self.headers.get("Host")
        if not host:
            return False  # every real browser sends Host; refuse ambiguity
        try:
            hostname = urllib.parse.urlsplit(f"//{host}").hostname
        except ValueError:
            return False
        return hostname in self.allowed_hosts

    def _send(self, code: int, body: bytes, ctype: str = "text/plain") -> None:
        self.send_response(code)
        self.send_header("Content-Type", f"{ctype}; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.send_header("X-Dstack-IDE", "dstack-tpu")
        self.end_headers()
        self.wfile.write(body)

    def _resolve(self, rel: str) -> str:
        """Reject traversal: the resolved path must stay inside root."""
        rel = posixpath.normpath(urllib.parse.unquote(rel)).lstrip("/")
        if rel.startswith(".."):
            raise PermissionError(rel)
        full = os.path.realpath(os.path.join(self.root, rel))
        root = os.path.realpath(self.root)
        if full != root and not full.startswith(root + os.sep):
            raise PermissionError(rel)
        return full

    def _query(self) -> dict:
        parsed = urllib.parse.urlparse(self.path)
        return dict(urllib.parse.parse_qsl(parsed.query))

    def log_message(self, fmt, *args):  # quiet; job logs carry stdout already
        pass

    # -- routes -----------------------------------------------------------
    def do_GET(self) -> None:
        if not self._host_allowed():
            self._send(403, b"host not allowed")
            return
        route = urllib.parse.urlparse(self.path).path
        if route in ("/", "/index.html"):
            self._send(200, PAGE.encode(), "text/html")
        elif route == "/healthcheck":
            self._send(200, json.dumps({"status": "ok", "ide": "dstack-tpu"}).encode(),
                       "application/json")
        elif route == "/api/tree":
            self._send(200, json.dumps(self._tree()).encode(), "application/json")
        elif route == "/api/file":
            self._get_file()
        else:
            self._send(404, b"not found")

    def do_PUT(self) -> None:
        if not self._host_allowed():
            self._send(403, b"host not allowed")
            return
        if urllib.parse.urlparse(self.path).path != "/api/file":
            self._send(404, b"not found")
            return
        # CSRF guard: browsers attach an Origin header to cross-site writes;
        # a write whose Origin doesn't match the address the IDE is served on
        # comes from another site scripting the user's forwarded port. Our own
        # UI is same-origin, so its Origin (when sent) always matches Host.
        origin = self.headers.get("Origin")
        if origin:
            origin_host = urllib.parse.urlparse(origin).netloc
            if origin_host != (self.headers.get("Host") or ""):
                self._send(403, b"cross-origin write rejected")
                return
        rel = self._query().get("path", "")
        # Content-Length is client input: absent/chunked would silently write
        # an empty file, negative would read to EOF past the size cap.
        if "chunked" in (self.headers.get("Transfer-Encoding") or "").lower():
            self._send(411, b"chunked uploads not supported; send Content-Length")
            return
        try:
            length = int(self.headers.get("Content-Length") or "")
        except ValueError:
            self._send(411, b"missing or invalid Content-Length")
            return
        if length < 0:
            self._send(411, b"missing or invalid Content-Length")
            return
        if length > MAX_FILE_BYTES:
            self._send(413, b"file too large for the editor")
            return
        body = self.rfile.read(length)
        try:
            full = self._resolve(rel)
            os.makedirs(os.path.dirname(full) or ".", exist_ok=True)
            with open(full, "wb") as f:
                f.write(body)
        except PermissionError:
            self._send(403, b"path escapes workspace")
            return
        except OSError as e:
            self._send(500, str(e).encode())
            return
        self._send(200, b"saved")

    # No POST: a cross-site POST with a simple content type skips the CORS
    # preflight that protects PUT, so writes are PUT-only.

    def _get_file(self) -> None:
        rel = self._query().get("path", "")
        try:
            full = self._resolve(rel)
            if not os.path.isfile(full):
                self._send(404, b"no such file")
                return
            if os.path.getsize(full) > MAX_FILE_BYTES:
                self._send(413, b"file too large for the editor")
                return
            with open(full, "rb") as f:
                self._send(200, f.read())
        except PermissionError:
            self._send(403, b"path escapes workspace")

    def _tree(self) -> list:
        items = []

        def walk(dirpath: str, relbase: str, depth: int) -> None:
            try:
                names = sorted(os.listdir(dirpath))
            except OSError:
                return
            dirs = [n for n in names if os.path.isdir(os.path.join(dirpath, n))]
            files = [n for n in names if not os.path.isdir(os.path.join(dirpath, n))]
            for name in dirs:
                if name in SKIP_DIRS or name.startswith("."):
                    continue
                rel = posixpath.join(relbase, name) if relbase else name
                items.append({"name": name, "path": rel, "dir": True, "depth": depth})
                if depth < 6 and len(items) < 2000:
                    walk(os.path.join(dirpath, name), rel, depth + 1)
            for name in files:
                rel = posixpath.join(relbase, name) if relbase else name
                items.append({"name": name, "path": rel, "dir": False, "depth": depth})

        walk(self.root, "", 0)
        return items[:2000]


def serve(port: int, root: str, host: str = "127.0.0.1") -> ThreadingHTTPServer:
    # A non-default binding (e.g. a pod-internal IP) is reached by that name;
    # localhost spellings stay allowed for tunnel access. A wildcard bind is
    # reachable under any address the host owns — there the rebinding defense
    # (a localhost-tunnel concern) cannot enumerate valid names, so the Host
    # check is disabled rather than 403ing every legitimate remote client.
    bound = host.strip("[]")
    if bound in ("", "0.0.0.0", "::"):
        overrides = {"root": root, "_host_allowed": lambda self: True}
    else:
        overrides = {"root": root, "allowed_hosts": IdeHandler.allowed_hosts | {bound}}
    handler = type("BoundIdeHandler", (IdeHandler,), overrides)
    server = ThreadingHTTPServer((host, port), handler)
    return server


def main() -> None:
    parser = argparse.ArgumentParser(prog="dstack-tpu-ide")
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--root", default=".")
    parser.add_argument("--host", default="127.0.0.1")
    args = parser.parse_args()
    server = serve(args.port, args.root, args.host)
    print(f"dstack-tpu IDE on {args.host}:{server.server_address[1]}", flush=True)
    server.serve_forever()


if __name__ == "__main__":
    main()
