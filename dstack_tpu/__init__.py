"""dstack_tpu — a TPU-native AI workload orchestrator.

Capability parity target: dstack (see SURVEY.md). The accelerator atom here is a TPU
pod-slice topology (v5e/v5p/v6e), fleets are slices, and the cluster contract is
JAX/PJRT/MegaScale environment wiring instead of NCCL/MPI.
"""

__version__ = "0.1.0"
