"""Gateway TLS: SNI certificate store + a minimal ACME v2 (RFC 8555) client.

Parity: reference proxy/gateway/services/nginx.py:75-110 — certbot provisions a
certificate per service domain and nginx terminates TLS. TPU re-design: the
aiohttp appliance terminates TLS itself via an SNI callback over a directory of
per-domain certs, and issuance is a small ACME client speaking the REST flow
directly (directory -> nonce -> account -> order -> http-01 -> finalize), the
same SDK-free style as the repo's cloud clients. Crypto primitives (EC keys,
CSR, JWS signatures) come from ``gateway.minicrypto`` — the openssl CLI every
base image already ships — so there is no certbot, no nginx, and no native
Python crypto wheel in the dependency set at all.
"""

from __future__ import annotations

import base64
import datetime
import json
import logging
import os
import ssl
import threading
import urllib.request
from typing import Callable, Dict, Optional, Tuple

from dstack_tpu.gateway import minicrypto

logger = logging.getLogger(__name__)


def _b64u(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


# ---------------------------------------------------------------------------
# Certificate store + SNI


class CertStore:
    """certs_dir/<domain>/{fullchain.pem,privkey.pem}; hands aiohttp one parent
    SSLContext whose sni_callback swaps in the per-domain context."""

    def __init__(self, certs_dir: str) -> None:
        self.certs_dir = certs_dir
        os.makedirs(certs_dir, exist_ok=True)
        self._contexts: Dict[str, ssl.SSLContext] = {}
        self._lock = threading.Lock()
        self._load_all()

    def _domain_dir(self, domain: str) -> str:
        safe = domain.lower().strip(".")
        if "/" in safe or safe.startswith("."):
            raise ValueError(f"bad domain {domain!r}")
        return os.path.join(self.certs_dir, safe)

    def _load_all(self) -> None:
        for name in os.listdir(self.certs_dir):
            if name.startswith("."):  # .placeholder, dotfiles — not domains
                continue
            full = os.path.join(self.certs_dir, name, "fullchain.pem")
            key = os.path.join(self.certs_dir, name, "privkey.pem")
            if os.path.exists(full) and os.path.exists(key):
                try:
                    self._contexts[name] = self._make_ctx(full, key)
                except ssl.SSLError:
                    logger.exception("skipping unloadable cert for %s", name)

    @staticmethod
    def _make_ctx(fullchain: str, privkey: str) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(fullchain, privkey)
        return ctx

    def put(self, domain: str, fullchain_pem: str, privkey_pem: str,
            managed: bool = False) -> None:
        """``managed=True`` marks the cert as ACME-issued (renewable); without
        it the cert is operator-provisioned and the renewal sweep must never
        touch it (the reference's `certificate` passthrough)."""
        d = self._domain_dir(domain)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "fullchain.pem"), "w") as f:
            f.write(fullchain_pem)
        key_path = os.path.join(d, "privkey.pem")
        fd = os.open(key_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(privkey_pem)
        marker = os.path.join(d, "acme-managed")
        if managed:
            with open(marker, "w") as f:
                f.write("issued by the gateway's ACME client\n")
        elif os.path.exists(marker):
            os.unlink(marker)  # operator override takes the domain back
        with self._lock:
            self._contexts[domain.lower()] = self._make_ctx(
                os.path.join(d, "fullchain.pem"), key_path
            )

    def is_managed(self, domain: str) -> bool:
        return os.path.exists(os.path.join(self._domain_dir(domain), "acme-managed"))

    def has(self, domain: str) -> bool:
        return domain.lower() in self._contexts

    def expiry(self, domain: str) -> Optional[datetime.datetime]:
        """not_valid_after of the stored leaf certificate (UTC), or None."""
        path = os.path.join(self._domain_dir(domain), "fullchain.pem")
        try:
            with open(path, "rb") as f:
                pem = f.read()
        except OSError:
            return None
        try:
            return minicrypto.cert_not_after(pem)
        except (minicrypto.CryptoError, ValueError):
            return None

    def domains(self):
        return sorted(self._contexts)

    def server_context(self) -> ssl.SSLContext:
        """Parent context: a self-signed placeholder cert (so non-SNI clients
        still complete a handshake) + the SNI swap into per-domain contexts."""
        placeholder_dir = os.path.join(self.certs_dir, ".placeholder")
        full = os.path.join(placeholder_dir, "fullchain.pem")
        key = os.path.join(placeholder_dir, "privkey.pem")
        if not (os.path.exists(full) and os.path.exists(key)):
            os.makedirs(placeholder_dir, exist_ok=True)
            chain, priv = self_signed_cert("dstack-tpu-gateway.invalid")
            with open(full, "w") as f:
                f.write(chain)
            fd = os.open(key, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
            with os.fdopen(fd, "w") as f:
                f.write(priv)
        parent = self._make_ctx(full, key)

        def sni(ssl_obj, server_name, _ctx):
            if server_name:
                with self._lock:
                    per = self._contexts.get(server_name.lower())
                if per is not None:
                    ssl_obj.context = per
            return None

        parent.sni_callback = sni
        return parent


def self_signed_cert(cn: str, days: int = 3650) -> Tuple[str, str]:
    """(cert_pem, key_pem) — placeholder/test certificates."""
    return minicrypto.self_signed_cert(cn, days=days)


# ---------------------------------------------------------------------------
# ACME v2 client (http-01)


class AcmeError(RuntimeError):
    pass


class AcmeClient:
    """Minimal RFC 8555 client: ES256 account key, http-01 only.

    ``publish(token, key_authorization)`` / ``unpublish(token)`` hook the
    challenge body into whatever serves
    ``/.well-known/acme-challenge/{token}`` on port 80 (the gateway app).
    """

    def __init__(
        self,
        directory_url: str,
        publish: Callable[[str, str], None],
        unpublish: Callable[[str], None],
        contact: Optional[str] = None,
        timeout: float = 10.0,
        account_path: Optional[str] = None,
        poll_interval: float = 0.5,
        poll_tries: int = 30,
    ) -> None:
        self.directory_url = directory_url
        self.publish = publish
        self.unpublish = unpublish
        self.contact = contact
        self.timeout = timeout
        self.account_path = account_path
        self.poll_interval = poll_interval
        self.poll_tries = poll_tries
        self.account_key: Optional[str] = None  # P-256 private key, PKCS#8 PEM
        self.kid: Optional[str] = None
        self._nonce: Optional[str] = None
        self._dir: Optional[dict] = None
        # obtain() mutates _nonce/kid/_dir; issuances for different domains may
        # be kicked off from concurrent registrations, so serialize them.
        self._op_lock = threading.Lock()
        if account_path and os.path.exists(account_path):
            self._load_account()
        if self.account_key is None:
            self.account_key = minicrypto.generate_ec_key_pem()

    def _load_account(self) -> None:
        try:
            with open(self.account_path) as f:
                data = json.load(f)
            if data.get("directory_url") != self.directory_url:
                # The kid belongs to a different CA (e.g. staging -> prod
                # switch); replaying it gets accountDoesNotExist forever.
                logger.info("ACME directory changed (%s -> %s); registering anew",
                            data.get("directory_url"), self.directory_url)
                return
            key_pem = data["key_pem"]
            minicrypto.pubkey_xy(key_pem)  # validates the stored key parses
            self.account_key = key_pem
            self.kid = data.get("kid")
        except (OSError, ValueError, KeyError, TypeError, minicrypto.CryptoError):
            logger.exception("unreadable ACME account file %s; re-registering",
                             self.account_path)
            self.account_key = None
            self.kid = None

    def _save_account(self) -> None:
        """Persist the account key + kid so restarts reuse the registration
        (RFC 8555 accounts are long-lived; re-registering per process hits CA
        rate limits and loses authorization caching)."""
        if not self.account_path:
            return
        key_pem = self.account_key
        fd = os.open(self.account_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump({"key_pem": key_pem, "kid": self.kid,
                       "directory_url": self.directory_url}, f)

    # -- low-level JOSE/HTTP plumbing ------------------------------------

    def _jwk(self) -> dict:
        x, y = minicrypto.pubkey_xy(self.account_key)
        return {
            "crv": "P-256",
            "kty": "EC",
            "x": _b64u(x.to_bytes(32, "big")),
            "y": _b64u(y.to_bytes(32, "big")),
        }

    def thumbprint(self) -> str:
        import hashlib

        canonical = json.dumps(self._jwk(), separators=(",", ":"), sort_keys=True)
        return _b64u(hashlib.sha256(canonical.encode()).digest())

    def _sign(self, protected_b64: str, payload_b64: str) -> str:
        raw = minicrypto.ecdsa_sign_p256(
            self.account_key, f"{protected_b64}.{payload_b64}".encode()
        )
        return _b64u(raw)

    def _http(self, method: str, url: str, data: Optional[bytes] = None,
              headers: Optional[dict] = None) -> Tuple[int, dict, bytes]:
        req = urllib.request.Request(url, data=data, headers=headers or {}, method=method)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                hdrs = dict(resp.headers)
                body = resp.read()
                status = resp.status
        except urllib.error.HTTPError as e:
            hdrs, body, status = dict(e.headers), e.read(), e.code
        nonce = next((v for k, v in hdrs.items() if k.lower() == "replay-nonce"), None)
        if nonce:
            self._nonce = nonce
        return status, hdrs, body

    def _directory(self) -> dict:
        if self._dir is None:
            status, _, body = self._http("GET", self.directory_url)
            if status != 200:
                raise AcmeError(f"ACME directory fetch failed: HTTP {status}")
            self._dir = json.loads(body)
        return self._dir

    def _fresh_nonce(self) -> str:
        if self._nonce is None:
            self._http("HEAD", self._directory()["newNonce"])
        if self._nonce is None:
            raise AcmeError("ACME server returned no Replay-Nonce")
        nonce, self._nonce = self._nonce, None
        return nonce

    def _post(self, url: str, payload: Optional[dict]) -> Tuple[int, dict, bytes]:
        # RFC 8555 §6.5: on urn:ietf:params:acme:error:badNonce the server
        # includes a fresh Replay-Nonce and the client SHOULD retry the request
        # with it (_http already captured it). Last attempt returns whatever
        # the server said.
        last_attempt = 2
        for attempt in range(last_attempt + 1):
            protected: dict = {"alg": "ES256", "nonce": self._fresh_nonce(), "url": url}
            if self.kid:
                protected["kid"] = self.kid
            else:
                protected["jwk"] = self._jwk()
            protected_b64 = _b64u(json.dumps(protected).encode())
            payload_b64 = "" if payload is None else _b64u(json.dumps(payload).encode())
            jws = {
                "protected": protected_b64,
                "payload": payload_b64,
                "signature": self._sign(protected_b64, payload_b64),
            }
            status, hdrs, body = self._http(
                "POST", url, json.dumps(jws).encode(),
                {"Content-Type": "application/jose+json"},
            )
            if status == 400 and attempt < last_attempt:
                try:
                    err_type = json.loads(body).get("type")
                except ValueError:
                    err_type = None
                if err_type == "urn:ietf:params:acme:error:badNonce":
                    logger.info("badNonce from %s; retrying with fresh nonce", url)
                    continue
            return status, hdrs, body

    # -- the issuance flow ------------------------------------------------

    def obtain(self, domain: str) -> Tuple[str, str]:
        """Blocking issuance: returns (fullchain_pem, privkey_pem)."""
        with self._op_lock:
            return self._obtain_locked(domain)

    def _obtain_locked(self, domain: str) -> Tuple[str, str]:
        import time

        d = self._directory()
        # Account (idempotent: onlyReturnExisting is unnecessary, we keep kid).
        if self.kid is None:
            payload = {"termsOfServiceAgreed": True}
            if self.contact:
                payload["contact"] = [f"mailto:{self.contact}"]
            status, hdrs, body = self._post(d["newAccount"], payload)
            if status not in (200, 201):
                raise AcmeError(f"newAccount failed: HTTP {status}: {body[:200]!r}")
            self.kid = next(
                (v for k, v in hdrs.items() if k.lower() == "location"), None
            )
            if not self.kid:
                raise AcmeError("newAccount returned no Location (kid)")
            self._save_account()

        status, hdrs, body = self._post(
            d["newOrder"], {"identifiers": [{"type": "dns", "value": domain}]}
        )
        if status not in (200, 201):
            raise AcmeError(f"newOrder failed: HTTP {status}: {body[:200]!r}")
        order = json.loads(body)
        order_url = next((v for k, v in hdrs.items() if k.lower() == "location"), "")

        published = []
        try:
            for authz_url in order["authorizations"]:
                status, _, body = self._post(authz_url, None)  # POST-as-GET
                if status != 200:
                    raise AcmeError(f"authz fetch failed: HTTP {status}")
                authz = json.loads(body)
                challenge = next(
                    (c for c in authz["challenges"] if c["type"] == "http-01"), None
                )
                if challenge is None:
                    raise AcmeError("server offered no http-01 challenge")
                key_auth = f"{challenge['token']}.{self.thumbprint()}"
                self.publish(challenge["token"], key_auth)
                published.append(challenge["token"])
                status, _, body = self._post(challenge["url"], {})
                if status not in (200, 202):
                    raise AcmeError(f"challenge answer failed: HTTP {status}")
                # Poll the authorization until valid.
                for _ in range(self.poll_tries):
                    status, _, body = self._post(authz_url, None)
                    state = json.loads(body).get("status")
                    if state == "valid":
                        break
                    if state in ("invalid", "revoked", "expired"):
                        raise AcmeError(f"authorization {state} for {domain}")
                    time.sleep(self.poll_interval)
                else:
                    raise AcmeError(f"authorization pending past deadline for {domain}")

            cert_key = minicrypto.generate_ec_key_pem()
            csr_b64 = _b64u(minicrypto.make_csr_der(cert_key, domain))
            status, _, body = self._post(order["finalize"], {"csr": csr_b64})
            if status != 200:
                raise AcmeError(f"finalize failed: HTTP {status}: {body[:200]!r}")

            cert_url = json.loads(body).get("certificate")
            for _ in range(self.poll_tries):
                if cert_url:
                    break
                status, _, body = self._post(order_url, None)
                data = json.loads(body)
                if data.get("status") == "invalid":
                    raise AcmeError("order invalid after finalize")
                cert_url = data.get("certificate")
                time.sleep(self.poll_interval)
            if not cert_url:
                raise AcmeError("order never reached valid/certificate")
            status, _, body = self._post(cert_url, None)
            if status != 200:
                raise AcmeError(f"certificate download failed: HTTP {status}")
            return body.decode(), cert_key
        finally:
            for token in published:
                self.unpublish(token)
