"""Glue between the gateway app and gateway.tls: challenge hosting + issuance.

One manager per appliance: owns the CertStore (SNI) and, when an ACME directory
is configured, an AcmeClient whose http-01 bodies the HTTP app serves from
``/.well-known/acme-challenge/``. Domains with operator-provisioned certs in
the store never trigger issuance (the reference's `certificate` passthrough).

Renewal parity: the reference's certbot both issues AND renews
(ref proxy/gateway/services/nginx.py:75-110 + certbot's systemd timer); here
``check_renewals`` re-issues any cert inside ``renew_before_days`` of expiry
and ``renew_loop`` runs it periodically (started by gateway.app.serve)."""

from __future__ import annotations

import asyncio
import datetime
import logging
import os
import ssl
import threading
from typing import Dict, List, Optional

from dstack_tpu.gateway.tls import AcmeClient, CertStore

logger = logging.getLogger(__name__)


class TlsManager:
    def __init__(
        self,
        certs_dir: str,
        acme_directory: Optional[str] = None,
        acme_contact: Optional[str] = None,
        renew_before_days: float = 30.0,
        renew_check_interval: float = 3600.0,
    ) -> None:
        self.store = CertStore(certs_dir)
        self._challenges: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._inflight: set = set()
        # Strong refs: the event loop only weak-refs tasks, so a bare
        # create_task() result can be collected mid-issuance.
        self._tasks: set = set()
        self.renew_before = datetime.timedelta(days=renew_before_days)
        self.renew_check_interval = renew_check_interval
        self.acme: Optional[AcmeClient] = None
        if acme_directory:
            self.acme = AcmeClient(
                acme_directory,
                publish=self._publish,
                unpublish=self._unpublish,
                contact=acme_contact,
                account_path=os.path.join(certs_dir, "acme_account.json"),
            )

    # http-01 plumbing -----------------------------------------------------
    def _publish(self, token: str, key_auth: str) -> None:
        with self._lock:
            self._challenges[token] = key_auth

    def _unpublish(self, token: str) -> None:
        with self._lock:
            self._challenges.pop(token, None)

    def challenge_body(self, token: str) -> Optional[str]:
        with self._lock:
            return self._challenges.get(token)

    # issuance -------------------------------------------------------------
    def _spawn(self, coro) -> asyncio.Task:
        task = asyncio.get_running_loop().create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    def ensure_async(self, domain: str, force: bool = False) -> None:
        """Fire-and-forget: issue the domain's cert unless present/in flight.
        ``force=True`` re-issues over an existing cert (renewal)."""
        domain = domain.lower()
        if self.acme is None or (not force and self.store.has(domain)):
            return
        with self._lock:
            if domain in self._inflight:
                return
            self._inflight.add(domain)

        async def _run() -> None:
            try:
                chain, key = await asyncio.to_thread(self.acme.obtain, domain)
                self.store.put(domain, chain, key, managed=True)
                logger.info("obtained certificate for %s", domain)
            except Exception:
                logger.exception("ACME issuance failed for %s", domain)
            finally:
                with self._lock:
                    self._inflight.discard(domain)

        self._spawn(_run())

    async def ensure(self, domain: str) -> bool:
        """Blocking variant (tests / eager callers): True when a cert exists."""
        domain = domain.lower()
        if self.store.has(domain):
            return True
        if self.acme is None:
            return False
        try:
            chain, key = await asyncio.to_thread(self.acme.obtain, domain)
        except Exception:
            logger.exception("ACME issuance failed for %s", domain)
            return False
        self.store.put(domain, chain, key, managed=True)
        return True

    # renewal --------------------------------------------------------------
    def renewal_due(self, domain: str) -> bool:
        exp = self.store.expiry(domain)
        if exp is None:
            return False
        return exp - datetime.datetime.now(datetime.timezone.utc) < self.renew_before

    def check_renewals(self) -> List[str]:
        """Kick off re-issuance for every ACME-managed cert inside the renewal
        window; returns the domains scheduled (issuance runs in background).
        Operator-provisioned certs (no acme-managed marker) are never touched —
        renewing them would replace a private-CA cert and hammer the CA with
        doomed http-01 attempts."""
        if self.acme is None:
            return []
        due = [
            d for d in self.store.domains()
            if self.store.is_managed(d) and self.renewal_due(d)
        ]
        for domain in due:
            logger.info("certificate for %s expires within %s; renewing",
                        domain, self.renew_before)
            self.ensure_async(domain, force=True)
        return due

    async def renew_loop(self) -> None:
        while True:
            await asyncio.sleep(self.renew_check_interval)
            try:
                self.check_renewals()
            except Exception:
                logger.exception("renewal sweep failed")

    def start_renewal(self) -> None:
        """Start the periodic renewal sweep (call from a running loop)."""
        if self.acme is not None:
            self._spawn(self.renew_loop())

    def server_context(self) -> ssl.SSLContext:
        return self.store.server_context()
