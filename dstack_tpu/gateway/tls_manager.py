"""Glue between the gateway app and gateway.tls: challenge hosting + issuance.

One manager per appliance: owns the CertStore (SNI) and, when an ACME directory
is configured, an AcmeClient whose http-01 bodies the HTTP app serves from
``/.well-known/acme-challenge/``. Domains with operator-provisioned certs in
the store never trigger issuance (the reference's `certificate` passthrough)."""

from __future__ import annotations

import asyncio
import logging
import ssl
import threading
from typing import Dict, Optional

from dstack_tpu.gateway.tls import AcmeClient, CertStore

logger = logging.getLogger(__name__)


class TlsManager:
    def __init__(
        self,
        certs_dir: str,
        acme_directory: Optional[str] = None,
        acme_contact: Optional[str] = None,
    ) -> None:
        self.store = CertStore(certs_dir)
        self._challenges: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._inflight: set = set()
        self.acme: Optional[AcmeClient] = None
        if acme_directory:
            self.acme = AcmeClient(
                acme_directory,
                publish=self._publish,
                unpublish=self._unpublish,
                contact=acme_contact,
            )

    # http-01 plumbing -----------------------------------------------------
    def _publish(self, token: str, key_auth: str) -> None:
        with self._lock:
            self._challenges[token] = key_auth

    def _unpublish(self, token: str) -> None:
        with self._lock:
            self._challenges.pop(token, None)

    def challenge_body(self, token: str) -> Optional[str]:
        with self._lock:
            return self._challenges.get(token)

    # issuance -------------------------------------------------------------
    def ensure_async(self, domain: str) -> None:
        """Fire-and-forget: issue the domain's cert unless present/in flight."""
        domain = domain.lower()
        if self.store.has(domain) or self.acme is None:
            return
        with self._lock:
            if domain in self._inflight:
                return
            self._inflight.add(domain)

        async def _run() -> None:
            try:
                chain, key = await asyncio.to_thread(self.acme.obtain, domain)
                self.store.put(domain, chain, key)
                logger.info("obtained certificate for %s", domain)
            except Exception:
                logger.exception("ACME issuance failed for %s", domain)
            finally:
                with self._lock:
                    self._inflight.discard(domain)

        asyncio.get_running_loop().create_task(_run())

    async def ensure(self, domain: str) -> bool:
        """Blocking variant (tests / eager callers): True when a cert exists."""
        domain = domain.lower()
        if self.store.has(domain):
            return True
        if self.acme is None:
            return False
        try:
            chain, key = await asyncio.to_thread(self.acme.obtain, domain)
        except Exception:
            logger.exception("ACME issuance failed for %s", domain)
            return False
        self.store.put(domain, chain, key)
        return True

    def server_context(self) -> ssl.SSLContext:
        return self.store.server_context()
