"""Zero-python-dependency crypto for the gateway's TLS stack, backed by the
``openssl`` CLI.

The ACME client and SNI cert store need exactly five primitives: EC P-256
keygen, ES256 (ECDSA/SHA-256) JWS signatures, CSR generation, self-signed
certs, and certificate field parsing. The ``cryptography`` wheel ships all of
them but is a heavyweight native dependency the serving images don't need for
anything else — while every base image (and every CI host) already carries
the openssl binary. So this module shells out: keys are PEM strings
end-to-end, each call is one short-lived ``openssl`` process, and the only
parsing done in Python is two tiny DER structures (an ECDSA signature's
r/s SEQUENCE and the uncompressed point at the tail of a P-256 SPKI) whose
layouts are fixed by the curve.

Local CA helpers (``sign_csr``) are included for the test harness's fake ACME
CA and for private-CA deployments.
"""

from __future__ import annotations

import datetime
import os
import secrets
import subprocess
import tempfile
from typing import Optional, Tuple

OPENSSL = os.environ.get("DSTACK_TPU_OPENSSL", "openssl")


class CryptoError(RuntimeError):
    pass


def _run(args, input_bytes: Optional[bytes] = None) -> bytes:
    proc = subprocess.run(
        [OPENSSL, *args], input=input_bytes, capture_output=True
    )
    if proc.returncode != 0:
        raise CryptoError(
            f"openssl {' '.join(args[:3])}... failed: "
            f"{proc.stderr.decode(errors='replace')[:300]}"
        )
    return proc.stdout


class _TempFiles:
    """Private scratch dir for key material passed to the CLI (0700 dir,
    0600 files; gone when the operation ends)."""

    def __enter__(self):
        self._dir = tempfile.TemporaryDirectory(prefix="dstack-tpu-crypto-")
        return self

    def __exit__(self, *exc):
        self._dir.cleanup()
        return False

    def write(self, name: str, content) -> str:
        path = os.path.join(self._dir.name, name)
        data = content.encode() if isinstance(content, str) else content
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        return path

    def path(self, name: str) -> str:
        return os.path.join(self._dir.name, name)


# -- keys -------------------------------------------------------------------


def generate_ec_key_pem() -> str:
    """Fresh P-256 private key, PKCS#8 PEM."""
    return _run(
        ["genpkey", "-algorithm", "EC", "-pkeyopt", "ec_paramgen_curve:P-256"]
    ).decode()


def pubkey_xy(key_pem: str) -> Tuple[int, int]:
    """(x, y) of the public point — what an ES256 JWK carries. The DER SPKI
    for P-256 always ends with the 65-byte uncompressed point 04 || X || Y."""
    with _TempFiles() as tf:
        der = _run(["pkey", "-in", tf.write("k.pem", key_pem), "-pubout",
                    "-outform", "DER"])
    point = der[-65:]
    if len(point) != 65 or point[0] != 0x04:
        raise CryptoError("unexpected SPKI layout for P-256 public key")
    return int.from_bytes(point[1:33], "big"), int.from_bytes(point[33:], "big")


def ecdsa_sign_p256(key_pem: str, data: bytes) -> bytes:
    """ES256 signature over `data`, raw 64-byte r||s (JWS format)."""
    with _TempFiles() as tf:
        der = _run(["dgst", "-sha256", "-sign", tf.write("k.pem", key_pem)],
                   input_bytes=data)
    r, s = _parse_ecdsa_der(der)
    return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def _parse_ecdsa_der(sig: bytes) -> Tuple[int, int]:
    """DER ECDSA-Sig-Value: SEQUENCE { INTEGER r, INTEGER s }."""
    if len(sig) < 8 or sig[0] != 0x30:
        raise CryptoError("bad DER signature")
    i = 2
    if sig[1] & 0x80:  # long-form length never happens for P-256 but be safe
        i = 2 + (sig[1] & 0x7F)

    def read_int(i: int) -> Tuple[int, int]:
        if sig[i] != 0x02:
            raise CryptoError("bad DER signature integer")
        n = sig[i + 1]
        start = i + 2
        return int.from_bytes(sig[start:start + n], "big"), start + n

    r, i = read_int(i)
    s, _ = read_int(i)
    return r, s


def generate_rsa_key_pem(bits: int = 2048) -> str:
    """Fresh RSA private key, PKCS#8 PEM (tests / local service accounts)."""
    return _run(
        ["genpkey", "-algorithm", "RSA", "-pkeyopt", f"rsa_keygen_bits:{bits}"]
    ).decode()


def rsa_sign_sha256(key_pem: str, data: bytes) -> bytes:
    """RS256 (RSASSA-PKCS1-v1_5 over SHA-256) signature — the GCP OAuth JWT
    grant's algorithm (backends/gcp/auth.sign_jwt_rs256). ``openssl dgst
    -sign`` with an RSA key emits exactly this scheme."""
    with _TempFiles() as tf:
        return _run(["dgst", "-sha256", "-sign", tf.write("k.pem", key_pem)],
                    input_bytes=data)


def rsa_verify_sha256(key_pem: str, data: bytes, signature: bytes) -> bool:
    """Verify an RS256 signature; accepts the private key PEM (the public key
    is derived) or a public key PEM."""
    with _TempFiles() as tf:
        priv = tf.write("k.pem", key_pem)
        if "PRIVATE KEY" in key_pem:
            pub = tf.path("pub.pem")
            with open(pub, "wb") as f:
                f.write(_run(["pkey", "-in", priv, "-pubout"]))
        else:
            pub = priv
        try:
            _run(["dgst", "-sha256", "-verify", pub, "-signature",
                  tf.write("sig.bin", signature)], input_bytes=data)
            return True
        except CryptoError:
            return False


# -- certificates -----------------------------------------------------------


def self_signed_cert(cn: str, days: int = 3650, is_ca: bool = False) -> Tuple[str, str]:
    """(cert_pem, key_pem). Leaf certs carry a DNS SAN for `cn` (hostname
    verification needs SANs, not CNs); `is_ca` relies on openssl's default
    v3_ca section (basicConstraints CA:TRUE) — adding it again would mint a
    duplicate extension that verifiers reject."""
    key_pem = generate_ec_key_pem()
    with _TempFiles() as tf:
        args = [
            "req", "-x509", "-new", "-key", tf.write("k.pem", key_pem),
            "-subj", f"/CN={cn}", "-days", str(days), "-sha256",
            "-out", tf.path("cert.pem"),
        ]
        if not is_ca:
            args += ["-addext", f"subjectAltName=DNS:{cn}"]
        _run(args)
        with open(tf.path("cert.pem")) as f:
            cert_pem = f.read()
    return cert_pem, key_pem


def make_csr_der(key_pem: str, domain: str) -> bytes:
    """PKCS#10 CSR (DER) for `domain` with a DNS SAN — the ACME finalize body."""
    with _TempFiles() as tf:
        return _run([
            "req", "-new", "-key", tf.write("k.pem", key_pem),
            "-subj", f"/CN={domain}", "-addext", f"subjectAltName=DNS:{domain}",
            "-outform", "DER",
        ])


def csr_cn(csr_der: bytes) -> str:
    """The CSR subject's CN (RFC 2253 form strips to `CN=name`)."""
    with _TempFiles() as tf:
        out = _run([
            "req", "-inform", "DER", "-in", tf.write("csr.der", csr_der),
            "-noout", "-subject", "-nameopt", "RFC2253",
        ]).decode().strip()
    subject = out.split("=", 1)[1]
    for part in subject.split(","):
        if part.strip().startswith("CN="):
            return part.strip()[3:]
    raise CryptoError(f"CSR subject has no CN: {subject!r}")


def sign_csr(
    csr_der: bytes, ca_cert_pem: str, ca_key_pem: str, days: int = 30
) -> str:
    """CA-sign a CSR (test harness / private-CA issuance); returns the leaf
    PEM. The SAN is re-derived from the CSR's CN (``openssl x509 -req`` drops
    requested extensions unless an extfile restates them)."""
    cn = csr_cn(csr_der)
    with _TempFiles() as tf:
        csr_pem = _run([  # x509 -req reads PEM CSRs only (openssl 1.1.1)
            "req", "-inform", "DER", "-in", tf.write("csr.der", csr_der),
            "-outform", "PEM",
        ])
        _run([
            "x509", "-req", "-in", tf.write("csr.pem", csr_pem),
            "-CA", tf.write("ca.pem", ca_cert_pem),
            "-CAkey", tf.write("cakey.pem", ca_key_pem),
            "-set_serial", str(secrets.randbits(63)),
            "-days", str(days), "-sha256",
            "-extfile", tf.write("ext.cnf", f"subjectAltName=DNS:{cn}\n"),
            "-out", tf.path("leaf.pem"),
        ])
        with open(tf.path("leaf.pem")) as f:
            return f.read()


def _x509_field(cert, flag: str, inform: str) -> str:
    with _TempFiles() as tf:
        name = "cert.der" if inform == "DER" else "cert.pem"
        out = _run([
            "x509", "-inform", inform, "-in", tf.write(name, cert),
            "-noout", flag, "-nameopt", "RFC2253",
        ]).decode().strip()
    return out.split("=", 1)[1]


def cert_subject(cert, inform: str = "PEM") -> str:
    """RFC 2253 subject, e.g. ``CN=svc.test`` (pass DER for a live peer cert)."""
    return _x509_field(cert, "-subject", inform)


def cert_issuer(cert, inform: str = "PEM") -> str:
    return _x509_field(cert, "-issuer", inform)


_MONTHS = {
    "Jan": 1, "Feb": 2, "Mar": 3, "Apr": 4, "May": 5, "Jun": 6,
    "Jul": 7, "Aug": 8, "Sep": 9, "Oct": 10, "Nov": 11, "Dec": 12,
}


def cert_not_after(cert_pem) -> datetime.datetime:
    """The leaf's notAfter as an aware UTC datetime. openssl always prints
    English month abbreviations ("notAfter=Sep  2 09:25:25 2026 GMT") but
    strptime's %b follows LC_TIME — parse by hand so a non-English locale
    can't silently disable renewal sweeps."""
    with _TempFiles() as tf:
        out = _run([
            "x509", "-in", tf.write("cert.pem", cert_pem), "-noout", "-enddate",
        ]).decode().strip()
    stamp = out.split("=", 1)[1].split()
    try:
        mon, day, clock, year = stamp[0], int(stamp[1]), stamp[2], int(stamp[3])
        hh, mm, ss = (int(p) for p in clock.split(":"))
        return datetime.datetime(
            year, _MONTHS[mon], day, hh, mm, ss, tzinfo=datetime.timezone.utc
        )
    except (KeyError, IndexError, ValueError) as e:
        raise CryptoError(f"unparseable notAfter {out!r}: {e}")
