"""Gateway appliance: the standalone ingress VM the control plane provisions.

Parity: reference proxy/gateway/app.py + gateway/services/nginx.py:75-110
(per-service nginx server blocks) + gateway/services/registry.py:34-373 (the
OpenAI-compatible model registry). TPU re-design: one aiohttp process replaces
the nginx+python pair — aiohttp streams SSE/chunked inference output fine,
needs no config-file reloads (the registry is in-process, updated over the
control plane's sync API), and ships as a single module the startup script can
launch (`python -m dstack_tpu.gateway`). TLS terminates IN the appliance:
``--tls-port``/``--certs-dir`` serve HTTPS with per-domain SNI certs, and
``--acme-directory`` auto-issues them over ACME http-01 when a service
registers a domain (gateway/tls.py — the certbot+nginx equivalent).

Routing surface:
  - path:   /services/{project}/{run}/...       (always available)
  - domain: Host == service domain -> /...      (when a domain is registered)
  - model:  POST /models/{project}/v1/chat/completions (+ /completions,
            /models/{project}/v1/models to list) routed by body["model"]
Control surface (Bearer ``--token``):
  - POST /api/registry/register    {project, run_name, domain?, model?, replicas}
  - POST /api/registry/unregister  {project, run_name}
  - GET  /api/registry/services
  - GET  /healthcheck              (unauthenticated)
"""

from __future__ import annotations

import argparse
import json
import logging
import time
from typing import Dict, List, Optional, Tuple

from aiohttp import web

from dstack_tpu.core.services.http_forward import forward

logger = logging.getLogger(__name__)

from dstack_tpu.core.services.stats_window import STATS_BUCKET, STATS_WINDOW


class ServiceEntry:
    def __init__(self, data: dict) -> None:
        self.project: str = data["project"]
        self.run_name: str = data["run_name"]
        self.domain: Optional[str] = data.get("domain")
        model = data.get("model") or {}
        self.model_name: Optional[str] = model.get("name")
        self.model_prefix: str = (model.get("prefix") or "/v1").rstrip("/")
        self.replicas: List[Tuple[str, int]] = [
            (r["host"], int(r["port"])) for r in data.get("replicas", [])
        ]
        self.rate_limits: List[dict] = data.get("rate_limits") or []
        self._rr = 0
        # Wall-clock bucket -> admitted request count; the control plane pulls
        # these so gateway-routed traffic feeds the RPS autoscaler exactly like
        # in-server proxy traffic (the reference's server pulls its gateway's
        # nginx-access-log stats the same way).
        self.request_buckets: Dict[int, int] = {}

    def record_request(self) -> None:
        bucket = int(time.time() // STATS_BUCKET) * int(STATS_BUCKET)
        self.request_buckets[bucket] = self.request_buckets.get(bucket, 0) + 1
        cutoff = bucket - int(STATS_WINDOW)
        for b in [b for b in self.request_buckets if b < cutoff]:
            del self.request_buckets[b]

    def pick_replica(self) -> Tuple[str, int]:
        replica = self.replicas[self._rr % len(self.replicas)]
        self._rr += 1
        return replica

    def to_dict(self) -> dict:
        return {
            "project": self.project,
            "run_name": self.run_name,
            "domain": self.domain,
            "model": (
                {"name": self.model_name, "prefix": self.model_prefix}
                if self.model_name
                else None
            ),
            "replicas": [{"host": h, "port": p} for h, p in self.replicas],
            "rate_limits": self.rate_limits,
        }


class Registry:
    def __init__(self) -> None:
        self._services: Dict[Tuple[str, str], ServiceEntry] = {}

    def register(self, data: dict) -> ServiceEntry:
        entry = ServiceEntry(data)
        old = self._services.get((entry.project, entry.run_name))
        if old is not None:
            # Re-registration (replica set changed) must not zero the stats
            # the autoscaler is about to pull.
            entry.request_buckets = old.request_buckets
        self._services[(entry.project, entry.run_name)] = entry
        return entry

    def unregister(self, project: str, run_name: str) -> bool:
        return self._services.pop((project, run_name), None) is not None

    def get(self, project: str, run_name: str) -> Optional[ServiceEntry]:
        return self._services.get((project, run_name))

    def by_domain(self, host: str) -> Optional[ServiceEntry]:
        host = host.split(":")[0].lower()
        for entry in self._services.values():
            if entry.domain and entry.domain.lower() == host:
                return entry
        return None

    def by_model(self, project: str, model_name: str) -> Optional[ServiceEntry]:
        for entry in self._services.values():
            if entry.project == project and entry.model_name == model_name:
                return entry
        return None

    def models(self, project: str) -> List[ServiceEntry]:
        return [
            e for e in self._services.values() if e.project == project and e.model_name
        ]

    def all(self) -> List[ServiceEntry]:
        return list(self._services.values())


def create_app(token: str, tls_manager=None) -> web.Application:
    """`tls_manager` (gateway.tls_manager.TlsManager) enables in-appliance TLS:
    http-01 challenge serving on this HTTP app + auto-issuance for registered
    domains (reference nginx.py:75-110 runs certbot for the same purpose)."""
    from dstack_tpu.core.services.rate_limit import RateLimiter

    registry = Registry()
    limiter = RateLimiter()
    app = web.Application()
    app["registry"] = registry
    app["tls_manager"] = tls_manager

    def _rate_check(entry: ServiceEntry, path: str) -> None:
        if entry.rate_limits and not limiter.check(
            f"{entry.project}/{entry.run_name}", path, entry.rate_limits
        ):
            raise web.HTTPTooManyRequests(text="rate limit exceeded")

    def _auth(request: web.Request) -> None:
        header = request.headers.get("Authorization", "")
        if not token or header != f"Bearer {token}":
            raise web.HTTPUnauthorized(text="bad gateway token")

    async def healthcheck(request: web.Request) -> web.Response:
        return web.json_response(
            {"status": "ok", "service": "dstack-tpu-gateway", "services": len(registry.all())}
        )

    async def register(request: web.Request) -> web.Response:
        _auth(request)
        entry = registry.register(await request.json())
        logger.info(
            "registered %s/%s: %d replica(s)%s",
            entry.project, entry.run_name, len(entry.replicas),
            f", model {entry.model_name}" if entry.model_name else "",
        )
        if entry.domain and tls_manager is not None:
            # Issue (or load) the domain's certificate off the request path;
            # the SNI callback picks it up the moment it lands in the store.
            tls_manager.ensure_async(entry.domain)
        return web.json_response(entry.to_dict())

    async def unregister(request: web.Request) -> web.Response:
        _auth(request)
        body = await request.json()
        removed = registry.unregister(body["project"], body["run_name"])
        return web.json_response({"removed": removed})

    async def list_services(request: web.Request) -> web.Response:
        _auth(request)
        return web.json_response([e.to_dict() for e in registry.all()])

    async def registry_stats(request: web.Request) -> web.Response:
        """Per-service request buckets for the control plane's autoscaler.
        `now` is THIS host's wall clock: bucket keys are local timestamps, so
        the puller rebases them by the clock delta — an appliance VM without
        NTP must not silently suppress (or future-date) scaling signal."""
        _auth(request)
        import time as _time

        return web.json_response({
            "now": _time.time(),
            "services": [
                {
                    "project": e.project,
                    "run_name": e.run_name,
                    "buckets": {str(b): c for b, c in sorted(e.request_buckets.items())},
                }
                for e in registry.all()
            ],
        })

    async def route_service(request: web.Request) -> web.StreamResponse:
        entry = registry.get(
            request.match_info["project"], request.match_info["run_name"]
        )
        if entry is None:
            raise web.HTTPNotFound(text="unknown service")
        _rate_check(entry, "/" + request.match_info.get("tail", ""))
        # Record BEFORE the replica check (like the in-server proxy): demand
        # against a scaled-to-zero service is exactly what wakes it.
        entry.record_request()
        if not entry.replicas:
            raise web.HTTPServiceUnavailable(text="service has no replicas")
        host, port = entry.pick_replica()
        return await forward(request, host, port, request.match_info.get("tail", ""))

    async def route_model(request: web.Request) -> web.StreamResponse:
        project = request.match_info["project"]
        tail = request.match_info.get("tail", "")
        if request.method == "GET" and tail == "models":
            return web.json_response(
                {
                    "object": "list",
                    "data": [
                        {"id": e.model_name, "object": "model", "owned_by": e.project}
                        for e in registry.models(project)
                    ],
                }
            )
        body = await request.read()
        try:
            model_name = json.loads(body).get("model")
        except (ValueError, AttributeError):
            model_name = None
        if not model_name:
            raise web.HTTPBadRequest(text="request body must name a model")
        entry = registry.by_model(project, model_name)
        if entry is None:
            raise web.HTTPNotFound(text=f"no service serves model {model_name}")
        # Limits match the upstream path the request lands on, same as /services/.
        _rate_check(entry, f"{entry.model_prefix}/{tail}")
        entry.record_request()  # before the replica check: wakes scaled-to-zero
        if not entry.replicas:
            raise web.HTTPServiceUnavailable(text="service has no replicas")
        host, port = entry.pick_replica()
        return await forward(
            request, host, port, f"{entry.model_prefix}/{tail}", body=body
        )

    async def route_domain(request: web.Request) -> web.StreamResponse:
        entry = registry.by_domain(request.headers.get("Host", ""))
        if entry is None:
            raise web.HTTPNotFound(text="unknown host")
        _rate_check(entry, request.path)
        entry.record_request()  # before the replica check: wakes scaled-to-zero
        if not entry.replicas:
            raise web.HTTPServiceUnavailable(text="service has no replicas")
        host, port = entry.pick_replica()
        return await forward(request, host, port, request.match_info.get("tail", ""))

    async def acme_challenge(request: web.Request) -> web.Response:
        body = None
        if tls_manager is not None:
            body = tls_manager.challenge_body(request.match_info["token"])
        if body is None:
            raise web.HTTPNotFound()
        return web.Response(text=body)

    app.router.add_get("/healthcheck", healthcheck)
    app.router.add_get("/.well-known/acme-challenge/{token}", acme_challenge)
    app.router.add_post("/api/registry/register", register)
    app.router.add_post("/api/registry/unregister", unregister)
    app.router.add_get("/api/registry/services", list_services)
    app.router.add_get("/api/registry/stats", registry_stats)
    app.router.add_route("*", "/services/{project}/{run_name}/{tail:.*}", route_service)
    app.router.add_route("*", "/models/{project}/v1/{tail:.*}", route_model)
    # Domain-based routing is the catch-all: anything not matching the fixed
    # prefixes is tried against registered domains.
    app.router.add_route("*", "/{tail:.*}", route_domain)
    return app


async def serve(
    host: str,
    port: int,
    token: str,
    tls_port: Optional[int] = None,
    certs_dir: Optional[str] = None,
    acme_directory: Optional[str] = None,
    acme_contact: Optional[str] = None,
) -> None:
    import asyncio

    tls_manager = None
    if certs_dir:
        from dstack_tpu.gateway.tls_manager import TlsManager

        tls_manager = TlsManager(certs_dir, acme_directory, acme_contact)
    runner = web.AppRunner(create_app(token, tls_manager=tls_manager))
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    actual = site._server.sockets[0].getsockname()[1]  # port 0 -> ephemeral
    print(f"dstack-tpu-gateway listening on {host}:{actual}", flush=True)
    if tls_manager is not None and tls_port is not None:
        tls_site = web.TCPSite(
            runner, host, tls_port, ssl_context=tls_manager.server_context()
        )
        await tls_site.start()
        tls_actual = tls_site._server.sockets[0].getsockname()[1]
        print(f"dstack-tpu-gateway tls on {host}:{tls_actual}", flush=True)
    if tls_manager is not None:
        # Renewal runs even without a TLS listener: issued certs may be
        # consumed from --certs-dir by an external terminator.
        tls_manager.start_renewal()
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        # Drain the shared upstream keep-alive pool on shutdown/cancellation.
        from dstack_tpu.core.services.http_forward import close_session

        await close_session()


def main() -> None:
    import asyncio

    parser = argparse.ArgumentParser(prog="dstack-tpu-gateway")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--token", required=True)
    parser.add_argument("--tls-port", type=int, default=None,
                        help="HTTPS listener (SNI certs from --certs-dir)")
    parser.add_argument("--certs-dir", default=None,
                        help="per-domain cert store; enables TLS features")
    parser.add_argument("--acme-directory", default=None,
                        help="ACME v2 directory URL for auto-issuance (http-01)")
    parser.add_argument("--acme-contact", default=None)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    asyncio.run(serve(args.host, args.port, args.token, tls_port=args.tls_port,
                      certs_dir=args.certs_dir, acme_directory=args.acme_directory,
                      acme_contact=args.acme_contact))


if __name__ == "__main__":
    main()
