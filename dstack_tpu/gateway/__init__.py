"""Gateway appliance package; `python -m dstack_tpu.gateway` runs it."""
