"""Client-side port forwarding over the control plane's attach bridge.

Parity: reference api/_public/runs.py:244-351 (Run.attach: client-side SSH
port-forward). TPU re-design: no instance keys needed client-side — each local
TCP connection is piped over a WebSocket to the server, which relays to the
worker over its pooled SSH tunnels (server/services/attach.py).
"""

from __future__ import annotations

import asyncio
import logging
import threading
from typing import List, Optional, Tuple

import aiohttp

logger = logging.getLogger(__name__)


async def _pipe_connection(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    ws_url: str,
    token: str,
) -> None:
    try:
        async with aiohttp.ClientSession() as session:
            async with session.ws_connect(
                ws_url, headers={"Authorization": f"Bearer {token}"}, heartbeat=30
            ) as ws:

                async def local_to_ws() -> None:
                    try:
                        while True:
                            data = await reader.read(64 * 1024)
                            if not data:
                                break
                            await ws.send_bytes(data)
                    except (ConnectionError, asyncio.CancelledError):
                        pass
                    finally:
                        if not ws.closed:
                            await ws.close()

                pump = asyncio.ensure_future(local_to_ws())
                try:
                    async for msg in ws:
                        if msg.type == aiohttp.WSMsgType.BINARY:
                            writer.write(msg.data)
                            await writer.drain()
                        elif msg.type in (aiohttp.WSMsgType.CLOSE, aiohttp.WSMsgType.ERROR):
                            break
                finally:
                    pump.cancel()
    except aiohttp.ClientError as e:
        logger.warning("attach: bridge connection failed: %s", e)
    finally:
        try:
            writer.close()
            await writer.wait_closed()
        except (ConnectionError, OSError):
            pass


async def forward_port(
    server_url: str,
    token: str,
    project: str,
    run_name: str,
    local_port: int,
    remote_port: int,
) -> asyncio.AbstractServer:
    """Listen on 127.0.0.1:local_port and pipe every connection to remote_port on
    the run's worker. Returns the asyncio server (close() to stop)."""
    base = server_url.rstrip("/")
    ws_url = f"{base}/api/project/{project}/runs/{run_name}/attach/{remote_port}"

    async def on_connect(reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        await _pipe_connection(reader, writer, ws_url, token)

    server = await asyncio.start_server(on_connect, "127.0.0.1", local_port)
    logger.info("forwarding 127.0.0.1:%s -> %s:%s", local_port, run_name, remote_port)
    return server


class PortForwarder:
    """Sync facade for the CLI: runs forward_port servers on a daemon thread."""

    def __init__(
        self,
        server_url: str,
        token: str,
        project: str,
        run_name: str,
        forwards: List[Tuple[int, int]],  # (local_port, remote_port)
    ) -> None:
        self._args = (server_url, token, project, run_name)
        self._forwards = forwards
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()

    def start(self) -> None:
        def _run() -> None:
            loop = asyncio.new_event_loop()
            self._loop = loop
            asyncio.set_event_loop(loop)

            async def _open_all() -> None:
                for local, remote in self._forwards:
                    await forward_port(*self._args, local, remote)
                self._started.set()

            loop.run_until_complete(_open_all())
            loop.run_forever()

        self._thread = threading.Thread(target=_run, daemon=True, name="attach-forwarder")
        self._thread.start()
        self._started.wait(timeout=10)

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5)
