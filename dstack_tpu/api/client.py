"""Synchronous REST client.

Parity: reference src/dstack/api (Client -> RunCollection api/_public/runs.py:391-736,
low-level wrappers api/server/_*.py) — one flat client class per domain, returning
parsed wire models."""

from __future__ import annotations

import json
import time
from typing import Any, Dict, Iterator, List, Optional

import requests

from dstack_tpu.core.errors import (
    ForbiddenError,
    NotAuthenticatedError,
    ResourceExistsError,
    ResourceNotExistsError,
    ServerClientError,
)
from dstack_tpu.core.models.fleets import Fleet, FleetPlan, FleetSpec
from dstack_tpu.core.models.instances import Instance
from dstack_tpu.core.models.gateways import Gateway
from dstack_tpu.core.models.logs import JobSubmissionLogs
from dstack_tpu.core.models.metrics import JobMetrics
from dstack_tpu.core.models.runs import Run, RunPlan, RunSpec
from dstack_tpu.core.models.volumes import Volume

_STATUS_ERRORS = {
    401: NotAuthenticatedError,
    403: ForbiddenError,
    404: ResourceNotExistsError,
    409: ResourceExistsError,
}


class ApiError(ServerClientError):
    pass


class Client:
    """`Client(url, token, project)`; sub-APIs: runs, fleets, volumes, secrets, repos,
    offers, backends, logs, instances."""

    def __init__(self, url: str, token: str, project: str = "main", timeout: float = 30.0):
        self.url = url.rstrip("/")
        self.token = token
        self.project = project
        self.timeout = timeout
        from dstack_tpu.core.compatibility import API_VERSION, API_VERSION_HEADER

        self._session = requests.Session()
        self._session.headers["Authorization"] = f"Bearer {token}"
        self._session.headers[API_VERSION_HEADER] = API_VERSION
        self.runs = RunsApi(self)
        self.fleets = FleetsApi(self)
        self.volumes = VolumesApi(self)
        self.secrets = SecretsApi(self)
        self.repos = ReposApi(self)
        self.offers = OffersApi(self)
        self.backends = BackendsApi(self)
        self.logs = LogsApi(self)
        self.metrics = MetricsApi(self)
        self.gateways = GatewaysApi(self)
        self.projects = ProjectsApi(self)
        self.instances = InstancesApi(self)
        self.usage = UsageApi(self)

    def post(self, path: str, body: Optional[dict] = None, data: Optional[bytes] = None) -> Any:
        url = self.url + path
        if data is not None:
            resp = self._session.post(url, data=data, timeout=self.timeout)
        else:
            resp = self._session.post(url, json=body or {}, timeout=self.timeout)
        if resp.status_code >= 400:
            detail = ""
            try:
                detail = resp.json()["detail"][0]["msg"]
            except Exception:
                detail = resp.text[:300]
            err_cls = _STATUS_ERRORS.get(resp.status_code, ApiError)
            raise err_cls(detail)
        if not resp.content:
            return None
        return resp.json()

    def _p(self, path: str) -> str:
        return f"/api/project/{self.project}{path}"


class RunsApi:
    def __init__(self, client: Client):
        self._c = client

    def get_plan(self, run_spec: dict) -> RunPlan:
        data = self._c.post(self._c._p("/runs/get_plan"), {"run_spec": run_spec})
        return RunPlan.model_validate(data)

    def submit(self, run_spec: dict) -> Run:
        data = self._c.post(self._c._p("/runs/submit"), {"run_spec": run_spec})
        return Run.model_validate(data)

    def apply_plan(self, run_spec: dict, force: bool = False) -> Run:
        data = self._c.post(
            self._c._p("/runs/apply_plan"), {"run_spec": run_spec, "force": force}
        )
        return Run.model_validate(data)

    def update(self, run_spec: dict) -> Run:
        """In-place update of a live run (only update-safe fields may change)."""
        data = self._c.post(self._c._p("/runs/update"), {"run_spec": run_spec})
        return Run.model_validate(data)

    def list(
        self,
        only_active: bool = False,
        limit: int = 1000,
        prev_submitted_at: Optional[str] = None,
        prev_run_id: Optional[str] = None,
    ) -> List[Run]:
        """Newest first; keyset-paginate by passing the last run's
        submitted_at/id as prev_submitted_at/prev_run_id."""
        body = {"only_active": only_active, "limit": limit}
        if prev_submitted_at is not None:
            body["prev_submitted_at"] = prev_submitted_at
        if prev_run_id is not None:
            body["prev_run_id"] = prev_run_id
        data = self._c.post(self._c._p("/runs/list"), body)
        return [Run.model_validate(r) for r in data]

    def get(self, run_name: str) -> Run:
        data = self._c.post(self._c._p("/runs/get"), {"run_name": run_name})
        return Run.model_validate(data)

    def get_events(self, run_name: str) -> dict:
        """Lifecycle timeline + derived phase durations:
        {"run_name", "status", "events": [...], "phases": {...}}."""
        return self._c.post(self._c._p("/runs/get_events"), {"run_name": run_name})

    def get_metrics(self, run_name: str, limit: int = 50) -> dict:
        """Workload telemetry: {"run_name", "status", "goodput": {...ledger},
        "latest": step point | None, "engine": gauges | None,
        "profile": latest profile mark | None, "points": [step points]}."""
        return self._c.post(
            self._c._p("/runs/get_metrics"), {"run_name": run_name, "limit": limit}
        )

    def get_traces(
        self,
        run_name: str,
        request_id: Optional[str] = None,
        trace_id: Optional[str] = None,
        limit: int = 20,
    ) -> dict:
        """Flight-recorder traces merged across the service's replicas:
        {"run_name", "status", "replicas_queried", "errors", "traces": [...]}.
        Narrow with request_id (engine req id) or trace_id (the
        X-Dstack-Trace-Id a response carried)."""
        body: dict = {"run_name": run_name, "limit": limit}
        if request_id is not None:
            body["request_id"] = request_id
        if trace_id is not None:
            body["trace_id"] = trace_id
        return self._c.post(self._c._p("/runs/get_traces"), body)

    def profile(self, run_name: str, seconds: float = 5.0) -> dict:
        """Trigger an on-demand profiler capture in the run's live workload;
        returns the agent ack ({"id", "artifact_dir", ...}). Completion shows
        up as a profile_end mark in get_metrics()["profile"]."""
        return self._c.post(
            self._c._p("/runs/profile"), {"run_name": run_name, "seconds": seconds}
        )

    def stop(self, run_names: List[str], abort: bool = False) -> None:
        self._c.post(self._c._p("/runs/stop"), {"runs_names": run_names, "abort": abort})

    def delete(self, run_names: List[str]) -> None:
        self._c.post(self._c._p("/runs/delete"), {"runs_names": run_names})

    def wait(self, run_name: str, poll: float = 2.0, timeout: Optional[float] = None) -> Run:
        """Block until the run reaches a terminal status."""
        deadline = time.monotonic() + timeout if timeout else None
        while True:
            run = self.get(run_name)
            if run.status.is_finished():
                return run
            if deadline and time.monotonic() > deadline:
                raise TimeoutError(f"run {run_name} still {run.status.value}")
            time.sleep(poll)


class FleetsApi:
    def __init__(self, client: Client):
        self._c = client

    def list(self) -> List[Fleet]:
        return [Fleet.model_validate(f) for f in self._c.post(self._c._p("/fleets/list"))]

    def get(self, name: str) -> Fleet:
        return Fleet.model_validate(self._c.post(self._c._p("/fleets/get"), {"name": name}))

    def get_plan(self, spec: dict) -> FleetPlan:
        return FleetPlan.model_validate(
            self._c.post(self._c._p("/fleets/get_plan"), {"spec": spec})
        )

    def apply_plan(self, spec: dict, force: bool = False) -> Fleet:
        return Fleet.model_validate(
            self._c.post(self._c._p("/fleets/apply_plan"), {"spec": spec, "force": force})
        )

    def delete(self, names: List[str]) -> None:
        self._c.post(self._c._p("/fleets/delete"), {"names": names})


class VolumesApi:
    def __init__(self, client: Client):
        self._c = client

    def list(self) -> List[Volume]:
        return [Volume.model_validate(v) for v in self._c.post(self._c._p("/volumes/list"))]

    def create(self, configuration: dict) -> Volume:
        return Volume.model_validate(
            self._c.post(self._c._p("/volumes/create"), {"configuration": configuration})
        )

    def delete(self, names: List[str]) -> None:
        self._c.post(self._c._p("/volumes/delete"), {"names": names})


class SecretsApi:
    def __init__(self, client: Client):
        self._c = client

    def set(self, name: str, value: str) -> None:
        self._c.post(self._c._p("/secrets/set"), {"name": name, "value": value})

    def list(self) -> List[str]:
        return [s["name"] for s in self._c.post(self._c._p("/secrets/list"))]

    def delete(self, names: List[str]) -> None:
        self._c.post(self._c._p("/secrets/delete"), {"names": names})


class ReposApi:
    def __init__(self, client: Client):
        self._c = client

    def init(self, repo_name: str, repo_info: Optional[dict] = None) -> dict:
        return self._c.post(
            self._c._p("/repos/init"), {"repo_name": repo_name, "repo_info": repo_info}
        )

    def list(self) -> List[dict]:
        return self._c.post(self._c._p("/repos/list"))

    def upload_code(self, repo_name: str, blob: bytes) -> str:
        data = self._c.post(self._c._p(f"/repos/{repo_name}/upload_code"), data=blob)
        return data["code_hash"]


class OffersApi:
    def __init__(self, client: Client):
        self._c = client

    def list(
        self,
        resources: Optional[dict] = None,
        spot: Optional[bool] = None,
        max_price: Optional[float] = None,
        limit: int = 100,
    ) -> dict:
        return self._c.post(
            self._c._p("/offers/list"),
            {"resources": resources, "spot": spot, "max_price": max_price, "limit": limit},
        )


class BackendsApi:
    def __init__(self, client: Client):
        self._c = client

    def create(self, config: dict) -> None:
        self._c.post(self._c._p("/backends/create"), config)

    def list(self) -> List[dict]:
        return self._c.post(self._c._p("/backends/list"))

    def delete(self, types: List[str]) -> None:
        self._c.post(self._c._p("/backends/delete"), {"types": types})


class InstancesApi:
    def __init__(self, client: Client):
        self._c = client

    def list(self) -> List[Instance]:
        data = self._c.post(self._c._p("/instances/list"))
        return [Instance.model_validate(i) for i in data]


class ProjectsApi:
    def __init__(self, client: Client):
        self._c = client

    def list(self) -> List[dict]:
        return self._c.post("/api/projects/list")

    def create(self, name: str) -> dict:
        return self._c.post("/api/projects/create", {"project_name": name})

    def delete(self, names: List[str]) -> None:
        self._c.post("/api/projects/delete", {"projects_names": names})


class UsageApi:
    def __init__(self, client: Client):
        self._c = client

    def get(self, project: Optional[str] = None, since: Optional[str] = None) -> dict:
        """Fleet accounting readout: per-run chip-seconds/dollars/goodput rows,
        per-project totals, and the fleet summary (chips by state, $/hr burn).
        Scoped to the caller's projects; `project` narrows to one, `since` is
        an ISO timestamp filtering the ledger's UTC-hour buckets."""
        body: Dict[str, Any] = {}
        if project:
            body["project"] = project
        if since:
            body["since"] = since
        return self._c.post("/api/usage/get", body)


class GatewaysApi:
    def __init__(self, client: Client):
        self._c = client

    def list(self) -> List[Gateway]:
        data = self._c.post(self._c._p("/gateways/list"))
        return [Gateway.model_validate(g) for g in data]

    def create(self, configuration: dict) -> Gateway:
        data = self._c.post(self._c._p("/gateways/create"), {"configuration": configuration})
        return Gateway.model_validate(data)

    def delete(self, names: List[str]) -> None:
        self._c.post(self._c._p("/gateways/delete"), {"names": names})


class MetricsApi:
    def __init__(self, client: Client):
        self._c = client

    def get_job(
        self,
        run_name: str,
        replica_num: int = 0,
        job_num: int = 0,
        limit: int = 100,
        after: Optional[str] = None,
        before: Optional[str] = None,
    ) -> JobMetrics:
        data = self._c.post(
            self._c._p("/metrics/job"),
            {
                "run_name": run_name,
                "replica_num": replica_num,
                "job_num": job_num,
                "limit": limit,
                "after": after,
                "before": before,
            },
        )
        return JobMetrics.model_validate(data)


class LogsApi:
    def __init__(self, client: Client):
        self._c = client

    def poll(
        self,
        run_name: str,
        job_id: Optional[str] = None,
        start_line: int = 0,
        limit: int = 1000,
    ) -> JobSubmissionLogs:
        data = self._c.post(
            self._c._p("/logs/poll"),
            {"run_name": run_name, "job_id": job_id, "start_line": start_line, "limit": limit},
        )
        return JobSubmissionLogs.model_validate(data)

    def tail(self, run_name: str, poll: float = 1.0) -> Iterator[str]:
        """Yield log lines until the run finishes."""
        line = 0
        while True:
            batch = self.poll(run_name, start_line=line)
            for ev in batch.logs:
                yield ev.message
            line += len(batch.logs)
            run = self._c.runs.get(run_name)
            if run.status.is_finished() and not batch.logs:
                # One final poll so the tail is complete.
                batch = self.poll(run_name, start_line=line)
                for ev in batch.logs:
                    yield ev.message
                return
            if not batch.logs:
                time.sleep(poll)
