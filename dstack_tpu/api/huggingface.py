"""Hugging Face fine-tuning sugar for the Python SDK.

Parity: reference src/dstack/api/huggingface/__init__.py:6 — a
`SFTFineTuningTask` that packages model/dataset/hyperparameters into a
ready-to-submit Task so users fine-tune without writing a configuration.
TPU re-design: the reference's knobs are CUDA-shaped (4-bit bitsandbytes
quantization, paged optimizers); on TPU the natural knobs are bf16 (MXU
native), LoRA, and a slice topology, and the generated commands run TRL's
maintained `trl sft` entrypoint against the requested accelerator.

Usage::

    from dstack_tpu.api import Client
    from dstack_tpu.api.huggingface import SFTFineTuningTask

    task = SFTFineTuningTask(
        model_name="google/gemma-2b",
        dataset_name="tatsu-lab/alpaca",
        env={"HF_TOKEN": "..."},
        tpu="v5litepod-8",
    )
    client.runs.submit({"run_name": "sft", "configuration": task.dict()})
"""

from __future__ import annotations

import shlex
from typing import Dict, List, Optional

from dstack_tpu.core.models.configurations import TaskConfiguration

_TOKEN_VARS = ("HF_TOKEN", "HUGGING_FACE_HUB_TOKEN")


def SFTFineTuningTask(
    model_name: str,
    dataset_name: str,
    env: Dict[str, str],
    new_model_name: Optional[str] = None,
    tpu: Optional[str] = None,
    report_to: Optional[str] = None,
    per_device_train_batch_size: int = 4,
    gradient_accumulation_steps: int = 1,
    learning_rate: float = 2e-4,
    weight_decay: float = 0.001,
    lora: bool = True,
    lora_r: int = 64,
    lora_alpha: int = 16,
    lora_dropout: float = 0.1,
    max_seq_length: Optional[int] = None,
    num_train_epochs: float = 1,
    max_steps: int = -1,
    bf16: bool = True,
    gradient_checkpointing: bool = True,
    warmup_ratio: float = 0.03,
    logging_steps: int = 25,
    save_steps: int = 0,
) -> TaskConfiguration:
    """Build a supervised-fine-tuning TaskConfiguration (TRL ``trl sft``).

    ``env`` must carry an HF token (HF_TOKEN or HUGGING_FACE_HUB_TOKEN) so
    gated models/datasets resolve and the tuned model can push back to the
    hub as ``new_model_name``; ``report_to="wandb"`` additionally requires
    WANDB_API_KEY — both validated here, at authoring time, the same contract
    the reference enforces.
    """
    if not any(v in env for v in _TOKEN_VARS):
        raise ValueError(
            "env must include HF_TOKEN (or HUGGING_FACE_HUB_TOKEN) — needed for"
            " gated models and to push the fine-tuned model"
        )
    if report_to == "wandb" and "WANDB_API_KEY" not in env:
        raise ValueError('report_to="wandb" requires WANDB_API_KEY in env')
    if report_to not in (None, "none", "wandb", "tensorboard"):
        raise ValueError(f"unsupported report_to: {report_to!r}")

    output_dir = "./sft-output"
    # User-provided names land in a shell command line: quote them so a name
    # with spaces/metacharacters can't break or alter the generated command.
    args: List[str] = [
        f"--model_name_or_path {shlex.quote(model_name)}",
        f"--dataset_name {shlex.quote(dataset_name)}",
        f"--output_dir {shlex.quote(output_dir)}",
        f"--per_device_train_batch_size {per_device_train_batch_size}",
        f"--gradient_accumulation_steps {gradient_accumulation_steps}",
        f"--learning_rate {learning_rate}",
        f"--weight_decay {weight_decay}",
        f"--num_train_epochs {num_train_epochs}",
        f"--warmup_ratio {warmup_ratio}",
        f"--logging_steps {logging_steps}",
    ]
    if max_steps > 0:
        args.append(f"--max_steps {max_steps}")
    if max_seq_length:
        args.append(f"--max_seq_length {max_seq_length}")
    if bf16:
        args.append("--bf16 True")
    if gradient_checkpointing:
        args.append("--gradient_checkpointing True")
    if save_steps > 0:
        args.append(f"--save_steps {save_steps}")
    if lora:
        args += [
            "--use_peft",
            f"--lora_r {lora_r}",
            f"--lora_alpha {lora_alpha}",
            f"--lora_dropout {lora_dropout}",
        ]
    if report_to:
        args.append(f"--report_to {report_to}")
    if new_model_name:
        args += ["--push_to_hub", f"--hub_model_id {shlex.quote(new_model_name)}"]

    arg_str = " ".join(args)
    commands = [
        "pip install -q 'trl>=0.8' peft datasets",
        f"trl sft {arg_str}",
    ]

    conf: Dict = {
        "type": "task",
        "commands": commands,
        "env": env,
    }
    if tpu:
        conf["resources"] = {"tpu": tpu}
    return TaskConfiguration.model_validate(conf)
