"""Python SDK (parity: reference src/dstack/api — Client + RunCollection)."""

from dstack_tpu.api.client import Client  # noqa: F401
