"""dstack-tpu CLI.

Parity: reference src/dstack/_internal/cli (main.py + commands/*) — argparse
subcommands: server/config/init/apply/ps/stop/logs/delete/offer/fleet/volume/secret/
backend. `apply` dispatches on the configuration `type` (run vs fleet vs volume), like
the reference ApplyCommand (cli/commands/apply.py:90-135)."""

from __future__ import annotations

import argparse
import io
import os
import sys
import tarfile
import time
from pathlib import Path
from typing import List, Optional

import yaml

from dstack_tpu.api.client import Client
from dstack_tpu.cli.config import CliConfig
from dstack_tpu.core.errors import DstackTpuError
from dstack_tpu.core.models.configurations import parse_configuration
from dstack_tpu.server import settings as server_settings


def _client() -> Client:
    cfg = CliConfig.load()
    if not cfg.token:
        raise DstackTpuError(
            "no token configured; run `dstack-tpu config --url URL --token TOKEN`"
        )
    return Client(cfg.url, cfg.token, cfg.project)


def _table(headers: List[str], rows: List[List[str]]) -> str:
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(str(cell)))
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*headers)]
    for row in rows:
        lines.append(fmt.format(*(str(c) for c in row)))
    return "\n".join(lines)


def _age(iso: Optional[str]) -> str:
    if not iso:
        return "-"
    from dstack_tpu.utils.common import from_iso, now_utc, pretty_resources_duration

    try:
        dt = from_iso(str(iso))
    except ValueError:
        return "-"
    return pretty_resources_duration((now_utc() - dt).total_seconds())


# ---------------------------------------------------------------------------- commands


def cmd_server(args) -> None:
    from dstack_tpu.server.app import main as server_main

    server_main(host=args.host, port=args.port)


def cmd_config(args) -> None:
    cfg = CliConfig.load()
    if args.url:
        cfg.url = args.url
    if args.token:
        cfg.token = args.token
    if args.project:
        cfg.project = args.project
    cfg.save()
    print(f"configured {cfg.url} (project {cfg.project})")


def _repo_name() -> str:
    return Path.cwd().name or "repo"


def cmd_init(args) -> None:
    client = _client()
    result = client.repos.init(_repo_name())
    print(f"initialized repo {result['repo_id']} in project {client.project}")


def _pack_code(root: Path, max_size: int) -> Optional[bytes]:
    """tar.gz the working tree (skipping .git and obvious junk); None if too big."""
    buf = io.BytesIO()
    skip_dirs = {".git", "__pycache__", ".venv", "node_modules", ".pytest_cache"}
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        for path in sorted(root.rglob("*")):
            rel = path.relative_to(root)
            if any(part in skip_dirs for part in rel.parts):
                continue
            if path.is_file() and not path.is_symlink():
                tar.add(path, arcname=str(rel))
            if buf.tell() > max_size:
                return None
    data = buf.getvalue()
    return data if len(data) <= max_size else None


def _detect_git(root: Path):
    """(clone_url, commit) when the tree is a git clone whose HEAD exists on a
    remote; None otherwise (falls back to tarball upload)."""
    import subprocess

    def _git(*a):
        r = subprocess.run(
            ["git", "-C", str(root), *a], capture_output=True, text=True, timeout=20
        )
        return r.stdout.strip() if r.returncode == 0 else None

    url = _git("remote", "get-url", "origin")
    commit = _git("rev-parse", "HEAD")
    if not url or not commit:
        return None
    # The worker can only check the commit out if some remote ref contains it.
    if _git("branch", "-r", "--contains", commit) in (None, ""):
        return None
    return url, commit


def _pack_diff(root: Path, max_size: int) -> Optional[bytes]:
    """`git diff HEAD --binary` (staged + unstaged, tracked files); None when git
    fails or the diff exceeds the cap. An empty tree diffs to b""."""
    import subprocess

    r = subprocess.run(
        ["git", "-C", str(root), "diff", "HEAD", "--binary"],
        capture_output=True, timeout=60,
    )
    if r.returncode != 0 or len(r.stdout) > max_size:
        return None
    return r.stdout


def cmd_apply(args) -> None:
    path = Path(args.file)
    data = yaml.safe_load(path.read_text())
    conf = parse_configuration(data)
    client = _client()

    if conf.type == "fleet":
        plan = client.fleets.get_plan({"configuration": data, "configuration_path": str(path)})
        print(f"fleet {plan.effective_name}: {plan.total_offers} offers, action={plan.action}")
        if not args.yes and not _confirm():
            return
        fleet = client.fleets.apply_plan(
            {"configuration": data, "configuration_path": str(path)}, force=args.force
        )
        print(f"fleet {fleet.name} {fleet.status.value}")
        return
    if conf.type == "volume":
        vol = client.volumes.create(data)
        print(f"volume {vol.name} {vol.status.value}")
        return
    if conf.type == "gateway":
        gw = client.gateways.create(data)
        print(f"gateway {gw.name} {gw.status.value}")
        return

    # Run configurations (task/service/dev-environment).
    run_spec: dict = {"configuration": data, "configuration_path": str(path)}
    if args.name:
        run_spec["run_name"] = args.name
    plan = client.runs.get_plan(dict(run_spec))
    name = plan.effective_run_name
    print(f"run {name} ({conf.type}): {plan.total_offers} offers")
    for offer in plan.offers[:3]:
        inst = offer["instance"]
        print(
            f"  {offer['backend']:>8} {offer['region']:<16} {inst['name']:<14}"
            f" ${offer['price']}/hr" + (" (spot)" if offer.get("spot") else "")
        )
    if plan.total_offers == 0:
        print("  no offers match the requirements", file=sys.stderr)
    if not args.yes and not _confirm():
        return

    run_spec["run_name"] = name
    if not args.no_repo:
        cwd = Path.cwd()
        repo = _repo_name()
        git = _detect_git(cwd)
        diff = _pack_diff(cwd, server_settings.MAX_CODE_SIZE) if git else None
        if git is not None and diff is not None:
            # Git mode: workers clone + checkout; only the working-tree diff
            # travels, so repo size never hits the upload cap.
            clone_url, commit = git
            client.repos.init(repo, repo_info={"clone_url": clone_url})
            repo_data = {"mode": "git", "clone_url": clone_url, "commit": commit}
            if diff:
                repo_data["code_hash"] = client.repos.upload_code(repo, diff)
            run_spec["repo_id"] = repo
            run_spec["repo_data"] = repo_data
        else:
            code = _pack_code(cwd, server_settings.MAX_CODE_SIZE)
            if code is None:
                print("warning: working tree exceeds the code size limit; running without code")
            else:
                client.repos.init(repo)
                code_hash = client.repos.upload_code(repo, code)
                run_spec["repo_id"] = repo
                run_spec["repo_data"] = {"code_hash": code_hash}

    if plan.action == "update":
        run = client.runs.update(run_spec)
        print(f"updated {run.run_name} in place ({run.status.value})")
    else:
        run = client.runs.submit(run_spec)
        print(f"submitted {run.run_name} ({run.status.value})")
    if args.detach:
        return
    _attach(client, run.run_name)


def _confirm() -> bool:
    if not sys.stdin.isatty():
        # Non-interactive without -y must not silently act on paid resources —
        # and scripts must SEE the refusal, so this is an error exit, not a
        # quiet False (a cron `stop` that exits 0 having stopped nothing would
        # leave a billing run behind).
        print("error: not a terminal; pass -y to confirm", file=sys.stderr)
        sys.exit(1)
    answer = input("continue? [y/N] ").strip().lower()
    return answer in ("y", "yes")


def cmd_attach(args) -> None:
    client = _client()
    run = client.runs.get(args.run_name)
    forwards = []
    for f in args.forward or []:
        local, _, remote = f.partition(":")
        forwards.append((int(local), int(remote or local)))
    conf = run.run_spec.configuration
    if not forwards and getattr(conf, "type", None) == "dev-environment":
        from dstack_tpu.core.models.configurations import DEFAULT_IDE_PORT

        forwards = [(DEFAULT_IDE_PORT, DEFAULT_IDE_PORT)]
    _attach(client, args.run_name, forwards=forwards)


def _attach(client: Client, run_name: str, forwards=None) -> None:
    """Stream status transitions + logs until the run finishes; optionally forward
    ports over the control plane's attach bridge (parity: reference Run.attach +
    attach.py:28 port-forward — but WS-bridged, see api/attach.py)."""
    forwarder = None
    if forwards:
        from dstack_tpu.api.attach import PortForwarder

        forwarder = PortForwarder(
            client.url, client.token, client.project, run_name, forwards
        )
        forwarder.start()
        for local, remote in forwards:
            print(f"forwarding 127.0.0.1:{local} -> {run_name}:{remote}", file=sys.stderr)
    try:
        _attach_stream(client, run_name)
    finally:
        if forwarder is not None:
            forwarder.stop()


def _attach_stream(client: Client, run_name: str) -> None:
    print(f"attached to {run_name} (Ctrl-C to detach)")
    last_status = None
    line = 0
    try:
        while True:
            run = client.runs.get(run_name)
            if run.status.value != last_status:
                print(f"[{run.status.value}]", file=sys.stderr)
                last_status = run.status.value
            batch = client.logs.poll(run_name, start_line=line)
            for ev in batch.logs:
                sys.stdout.write(ev.message.replace("\r\n", "\n"))
            sys.stdout.flush()
            line += len(batch.logs)
            if run.status.is_finished():
                if not batch.logs:
                    tail = client.logs.poll(run_name, start_line=line)
                    for ev in tail.logs:
                        sys.stdout.write(ev.message.replace("\r\n", "\n"))
                    sys.stdout.flush()
                    print(f"run {run_name} finished: {run.status.value}", file=sys.stderr)
                    if run.status.value == "failed":
                        sys.exit(1)
                    return
            else:
                time.sleep(1.0)
    except KeyboardInterrupt:
        print(f"\ndetached; `dstack-tpu stop {run_name}` to stop the run", file=sys.stderr)


def _watch_loop(render, watch: bool, interval: float) -> None:
    """Run `render()` once, or top(1)-style on an interval until Ctrl-C.
    The whole loop sits under the KeyboardInterrupt handler: an interrupt
    mid-request (slow server) must exit as cleanly as one mid-sleep."""
    try:
        while True:
            render()
            if not watch:
                return
            time.sleep(interval)
    except KeyboardInterrupt:
        return


def _clear_screen() -> None:
    sys.stdout.write("\033[2J\033[H")


def cmd_ps(args) -> None:
    # -w refreshes top(1)-style until Ctrl-C (reference cli/commands/ps.py:35).
    client = _client()

    def render() -> None:
        runs = client.runs.list()
        if not args.all:
            runs = [r for r in runs if not r.status.is_finished()] or runs[:5]
        headers = ["NAME", "TYPE", "RESOURCES", "STATUS", "OWNER", "COST", "AGE"]
        if args.verbose:
            headers.append("WAITING")
            headers.append("PHASES")
        rows = []
        for r in runs:
            conf = r.run_spec.configuration
            resources = conf.resources.pretty() if conf.resources else ""
            # OWNER: which server replica's scheduler holds the run's lease
            # (multi-replica control plane); finished runs hold no lease.
            owner = getattr(r, "owner", None) or "-"
            row = [
                r.run_name, conf.type, resources, r.status.value, owner,
                f"${r.cost:.2f}", _age(r.submitted_at),
            ]
            if args.verbose:
                # WAITING: why the scheduler's last placement pass failed,
                # from the placement decision log (runs.status_message carries
                # `waiting: <reason>` while the run sits queued).
                msg = r.status_message or ""
                row.append(
                    msg[len("waiting:"):].strip()
                    if msg.startswith("waiting:") and r.status.value in ("pending", "submitted")
                    else "-"
                )
                # One events call per listed run: -v is an operator surface,
                # and ps caps the listing anyway.
                try:
                    row.append(_phase_summary(client.runs.get_events(r.run_name)["phases"]))
                except DstackTpuError:
                    row.append("-")
            rows.append(row)
        if args.watch:
            _clear_screen()
        print(_table(headers, rows), flush=True)

    _watch_loop(render, args.watch, 2.0)


def cmd_stop(args) -> None:
    # Parity: the reference's stop prompts unless -y (cli/commands/stop.py).
    if not args.yes and not _confirm():
        return
    client = _client()
    client.runs.stop(args.runs, abort=args.abort)
    print(f"{'aborting' if args.abort else 'stopping'} {', '.join(args.runs)}")


def cmd_delete(args) -> None:
    if not args.yes and not _confirm():
        return
    client = _client()
    client.runs.delete(args.runs)
    print(f"deleted {', '.join(args.runs)}")


def cmd_logs(args) -> None:
    client = _client()
    if args.follow:
        for message in client.logs.tail(args.run_name):
            sys.stdout.write(message.replace("\r\n", "\n"))
            sys.stdout.flush()
        return
    line = 0
    while True:
        batch = client.logs.poll(args.run_name, start_line=line)
        if not batch.logs:
            break
        for ev in batch.logs:
            sys.stdout.write(ev.message.replace("\r\n", "\n"))
        line += len(batch.logs)
    sys.stdout.flush()


def _fmt_goodput(ledger: dict) -> str:
    """One-line goodput attribution:
    `93.1% (compile 12s, checkpoint 2s, restart 40s, rework 31s)`."""
    if not ledger or ledger.get("ratio") is None:
        return "-"
    parts = []
    for key, label in (("compile_s", "compile"), ("input_wait_s", "input"),
                       ("checkpoint_s", "checkpoint"), ("restart_s", "restart"),
                       ("rework_s", "rework"), ("other_s", "other")):
        v = ledger.get(key) or 0.0
        if v >= 0.05:
            parts.append(f"{label} {_fmt_secs(v)}")
    detail = f" ({', '.join(parts)})" if parts else ""
    return f"{ledger['ratio'] * 100:.1f}%{detail}"


def _workload_rows(points: list) -> list:
    from dstack_tpu.utils.common import from_iso

    rows = []
    for p in points:
        try:
            t = from_iso(p["ts"]).strftime("%H:%M:%S")
        except (KeyError, ValueError):
            t = "-"
        mfu = p.get("mfu")
        rows.append(
            [
                t,
                str(p.get("step", "-")),
                _fmt_secs(p.get("step_time_s")),
                f"{p['tokens_per_sec']:,.0f}" if p.get("tokens_per_sec") is not None else "-",
                f"{mfu * 100:.1f}%" if mfu is not None else "-",
                f"{p['loss']:.4f}" if p.get("loss") is not None else "-",
                _fmt_secs(p.get("input_wait_s")) if p.get("input_wait_s") else "-",
            ]
        )
    return rows


def _host_rows(hosts: list) -> list:
    """Per-host gang table rows (gang-health view: one row per host of the
    run, straggler flag last)."""
    rows = []
    for h in hosts:
        cpu = h.get("cpu_percent")
        mem = h.get("mem_bytes")
        rows.append(
            [
                h.get("host", "-"),
                str(h["last_step"]) if h.get("last_step") is not None else "-",
                _fmt_secs(h.get("median_step_s")),
                _fmt_secs(h.get("collective_wait_s")) if h.get("collective_wait_s") else "-",
                _fmt_secs(h.get("input_wait_s")) if h.get("input_wait_s") else "-",
                f"{cpu:.0f}%" if cpu is not None else "-",
                f"{mem / (1024 ** 3):.1f}GB" if mem is not None else "-",
                "STRAGGLER" if h.get("straggler") else "",
            ]
        )
    return rows


def _fmt_skew(skew) -> str:
    if not skew or skew.get("ratio") is None:
        return "-"
    return (
        f"{skew['ratio']:.2f}x (slowest {skew.get('slowest_host', '-')},"
        f" gang median {_fmt_secs(skew.get('gang_median_s'))})"
    )


def cmd_metrics(args) -> None:
    client = _client()
    def render() -> None:
        m = client.metrics.get_job(
            args.run_name, replica_num=args.replica, job_num=args.job, limit=args.limit
        )
        try:
            wl = client.runs.get_metrics(args.run_name, limit=args.limit)
        except Exception:
            wl = None  # an old server without the workload channel
        if args.json:
            # Machine-readable: the workload-metrics payload (hosts/skew/
            # goodput included) plus the sampled resource points — what
            # `dstack-tpu top` and scripts build on.
            import json as json_lib

            payload = dict(wl or {})
            payload["job_metrics"] = [
                {
                    "timestamp": p.timestamp.isoformat(),
                    "cpu_usage_percent": p.cpu_usage_percent,
                    "memory_usage_bytes": p.memory_usage_bytes,
                    "tpu_duty_cycle_percent": p.tpu_duty_cycle_percent,
                    "tpu_hbm_usage_bytes": p.tpu_hbm_usage_bytes,
                }
                for p in m.points
            ]
            print(json_lib.dumps(payload), flush=True)
            return
        if not m.points and not (wl and (wl.get("points") or wl.get("engine"))):
            if not args.watch:
                print("no metrics collected yet (the job may have just started)")
                return
        rows = []
        for p in m.points:
            rows.append(
                [
                    p.timestamp.strftime("%H:%M:%S"),
                    f"{p.cpu_usage_percent:.1f}%",
                    f"{p.memory_usage_bytes / (1024 ** 2):.0f}MB",
                    f"{p.tpu_duty_cycle_percent:.0f}%" if p.tpu_duty_cycle_percent is not None else "-",
                    f"{p.tpu_hbm_usage_bytes / (1024 ** 3):.1f}GB"
                    if p.tpu_hbm_usage_bytes is not None
                    else "-",
                ]
            )
        if args.watch:
            _clear_screen()
        print(_table(["TIME", "CPU", "MEM", "TPU DUTY", "HBM"], rows), flush=True)
        if wl is None:
            return
        # Workload telemetry (emitted by the job itself): per-step series,
        # engine gauges for services, and the goodput ledger.
        points = wl.get("points") or []
        if points:
            print()
            print(
                _table(
                    ["TIME", "STEP", "STEP TIME", "TOK/S", "MFU", "LOSS", "INPUT WAIT"],
                    _workload_rows(points[-args.limit:]),
                ),
                flush=True,
            )
        engine = wl.get("engine")
        if engine:
            print()
            print(
                _table(
                    ["QUEUE", "ACTIVE", "TOKENS", "PREEMPT", "PREFIX HIT", "SPEC ACCEPT"],
                    [[
                        str(engine.get("queue_depth", "-")),
                        str(engine.get("active", "-")),
                        str(engine.get("generated_tokens", "-")),
                        str(engine.get("preemptions", "-")),
                        f"{engine['prefix_hit_rate']:.2f}" if engine.get("prefix_hit_rate") is not None else "-",
                        f"{engine['spec_accept_rate']:.2f}" if engine.get("spec_accept_rate") is not None else "-",
                    ]],
                ),
                flush=True,
            )
        # Per-host gang view (ISSUE 15): every host of the run with its
        # window-median step time, collective/input wait, hardware sample,
        # and the straggler flag; skew line when the gang has >= 2 hosts.
        hosts = wl.get("hosts") or []
        if len(hosts) > 1 or any(h.get("straggler") for h in hosts):
            print()
            print(
                _table(
                    ["HOST", "LAST STEP", "STEP TIME", "COLL WAIT", "INPUT WAIT",
                     "CPU", "MEM", "FLAG"],
                    _host_rows(hosts),
                ),
                flush=True,
            )
            if wl.get("skew"):
                print(f"\nstep skew: {_fmt_skew(wl['skew'])}", flush=True)
        if points or engine:
            print(f"\ngoodput: {_fmt_goodput(wl.get('goodput'))}", flush=True)
            if wl.get("dropped"):
                print(f"(emitter dropped {wl['dropped']} points)", flush=True)

    _watch_loop(render, args.watch, args.interval)


def cmd_profile(args) -> None:
    """Trigger jax.profiler trace capture inside a run's live workload and
    wait for the artifact (`dstack-tpu profile RUN --seconds N`)."""
    import time as time_lib

    client = _client()
    # Snapshot the latest profile mark BEFORE requesting: agent profile ids
    # restart with the agent process, so an id match alone could hit a STALE
    # profile_end from a capture that predates this request.
    try:
        before = (client.runs.get_metrics(args.run_name) or {}).get("profile")
    except Exception:
        before = None
    ack = client.runs.profile(args.run_name, seconds=args.seconds)
    print(
        f"profile requested (id {ack.get('id')}): capturing {args.seconds:g}s"
        f" on job {ack.get('job_num')}/{ack.get('replica_num')}"
    )
    print(f"artifact dir (on the runner host): {ack.get('artifact_dir')}")
    if args.no_wait:
        return
    # The capture completes asynchronously: the workload's profile_end mark
    # flows back through the agent's next metrics samples.
    deadline = time_lib.monotonic() + args.seconds + args.timeout
    want_id = ack.get("id")
    while time_lib.monotonic() < deadline:
        time_lib.sleep(2.0)
        mark = (client.runs.get_metrics(args.run_name) or {}).get("profile")
        if not mark or mark == before:
            continue  # nothing new since the request
        if want_id is not None and mark.get("profile_id") != want_id:
            continue
        if mark.get("event") == "profile_end":
            print(f"trace captured: {mark.get('artifact')}")
            return
        if mark.get("event") == "profile_error":
            raise DstackTpuError(f"profiler failed in the workload: {mark.get('error')}")
    raise DstackTpuError(
        "timed out waiting for the profile_end mark (the capture may still"
        f" finish; re-check `dstack-tpu metrics {args.run_name}` later —"
        f" the artifact would land in {ack.get('artifact_dir')})"
    )


def _fmt_secs(seconds) -> str:
    if seconds is None:
        return "-"
    if seconds < 1:
        return f"{seconds * 1000:.0f}ms"
    if seconds < 60:
        return f"{seconds:.1f}s"
    from dstack_tpu.utils.common import pretty_resources_duration

    return pretty_resources_duration(seconds)


def _phase_summary(phases: dict) -> str:
    parts = []
    for name in ("queue", "provision", "pull", "run"):
        if phases.get(name) is not None:
            parts.append(f"{name}={_fmt_secs(phases[name])}")
    return " ".join(parts) or "-"


def cmd_events(args) -> None:
    """Print a run's lifecycle timeline with per-phase durations."""
    client = _client()
    data = client.runs.get_events(args.run_name)
    if args.json:
        import json as json_lib

        print(json_lib.dumps(data), flush=True)
        return
    events = data["events"]
    if not events:
        print(f"no events recorded for {args.run_name}")
        return
    from dstack_tpu.utils.common import from_iso

    t0 = from_iso(events[0]["timestamp"])
    rows = []
    for ev in events:
        offset = (from_iso(ev["timestamp"]) - t0).total_seconds()
        transition = (
            f"{ev['old_status']} -> {ev['new_status']}"
            if ev["old_status"]
            else ev["new_status"]
        )
        scope = "run" if ev["job_id"] is None else f"job {ev['job_id'][:8]}"
        detail = ev["reason"] or ""
        if ev["message"]:
            detail = f"{detail}: {ev['message']}" if detail else ev["message"]
        rows.append(
            [f"+{_fmt_secs(offset)}", scope, transition, ev["actor"], detail or "-"]
        )
    print(f"run {data['run_name']} ({data['status']})")
    print(_table(["TIME", "SCOPE", "TRANSITION", "ACTOR", "REASON"], rows))
    phases = data["phases"]
    print()
    print("phases:")
    for name in ("queue", "provision", "pull", "run", "total"):
        print(f"  {name:<10} {_fmt_secs(phases.get(name))}")


def cmd_top(args) -> None:
    """Live fleet health view (`dstack-tpu top`): runs × hosts over the
    existing REST API — last step, step time, collective wait, MFU, goodput,
    skew, straggler flag per host — so an operator watches a pod's health
    without a Prometheus stack. A one-line fleet accounting header (chips by
    state, queued runs, $/hr burn) tops the frame. Refreshes top(1)-style by
    default; --once renders a single frame; --json emits one frame of
    machine-readable fleet summary + live runs."""
    client = _client()

    def _fleet_header() -> tuple:
        try:
            fleet = client.usage.get()["fleet"]
        except DstackTpuError:
            return None, ""
        line = (
            f"fleet: {fleet['total_chips']} chips"
            f" ({fleet['allocated_chips']} allocated, {fleet['idle_chips']} idle,"
            f" {fleet['provisioning_chips']} provisioning)"
            f" · {fleet['queued_runs']} queued"
            f" · ${fleet['dollars_per_hour']:.2f}/hr"
        )
        return fleet, line

    if args.json:
        import json as json_lib

        fleet, _ = _fleet_header()
        runs = [r for r in client.runs.list() if not r.status.is_finished()]
        print(
            json_lib.dumps(
                {
                    "fleet": fleet,
                    "runs": [
                        {"run_name": r.run_name, "status": r.status.value}
                        for r in runs
                    ],
                }
            ),
            flush=True,
        )
        return

    def render() -> None:
        runs = [r for r in client.runs.list() if not r.status.is_finished()]
        headers = ["RUN", "STATUS", "HOST", "STEP", "STEP TIME", "COLL WAIT",
                   "MFU", "TOK/S", "GOODPUT", "SKEW", "TTFT", "ITL", "FLAG"]
        rows = []
        for r in runs:
            try:
                wl = client.runs.get_metrics(r.run_name, limit=1)
            except DstackTpuError:
                wl = None
            if not wl:
                rows.append([r.run_name, r.status.value] + ["-"] * 11)
                continue
            latest = wl.get("latest") or {}
            ledger = wl.get("goodput") or {}
            goodput = (
                f"{ledger['ratio'] * 100:.1f}%" if ledger.get("ratio") is not None else "-"
            )
            skew = wl.get("skew") or {}
            skew_s = f"{skew['ratio']:.2f}x" if skew.get("ratio") is not None else "-"
            # Serving latency (engine flight-recorder summary, rendered
            # p50/p99): only service runs emit these; training rows show "-".
            engine = wl.get("engine") or {}
            ttft_s = (
                f"{engine['ttft_p50_ms']:.0f}/{engine['ttft_p99_ms']:.0f}ms"
                if engine.get("ttft_p50_ms") is not None else "-"
            )
            itl_s = (
                f"{engine['itl_p50_ms']:.0f}/{engine['itl_p99_ms']:.0f}ms"
                if engine.get("itl_p50_ms") is not None else "-"
            )
            hosts = wl.get("hosts") or []
            if not hosts:
                mfu = latest.get("mfu")
                rows.append(
                    [
                        r.run_name, r.status.value, "-",
                        str(latest.get("step", "-")),
                        _fmt_secs(latest.get("step_time_s")),
                        "-",
                        f"{mfu * 100:.1f}%" if mfu is not None else "-",
                        f"{latest['tokens_per_sec']:,.0f}"
                        if latest.get("tokens_per_sec") is not None else "-",
                        goodput, skew_s, ttft_s, itl_s, "",
                    ]
                )
                continue
            for i, h in enumerate(hosts):
                mfu = h.get("mfu")
                rows.append(
                    [
                        r.run_name if i == 0 else "",  # group rows by run
                        r.status.value if i == 0 else "",
                        h.get("host", "-"),
                        str(h["last_step"]) if h.get("last_step") is not None else "-",
                        _fmt_secs(h.get("median_step_s")),
                        _fmt_secs(h.get("collective_wait_s"))
                        if h.get("collective_wait_s") else "-",
                        f"{mfu * 100:.1f}%" if mfu is not None else "-",
                        f"{latest['tokens_per_sec']:,.0f}"
                        if i == 0 and latest.get("tokens_per_sec") is not None else
                        ("-" if i == 0 else ""),
                        goodput if i == 0 else "",
                        skew_s if i == 0 else "",
                        ttft_s if i == 0 else "",
                        itl_s if i == 0 else "",
                        "STRAGGLER" if h.get("straggler") else "",
                    ]
                )
        if not args.once:
            _clear_screen()
        _, header = _fleet_header()
        if header:
            print(header, flush=True)
        if rows:
            print(_table(headers, rows), flush=True)
        else:
            print("no live runs", flush=True)

    _watch_loop(render, not args.once, args.interval)


def _fmt_ms(seconds) -> str:
    if seconds is None:
        return "-"
    return f"{seconds * 1000:.1f}ms" if seconds < 1 else f"{seconds:.2f}s"


def _render_trace_timeline(t: dict) -> None:
    """ASCII span timeline for one flight-recorder record: where the
    request's wall time went, stage by stage, on one scale."""
    total = t.get("total_s") or 0.0
    stages = [
        ("queue", t.get("queue_wait_s") or 0.0),
        ("prefill", t.get("prefill_s") or 0.0),
        ("decode", t.get("decode_s") or 0.0),
    ]
    print(
        f"request {t.get('req_id', '-')}  trace {t.get('trace_id') or '-'}"
        f"  replica {t.get('replica', '-')}"
        + ("  [SLOW]" if t.get("slow") else "")
    )
    print(
        f"  prompt {t.get('prompt_tokens', '-')} tok"
        f" (cached {t.get('cached_tokens', 0)}),"
        f" generated {t.get('tokens', '-')} tok,"
        f" preemptions {t.get('preemptions', 0)},"
        f" spec accepted {t.get('spec_accepted', 0)}/{t.get('spec_proposed', 0)}"
    )
    width = 40
    offset = 0.0
    for name, dur in stages:
        if total > 0:
            lead = int(round(offset / total * width))
            bar = max(int(round(dur / total * width)), 1 if dur > 0 else 0)
        else:
            lead = bar = 0
        print(f"  {name:<8} {' ' * lead}{'█' * bar:<{width - lead}} {_fmt_ms(dur)}")
        offset += dur
    print(f"  {'total':<8} {'─' * width} {_fmt_ms(total)}"
          f"  (ttft {_fmt_ms(t.get('ttft_s'))})")


def cmd_trace(args) -> None:
    """Per-request flight-recorder view (`dstack-tpu trace <run>`): the last
    N completed requests across the service's replicas, and a stage-by-stage
    span timeline for a specific request (--request engine id, or --trace the
    X-Dstack-Trace-Id a client response carried)."""
    client = _client()
    data = client.runs.get_traces(
        args.run_name,
        request_id=args.request,
        trace_id=args.trace,
        limit=args.limit,
    )
    if args.json:
        import json as json_lib

        print(json_lib.dumps(data), flush=True)
        return
    for err in data.get("errors") or []:
        print(f"warning: replica {err.get('replica')}: {err.get('error')}")
    traces = data.get("traces") or []
    if not traces:
        where = (
            f" matching {args.request or args.trace}"
            if (args.request or args.trace) else ""
        )
        print(
            f"no recorded request traces{where}"
            f" ({data.get('replicas_queried', 0)} replicas queried;"
            " the flight recorder only holds completed requests)"
        )
        return
    if args.request or args.trace:
        # Narrowed query: full span timeline per match (usually exactly one).
        for t in traces:
            _render_trace_timeline(t)
            print()
        return
    rows = [
        [
            t.get("req_id", "-"),
            (t.get("trace_id") or "-")[:16],
            str(t.get("replica", "-")),
            _fmt_ms(t.get("queue_wait_s")),
            _fmt_ms(t.get("prefill_s")),
            _fmt_ms(t.get("ttft_s")),
            _fmt_ms(t.get("decode_s")),
            _fmt_ms(t.get("total_s")),
            str(t.get("tokens", "-")),
            "SLOW" if t.get("slow") else "",
        ]
        for t in traces
    ]
    print(
        _table(
            ["REQUEST", "TRACE", "REPLICA", "QUEUE", "PREFILL", "TTFT",
             "DECODE", "TOTAL", "TOK", "FLAG"],
            rows,
        ),
        flush=True,
    )
    print(
        "\nrun `dstack-tpu trace "
        f"{args.run_name} --request <REQUEST>` for a span timeline"
    )


def cmd_offer(args) -> None:
    client = _client()
    resources = {}
    if args.tpu:
        resources["tpu"] = args.tpu
    result = client.offers.list(
        resources=resources, spot=args.spot, max_price=args.max_price, limit=args.limit
    )
    rows = [
        [
            o["backend"],
            o["region"],
            o["instance"]["name"],
            str(o.get("hosts_per_slice", 1)),
            "spot" if o.get("spot") else "on-demand",
            f"${o['price']}/hr",
        ]
        for o in result["offers"][: args.limit]
    ]
    print(_table(["BACKEND", "REGION", "INSTANCE", "HOSTS", "KIND", "PRICE"], rows))
    print(f"{result['total']} offers total")


def _parse_since(value):
    """`--since` accepts a relative window (\"2h\", \"30m\", \"1d\") or an ISO
    timestamp; relatives resolve client-side so the server stays stateless."""
    if not value:
        return None
    import datetime
    import re as re_lib

    from dstack_tpu.utils.common import now_utc, to_iso

    m = re_lib.fullmatch(r"(\d+)([smhd])", value.strip())
    if m:
        seconds = int(m.group(1)) * {"s": 1, "m": 60, "h": 3600, "d": 86400}[m.group(2)]
        return to_iso(now_utc() - datetime.timedelta(seconds=seconds))
    return value


def cmd_usage(args) -> None:
    """Fleet accounting readout (`dstack-tpu usage`): chip-seconds, estimated
    dollars, goodput-weighted chip-seconds, and queue wait attributed to each
    run, with per-project totals and the fleet burn line."""
    client = _client()
    data = client.usage.get(project=args.project, since=_parse_since(args.since))
    if args.json:
        import json as json_lib

        print(json_lib.dumps(data), flush=True)
        return
    fleet = data["fleet"]
    print(
        f"fleet: {fleet['total_chips']} chips"
        f" ({fleet['allocated_chips']} allocated, {fleet['idle_chips']} idle,"
        f" {fleet['provisioning_chips']} provisioning)"
        f" · {fleet['queued_runs']} queued"
        f" · ${fleet['dollars_per_hour']:.2f}/hr"
    )
    if not data["runs"]:
        print("no usage recorded" + (f" since {data['since']}" if data["since"] else ""))
        return
    rows = [
        [
            r["project"],
            r["run_name"],
            r["user"] or "-",
            f"{r['chip_seconds']:,.0f}",
            f"{r['goodput_chip_seconds']:,.0f}",
            f"${r['dollars']:.2f}",
            _fmt_secs(r["queue_wait_s"]) if r["queue_wait_s"] is not None else "-",
            r["status"],
        ]
        for r in data["runs"]
    ]
    print(
        _table(
            ["PROJECT", "RUN", "USER", "CHIP-S", "GOODPUT-CHIP-S", "$EST",
             "QUEUE-WAIT", "STATUS"],
            rows,
        )
    )
    print()
    totals = [
        [
            t["project"], str(t["runs"]), f"{t['chip_seconds']:,.0f}",
            f"{t['goodput_chip_seconds']:,.0f}", f"${t['dollars']:.2f}",
        ]
        for t in data["projects"]
    ]
    print(_table(["PROJECT", "RUNS", "CHIP-S", "GOODPUT-CHIP-S", "$EST"], totals))


_SUBCOMMANDS = (
    "server config init apply attach metrics events ps top trace usage stop delete logs"
    " offer fleet gateway volume secret backend instance project profile stats completion"
)


def cmd_completion(args) -> None:
    """Emit a shell completion script (parity: reference `dstack completion`)."""
    if args.shell == "bash":
        print(f'complete -W "{_SUBCOMMANDS}" dstack-tpu')
    else:  # zsh
        print("autoload -Uz compinit && compinit")
        print(f'compdef "_arguments \'1:command:({_SUBCOMMANDS})\'" dstack-tpu')


def cmd_gateway(args) -> None:
    client = _client()
    if args.action == "list":
        rows = [
            [g.name, g.status.value, g.ip_address or "-", g.hostname or "-",
             "yes" if g.default else ""]
            for g in client.gateways.list()
        ]
        print(_table(["GATEWAY", "STATUS", "IP", "DOMAIN", "DEFAULT"], rows))
    elif args.action == "delete":
        client.gateways.delete(args.names)
        print(f"deleted {len(args.names)} gateway(s)")


def cmd_project(args) -> None:
    client = _client()
    if args.action == "list":
        rows = [
            [
                p["project_name"],
                (p.get("owner") or {}).get("username", "-"),
                str(len(p.get("members") or [])),
            ]
            for p in client.projects.list()
        ]
        print(_table(["PROJECT", "OWNER", "MEMBERS"], rows))
    elif args.action == "create":
        for name in args.names:
            client.projects.create(name)
            print(f"created project {name}")
    elif args.action == "delete":
        client.projects.delete(args.names)
        print(f"deleted {len(args.names)} project(s)")


def cmd_fleet(args) -> None:
    client = _client()
    if args.action == "list":
        rows = []
        for f in client.fleets.list():
            rows.append(
                [
                    f.name,
                    f.status.value,
                    str(len(f.instances)),
                    ", ".join(sorted({i.status.value for i in f.instances})) or "-",
                ]
            )
        print(_table(["FLEET", "STATUS", "INSTANCES", "INSTANCE STATUS", ], rows))
    elif args.action == "delete":
        client.fleets.delete(args.names)
        print(f"deleting {', '.join(args.names)}")


def cmd_volume(args) -> None:
    client = _client()
    if args.action == "list":
        rows = [
            [v.name, v.configuration.backend, v.configuration.region, v.status.value,
             str(len(v.attachments))]
            for v in client.volumes.list()
        ]
        print(_table(["VOLUME", "BACKEND", "REGION", "STATUS", "ATTACHED"], rows))
    elif args.action == "delete":
        client.volumes.delete(args.names)
        print(f"deleted {', '.join(args.names)}")


def cmd_secret(args) -> None:
    client = _client()
    if args.action == "set":
        if not args.name or args.value is None:
            raise DstackTpuError("usage: dstack-tpu secret set NAME VALUE")
        client.secrets.set(args.name, args.value)
        print(f"secret {args.name} set")
    elif args.action == "list":
        for name in client.secrets.list():
            print(name)
    elif args.action == "delete":
        if not args.name:
            raise DstackTpuError("usage: dstack-tpu secret delete NAME")
        client.secrets.delete([args.name])
        print(f"secret {args.name} deleted")


def cmd_backend(args) -> None:
    client = _client()
    if args.action in ("create", "delete") and not args.type:
        raise DstackTpuError(f"usage: dstack-tpu backend {args.action} TYPE")
    if args.action == "list":
        for b in client.backends.list():
            print(b["type"])
    elif args.action == "create":
        client.backends.create({"type": args.type})
        print(f"backend {args.type} configured")
    elif args.action == "delete":
        client.backends.delete([args.type])
        print(f"backend {args.type} removed")


def cmd_instance(args) -> None:
    client = _client()
    rows = [
        [
            i.name,
            i.fleet_name or "-",
            i.instance_type.name if i.instance_type else "-",
            i.status.value,
            i.slice_name or "-",
            f"{i.worker_num}/{i.hosts_per_slice}",
        ]
        for i in client.instances.list()
    ]
    print(_table(["INSTANCE", "FLEET", "TYPE", "STATUS", "SLICE", "WORKER"], rows))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dstack-tpu", description="TPU workload orchestrator")
    sub = p.add_subparsers(dest="command", required=True)

    s = sub.add_parser("server", help="start the control-plane server")
    s.add_argument("--host", default=None)
    s.add_argument("--port", type=int, default=None)
    s.set_defaults(func=cmd_server)

    s = sub.add_parser("config", help="configure server url/token/project")
    s.add_argument("--url")
    s.add_argument("--token")
    s.add_argument("--project")
    s.set_defaults(func=cmd_config)

    s = sub.add_parser("init", help="register the current directory as a repo")
    s.set_defaults(func=cmd_init)

    s = sub.add_parser("apply", help="apply a configuration (run/fleet/volume)")
    s.add_argument("-f", "--file", required=True)
    s.add_argument("-y", "--yes", action="store_true")
    s.add_argument("-d", "--detach", action="store_true")
    s.add_argument("--force", action="store_true")
    s.add_argument("--name", help="override the run name")
    s.add_argument("--no-repo", action="store_true", help="do not upload the working tree")
    s.set_defaults(func=cmd_apply)

    s = sub.add_parser("attach", help="stream logs and forward ports to a run")
    s.add_argument("run_name")
    s.add_argument(
        "-L", "--forward", action="append", metavar="LOCAL[:REMOTE]",
        help="forward 127.0.0.1:LOCAL to the run's REMOTE port (repeatable)",
    )
    s.set_defaults(func=cmd_attach)

    for alias in ("metrics", "stats"):
        s = sub.add_parser(
            alias,
            help="show a run's resource + workload metrics (step time, tok/s,"
                 " MFU, loss, engine gauges, goodput)",
        )
        s.add_argument("run_name")
        s.add_argument("--replica", type=int, default=0)
        s.add_argument("--job", type=int, default=0)
        s.add_argument("--limit", type=int, default=20)
        s.add_argument("-w", "--watch", action="store_true", help="refresh continuously")
        s.add_argument("--interval", type=float, default=5.0)
        s.add_argument("--json", action="store_true",
                       help="machine-readable output (workload metrics incl."
                            " per-host table, skew, goodput + resource points)")
        s.set_defaults(func=cmd_metrics)

    s = sub.add_parser(
        "profile",
        help="capture a jax.profiler trace inside a run's live workload",
    )
    s.add_argument("run_name")
    s.add_argument("--seconds", type=float, default=5.0,
                   help="trace capture duration")
    s.add_argument("--no-wait", action="store_true", dest="no_wait",
                   help="request the capture and return immediately")
    s.add_argument("--timeout", type=float, default=180.0,
                   help="extra seconds to wait for the artifact after the"
                        " capture window closes (trace start/stop can lag"
                        " tens of seconds on a loaded host)")
    s.set_defaults(func=cmd_profile)

    s = sub.add_parser("ps", help="list runs")
    s.add_argument("-a", "--all", action="store_true")
    s.add_argument("-w", "--watch", action="store_true", help="refresh continuously")
    s.add_argument(
        "-v", "--verbose", action="store_true",
        help="include per-run phase durations (queue/provision/pull/run)",
    )
    s.set_defaults(func=cmd_ps)

    s = sub.add_parser("events", help="print a run's lifecycle timeline")
    s.add_argument("run_name")
    s.add_argument("--json", action="store_true",
                   help="machine-readable output (events + phases)")
    s.set_defaults(func=cmd_events)

    s = sub.add_parser(
        "top",
        help="live fleet health: runs × hosts with step time, collective"
             " wait, MFU, goodput, skew, straggler flags",
    )
    s.add_argument("--interval", type=float, default=2.0)
    s.add_argument("--once", action="store_true",
                   help="render one frame and exit (no refresh loop)")
    s.add_argument("--json", action="store_true",
                   help="machine-readable single frame (fleet summary + live runs)")
    s.set_defaults(func=cmd_top)

    s = sub.add_parser(
        "usage",
        help="fleet accounting: chip-seconds, $ estimate, goodput-weighted"
             " chip-seconds, and queue wait per run and project",
    )
    s.add_argument("--project", help="narrow to one project")
    s.add_argument("--since",
                   help="only count ledger buckets at or after this time"
                        " (ISO timestamp, or a relative window like 2h / 30m / 1d)")
    s.add_argument("--json", action="store_true",
                   help="machine-readable output (runs, project totals, fleet)")
    s.set_defaults(func=cmd_usage)

    s = sub.add_parser(
        "trace",
        help="per-request serving traces from the replicas' flight recorders"
             " (stage timeline: queue wait, prefill, TTFT, decode)",
    )
    s.add_argument("run_name")
    s.add_argument("--request", help="narrow to one engine request id")
    s.add_argument("--trace",
                   help="narrow to one trace id (the X-Dstack-Trace-Id header"
                        " a proxied response carried)")
    s.add_argument("--limit", type=int, default=20)
    s.add_argument("--json", action="store_true",
                   help="machine-readable output (merged trace records)")
    s.set_defaults(func=cmd_trace)

    s = sub.add_parser("stop", help="stop runs")
    s.add_argument("runs", nargs="+")
    s.add_argument("-x", "--abort", action="store_true")
    s.add_argument("-y", "--yes", action="store_true")
    s.set_defaults(func=cmd_stop)

    s = sub.add_parser("delete", help="delete finished runs")
    s.add_argument("runs", nargs="+")
    s.add_argument("-y", "--yes", action="store_true")
    s.set_defaults(func=cmd_delete)

    s = sub.add_parser("logs", help="print run logs")
    s.add_argument("run_name")
    s.add_argument("-f", "--follow", action="store_true")
    s.set_defaults(func=cmd_logs)

    s = sub.add_parser("offer", help="browse TPU slice offers")
    s.add_argument("--tpu", help="slice name, e.g. v5p-16")
    s.add_argument("--spot", action="store_true", default=None)
    s.add_argument("--max-price", type=float)
    s.add_argument("--limit", type=int, default=30)
    s.set_defaults(func=cmd_offer)

    s = sub.add_parser("fleet", help="manage fleets")
    s.add_argument("action", choices=["list", "delete"])
    s.add_argument("names", nargs="*")
    s.set_defaults(func=cmd_fleet)

    s = sub.add_parser("project", help="manage projects")
    s.add_argument("action", choices=["list", "create", "delete"])
    s.add_argument("names", nargs="*")
    s.set_defaults(func=cmd_project)

    s = sub.add_parser("completion", help="print a shell completion script")
    s.add_argument("shell", choices=["bash", "zsh"])
    s.set_defaults(func=cmd_completion)

    s = sub.add_parser("gateway", help="manage gateways")
    s.add_argument("action", choices=["list", "delete"])
    s.add_argument("names", nargs="*")
    s.set_defaults(func=cmd_gateway)

    s = sub.add_parser("volume", help="manage volumes")
    s.add_argument("action", choices=["list", "delete"])
    s.add_argument("names", nargs="*")
    s.set_defaults(func=cmd_volume)

    s = sub.add_parser("secret", help="manage project secrets")
    s.add_argument("action", choices=["set", "list", "delete"])
    s.add_argument("name", nargs="?")
    s.add_argument("value", nargs="?")
    s.set_defaults(func=cmd_secret)

    s = sub.add_parser("backend", help="manage project backends")
    s.add_argument("action", choices=["list", "create", "delete"])
    s.add_argument("type", nargs="?")
    s.set_defaults(func=cmd_backend)

    s = sub.add_parser("instance", help="list instances")
    s.set_defaults(func=cmd_instance)

    return p


def main(argv: Optional[List[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    try:
        args.func(args)
    except DstackTpuError as e:
        print(f"error: {e}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
