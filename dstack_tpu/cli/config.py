"""CLI-side config: ~/.dstack-tpu/config.yml (parity: reference
core/services/configs ConfigManager — server url/token/project per profile)."""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional

import yaml

CONFIG_DIR = Path(os.getenv("DSTACK_TPU_CLI_CONFIG_DIR", os.path.expanduser("~/.dstack-tpu")))
CONFIG_PATH = CONFIG_DIR / "config.yml"


class CliConfig:
    def __init__(self, url: str = "http://127.0.0.1:3000", token: str = "", project: str = "main"):
        self.url = url
        self.token = token
        self.project = project

    @classmethod
    def load(cls) -> "CliConfig":
        if not CONFIG_PATH.exists():
            return cls(
                url=os.getenv("DSTACK_TPU_URL", "http://127.0.0.1:3000"),
                token=os.getenv("DSTACK_TPU_TOKEN", ""),
                project=os.getenv("DSTACK_TPU_PROJECT", "main"),
            )
        data = yaml.safe_load(CONFIG_PATH.read_text()) or {}
        return cls(
            url=os.getenv("DSTACK_TPU_URL") or data.get("url", "http://127.0.0.1:3000"),
            token=os.getenv("DSTACK_TPU_TOKEN") or data.get("token", ""),
            project=os.getenv("DSTACK_TPU_PROJECT") or data.get("project", "main"),
        )

    def save(self) -> None:
        CONFIG_DIR.mkdir(parents=True, exist_ok=True)
        CONFIG_PATH.write_text(
            yaml.safe_dump({"url": self.url, "token": self.token, "project": self.project})
        )
        os.chmod(CONFIG_PATH, 0o600)
