"""Ordered schema migrations (alembic equivalent; parity: reference server/migrations/).

Wire payloads (specs, provisioning data) are stored as JSON text next to indexed scalar
columns — the same shape the reference uses for run_spec/job_spec columns."""

from __future__ import annotations

import sqlite3
from typing import List, Tuple

MIGRATIONS: List[Tuple[int, str]] = [
    (
        1,
        """
        CREATE TABLE users (
            id TEXT PRIMARY KEY,
            username TEXT NOT NULL UNIQUE,
            global_role TEXT NOT NULL DEFAULT 'user',
            email TEXT,
            token TEXT NOT NULL UNIQUE,
            active INTEGER NOT NULL DEFAULT 1,
            created_at TEXT NOT NULL
        );
        CREATE TABLE projects (
            id TEXT PRIMARY KEY,
            name TEXT NOT NULL,
            owner_id TEXT NOT NULL REFERENCES users(id),
            created_at TEXT NOT NULL,
            deleted INTEGER NOT NULL DEFAULT 0
        );
        CREATE UNIQUE INDEX ux_projects_live_name ON projects(name) WHERE deleted = 0;
        CREATE TABLE members (
            project_id TEXT NOT NULL REFERENCES projects(id) ON DELETE CASCADE,
            user_id TEXT NOT NULL REFERENCES users(id) ON DELETE CASCADE,
            project_role TEXT NOT NULL DEFAULT 'user',
            PRIMARY KEY (project_id, user_id)
        );
        CREATE TABLE backends (
            id TEXT PRIMARY KEY,
            project_id TEXT NOT NULL REFERENCES projects(id) ON DELETE CASCADE,
            type TEXT NOT NULL,
            config TEXT NOT NULL,
            auth TEXT,
            UNIQUE (project_id, type)
        );
        CREATE TABLE repos (
            id TEXT PRIMARY KEY,
            project_id TEXT NOT NULL REFERENCES projects(id) ON DELETE CASCADE,
            name TEXT NOT NULL,
            type TEXT NOT NULL DEFAULT 'local',
            info TEXT,
            creds TEXT,
            UNIQUE (project_id, name)
        );
        CREATE TABLE codes (
            id TEXT PRIMARY KEY,
            repo_id TEXT NOT NULL REFERENCES repos(id) ON DELETE CASCADE,
            blob_hash TEXT NOT NULL,
            blob BLOB,
            UNIQUE (repo_id, blob_hash)
        );
        CREATE TABLE fleets (
            id TEXT PRIMARY KEY,
            project_id TEXT NOT NULL REFERENCES projects(id) ON DELETE CASCADE,
            name TEXT NOT NULL,
            status TEXT NOT NULL DEFAULT 'active',
            status_message TEXT,
            spec TEXT NOT NULL,
            created_at TEXT NOT NULL,
            last_processed_at TEXT,
            auto_created INTEGER NOT NULL DEFAULT 0,
            deleted INTEGER NOT NULL DEFAULT 0
        );
        CREATE INDEX ix_fleets_project ON fleets(project_id, deleted);
        CREATE TABLE instances (
            id TEXT PRIMARY KEY,
            project_id TEXT NOT NULL REFERENCES projects(id) ON DELETE CASCADE,
            fleet_id TEXT REFERENCES fleets(id),
            name TEXT NOT NULL,
            instance_num INTEGER NOT NULL DEFAULT 0,
            status TEXT NOT NULL DEFAULT 'pending',
            unreachable INTEGER NOT NULL DEFAULT 0,
            termination_reason TEXT,
            created_at TEXT NOT NULL,
            started_at TEXT,
            finished_at TEXT,
            last_processed_at TEXT,
            backend TEXT,
            region TEXT,
            availability_zone TEXT,
            price REAL,
            instance_type TEXT,
            offer TEXT,
            job_provisioning_data TEXT,
            remote_connection_info TEXT,
            profile TEXT,
            requirements TEXT,
            slice_id TEXT,
            slice_name TEXT,
            worker_num INTEGER NOT NULL DEFAULT 0,
            hosts_per_slice INTEGER NOT NULL DEFAULT 1,
            total_blocks INTEGER NOT NULL DEFAULT 1,
            busy_blocks INTEGER NOT NULL DEFAULT 0,
            idle_since TEXT,
            idle_duration INTEGER,
            termination_deadline TEXT,
            health TEXT,
            deleted INTEGER NOT NULL DEFAULT 0
        );
        CREATE INDEX ix_instances_project ON instances(project_id, deleted, status);
        CREATE INDEX ix_instances_slice ON instances(slice_id);
        CREATE TABLE runs (
            id TEXT PRIMARY KEY,
            project_id TEXT NOT NULL REFERENCES projects(id) ON DELETE CASCADE,
            user_id TEXT NOT NULL REFERENCES users(id),
            repo_id TEXT,
            fleet_id TEXT,
            run_name TEXT NOT NULL,
            submitted_at TEXT NOT NULL,
            last_processed_at TEXT,
            status TEXT NOT NULL DEFAULT 'submitted',
            termination_reason TEXT,
            status_message TEXT,
            run_spec TEXT NOT NULL,
            service_spec TEXT,
            desired_replica_count INTEGER NOT NULL DEFAULT 1,
            next_triggered_at TEXT,
            deleted INTEGER NOT NULL DEFAULT 0
        );
        CREATE UNIQUE INDEX ux_runs_live_name ON runs(project_id, run_name) WHERE deleted = 0;
        CREATE INDEX ix_runs_status ON runs(status) WHERE deleted = 0;
        CREATE TABLE jobs (
            id TEXT PRIMARY KEY,
            project_id TEXT NOT NULL REFERENCES projects(id) ON DELETE CASCADE,
            run_id TEXT NOT NULL REFERENCES runs(id) ON DELETE CASCADE,
            run_name TEXT NOT NULL,
            job_num INTEGER NOT NULL DEFAULT 0,
            replica_num INTEGER NOT NULL DEFAULT 0,
            submission_num INTEGER NOT NULL DEFAULT 0,
            job_spec TEXT NOT NULL,
            status TEXT NOT NULL DEFAULT 'submitted',
            termination_reason TEXT,
            termination_reason_message TEXT,
            exit_status INTEGER,
            submitted_at TEXT NOT NULL,
            last_processed_at TEXT,
            finished_at TEXT,
            job_provisioning_data TEXT,
            job_runtime_data TEXT,
            instance_id TEXT REFERENCES instances(id),
            used_instance_id TEXT,
            disconnected_at TEXT,
            inactivity_secs INTEGER,
            remove_at TEXT
        );
        CREATE INDEX ix_jobs_run ON jobs(run_id);
        CREATE INDEX ix_jobs_status ON jobs(status);
        CREATE TABLE volumes (
            id TEXT PRIMARY KEY,
            project_id TEXT NOT NULL REFERENCES projects(id) ON DELETE CASCADE,
            user_id TEXT,
            name TEXT NOT NULL,
            status TEXT NOT NULL DEFAULT 'submitted',
            status_message TEXT,
            configuration TEXT NOT NULL,
            external INTEGER NOT NULL DEFAULT 0,
            created_at TEXT NOT NULL,
            last_processed_at TEXT,
            last_job_processed_at TEXT,
            provisioning_data TEXT,
            volume_id TEXT,
            deleted INTEGER NOT NULL DEFAULT 0
        );
        CREATE UNIQUE INDEX ux_volumes_live_name ON volumes(project_id, name) WHERE deleted = 0;
        CREATE TABLE volume_attachments (
            volume_id TEXT NOT NULL REFERENCES volumes(id) ON DELETE CASCADE,
            instance_id TEXT NOT NULL REFERENCES instances(id) ON DELETE CASCADE,
            attachment_data TEXT,
            PRIMARY KEY (volume_id, instance_id)
        );
        CREATE TABLE gateways (
            id TEXT PRIMARY KEY,
            project_id TEXT NOT NULL REFERENCES projects(id) ON DELETE CASCADE,
            name TEXT NOT NULL,
            status TEXT NOT NULL DEFAULT 'submitted',
            status_message TEXT,
            configuration TEXT NOT NULL,
            created_at TEXT NOT NULL,
            last_processed_at TEXT,
            ip_address TEXT,
            hostname TEXT,
            provisioning_data TEXT,
            is_default INTEGER NOT NULL DEFAULT 0,
            deleted INTEGER NOT NULL DEFAULT 0
        );
        CREATE UNIQUE INDEX ux_gateways_live_name ON gateways(project_id, name) WHERE deleted = 0;
        CREATE TABLE job_metrics_points (
            job_id TEXT NOT NULL REFERENCES jobs(id) ON DELETE CASCADE,
            timestamp TEXT NOT NULL,
            cpu_usage_micro INTEGER NOT NULL DEFAULT 0,
            memory_usage_bytes INTEGER NOT NULL DEFAULT 0,
            memory_working_set_bytes INTEGER NOT NULL DEFAULT 0,
            tpu TEXT
        );
        CREATE INDEX ix_job_metrics_points_job ON job_metrics_points(job_id, timestamp);
        CREATE TABLE secrets (
            id TEXT PRIMARY KEY,
            project_id TEXT NOT NULL REFERENCES projects(id) ON DELETE CASCADE,
            name TEXT NOT NULL,
            value TEXT NOT NULL,
            UNIQUE (project_id, name)
        );
        """,
    ),
    (
        2,
        """
        CREATE TABLE service_stats (
            run_id TEXT NOT NULL,
            bucket INTEGER NOT NULL,
            count INTEGER NOT NULL,
            PRIMARY KEY (run_id, bucket)
        );
        """,
    ),
    (
        3,
        """
        CREATE TABLE run_events (
            id TEXT PRIMARY KEY,
            run_id TEXT NOT NULL,
            job_id TEXT,
            timestamp TEXT NOT NULL,
            actor TEXT NOT NULL,
            old_status TEXT,
            new_status TEXT NOT NULL,
            reason TEXT,
            message TEXT,
            trace_id TEXT,
            seq INTEGER NOT NULL DEFAULT 0
        );
        CREATE INDEX ix_run_events_run ON run_events(run_id, seq);
        CREATE INDEX ix_run_events_job ON run_events(job_id);
        """,
    ),
    (
        4,
        # Workload telemetry (train/serve emitters -> agent sidecar tail ->
        # collect_job_metrics). `kind` is the point discriminator
        # (step/engine/mark/emitter); the full point stays as JSON in `data` —
        # the schema evolves workload-side without migrations. The jobs cursor
        # column fixes collection starvation: ordering by a metrics-OWNED
        # timestamp (advanced every pass) rotates through >MAX_JOBS_PER_PASS
        # running jobs instead of resampling the same 100 forever.
        """
        CREATE TABLE workload_metrics_points (
            job_id TEXT NOT NULL REFERENCES jobs(id) ON DELETE CASCADE,
            timestamp TEXT NOT NULL,
            kind TEXT NOT NULL,
            data TEXT NOT NULL
        );
        CREATE INDEX ix_workload_metrics_points_job
            ON workload_metrics_points(job_id, timestamp);
        ALTER TABLE jobs ADD COLUMN metrics_sampled_at TEXT;
        """,
    ),
    (
        5,
        # Run-ownership leases for multi-replica scheduling (generalizes the
        # migration-1 conditional slice claim to whole runs): each scheduler
        # pass processes only runs whose lease it holds; expired leases are
        # reclaimed by any live replica, which then reconciles the orphaned
        # run (services/leases.py). `reclaims` counts ownership changes — a
        # hot counter there means replicas are flapping or the TTL is too
        # tight for the pass cadence.
        """
        CREATE TABLE run_leases (
            run_id TEXT PRIMARY KEY,
            owner TEXT NOT NULL,
            acquired_at TEXT NOT NULL,
            heartbeat_at TEXT NOT NULL,
            expires_at TEXT NOT NULL,
            reclaims INTEGER NOT NULL DEFAULT 0
        );
        CREATE INDEX ix_run_leases_owner ON run_leases(owner);
        CREATE INDEX ix_run_leases_expires ON run_leases(expires_at);
        """,
    ),
    (
        6,
        # Fleet accounting ledger (services/usage.py): chip-seconds and
        # dollars attributed to (project, user, run), one row per run per
        # UTC-hour bucket, accrued incrementally by the metering pass.
        # `last_sampled_at` is the per-run accrual cursor (MAX across the
        # run's buckets) so metering is idempotent across restarts and
        # replicas; rows are deleted when their run or project is deleted
        # (the per-project /metrics counter resets, which rate() tolerates).
        """
        CREATE TABLE usage_samples (
            run_id TEXT NOT NULL,
            project_id TEXT NOT NULL,
            user_id TEXT,
            bucket TEXT NOT NULL,
            chip_seconds REAL NOT NULL DEFAULT 0,
            dollars REAL NOT NULL DEFAULT 0,
            goodput_chip_seconds REAL NOT NULL DEFAULT 0,
            last_sampled_at TEXT,
            PRIMARY KEY (run_id, bucket)
        );
        CREATE INDEX ix_usage_samples_project ON usage_samples(project_id, bucket);
        """,
    ),
    (
        7,
        # Cross-replica scheduler notify (services/leases.py notify/
        # last_notify): piggybacked on run_leases as sentinel rows
        # (run_id = 'notify:<loop name>') so a submit on replica A wakes
        # replica B's submitted pass on its next short poll tick instead of
        # its next full interval — the DB-visible analogue of the in-process
        # background.wake() event. Real lease rows leave the column NULL.
        """
        ALTER TABLE run_leases ADD COLUMN notify_at TEXT;
        """,
    ),
]


def migrate(conn, dialect=None) -> None:
    """Apply pending migrations. `conn` is a sqlite3.Connection (default) or
    the postgres connection adapter; `dialect` (server.db dialect object)
    rewrites/splits the portable DDL for engines without executescript. The
    DDL itself is authored once: both engines accept the TEXT/INTEGER/REAL
    columns, partial indexes, and ON CONFLICT clauses used here."""
    # Multi-replica bootstrap: when several server processes share a postgres
    # database, only one may apply DDL at a time (reference runs alembic under
    # an advisory lock for the same reason). The lock comes FIRST — postgres's
    # CREATE TABLE IF NOT EXISTS is itself racy across sessions, and
    # pg_advisory_xact_lock needs no table; postgres DDL is transactional so
    # everything below sits inside the one locked transaction.
    if dialect is not None:
        dialect.tx_advisory_lock(conn, "dstack-migrations")
    conn.execute("CREATE TABLE IF NOT EXISTS schema_version (version INTEGER NOT NULL)")
    row = conn.execute("SELECT MAX(version) AS v FROM schema_version").fetchone()
    current = row["v"] if row and row["v"] is not None else 0
    for version, script in MIGRATIONS:
        if version > current:
            if dialect is not None:
                dialect.run_script(conn, script)
            else:
                conn.executescript(script)
            conn.execute("INSERT INTO schema_version (version) VALUES (?)", (version,))
    conn.commit()
