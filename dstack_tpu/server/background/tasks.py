"""Background task entry points. Filled in by the scheduler milestone (M3); the
placeholders keep the server bootable before then."""

from __future__ import annotations

from dstack_tpu.server.db import Database


async def process_runs(db: Database) -> None:
    return None


async def process_submitted_jobs(db: Database) -> None:
    return None


async def process_running_jobs(db: Database) -> None:
    return None


async def process_terminating_jobs(db: Database) -> None:
    return None


async def process_instances(db: Database) -> None:
    return None
