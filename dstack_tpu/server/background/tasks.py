"""The async control-plane FSM loops.

Parity: reference server/background/tasks/ —
  process_submitted_jobs.py:124-341 (two-phase assign-or-provision scheduler),
  process_running_jobs.py:116-300 (provisioning→pulling→running via the runner agent),
  process_runs.py:212-449 (run FSM: aggregation, retries w/ backoff, stop criteria),
  process_terminating_jobs.py:27, process_instances.py:165-1118.

TPU re-design (SURVEY §7 hard parts a+b): the placement atom is a *slice* — a replica's
jobs are gang-placed onto whole slices (all hosts of each slice at once), never onto
independent VMs. Multislice replicas (tpu.count > 1) place one slice at a time; partial
placements park provisioned slices in the pool as idle so the next pass completes the
gang instead of leaking capacity.

Concurrency model: each pass fans out over independent runs/gangs with a bounded
asyncio.gather (settings.SCHEDULER_CONCURRENCY in flight); per-run keyed locks
(services/locking) serialize same-run work, and every work item re-fetches its rows
fresh under the lock so an overlapping pass degrades to a no-op instead of a double
placement. Cross-run races on pool slices are settled in the DB: mark_slice_busy_tx
claims a slice conditionally and the losing transaction rolls back (SliceBusyError).
Hot queries are batched (grouped IN fetches / executemany) and identical offer
queries are served from a TTL cache (services/offers).
"""

from __future__ import annotations

import asyncio
import functools
import json
import logging
from typing import Awaitable, Dict, Iterable, List, Optional, Tuple

from dstack_tpu.core.errors import BackendError, NoCapacityError
from dstack_tpu.core.models.instances import InstanceOffer, InstanceStatus
from dstack_tpu.core.models.logs import LogEvent
from dstack_tpu.core.models.profiles import (
    DEFAULT_RUN_TERMINATION_IDLE_TIME,
    CreationPolicy,
    Profile,
    RetryEvent,
    StartupOrder,
    StopCriteria,
)
from dstack_tpu.core.models.runs import (
    JobRuntimeData,
    JobStatus,
    JobTerminationReason,
    RunSpec,
    RunStatus,
    RunTerminationReason,
)
from dstack_tpu.core import faults, tracing
from dstack_tpu.server import settings
from dstack_tpu.server.db import Database, in_clause, loads, new_id
from dstack_tpu.server.services import backends as backends_service
from dstack_tpu.server.services import events as events_service
from dstack_tpu.server.services import fleets as fleets_service
from dstack_tpu.server.services import instances as instances_service
from dstack_tpu.server.services import leases as leases_service
from dstack_tpu.server.services import logs as logs_service
from dstack_tpu.server.services import offers as offers_service
from dstack_tpu.server.services import jobs as jobs_service
from dstack_tpu.server.services import resilience
from dstack_tpu.server.services import usage as usage_service
from dstack_tpu.server.services.jobs import (
    build_cluster_info,
    job_jpd,
    job_jrd,
    job_spec as load_job_spec,
    set_job_status,
    terminate_job,
    touch_jobs,
)
from dstack_tpu.server.services.locking import get_locker
from dstack_tpu.server.services.runner.client import get_runner_client
from dstack_tpu.utils.common import from_iso, now_utc, to_iso

logger = logging.getLogger(__name__)

# Which job failures a retry event covers (reference runs.py:92-95).
_REASON_TO_RETRY_EVENT = {
    JobTerminationReason.FAILED_TO_START_DUE_TO_NO_CAPACITY: RetryEvent.NO_CAPACITY,
    JobTerminationReason.INTERRUPTED_BY_NO_CAPACITY: RetryEvent.INTERRUPTION,
    JobTerminationReason.INSTANCE_UNREACHABLE: RetryEvent.INTERRUPTION,
    JobTerminationReason.CONTAINER_EXITED_WITH_ERROR: RetryEvent.ERROR,
    JobTerminationReason.EXECUTOR_ERROR: RetryEvent.ERROR,
    JobTerminationReason.CREATING_CONTAINER_ERROR: RetryEvent.ERROR,
    JobTerminationReason.PORTS_BINDING_FAILED: RetryEvent.ERROR,
}


def _traced_pass(fn):
    """Run a scheduler pass under a timed span: the pass duration lands in the
    ``dstack_tpu_scheduler_pass_duration_seconds{pass=...}`` histogram and a
    pass that overruns DSTACK_TPU_TRACE_SLOW_SECONDS WARNs with its trace id."""
    name = fn.__name__

    @functools.wraps(fn)
    async def wrapper(*args, **kwargs):
        tracing.new_trace()
        with tracing.span(
            f"scheduler.{name}",
            histogram="dstack_tpu_scheduler_pass_duration_seconds",
            labels={"pass": name},
        ):
            return await fn(*args, **kwargs)

    return wrapper


async def _fan_out(coros: Iterable[Awaitable]) -> None:
    """Run a pass's independent work items concurrently, capped at
    settings.SCHEDULER_CONCURRENCY in flight. Every item is awaited even when one
    fails (no leaked tasks); the first exception re-raises after the pass drains,
    preserving the serial loops' propagation behavior."""
    coros = list(coros)
    if not coros:
        return
    if len(coros) == 1 or settings.SCHEDULER_CONCURRENCY <= 1:
        first: Optional[BaseException] = None
        for c in coros:
            try:
                await c
            except BaseException as e:
                if first is None:
                    first = e
        if first is not None:
            raise first
        return
    sem = asyncio.Semaphore(settings.SCHEDULER_CONCURRENCY)

    async def _run(coro: Awaitable):
        async with sem:
            return await coro

    results = await asyncio.gather(*(_run(c) for c in coros), return_exceptions=True)
    for r in results:
        if isinstance(r, BaseException):
            raise r


async def _claim_owned(db: Database, run_ids: Iterable[str]) -> set:
    """Lease gate for the run-keyed passes: claim/renew the candidate runs and
    return the subset this replica owns. Runs reclaimed from an expired holder
    (their replica died mid-work) are reconciled first — runner probes + a
    ``reconciled`` run_event — before this pass schedules them."""
    owned, reclaimed = await leases_service.claim_runs(db, run_ids)
    if reclaimed:
        # Concurrent: a mass reclaim (the dead replica owned many runs) must
        # not serialize one probe-timeout per run in front of this pass.
        async def _reconcile(run_id: str) -> None:
            try:
                await leases_service.reconcile_run(db, run_id)
            except Exception:
                logger.exception("reconciling reclaimed run %s failed", run_id)

        await asyncio.gather(*(_reconcile(r) for r in reclaimed))
    return owned


# =====================================================================================
# process_submitted_jobs


@_traced_pass
async def process_submitted_jobs(db: Database, batch: Optional[int] = None) -> None:
    batch = batch or settings.PROCESS_BATCH_SIZE
    # Order by last processing attempt, not submission time: jobs parked in `submitted`
    # by a no-capacity retry window rotate to the back instead of head-of-line blocking
    # fresh runs.
    rows = await db.fetchall(
        "SELECT j.*, r.status AS run_status FROM jobs j JOIN runs r ON r.id = j.run_id"
        " WHERE j.status = 'submitted' AND r.status NOT IN"
        " ('terminating', 'terminated', 'failed', 'done')"
        " ORDER BY COALESCE(j.last_processed_at, j.submitted_at) LIMIT ?",
        (batch * 4,),
    )
    # Group into replicas (the gang unit); cap work per pass at `batch` replicas.
    groups: Dict[Tuple[str, int, int], List] = {}
    for r in rows:
        groups.setdefault((r["run_id"], r["replica_num"], r["submission_num"]), []).append(r)
    # Claim only what this pass will actually process: claiming the whole
    # over-fetched candidate list would let one replica hoard every queued run
    # while its siblings idle (last_processed_at ordering rotates the rest
    # into later passes).
    keys = list(groups)[: batch]
    owned = await _claim_owned(db, (key[0] for key in keys))
    groups = {key: groups[key] for key in keys if key[0] in owned}

    async def _one(run_id: str, replica_num: int, submission_num: int) -> None:
        # Keyed lock + fresh gang re-fetch inside _place_replica: an overlapping
        # pass (or a sibling replica task of the same run) placing the same gang
        # first turns this item into a no-op. Each work item gets its own trace
        # so the run_events it writes are joinable to its log lines.
        tracing.new_trace()
        async with get_locker().lock(f"run:{run_id}"):
            with tracing.span("scheduler.place_replica", run=run_id, replica=replica_num):
                await _place_replica(db, run_id, replica_num, submission_num)

    await _fan_out(_one(*key) for key in groups)


async def _place_replica(db: Database, run_id: str, replica_num: int, submission_num: int) -> None:
    # Re-fetch the full gang under the lock (the batch query may have truncated it).
    job_rows = await db.fetchall(
        "SELECT * FROM jobs WHERE run_id = ? AND replica_num = ? AND submission_num = ?"
        " ORDER BY job_num",
        (run_id, replica_num, submission_num),
    )
    job_rows = [r for r in job_rows if r["status"] == "submitted"]
    if not job_rows:
        return
    run_row = await db.fetchone("SELECT * FROM runs WHERE id = ?", (run_id,))
    if run_row is None or RunStatus(run_row["status"]).is_finished():
        return
    project_row = await db.fetchone("SELECT * FROM projects WHERE id = ?", (run_row["project_id"],))
    run_spec = RunSpec.model_validate(loads(run_row["run_spec"]))
    profile = run_spec.merged_profile()
    spec0 = load_job_spec(job_rows[0])
    requirements = spec0.requirements

    tpu = requirements.resources.tpu
    hosts_per_slice = tpu.hosts if tpu is not None else 1
    slice_name = tpu.slice_name if tpu is not None else None

    # Which fleets may be used (profile.fleets names -> ids).
    fleet_ids: Optional[List[str]] = None
    if profile.fleets:
        frows = await db.fetchall(
            f"SELECT id FROM fleets WHERE project_id = ? AND deleted = 0 AND name IN"
            f" ({','.join('?' for _ in profile.fleets)})",
            [run_row["project_id"], *profile.fleets],
        )
        fleet_ids = [r["id"] for r in frows]

    # Requested volumes must be active before placement; a volume still
    # provisioning parks the gang for the next pass (process_volumes drives it).
    run_volumes = []
    if spec0.volumes:
        from dstack_tpu.server.services import volumes as volumes_service

        for m in spec0.volumes:
            vrow = await volumes_service.get_volume_row(db, run_row["project_id"], m.name)
            if vrow is None:
                for j in job_rows:
                    await set_job_status(
                        db, j, JobStatus.TERMINATING,
                        JobTerminationReason.VOLUME_ERROR,
                        f"volume {m.name} does not exist",
                    )
                return
            if vrow["status"] != "active":
                await touch_jobs(db, job_rows)
                return
            run_volumes.append(
                await volumes_service.row_to_volume(db, vrow, project_row["name"])
            )

    # Slice-by-slice gang placement. job_num w of slice s is job_rows[s*hosts+w].
    num_slices = max(1, len(job_rows) // max(1, hosts_per_slice))
    idle_slices = await instances_service.find_idle_slices(
        db,
        run_row["project_id"],
        requirements,
        slice_name,
        hosts_per_slice,
        fleet_ids,
        profile=profile,
    )
    offers: Optional[List[InstanceOffer]] = None
    placed_all = True
    breaker_open = False
    # Placement decision log (ISSUE 19): per-slice rejection reasons for this
    # pass. quota_reserved is the fair-share stub (ROADMAP item 3) — counted
    # nowhere yet, documented in the taxonomy.
    offer_count = 0
    reject_reasons = {
        "no_offers": 0, "no_capacity": 0, "breaker_open": 0,
        "slice_busy": 0, "quota_reserved": 0,
    }
    for s in range(num_slices):
        slice_jobs = job_rows[s * hosts_per_slice : (s + 1) * hosts_per_slice]
        if not slice_jobs or slice_jobs[0]["status"] != "submitted":
            continue
        # Phase 1: reuse an idle slice from the pool (reference
        # process_submitted_jobs.py:344 _assign_job_to_pool_instance). Mark-busy and
        # the gang's assignments commit in one transaction: a crash mid-pass must not
        # leave a busy slice with unassigned jobs (or vice versa).
        # TPU data disks attach at slice-create time only: a volume-backed gang can
        # reuse a slice only if that slice already carries ALL its volumes.
        if run_volumes and idle_slices:
            idle_slices = await _slices_with_volumes(db, idle_slices, run_volumes)
        assigned = False
        while idle_slices:
            workers = idle_slices.pop(0)

            def _assign_pool(conn, workers=workers, slice_jobs=slice_jobs):
                instances_service.mark_slice_busy_tx(conn, [w["id"] for w in workers])
                for w_row, j_row in zip(workers, slice_jobs):
                    _assign_job_tx(conn, j_row, w_row["id"], loads(w_row["job_provisioning_data"]))

            try:
                await db.run(_assign_pool)
            except instances_service.SliceBusyError:
                # A concurrent placement (another run's task holds a different
                # lock) won this slice; the transaction rolled back whole — try
                # the next candidate.
                reject_reasons["slice_busy"] += 1
                continue
            assigned = True
            break
        if assigned:
            continue
        # Phase 2: provision a new slice (reference :415 _run_job_on_new_instance).
        if profile.creation_policy == CreationPolicy.REUSE:
            placed_all = False
            reject_reasons["no_capacity"] += 1
            continue
        if offers is None:
            offers = await offers_service.get_offers_by_requirements(
                db, project_row, requirements, profile
            )
            offers = [o for o in offers if o.availability.is_available()]
            offer_count = len(offers)
        if not offers:
            placed_all = False
            reject_reasons["no_offers"] += 1
            continue
        outcome = await _provision_slice(
            db, project_row, run_row, run_spec, offers, slice_jobs, volumes=run_volumes
        )
        if outcome != "created":
            placed_all = False
            reject_reasons[outcome] += 1
            if outcome == "breaker_open":
                breaker_open = True

    if not placed_all:
        await _record_placement_attempt(
            db, run_row, project_row, offer_count, reject_reasons
        )
        if breaker_open:
            # Graceful degradation: at least one matching offer sits behind a
            # backend whose circuit is open. That is not "no capacity" — the
            # backend is (temporarily) unreachable. Requeue and say why instead
            # of burning the run's retry window on a dead API.
            await _requeue_breaker_open(db, run_row, job_rows)
        else:
            await _handle_no_capacity(db, run_row, job_rows, profile)
    else:
        # Placed: the run is no longer waiting — its pending-reason series and
        # WAITING message must not outlive the decision that resolved them.
        usage_service.clear_pending(run_row["run_name"])
        await db.execute(
            "UPDATE runs SET status_message = NULL"
            " WHERE id = ? AND status_message LIKE 'waiting:%'",
            (run_row["id"],),
        )


def _assign_job_tx(conn, job_row, instance_id: str, jpd_dict: dict) -> None:
    conn.execute(
        "UPDATE jobs SET status = 'provisioning', instance_id = ?,"
        " job_provisioning_data = ?, last_processed_at = ? WHERE id = ?",
        (instance_id, json.dumps(jpd_dict), to_iso(now_utc()), job_row["id"]),
    )
    events_service.record_event_tx(
        conn,
        job_row["run_id"],
        "provisioning",
        old_status=job_row["status"],
        job_id=job_row["id"],
        actor="scheduler",
    )


async def _slices_with_volumes(db: Database, slices: List[List], volumes: List) -> List[List]:
    """The subset of slices where every volume is attached to every worker —
    one grouped attachment fetch (was: one query per slice per volume)."""
    worker_ids = [w["id"] for workers in slices for w in workers]
    vol_ids = [str(v.id) for v in volumes]
    rows = await db.fetchall(
        f"SELECT volume_id, instance_id FROM volume_attachments"
        f" WHERE volume_id IN ({in_clause(vol_ids)})"
        f" AND instance_id IN ({in_clause(worker_ids)})",
        [*vol_ids, *worker_ids],
    )
    attached = {(r["volume_id"], r["instance_id"]) for r in rows}
    return [
        workers
        for workers in slices
        if all((v, w["id"]) in attached for v in vol_ids for w in workers)
    ]


def _volume_attachment_data(volume, index: int = 0) -> dict:
    """How the host exposes the disk (device path / host dir), per backend.

    ``index`` is the volume's 0-based position in the dataDisks list passed at
    slice create. The TPU API cannot assign device names to data disks, so they
    surface as ``google-persistent-disk-<n>`` with the boot disk at n=0 and data
    disks following in list order (reference gcp/compute.py:710)."""
    pd = volume.provisioning_data
    backend = pd.backend if pd else None
    if backend == "gcp":
        return {"device_name": f"/dev/disk/by-id/google-persistent-disk-{index + 1}"}
    if backend == "local":
        data = json.loads(pd.backend_data) if pd.backend_data else {}
        return {"host_dir": data.get("host_dir")}
    return {"device_name": f"/dev/disk/dstack/{volume.name}"}


async def _provision_slice(
    db: Database, project_row, run_row, run_spec: RunSpec, offers: List[InstanceOffer],
    slice_jobs: List, volumes: Optional[List] = None,
) -> str:
    """Try offers in price order until a slice provisions; create instance rows and
    assign the gang. Returns "created", "no_capacity" (every offer failed or was
    out of stock), or "breaker_open" (nothing created AND at least one offer was
    skipped because its backend's circuit is open — requeue, don't fail).

    The cloud create happens first (it cannot be inside a DB transaction), but ALL the
    bookkeeping it implies — fleet resolution, slice rows, busy marks, the gang's job
    assignments — commits as one transaction (reference wraps the pass in one session,
    process_submitted_jobs.py:193-241). A crash after create_slice but before commit
    leaves zero rows: the orphaned cloud slice is visible (billed) but the scheduler
    state is consistent and the next pass re-provisions cleanly."""
    breaker_skipped = False
    for offer in offers[: settings.MAX_OFFERS_TRIED]:
        target = f"backend:{offer.backend}"
        if resilience.is_open(target):
            # Dead backend API: don't spend this pass's budget dialing it.
            # (A cooled-down breaker reads not-open here, so exactly one offer
            # per cooldown becomes the half-open probe.)
            breaker_skipped = True
            continue
        try:
            compute = await backends_service.get_compute(db, project_row, offer.backend)
        except Exception:
            continue
        name = f"{run_row['run_name']}-{slice_jobs[0]['replica_num']}-{new_id()[:8]}"
        # Authorized keys: the user's run key plus the server's tunnel identity.
        keys = [k for k in (run_spec.ssh_key_pub, _server_public_key()) if k]

        async def _create(compute=compute, offer=offer, name=name, keys=keys):
            try:
                await faults.check("backend.create_slice", detail=offer.backend)
            except faults.FaultInjected as e:
                raise BackendError(f"fault injected: {e}") from e
            return await compute.create_slice(
                offer, name, ssh_public_key="\n".join(keys), volumes=volumes or None
            )

        try:
            with tracing.span(
                "backend.create_slice",
                histogram="dstack_tpu_backend_create_slice_seconds",
                labels={"backend": offer.backend},
                run=run_row["run_name"],
            ):
                # Single attempt (a timed-out create may still have provisioned
                # — retrying could double-buy), but with an explicit deadline
                # and breaker accounting: repeated failures open the backend's
                # circuit so later gangs skip it. A NoCapacityError is a
                # healthy backend saying no — it closes the breaker.
                jpds = await resilience.with_retry(
                    _create,
                    target=target,
                    op="create_slice",
                    attempts=1,
                    timeout=settings.BACKEND_CALL_TIMEOUT,
                    retry_on=(BackendError, asyncio.TimeoutError),
                    treat_as_success=(NoCapacityError,),
                )
        except resilience.BreakerOpenError:
            breaker_skipped = True
            continue
        except NoCapacityError as e:
            logger.debug("offer %s/%s no capacity: %s", offer.backend, offer.instance.name, e)
            continue
        except asyncio.TimeoutError:
            logger.warning(
                "offer %s/%s create_slice exceeded %ss deadline",
                offer.backend, offer.instance.name, settings.BACKEND_CALL_TIMEOUT,
            )
            continue
        except BackendError as e:
            logger.warning("offer %s/%s provisioning failed: %s", offer.backend, offer.instance.name, e)
            continue

        def _commit_placement(conn, offer=offer, name=name, jpds=jpds):
            fleet_id = _run_fleet_tx(conn, run_row, run_spec)
            ids = instances_service.create_slice_instances_tx(
                conn,
                project_row["id"],
                fleet_id,
                name,
                jpds,
                offer,
                status=InstanceStatus.PROVISIONING,
            )
            conn.execute(
                f"UPDATE instances SET busy_blocks = 1 WHERE id IN ({','.join('?' for _ in ids)})",
                ids,
            )
            if run_row["fleet_id"] is None:
                conn.execute(
                    "UPDATE runs SET fleet_id = ? WHERE id = ?", (fleet_id, run_row["id"])
                )
            for jpd, iid, j_row in zip(jpds, ids, slice_jobs):
                _assign_job_tx(conn, j_row, iid, json.loads(jpd.model_dump_json()))
            # Volumes attached at create time: record one attachment per
            # (volume, worker) — a TPU data disk reaches every host of the slice.
            for vol_index, vol in enumerate(volumes or []):
                data = json.dumps(_volume_attachment_data(vol, vol_index))
                for iid in ids:
                    conn.execute(
                        "INSERT INTO volume_attachments"
                        " (volume_id, instance_id, attachment_data) VALUES (?, ?, ?)"
                        " ON CONFLICT (volume_id, instance_id)"
                        " DO UPDATE SET attachment_data = excluded.attachment_data",
                        (str(vol.id), iid, data),
                    )

        await db.run(_commit_placement)
        return "created"
    return "breaker_open" if breaker_skipped else "no_capacity"


async def _record_placement_attempt(
    db: Database, run_row, project_row, offer_count: int, reasons: Dict[str, int]
) -> None:
    """The placement decision log (ISSUE 19): one structured
    ``placement_attempt`` run_event per failed pass — candidate-offer count +
    rejection-reason breakdown as JSON in the message — deduped per pass like
    backend_circuit_open (identical consecutive attempts stay silent). Also
    updates the pending-reason registry (the /metrics gauges) and the run's
    status_message (the ``ps -v`` WAITING column)."""
    primary = usage_service.set_pending(
        run_row["run_name"], run_row["id"], project_row["name"], offer_count, reasons
    )
    breakdown = {k: v for k, v in reasons.items() if v}
    message = json.dumps(
        {"offers": offer_count, "reasons": breakdown}, sort_keys=True
    )
    # Dedup window of 3: a stalled gang may interleave placement_attempt with
    # backend_circuit_open, and either event looking only at the very last row
    # would re-trigger the other every pass.
    recent = await db.fetchall(
        "SELECT new_status, message FROM run_events WHERE run_id = ?"
        " ORDER BY seq DESC LIMIT 3",
        (run_row["id"],),
    )
    if not any(
        r["new_status"] == "placement_attempt" and r["message"] == message
        for r in recent
    ):
        def _tx(conn) -> None:
            events_service.record_event_tx(
                conn,
                run_row["id"],
                "placement_attempt",
                old_status=run_row["status"],
                actor="scheduler",
                reason=primary,
                message=message,
            )

        await db.run(_tx)
    await db.execute(
        "UPDATE runs SET status_message = ? WHERE id = ?",
        (f"waiting: {primary}", run_row["id"]),
    )


async def _requeue_breaker_open(db: Database, run_row, job_rows: List) -> None:
    """Skip-and-requeue: the gang stays queued while its backend's circuit is
    open, with ONE reason'd run_event (not one per 1s pass) so the timeline
    answers "why isn't my run placing"."""
    submitted = [r for r in job_rows if r["status"] == "submitted"]
    await touch_jobs(db, submitted)
    # Same 3-deep dedup window as placement_attempt (the two interleave while
    # a gang is stalled behind an open breaker).
    recent = await db.fetchall(
        "SELECT reason FROM run_events WHERE run_id = ? ORDER BY seq DESC LIMIT 3",
        (run_row["id"],),
    )
    if any(r["reason"] == "backend_circuit_open" for r in recent):
        return

    def _tx(conn) -> None:
        events_service.record_event_tx(
            conn,
            run_row["id"],
            run_row["status"],
            old_status=run_row["status"],
            actor="scheduler",
            reason="backend_circuit_open",
            message="placement deferred: backend circuit breaker open; will retry",
        )

    await db.run(_tx)


def _server_public_key() -> str:
    try:
        from dstack_tpu.utils.ssh_keys import get_server_ssh_keypair

        _, public = get_server_ssh_keypair(settings.SERVER_DIR)
        return public
    except Exception:
        return ""


def _run_fleet_tx(conn, run_row, run_spec: RunSpec) -> str:
    profile = run_spec.merged_profile()
    if profile.fleets:
        row = conn.execute(
            "SELECT id FROM fleets WHERE project_id = ? AND name = ? AND deleted = 0",
            (run_row["project_id"], profile.fleets[0]),
        ).fetchone()
        if row is not None:
            return row["id"]
    if run_row["fleet_id"] is not None:
        return run_row["fleet_id"]
    return fleets_service.get_or_create_auto_fleet_tx(
        conn, run_row["project_id"], run_row["run_name"]
    )


async def _handle_no_capacity(db: Database, run_row, job_rows: List, profile: Profile) -> None:
    """No-capacity path: with an active retry window the gang stays queued; otherwise it
    fails (reference exp-backoff re-processing happens naturally via the loop cadence)."""
    retry = profile.retry
    submitted = [r for r in job_rows if r["status"] == "submitted"]
    if retry is not None and RetryEvent.NO_CAPACITY in retry.on_events:
        oldest = min(from_iso(r["submitted_at"]) for r in job_rows)
        if (now_utc() - oldest).total_seconds() < (retry.duration or 3600):
            await db.executemany(
                "UPDATE jobs SET last_processed_at = ? WHERE id = ?",
                [(to_iso(now_utc()), r["id"]) for r in submitted],
            )
            return
    for r in job_rows:
        await terminate_job(
            db,
            r,
            JobTerminationReason.FAILED_TO_START_DUE_TO_NO_CAPACITY,
            "no offers with capacity matched the requirements",
        )


# =====================================================================================
# process_running_jobs


@_traced_pass
async def process_running_jobs(db: Database, batch: Optional[int] = None) -> None:
    batch = batch or settings.PROCESS_BATCH_SIZE
    rows = await db.fetchall(
        "SELECT * FROM jobs WHERE status IN ('provisioning', 'pulling', 'running')"
        " ORDER BY last_processed_at LIMIT ?",
        (batch,),
    )
    by_run: Dict[str, List] = {}
    for row in rows:
        by_run.setdefault(row["run_id"], []).append(row)
    owned = await _claim_owned(db, by_run)
    by_run = {rid: rr for rid, rr in by_run.items() if rid in owned}

    async def _one_run(run_id: str, run_rows: List) -> None:
        tracing.new_trace()
        async with get_locker().lock(f"run:{run_id}"):
            # One grouped re-fetch under the lock replaces the per-job SELECT;
            # the run row (immutable run_spec) is shared by the whole gang.
            fresh_rows = await db.fetch_in(
                "SELECT * FROM jobs WHERE id IN ({in})", [r["id"] for r in run_rows]
            )
            fresh_by_id = {r["id"]: r for r in fresh_rows}
            run_row = await db.fetchone("SELECT * FROM runs WHERE id = ?", (run_id,))
            processed = False
            for row in run_rows:
                fresh = fresh_by_id.get(row["id"])
                if processed and fresh is not None:
                    # Processing a gang member can terminate its siblings
                    # (backend provisioning failure): later members of the same
                    # group re-check singly against the live row.
                    fresh = await db.fetchone(
                        "SELECT * FROM jobs WHERE id = ?", (row["id"],)
                    )
                if fresh is None or fresh["status"] not in (
                    "provisioning", "pulling", "running",
                ):
                    continue
                try:
                    await _process_active_job(db, fresh, run_row)
                except Exception:
                    logger.exception(
                        "run %s: job %s processing failed (trace=%s)",
                        row["run_name"], row["id"], tracing.current_trace_id(),
                    )
                    await touch_jobs(db, [row])
                processed = True

    await _fan_out(_one_run(rid, rr) for rid, rr in by_run.items())


async def _process_active_job(db: Database, job_row, run_row=None) -> None:
    status = JobStatus(job_row["status"])
    if status == JobStatus.PROVISIONING:
        await _process_provisioning(db, job_row, run_row)
    else:
        await _process_pulling_or_running(db, job_row, run_row)


async def _replica_rows(db: Database, job_row) -> List:
    return await db.fetchall(
        "SELECT * FROM jobs WHERE run_id = ? AND replica_num = ? AND submission_num = ?"
        " ORDER BY job_num",
        (job_row["run_id"], job_row["replica_num"], job_row["submission_num"]),
    )


async def _process_provisioning(db: Database, job_row, run_row=None) -> None:
    """Wait for the whole gang to be placed and the runner to come up, then submit the
    job spec + TPU cluster contract (reference _submit_job_to_runner :855)."""
    replica = await _replica_rows(db, job_row)
    spec = load_job_spec(job_row)

    # Gang gate: every job of the replica must hold provisioning data first.
    if any(r["status"] == "submitted" or not loads(r["job_provisioning_data"]) for r in replica):
        await _check_provisioning_deadline(db, job_row)
        return

    if run_row is None:
        run_row = await db.fetchone("SELECT * FROM runs WHERE id = ?", (job_row["run_id"],))
    run_spec = RunSpec.model_validate(loads(run_row["run_spec"]))
    conf = run_spec.configuration

    # startup_order gating (reference _should_wait_for_other_nodes :402).
    order = getattr(conf, "startup_order", StartupOrder.ANY)
    if order == StartupOrder.MASTER_FIRST and spec.job_num != 0:
        master = replica[0]
        if master["status"] not in ("running",):
            await touch_jobs(db, [job_row])
            return
    if order == StartupOrder.WORKERS_FIRST and spec.job_num == 0:
        if any(r["status"] not in ("running",) for r in replica[1:]):
            await touch_jobs(db, [job_row])
            return

    jpd = job_jpd(job_row)
    jrd = job_jrd(job_row) or JobRuntimeData()

    # Cloud slices provision asynchronously (GCP queued resources): hostname is unknown
    # until the node is READY. Poll the backend and persist the resolved endpoint
    # (reference update_provisioning_data, gcp/compute.py:350-407).
    if jpd.hostname is None:
        jpd = await _update_jpd_from_backend(db, job_row, jpd)
        if jpd is None or jpd.hostname is None:
            if jpd is not None:
                await _check_provisioning_deadline(db, job_row)
            return

    # The cluster contract carries every worker's endpoint: re-read the gang after
    # resolution and hold submission until all peers' hostnames are known too
    # (each peer resolves its own endpoint on its own pass).
    replica = await _replica_rows(db, job_row)
    if any((p := job_jpd(r)) is None or p.hostname is None for r in replica):
        await touch_jobs(db, [job_row])
        return

    client = get_runner_client(jpd, jrd)
    with tracing.span(
        "runner.healthcheck",
        histogram="dstack_tpu_runner_call_seconds",
        labels={"op": "healthcheck"},
        run=job_row["run_name"],
    ):
        health = await client.healthcheck()
    if health is None:
        await _check_provisioning_deadline(db, job_row)
        return

    pairs = [(load_job_spec(r), job_jpd(r)) for r in replica]
    hosts_per_slice = pairs[0][1].hosts_per_slice or 1
    num_slices = max(1, len(pairs) // max(1, hosts_per_slice))
    infos = build_cluster_info(pairs, num_slices=num_slices)
    info = infos[spec.job_num]

    spec, secrets = await _resolve_job_secrets(db, job_row["project_id"], spec)
    # Unique per submission: a retried gang gets fresh container labels, so the
    # agent's restart recovery can't resurrect a previous attempt's container.
    spec.job_submission_id = job_row["id"]
    # Service data plane: assign the app port and surface it as
    # DSTACK_SERVICE_PORT. On the shared-host local backend each replica gets an
    # ephemeral port (recorded in ports_mapping for the proxy) so replicas on one
    # host never collide; on cloud workers the configured port is used as-is.
    if spec.service_port is not None and spec.job_num == 0:
        assigned = spec.service_port
        if jpd.backend == "local":
            from dstack_tpu.core.services.ssh.tunnel import allocate_local_port

            assigned = jrd.ports_mapping.get(spec.service_port) or allocate_local_port()
        jrd.ports_mapping[spec.service_port] = assigned
        spec.env["DSTACK_SERVICE_PORT"] = str(assigned)
    # Volume mounts: resolve how THIS worker's host exposes each disk (device
    # path for cloud data disks, host dir on the local backend) from the
    # attachments the placement recorded.
    if spec.volumes and job_row["instance_id"]:
        att_rows = await db.fetchall(
            "SELECT va.attachment_data, v.name AS vol_name FROM volume_attachments va"
            " JOIN volumes v ON v.id = va.volume_id WHERE va.instance_id = ?",
            (job_row["instance_id"],),
        )
        by_name = {a["vol_name"]: loads(a["attachment_data"]) or {} for a in att_rows}
        for m in spec.volumes:
            data = by_name.get(m.name, {})
            m.device = data.get("device_name")
            m.host_dir = data.get("host_dir")
        jrd.volume_names = [m.name for m in spec.volumes]
    with tracing.span(
        "runner.submit",
        histogram="dstack_tpu_runner_call_seconds",
        labels={"op": "submit"},
        run=job_row["run_name"],
    ):
        await client.submit(spec, info, run_spec=loads(run_row["run_spec"]), secrets=secrets)
        code = await _get_code(db, job_row["project_id"], run_spec)
        if code:
            await client.upload_code(code)
        await client.run_job()

    if job_row["instance_id"]:
        await db.execute(
            "UPDATE instances SET status = 'busy' WHERE id = ? AND status = 'provisioning'",
            (job_row["instance_id"],),
        )
    jrd_json = jrd.model_dump_json()

    def _to_pulling(conn) -> None:
        conn.execute(
            "UPDATE jobs SET status = 'pulling', job_runtime_data = ?, last_processed_at = ?"
            " WHERE id = ?",
            (jrd_json, to_iso(now_utc()), job_row["id"]),
        )
        events_service.record_event_tx(
            conn,
            job_row["run_id"],
            "pulling",
            old_status=job_row["status"],
            job_id=job_row["id"],
            actor="scheduler",
        )

    await db.run(_to_pulling)


async def _process_pulling_or_running(db: Database, job_row, run_row=None) -> None:
    jpd = job_jpd(job_row)
    jrd = job_jrd(job_row) or JobRuntimeData()
    spec = load_job_spec(job_row)
    client = get_runner_client(jpd, jrd)
    try:
        with tracing.span(
            "runner.pull",
            histogram="dstack_tpu_runner_call_seconds",
            labels={"op": "pull"},
            run=job_row["run_name"],
        ):
            result = await client.pull(offset=jrd.pull_offset)
    except Exception:
        await _handle_runner_disconnect(db, job_row)
        return
    if result is None:
        await _handle_runner_disconnect(db, job_row)
        return
    await db.execute(
        "UPDATE jobs SET disconnected_at = NULL WHERE id = ?", (job_row["id"],)
    )
    if run_row is None:
        run_row = await db.fetchone(
            "SELECT run_name, project_id FROM runs WHERE id = ?", (job_row["run_id"],)
        )

    # Drain the paginated backlog, persisting each page's logs + offset as it lands so
    # a mid-drain failure never discards progress (the next tick resumes where this
    # one stopped).
    all_states: List[dict] = []
    for _ in range(20):
        events = [
            LogEvent.model_validate(
                {"timestamp": ev.get("ts") or to_iso(now_utc()), "message": ev.get("message", ""),
                 "log_source": ev.get("source", "stdout")}
            )
            for ev in result.get("logs", [])
        ]
        if events:
            logs_service.get_log_storage().write_logs(
                job_row["project_id"], run_row["run_name"], job_row["id"], events
            )
        all_states.extend(result.get("job_states", []))
        jrd.pull_offset = result.get("offset", jrd.pull_offset)
        if not result.get("has_more"):
            break
        await db.execute(
            "UPDATE jobs SET job_runtime_data = ? WHERE id = ?",
            (jrd.model_dump_json(), job_row["id"]),
        )
        try:
            result = await client.pull(offset=jrd.pull_offset)
        except Exception:
            break  # progress persisted; resume next tick
        if not result:
            break
    result = {"job_states": all_states}
    new_status: Optional[JobStatus] = None
    reason: Optional[JobTerminationReason] = None
    reason_msg: Optional[str] = None
    exit_status: Optional[int] = None
    for ev in result.get("job_states", []):
        state = ev.get("state")
        if state == "running":
            new_status = JobStatus.RUNNING
            if jrd.started_at is None:
                jrd.started_at = now_utc()
        elif state in ("done", "failed", "terminated", "aborted"):
            new_status = JobStatus.TERMINATING
            exit_status = ev.get("exit_status")
            if state == "done":
                reason = JobTerminationReason.DONE_BY_RUNNER
            elif state == "failed":
                reason = JobTerminationReason.CONTAINER_EXITED_WITH_ERROR
                reason_msg = ev.get("message") or f"exit status {exit_status}"
            else:
                reason = JobTerminationReason.TERMINATED_BY_SERVER
                reason_msg = ev.get("message")

    from dstack_tpu.server.services import proxy as proxy_service

    now = to_iso(now_utc())
    if new_status == JobStatus.TERMINATING:

        def _to_terminating(conn) -> None:
            conn.execute(
                "UPDATE jobs SET status = 'terminating', termination_reason = ?,"
                " termination_reason_message = ?, exit_status = ?, job_runtime_data = ?,"
                " last_processed_at = ? WHERE id = ?",
                (reason.value if reason else None, reason_msg, exit_status,
                 jrd.model_dump_json(), now, job_row["id"]),
            )
            events_service.record_event_tx(
                conn, job_row["run_id"], "terminating",
                old_status=job_row["status"], job_id=job_row["id"],
                actor="runner", reason=reason.value if reason else None,
                message=reason_msg,
            )

        await db.run(_to_terminating)
        proxy_service.route_table.invalidate_run(job_row["run_id"])
        return
    status_val = (
        new_status.value
        if new_status is not None
        else ("running" if job_row["status"] == "running" else job_row["status"])
    )

    def _update_status(conn) -> None:
        conn.execute(
            "UPDATE jobs SET status = ?, job_runtime_data = ?, last_processed_at = ? WHERE id = ?",
            (status_val, jrd.model_dump_json(), now, job_row["id"]),
        )
        if status_val != job_row["status"]:
            events_service.record_event_tx(
                conn, job_row["run_id"], status_val,
                old_status=job_row["status"], job_id=job_row["id"], actor="runner",
            )

    await db.run(_update_status)
    if status_val != job_row["status"]:
        # The run's replica set changed (e.g. a replica just turned RUNNING
        # with its ports_mapping): refresh the proxy's cached route.
        proxy_service.route_table.invalidate_run(job_row["run_id"])

    # max_duration enforcement, measured from the observed RUNNING transition so queue
    # and provisioning time don't count against the run-time budget.
    if spec.max_duration and jrd.started_at is not None:
        if (now_utc() - jrd.started_at).total_seconds() > spec.max_duration:
            await terminate_job(
                db, job_row, JobTerminationReason.MAX_DURATION_EXCEEDED,
                f"max_duration {spec.max_duration}s exceeded",
            )


async def _handle_runner_disconnect(db: Database, job_row) -> None:
    """Tolerate transient runner unreachability; fail the job after the grace window
    (reference process_running_jobs.py job_disconnected handling)."""
    now = now_utc()
    if job_row["disconnected_at"] is None:
        await db.execute(
            "UPDATE jobs SET disconnected_at = ?, last_processed_at = ? WHERE id = ?",
            (to_iso(now), to_iso(now), job_row["id"]),
        )
        return
    disconnected = from_iso(job_row["disconnected_at"])
    if (now - disconnected).total_seconds() > settings.RUNNER_DISCONNECT_TIMEOUT:
        await terminate_job(
            db, job_row, JobTerminationReason.INSTANCE_UNREACHABLE,
            f"runner unreachable for {settings.RUNNER_DISCONNECT_TIMEOUT}s",
        )
    else:
        await touch_jobs(db, [job_row])


async def _check_provisioning_deadline(db: Database, job_row) -> None:
    submitted = from_iso(job_row["submitted_at"])
    if (now_utc() - submitted).total_seconds() > settings.PROVISIONING_TIMEOUT:
        await terminate_job(
            db, job_row, JobTerminationReason.INSTANCE_UNREACHABLE,
            f"instance did not become reachable within {settings.PROVISIONING_TIMEOUT}s",
        )
    else:
        await touch_jobs(db, [job_row])


async def _update_jpd_from_backend(db: Database, job_row, jpd) -> Optional[JobProvisioningData]:
    """Poll the backend for a still-unresolved worker endpoint; persist when known.

    Returns the (possibly updated) jpd, or None when the slice failed to provision —
    in which case the whole gang is pushed to TERMINATING with a retryable
    no-capacity reason (spot stockouts/preemptions requeue via the run retry policy).
    """
    project_row = await db.fetchone(
        "SELECT * FROM projects WHERE id = ?", (job_row["project_id"],)
    )
    try:
        compute = await backends_service.get_compute(db, project_row, jpd.backend)
    except Exception:
        await touch_jobs(db, [job_row])
        return jpd

    async def _poll():
        try:
            await faults.check("backend.update", detail=jpd.backend)
        except faults.FaultInjected as e:
            raise asyncio.TimeoutError(f"fault injected: {e}") from e
        return await compute.update_provisioning_data(jpd)

    try:
        # Idempotent read: retried once under an explicit deadline. A
        # NoCapacityError/BackendError is the backend ANSWERING that the slice
        # failed — a real result, so it closes the breaker and propagates;
        # only timeouts/transport trouble count against the circuit.
        updated = await resilience.with_retry(
            _poll,
            target=f"backend:{jpd.backend}",
            op="update_provisioning_data",
            attempts=2,
            timeout=settings.BACKEND_POLL_TIMEOUT,
            retry_on=(asyncio.TimeoutError,),
            treat_as_success=(NoCapacityError, BackendError),
        )
    except (resilience.BreakerOpenError, asyncio.TimeoutError):
        # Backend API unreachable (or its circuit already open): the slice may
        # be fine — requeue the poll rather than terminating the gang.
        await touch_jobs(db, [job_row])
        return jpd
    except (NoCapacityError, BackendError) as e:
        logger.info("slice %s failed to provision: %s", jpd.slice_id, e)
        for r in await _replica_rows(db, job_row):
            await terminate_job(
                db, r, JobTerminationReason.INTERRUPTED_BY_NO_CAPACITY, str(e)
            )
        return None
    if updated.hostname is not None:
        jpd_json = updated.model_dump_json()
        await db.execute(
            "UPDATE jobs SET job_provisioning_data = ?, last_processed_at = ? WHERE id = ?",
            (jpd_json, to_iso(now_utc()), job_row["id"]),
        )
        if job_row["instance_id"]:
            await db.execute(
                "UPDATE instances SET job_provisioning_data = ? WHERE id = ?",
                (jpd_json, job_row["instance_id"]),
            )
        return updated
    await touch_jobs(db, [job_row])
    return updated


async def _resolve_job_secrets(db: Database, project_id: str, spec: JobSpec):
    """Interpolate ``${{ secrets.X }}`` references in the job env and registry auth.

    Only secrets the run configuration explicitly references are resolved — never the
    whole project store (any member could otherwise exfiltrate every project secret by
    printing its environment). Mirrors the reference's VariablesInterpolator pass in
    process_running_jobs; unreferenced placeholders are left as-is so a typo'd name is
    visible in the job env rather than silently empty.
    """
    from dstack_tpu.server.services import secrets as secrets_service
    from dstack_tpu.utils.interpolator import extract_references, interpolate_env

    env = dict(spec.env or {})
    auth = spec.registry_auth
    auth_values = [auth.username or "", auth.password or ""] if auth else []
    referenced = extract_references([*env.values(), *auth_values], "secrets")
    if not referenced:
        return spec, {}
    store = await secrets_service.get_secrets(db, project_id)
    available = {name: store[name] for name in referenced if name in store}
    missing = referenced - set(available)
    if missing:
        logger.warning("job references unknown secrets: %s", ", ".join(sorted(missing)))
    env = interpolate_env(env, {"secrets": available}, missing_ok=True)
    update: dict = {"env": env}
    if auth is not None and any("${{" in v for v in auth_values):
        # Registry credentials are the most common secret consumer (reference
        # interpolates registry_auth the same way).
        interpolated = interpolate_env(
            {"username": auth.username or "", "password": auth.password or ""},
            {"secrets": available},
            missing_ok=True,
        )
        update["registry_auth"] = type(auth)(**interpolated)
    return spec.model_copy(update=update), {}


async def _get_code(db: Database, project_id: str, run_spec: RunSpec) -> Optional[bytes]:
    repo_data = run_spec.repo_data or {}
    code_hash = repo_data.get("code_hash")
    if not run_spec.repo_id or not code_hash:
        return None
    row = await db.fetchone(
        "SELECT c.blob FROM codes c JOIN repos r ON r.id = c.repo_id"
        " WHERE r.project_id = ? AND r.name = ? AND c.blob_hash = ?",
        (project_id, run_spec.repo_id, code_hash),
    )
    if row is None:
        return None
    if row["blob"] is not None:
        return row["blob"]
    # Offloaded blob: fetch from the configured object store.
    from dstack_tpu.server.services import repos as repos_service
    from dstack_tpu.server.services import storage as storage_service

    store = storage_service.get_storage()
    if store is None:
        return None
    return await store.get(
        repos_service.code_blob_key(project_id, run_spec.repo_id, code_hash)
    )


# =====================================================================================
# process_terminating_jobs


@_traced_pass
async def process_terminating_jobs(db: Database, batch: Optional[int] = None) -> None:
    batch = batch or settings.PROCESS_BATCH_SIZE
    rows = await db.fetchall(
        "SELECT * FROM jobs WHERE status = 'terminating' ORDER BY last_processed_at LIMIT ?",
        (batch,),
    )
    by_run: Dict[str, List] = {}
    for row in rows:
        by_run.setdefault(row["run_id"], []).append(row)
    owned = await _claim_owned(db, by_run)
    by_run = {rid: rr for rid, rr in by_run.items() if rid in owned}

    async def _one_run(run_id: str, run_rows: List) -> None:
        tracing.new_trace()
        async with get_locker().lock(f"run:{run_id}"):
            # Grouped re-fetch is safe for the whole gang here: terminating one
            # job never rewrites its siblings' rows.
            fresh_rows = await db.fetch_in(
                "SELECT * FROM jobs WHERE id IN ({in})", [r["id"] for r in run_rows]
            )
            for fresh in fresh_rows:
                if fresh["status"] != "terminating":
                    continue
                try:
                    await _process_terminating_job(db, fresh)
                except Exception:
                    logger.exception(
                        "run %s: terminating job %s failed (trace=%s)",
                        fresh["run_name"], fresh["id"], tracing.current_trace_id(),
                    )

    await _fan_out(_one_run(rid, rr) for rid, rr in by_run.items())


async def _process_terminating_job(db: Database, job_row) -> None:
    """Stop the runner best-effort, release the slice back to the pool, finalize status
    (reference jobs/__init__.py:209 process_terminating_job)."""
    jpd = job_jpd(job_row)
    jrd = job_jrd(job_row)
    reason = (
        JobTerminationReason(job_row["termination_reason"])
        if job_row["termination_reason"]
        else JobTerminationReason.TERMINATED_BY_SERVER
    )
    if jpd is not None and job_row["status"] == "terminating":
        client = get_runner_client(jpd, jrd)
        try:
            await client.stop(abort=reason == JobTerminationReason.ABORTED_BY_USER)
        except Exception:
            pass
    if job_row["instance_id"]:
        await instances_service.release_instance(db, job_row["instance_id"])
        await db.execute(
            "UPDATE jobs SET used_instance_id = instance_id, instance_id = NULL WHERE id = ?",
            (job_row["id"],),
        )
    await set_job_status(db, job_row, reason.to_status(), reason)


# =====================================================================================
# process_runs


@_traced_pass
async def process_runs(db: Database, batch: Optional[int] = None) -> None:
    batch = batch or settings.PROCESS_BATCH_SIZE * 2
    rows = await db.fetchall(
        "SELECT * FROM runs WHERE deleted = 0 AND status NOT IN ('terminated', 'failed', 'done')"
        " ORDER BY last_processed_at IS NOT NULL, last_processed_at LIMIT ?",
        (batch,),
    )
    owned = await _claim_owned(db, (row["id"] for row in rows))
    rows = [row for row in rows if row["id"] in owned]
    # Leases of finished/deleted runs are released at finalize; the sweep
    # catches a crash between the terminal transition and the release.
    if settings.RUN_LEASES_ENABLED:
        await leases_service.sweep(db)

    async def _one(row) -> None:
        tracing.new_trace()
        async with get_locker().lock(f"run:{row['id']}"):
            fresh = await db.fetchone("SELECT * FROM runs WHERE id = ?", (row["id"],))
            if fresh is None or RunStatus(fresh["status"]).is_finished():
                return
            try:
                if fresh["status"] == "terminating":
                    await _process_terminating_run(db, fresh)
                else:
                    await _process_active_run(db, fresh)
            except Exception:
                logger.exception(
                    "run %s (%s) processing failed (trace=%s)",
                    row["run_name"], row["id"], tracing.current_trace_id(),
                )
            await db.execute(
                "UPDATE runs SET last_processed_at = ? WHERE id = ?",
                (to_iso(now_utc()), row["id"]),
            )

    await _fan_out(_one(row) for row in rows)


def _latest_submissions(job_rows: List) -> Dict[Tuple[int, int], object]:
    """The live gang view: each replica's LATEST submission's jobs only.

    Per-replica (not per-(replica, job)) because an elastic gang retry may
    resubmit onto a topology with a different host count — a shrunk gang must
    not leave the old submission's extra job_nums haunting the aggregation as
    phantom failures (they'd re-trigger retry against the healthy new gang)."""
    max_sub: Dict[int, int] = {}
    for r in job_rows:
        n = r["replica_num"]
        if r["submission_num"] > max_sub.get(n, -1):
            max_sub[n] = r["submission_num"]
    latest: Dict[Tuple[int, int], object] = {}
    for r in job_rows:
        if r["submission_num"] == max_sub[r["replica_num"]]:
            latest[(r["replica_num"], r["job_num"])] = r
    return latest


async def _process_terminating_run(db: Database, run_row) -> None:
    reason = (
        RunTerminationReason(run_row["termination_reason"])
        if run_row["termination_reason"]
        else RunTerminationReason.STOPPED_BY_USER
    )
    job_rows = await db.fetchall("SELECT * FROM jobs WHERE run_id = ?", (run_row["id"],))
    latest = _latest_submissions(job_rows)
    active = [r for r in latest.values() if not JobStatus(r["status"]).is_finished()]
    for r in active:
        if r["status"] != "terminating":
            await terminate_job(db, r, reason.to_job_termination_reason())
    if not active:
        final = reason.to_status().value

        def _finalize(conn) -> None:
            conn.execute(
                "UPDATE runs SET status = ? WHERE id = ?", (final, run_row["id"])
            )
            # A run that dies waiting must not keep its WAITING banner or its
            # pending-reason gauge (the terminal reason is on the timeline).
            conn.execute(
                "UPDATE runs SET status_message = NULL"
                " WHERE id = ? AND status_message LIKE 'waiting:%'",
                (run_row["id"],),
            )
            events_service.record_event_tx(
                conn, run_row["id"], final,
                old_status=run_row["status"], actor="scheduler", reason=reason.value,
            )
            # Ownership ends atomically with the terminal transition.
            leases_service.release_tx(conn, run_row["id"])

        await db.run(_finalize)
        usage_service.clear_pending(run_row["run_name"])


async def _process_active_run(db: Database, run_row) -> None:
    """Aggregate job statuses into the run FSM; drive retries and stop criteria
    (reference process_runs.py:212 _process_active_run)."""
    run_spec = RunSpec.model_validate(loads(run_row["run_spec"]))
    conf = run_spec.configuration
    profile = run_spec.merged_profile()
    job_rows = await db.fetchall("SELECT * FROM jobs WHERE run_id = ?", (run_row["id"],))
    latest = _latest_submissions(job_rows)

    # Replica view: replica is done/failed as a unit.
    replicas: Dict[int, List] = {}
    for (replica_num, _), r in sorted(latest.items()):
        replicas.setdefault(replica_num, []).append(r)

    # Scaled-down replicas are history, not signal: the autoscaler retired them on
    # purpose, so they must feed neither retries nor the run-status aggregation
    # (reference process_runs.py treats SCALED_DOWN the same way).
    def _scaled_down(rows: List) -> bool:
        return all(
            r["termination_reason"] == "scaled_down"
            and (JobStatus(r["status"]).is_finished() or r["status"] == "terminating")
            for r in rows
        )

    replicas = {n: rows for n, rows in replicas.items() if not _scaled_down(rows)}
    latest = {
        k: r for k, r in latest.items() if k[0] in replicas
    }

    # Dev environments stop themselves after inactivity; the attach bridge is the
    # activity signal (reference shim connections.go + dev-env inactivity stop,
    # process_running_jobs.py:764). Never-attached clocks run from job start.
    if getattr(conf, "type", None) == "dev-environment" and conf.inactivity_duration:
        from dstack_tpu.server.services.attach import activity as attach_activity

        master = latest.get((0, 0))
        if master is not None and master["status"] == "running":
            inact = attach_activity.inactivity_secs(run_row["id"])
            if inact is None:
                jrd = job_jrd(master)
                anchor = (
                    jrd.started_at
                    if jrd is not None and jrd.started_at
                    else from_iso(master["submitted_at"])
                )
                inact = int((now_utc() - anchor).total_seconds())
            await db.execute(
                "UPDATE jobs SET inactivity_secs = ? WHERE id = ?", (inact, master["id"])
            )
            if inact >= conf.inactivity_duration:
                logger.info(
                    "run %s: idle for %ss (limit %ss), stopping",
                    run_row["run_name"], inact, conf.inactivity_duration,
                )
                await _terminate_run(
                    db, run_row, RunTerminationReason.INACTIVITY_DURATION_EXCEEDED
                )
                return

    # stop_criteria: master-done ends the run when job 0 of replica 0 finishes OK
    # (reference _should_stop_on_master_done :443).
    if getattr(conf, "stop_criteria", None) == StopCriteria.MASTER_DONE:
        master = latest.get((0, 0))
        if master is not None and master["status"] == "done":
            await _terminate_run(db, run_row, RunTerminationReason.ALL_JOBS_DONE)
            return

    any_failed_no_retry = False
    for replica_num, rows in replicas.items():
        failed = [r for r in rows if JobStatus(r["status"]) in (JobStatus.FAILED, JobStatus.ABORTED)]
        if not failed:
            continue
        if await _maybe_retry_replica(db, run_row, profile, rows, failed):
            continue
        any_failed_no_retry = True
    if any_failed_no_retry:
        await _terminate_run(db, run_row, RunTerminationReason.JOB_FAILED)
        return

    statuses = [JobStatus(r["status"]) for r in latest.values()]
    if statuses and all(s == JobStatus.DONE for s in statuses):
        await _terminate_run(db, run_row, RunTerminationReason.ALL_JOBS_DONE)
        return

    new_status = RunStatus(run_row["status"])
    if any(s == JobStatus.RUNNING for s in statuses):
        new_status = RunStatus.RUNNING
    elif any(s in (JobStatus.PROVISIONING, JobStatus.PULLING) for s in statuses):
        new_status = RunStatus.PROVISIONING
    if new_status != RunStatus(run_row["status"]):

        def _run_status(conn) -> None:
            conn.execute(
                "UPDATE runs SET status = ? WHERE id = ?",
                (new_status.value, run_row["id"]),
            )
            events_service.record_event_tx(
                conn, run_row["id"], new_status.value,
                old_status=run_row["status"], actor="scheduler",
            )

        await db.run(_run_status)


def _retry_delay(submission_num: int, jitter_key: str = "") -> float:
    """Jittered exponential backoff between resubmissions (reference
    _get_retry_delay :206). The jitter is DETERMINISTIC per (run, submission) —
    hashed into [0.5, 1.0) of the exponential cap — so the elapsed-vs-delay
    comparison is stable across passes, while a capacity stockout that failed
    50 runs at once spreads their resubmissions over half the window instead
    of stampeding the backend in sync."""
    import zlib

    cap = min(settings.RETRY_BACKOFF_BASE * (2 ** submission_num), settings.RETRY_BACKOFF_MAX)
    if not jitter_key:
        return cap
    frac = (zlib.crc32(jitter_key.encode()) % 1024) / 1024.0
    return cap * (0.5 + 0.5 * frac)


async def _maybe_retry_replica(
    db: Database, run_row, profile: Profile, replica_rows: List, failed: List
) -> bool:
    """Gang retry: when any job of a replica fails retryably, the whole replica is
    resubmitted together (a slice gang can't partially restart)."""
    retry = profile.retry
    if retry is None:
        return False
    capacity_failure = True  # every failure is a lost/unobtainable slice
    for r in failed:
        reason = (
            JobTerminationReason(r["termination_reason"]) if r["termination_reason"] else None
        )
        event = _REASON_TO_RETRY_EVENT.get(reason)
        if event is None or event not in retry.on_events:
            return False
        if event not in (RetryEvent.NO_CAPACITY, RetryEvent.INTERRUPTION):
            capacity_failure = False
    # Duration window is anchored at the replica's FIRST submission (submission_num 0),
    # not the latest resubmission — otherwise every retry would reset the clock.
    first_row = await db.fetchone(
        "SELECT MIN(submitted_at) AS t FROM jobs WHERE run_id = ? AND replica_num = ?",
        (run_row["id"], replica_rows[0]["replica_num"]),
    )
    first_submitted = from_iso(first_row["t"])
    if (now_utc() - first_submitted).total_seconds() > (retry.duration or 3600):
        await _terminate_run(db, run_row, RunTerminationReason.RETRY_LIMIT_EXCEEDED)
        return True  # handled (run is terminating)

    active = [r for r in replica_rows if not JobStatus(r["status"]).is_finished()]
    for r in active:
        await terminate_job(db, r, JobTerminationReason.TERMINATED_BY_SERVER, "gang retry")
    if active:
        return True  # wait for teardown; resubmit next pass

    last_finished = max(
        (from_iso(r["finished_at"]) for r in failed if r["finished_at"]), default=None
    )
    submission_num = max(r["submission_num"] for r in replica_rows)
    if last_finished is not None and (now_utc() - last_finished).total_seconds() < _retry_delay(
        submission_num,
        jitter_key=f"{run_row['id']}:{replica_rows[0]['replica_num']}:{submission_num}",
    ):
        return True  # backoff window

    now = to_iso(now_utc())
    replica_num = replica_rows[0]["replica_num"]
    # Elastic rescue: when every failure is a capacity event (preempted slice
    # or stockout) and the run declares elastic topology bounds, rebuild the
    # gang's job specs for the next topology in the list — tried in order,
    # wrapping — instead of requeueing for hardware that may stay gone. The
    # gang size follows the new host count; the workload re-shards its
    # checkpoint on resume (workloads/checkpoint.py).
    spec_rows = [(r["job_num"], r["job_spec"]) for r in replica_rows]
    topo_msg = None
    run_spec = RunSpec.model_validate(loads(run_row["run_spec"]))
    elastic = getattr(run_spec.configuration, "elastic", None)
    if elastic and capacity_failure:
        from dstack_tpu.core.models.resources import TpuSliceSpec
        from dstack_tpu.server.services.jobs.configurators import get_job_specs

        topo = elastic[submission_num % len(elastic)]
        try:
            respec = run_spec.model_copy(deep=True)
            respec.configuration.resources.tpu = TpuSliceSpec.model_validate(topo)
            spec_rows = [
                (s.job_num, s.model_dump_json())
                for s in get_job_specs(respec, replica_num=replica_num)
            ]
            topo_msg = f"elastic retry onto {topo}"
        except Exception:
            logger.exception(
                "run %s: elastic topology %r rejected; retrying original gang",
                run_row["run_name"], topo,
            )
            spec_rows = [(r["job_num"], r["job_spec"]) for r in replica_rows]
    # One transaction: the resubmitted gang (and its lifecycle events) appears
    # whole or not at all (a partial gang would deadlock the slice-atomic
    # placement forever).
    gang = [
        (
            new_id(),
            replica_rows[0]["project_id"],
            run_row["id"],
            run_row["run_name"],
            job_num,
            replica_num,
            submission_num + 1,
            spec_json,
            now,
        )
        for job_num, spec_json in spec_rows
    ]

    def _resubmit(conn) -> None:
        conn.executemany(
            "INSERT INTO jobs (id, project_id, run_id, run_name, job_num, replica_num,"
            " submission_num, job_spec, status, submitted_at)"
            " VALUES (?, ?, ?, ?, ?, ?, ?, ?, 'submitted', ?)",
            gang,
        )
        for g in gang:
            events_service.record_event_tx(
                conn, g[2], "submitted", job_id=g[0],
                actor="scheduler", reason="gang_retry", message=topo_msg,
            )

    await db.run(_resubmit)
    logger.info(
        "run %s: retrying replica %s (submission %s%s)",
        run_row["run_name"], replica_num, submission_num + 1,
        f", {topo_msg}" if topo_msg else "",
    )
    return True


async def _terminate_run(db: Database, run_row, reason: RunTerminationReason) -> None:
    def _tx(conn) -> None:
        conn.execute(
            "UPDATE runs SET status = 'terminating', termination_reason = ? WHERE id = ?",
            (reason.value, run_row["id"]),
        )
        events_service.record_event_tx(
            conn, run_row["id"], "terminating",
            old_status=run_row["status"], actor="scheduler", reason=reason.value,
        )

    await db.run(_tx)
    run_row = await db.fetchone("SELECT * FROM runs WHERE id = ?", (run_row["id"],))
    await _process_terminating_run(db, run_row)


# =====================================================================================
# process_instances


async def process_instances(db: Database, batch: Optional[int] = None) -> None:
    batch = batch or settings.PROCESS_BATCH_SIZE * 2
    rows = await db.fetchall(
        "SELECT * FROM instances WHERE deleted = 0 AND status NOT IN ('terminated')"
        " ORDER BY last_processed_at IS NOT NULL, last_processed_at LIMIT ?",
        (batch,),
    )
    for row in rows:
        try:
            await _process_instance(db, row)
        except Exception:
            logger.exception(
                "instance %s (%s) processing failed (trace=%s)",
                row["name"], row["id"], tracing.current_trace_id(),
            )
        await db.execute(
            "UPDATE instances SET last_processed_at = ? WHERE id = ?",
            (to_iso(now_utc()), row["id"]),
        )
    await _cleanup_auto_fleets(db)
    # Tunnel hygiene: close tunnels whose workers no longer exist (ADVICE r2 —
    # the pool must not grow unbounded at fleet scale).
    from dstack_tpu.server.services.runner import ssh as runner_ssh

    live = await db.fetchall(
        "SELECT job_provisioning_data FROM instances"
        " WHERE deleted = 0 AND status != 'terminated'"
    )
    live_keys = set()
    for r in live:
        jpd = loads(r["job_provisioning_data"])
        if jpd:
            live_keys.add(f"{jpd.get('instance_id')}:{jpd.get('worker_num', 0)}")
    await runner_ssh.reap_tunnels(live_keys)


async def _process_instance(db: Database, row) -> None:
    status = InstanceStatus(row["status"])
    if status == InstanceStatus.PENDING:
        await _provision_pending_instance(db, row)
        return
    if status == InstanceStatus.PROVISIONING and row["busy_blocks"] == 0:
        # Unassigned slice coming up (fleet-provisioned, or released by a job before it
        # was ready): poll the runner; pool it as idle once reachable.
        jpd = loads(row["job_provisioning_data"])
        healthy = None
        if jpd:
            from dstack_tpu.core.models.runs import JobProvisioningData

            jpd_obj = JobProvisioningData.model_validate(jpd)
            if jpd_obj.hostname is None:
                # Cloud slice still resolving (GCP queued resource): poll the backend
                # here too — unassigned slices otherwise never become reachable.
                jpd_obj = await _resolve_instance_endpoint(db, row, jpd_obj)
            if jpd_obj is not None and jpd_obj.hostname is not None:
                client = get_runner_client(jpd_obj, None)
                healthy = await client.healthcheck()
        if healthy is not None:
            await db.execute(
                "UPDATE instances SET status = 'idle', idle_since = ? WHERE id = ?",
                (to_iso(now_utc()), row["id"]),
            )
        elif (now_utc() - from_iso(row["created_at"])).total_seconds() > settings.PROVISIONING_TIMEOUT:
            await db.execute(
                "UPDATE instances SET status = 'terminating', termination_reason = ?"
                " WHERE id = ?",
                ("did not become reachable while provisioning", row["id"]),
            )
        return
    if status == InstanceStatus.IDLE:
        await _check_idle_expiry(db, row)
        return
    if status == InstanceStatus.TERMINATING:
        await _terminate_slice_when_drained(db, row)


async def _provision_ssh_instance(db: Database, row) -> None:
    """SSH-fleet host: probe + install + start the runner over SSH, then hand the row
    to the PROVISIONING branch (healthcheck via tunnel -> idle). Reference
    process_instances.py:222 _add_remote + remote/provisioning.py:116."""
    from dstack_tpu.backends.remote import provisioning
    from dstack_tpu.core.errors import SSHError
    from dstack_tpu.core.models.configurations import SSHHostParams, SSHParams
    from dstack_tpu.core.models.fleets import FleetSpec
    from dstack_tpu.utils.runner_binary import find_runner_binary

    host = SSHHostParams.model_validate(loads(row["remote_connection_info"]))
    ssh_defaults = SSHParams()
    if row["fleet_id"]:
        fleet_row = await db.fetchone("SELECT * FROM fleets WHERE id = ?", (row["fleet_id"],))
        if fleet_row is not None:
            conf = FleetSpec.model_validate(loads(fleet_row["spec"])).configuration
            if conf.ssh_config is not None:
                ssh_defaults = conf.ssh_config
    binary_path = find_runner_binary()
    if binary_path is None:
        logger.error("ssh fleet %s: no runner binary available", row["name"])
        return
    with open(binary_path, "rb") as f:
        runner_binary = f.read()
    try:
        jpd, info = await provisioning.provision_ssh_host(
            host,
            runner_binary,
            default_user=ssh_defaults.user,
            default_identity_file=ssh_defaults.identity_file,
            # The healthcheck/runner tunnels authenticate with the server
            # identity, not the fleet's provisioning identity (ADVICE r2).
            authorize_keys=[_server_public_key()],
        )
    except SSHError as e:
        logger.info("ssh host %s not provisionable yet: %s", host.hostname, e)
        if (now_utc() - from_iso(row["created_at"])).total_seconds() > settings.PROVISIONING_TIMEOUT:
            await db.execute(
                "UPDATE instances SET status = 'terminating', termination_reason = ?"
                " WHERE id = ?",
                (f"ssh provisioning failed: {e}", row["id"]),
            )
        return
    await db.execute(
        "UPDATE instances SET status = 'provisioning', backend = 'ssh', region = ?,"
        " price = 0, instance_type = ?, job_provisioning_data = ?, worker_num = 0,"
        " hosts_per_slice = 1 WHERE id = ?",
        (
            jpd.region,
            jpd.instance_type.model_dump_json(),
            jpd.model_dump_json(),
            row["id"],
        ),
    )
    await db.execute(
        "UPDATE fleets SET status = 'active' WHERE id = ? AND status = 'submitted'",
        (row["fleet_id"],),
    )


async def _provision_pending_instance(db: Database, row) -> None:
    """Provision a cloud fleet's pending slice marker: one marker row becomes the
    slice's worker rows (reference process_instances.py:457 _create_instance)."""
    if row["remote_connection_info"]:
        await _provision_ssh_instance(db, row)
        return
    if row["fleet_id"] is None:
        return
    fleet_row = await db.fetchone("SELECT * FROM fleets WHERE id = ?", (row["fleet_id"],))
    if fleet_row is None:
        return
    from dstack_tpu.core.models.fleets import FleetSpec
    from dstack_tpu.core.models.runs import Requirements

    spec = FleetSpec.model_validate(loads(fleet_row["spec"]))
    conf = spec.configuration
    project_row = await db.fetchone("SELECT * FROM projects WHERE id = ?", (row["project_id"],))
    requirements = Requirements(resources=conf.resources)
    profile = fleets_service.fleet_profile(conf)
    offers = await offers_service.get_offers_by_requirements(
        db, project_row, requirements, profile
    )
    offers = [o for o in offers if o.availability.is_available()]
    for offer in offers[: settings.MAX_OFFERS_TRIED]:
        try:
            compute = await backends_service.get_compute(db, project_row, offer.backend)
        except Exception:
            continue
        try:
            # Same key set as the job path (_provision_slice): without the server
            # public key the startup script installs no authorized_keys and the
            # healthcheck tunnel can never authenticate (ADVICE r2).
            jpds = await compute.create_slice(
                offer, row["name"], ssh_public_key=_server_public_key()
            )
        except BackendError as e:
            logger.debug("fleet %s offer failed: %s", fleet_row["name"], e)
            continue
        # The marker becomes worker 0; extra workers get their own rows.
        await db.execute(
            "UPDATE instances SET status = 'provisioning', backend = ?, region = ?,"
            " availability_zone = ?, price = ?, instance_type = ?, offer = ?,"
            " job_provisioning_data = ?, slice_id = ?, slice_name = ?, worker_num = 0,"
            " hosts_per_slice = ? WHERE id = ?",
            (
                jpds[0].backend,
                jpds[0].region,
                jpds[0].availability_zone,
                jpds[0].price,
                jpds[0].instance_type.model_dump_json(),
                offer.model_dump_json(),
                jpds[0].model_dump_json(),
                jpds[0].slice_id,
                jpds[0].slice_name,
                jpds[0].hosts_per_slice,
                row["id"],
            ),
        )
        if len(jpds) > 1:
            await instances_service.create_slice_instances(
                db,
                row["project_id"],
                row["fleet_id"],
                row["name"],
                jpds[1:],
                offer,
                status=InstanceStatus.PROVISIONING,
            )
        await db.execute(
            "UPDATE fleets SET status = 'active' WHERE id = ? AND status = 'submitted'",
            (row["fleet_id"],),
        )
        return
    logger.info("fleet %s: no capacity for pending instance %s", fleet_row["name"], row["name"])


async def _resolve_instance_endpoint(db: Database, row, jpd):
    """Instance-row analog of _update_jpd_from_backend: poll the backend for an
    unassigned slice's endpoint; persist when known, terminate the slice on failure."""
    project_row = await db.fetchone(
        "SELECT * FROM projects WHERE id = ?", (row["project_id"],)
    )
    try:
        compute = await backends_service.get_compute(db, project_row, jpd.backend)
    except Exception:
        return jpd
    try:
        updated = await compute.update_provisioning_data(jpd)
    except BackendError as e:
        logger.info("instance %s failed to provision: %s", row["name"], e)
        slice_id = row["slice_id"]
        if slice_id:
            await db.execute(
                "UPDATE instances SET status = 'terminating', termination_reason = ?"
                " WHERE slice_id = ? AND deleted = 0",
                (str(e), slice_id),
            )
        else:
            await db.execute(
                "UPDATE instances SET status = 'terminating', termination_reason = ?"
                " WHERE id = ?",
                (str(e), row["id"]),
            )
        return None
    if updated.hostname is not None:
        await db.execute(
            "UPDATE instances SET job_provisioning_data = ? WHERE id = ?",
            (updated.model_dump_json(), row["id"]),
        )
    return updated


async def _check_idle_expiry(db: Database, row) -> None:
    idle_since = from_iso(row["idle_since"]) if row["idle_since"] else from_iso(row["created_at"])
    idle_duration = row["idle_duration"]
    if idle_duration is None:
        idle_duration = DEFAULT_RUN_TERMINATION_IDLE_TIME
    if idle_duration < 0:  # dont-destroy
        return
    if (now_utc() - idle_since).total_seconds() > idle_duration:
        # The whole slice retires together (it is one cloud resource).
        if row["slice_id"]:
            await db.execute(
                "UPDATE instances SET status = 'terminating', termination_reason = ?"
                " WHERE slice_id = ? AND deleted = 0 AND status = 'idle'",
                (f"idle for more than {idle_duration}s", row["slice_id"]),
            )
        else:
            await db.execute(
                "UPDATE instances SET status = 'terminating', termination_reason = ?"
                " WHERE id = ?",
                (f"idle for more than {idle_duration}s", row["id"]),
            )


async def _terminate_slice_when_drained(db: Database, row) -> None:
    """A slice is one cloud resource: call terminate once, after every worker row of the
    slice has reached TERMINATING (SURVEY §7 hard part (a))."""
    slice_id = row["slice_id"]
    if slice_id:
        workers = await db.fetchall(
            "SELECT * FROM instances WHERE slice_id = ? AND deleted = 0", (slice_id,)
        )
        if any(w["status"] not in ("terminating", "terminated") for w in workers):
            return
    else:
        workers = [row]
    if row["worker_num"] != 0:
        return  # worker 0 owns the cloud call
    backend_type = row["backend"]
    if backend_type and backend_type != "ssh":  # ssh hosts have no cloud resource
        project_row = await db.fetchone(
            "SELECT * FROM projects WHERE id = ?", (row["project_id"],)
        )
        try:
            compute = await backends_service.get_compute(db, project_row, backend_type)
            jpd = loads(row["job_provisioning_data"]) or {}
            await compute.terminate_slice(
                slice_id or row["id"], row["region"] or "", jpd.get("backend_data")
            )
        except Exception as e:
            logger.warning("terminate slice %s failed: %s", slice_id, e)
            deadline = row["termination_deadline"]
            ids = [w["id"] for w in workers]
            if deadline is None:
                await db.execute(
                    f"UPDATE instances SET termination_deadline = ? WHERE id IN"
                    f" ({','.join('?' for _ in ids)})",
                    [to_iso(now_utc()), *ids],
                )
                return
            if (now_utc() - from_iso(deadline)).total_seconds() < settings.TERMINATION_RETRY_WINDOW:
                return  # retry next pass; give up after the window to avoid a stuck row
    # Tear down any live SSH tunnels to the slice's workers.
    from dstack_tpu.core.models.runs import JobProvisioningData
    from dstack_tpu.server.services.runner import ssh as runner_ssh

    for w in workers:
        w_jpd = loads(w["job_provisioning_data"])
        if w_jpd:
            try:
                await runner_ssh.close_tunnel(JobProvisioningData.model_validate(w_jpd))
            except Exception:
                pass
    now = to_iso(now_utc())
    ids = [w["id"] for w in workers]
    await db.execute(
        f"UPDATE instances SET status = 'terminated', finished_at = ? WHERE id IN"
        f" ({','.join('?' for _ in ids)})",
        [now, *ids],
    )
    # The slice's data disks detach with the node (delete QR releases them);
    # drop the bookkeeping so the volume shows unattached and can be deleted.
    await db.execute(
        f"DELETE FROM volume_attachments WHERE instance_id IN ({','.join('?' for _ in ids)})",
        ids,
    )


async def _cleanup_auto_fleets(db: Database) -> None:
    await db.execute(
        "UPDATE fleets SET deleted = 1, status = 'terminated' WHERE auto_created = 1"
        " AND deleted = 0 AND NOT EXISTS (SELECT 1 FROM instances i WHERE i.fleet_id ="
        " fleets.id AND i.deleted = 0 AND i.status != 'terminated')"
        " AND NOT EXISTS (SELECT 1 FROM runs r WHERE r.fleet_id = fleets.id AND r.deleted = 0"
        " AND r.status NOT IN ('terminated', 'failed', 'done'))",
    )


async def process_metrics(db: Database) -> None:
    """Sample every running job's agent into job_metrics_points + TTL sweep,
    then join the fresh window across each run's gang for skew/straggler
    analysis (services/gang_health.py — one detector window per pass).

    Parity: reference background/tasks/process_metrics.py (collect_metrics /
    delete_metrics)."""
    from dstack_tpu.server.services import gang_health as gang_health_service
    from dstack_tpu.server.services import metrics as metrics_service

    await metrics_service.collect_job_metrics(db)
    await gang_health_service.check_gang_health(db)
    await metrics_service.enforce_utilization_policies(db)
    await metrics_service.sweep_metrics(db)
    # Fleet accounting tick (ISSUE 19): fold live jobs' accrual windows into
    # the usage_samples ledger — O(live runs) like the passes above.
    await usage_service.meter(db)


# =====================================================================================
# process_services: readiness probes + stats checkpoint; the scaling half lives
# in process_autoscaler (parity: reference autoscalers.py:60-110 RPSAutoscaler
# + process_runs.py scale handling; signals come from the in-server proxy)


async def process_services(
    db: Database, batch: Optional[int] = None, run_autoscaler: bool = True
) -> None:
    from dstack_tpu.server.services import proxy as proxy_service

    # Checkpoint the RPS window so a restart re-primes the autoscaler instead
    # of scaling on zero knowledge right after a deploy.
    await proxy_service.persist_stats(db)

    rows = await db.fetchall(
        "SELECT * FROM runs WHERE deleted = 0 AND status IN"
        " ('submitted', 'provisioning', 'running')"
        " ORDER BY last_processed_at IS NOT NULL, last_processed_at LIMIT ?",
        (batch or settings.PROCESS_BATCH_SIZE,),
    )
    for run_row in rows:
        run_spec = RunSpec.model_validate(loads(run_row["run_spec"]))
        conf = run_spec.configuration
        if getattr(conf, "type", None) != "service":
            continue
        # Readiness probes for every service (reference service probes): the
        # proxy and gateway route only to replicas whose socket answers.
        await proxy_service.probe_service_replicas(
            db, run_row["project_id"], run_row["run_name"]
        )
    # Scaling rides along so single-pass drivers (tests, one-shot maintenance
    # scripts) see the full behavior from one call. The LIVE server's
    # background scheduler passes run_autoscaler=False here — the dedicated
    # process_autoscaler loop is the only scaling cadence there, so two
    # near-simultaneous passes can't each apply a scale step.
    if run_autoscaler:
        await process_autoscaler(db, batch=batch)


async def process_autoscaler(db: Database, batch: Optional[int] = None) -> None:
    """The autoscaling pass: converge every autoscaled service's replica count
    onto its window signals — RPS for ``metric: rps``, p90 latency (TTFT for
    token streams) + engine queue depth for ``metric: latency``. Decisions are
    pure (`services/autoscaler.decide`); this pass only gathers signals,
    enforces the scale delays, and applies the diff under the run lock.
    Scale-ups insert replica jobs with actor="autoscaler" run_events, which is
    where cold-start tracking hooks in (services/events)."""
    from dstack_tpu.server.services import autoscaler as autoscaler_service
    from dstack_tpu.server.services import proxy as proxy_service
    from dstack_tpu.server.services.runs import classify_replicas, scale_run_replicas

    rows = await db.fetchall(
        "SELECT * FROM runs WHERE deleted = 0 AND status IN"
        " ('submitted', 'provisioning', 'running')"
        " ORDER BY last_processed_at IS NOT NULL, last_processed_at LIMIT ?",
        (batch or settings.PROCESS_BATCH_SIZE,),
    )
    for run_row in rows:
        run_spec = RunSpec.model_validate(loads(run_row["run_spec"]))
        conf = run_spec.configuration
        if getattr(conf, "type", None) != "service" or conf.scaling is None:
            continue
        async with get_locker().lock(f"run:{run_row['id']}"):
            job_rows = await db.fetchall(
                "SELECT * FROM jobs WHERE run_id = ?", (run_row["id"],)
            )
            active, _ = classify_replicas(job_rows)

            quantiles = proxy_service.stats.latency_quantiles(
                run_row["id"], window=60.0
            ) or {}
            sig = autoscaler_service.Signals(
                rps=proxy_service.stats.rps(run_row["id"], window=60.0),
                p50=quantiles.get("p50"),
                p90=quantiles.get("p90"),
                queue_depth=proxy_service.stats.queue_depth(run_row["id"]),
                inflight=proxy_service.stats.inflight(run_row["id"]),
            )
            target = autoscaler_service.decide(
                conf.scaling, conf.replicas.min or 0, conf.replicas.max or 1,
                len(active), sig,
            )
            diff = target - len(active)
            if diff == 0:
                continue

            # Scale delays, derived from the DB so a server restart keeps them:
            # last scale-up = newest job submission; last scale-down = newest
            # scaled_down termination.
            last_up = max(
                (from_iso(r["submitted_at"]) for r in job_rows if r["submitted_at"]),
                default=None,
            )
            last_down = max(
                (
                    from_iso(r["finished_at"])
                    for r in job_rows
                    if r["finished_at"] and r["termination_reason"] == "scaled_down"
                ),
                default=None,
            )
            last_scaled = max((t for t in (last_up, last_down) if t), default=None)
            elapsed = (now_utc() - last_scaled).total_seconds() if last_scaled else None
            if diff > 0 and active and elapsed is not None and elapsed < conf.scaling.scale_up_delay:
                continue  # scale-from-zero skips the delay (reference :80-83)
            if diff < 0 and elapsed is not None and elapsed < conf.scaling.scale_down_delay:
                continue

            logger.info(
                "autoscaler: %s %d -> %d replicas (rps=%.2f p90=%s queue=%s)",
                run_row["run_name"], len(active), target, sig.rps,
                f"{sig.p90:.3f}s" if sig.p90 is not None else "-",
                sig.queue_depth if sig.queue_depth is not None else "-",
            )
            await scale_run_replicas(db, run_row, diff)
            await db.execute(
                "UPDATE runs SET desired_replica_count = ? WHERE id = ?",
                (target, run_row["id"]),
            )


# =====================================================================================
# process_volumes (parity: reference background/tasks/process_volumes.py —
# submitted -> provisioning -> active via the backend, auto-cleanup of idle volumes)


async def process_volumes(db: Database, batch: Optional[int] = None) -> None:
    from dstack_tpu.core.models.volumes import VolumeStatus
    from dstack_tpu.server.services import volumes as volumes_service

    rows = await db.fetchall(
        "SELECT * FROM volumes WHERE deleted = 0 AND status IN ('submitted', 'provisioning')"
        " LIMIT ?",
        (batch or settings.PROCESS_BATCH_SIZE,),
    )
    for row in rows:
        project_row = await db.fetchone(
            "SELECT * FROM projects WHERE id = ?", (row["project_id"],)
        )
        volume = await volumes_service.row_to_volume(db, row, project_row["name"])
        conf = volume.configuration
        try:
            compute = await backends_service.get_compute(db, project_row, conf.backend)
            if volume.external:
                pd = await compute.register_volume(volume)
            else:
                pd = await compute.create_volume(volume)
        except NotImplementedError:
            await db.execute(
                "UPDATE volumes SET status = 'failed', status_message = ? WHERE id = ?",
                (f"backend {conf.backend} has no volume support", row["id"]),
            )
            continue
        except Exception as e:
            logger.warning("volume %s provisioning failed: %s", row["name"], e)
            await db.execute(
                "UPDATE volumes SET status = 'failed', status_message = ? WHERE id = ?",
                (str(e)[:500], row["id"]),
            )
            continue
        await db.execute(
            "UPDATE volumes SET status = ?, volume_id = ?, provisioning_data = ?,"
            " last_job_processed_at = ? WHERE id = ?",
            (
                VolumeStatus.ACTIVE.value,
                pd.volume_id,
                pd.model_dump_json(),
                to_iso(now_utc()),
                row["id"],
            ),
        )
        logger.info("volume %s active (%s)", row["name"], pd.volume_id)

    # Auto-cleanup: unattached active volumes past their idle duration.
    idle_rows = await db.fetchall(
        "SELECT v.* FROM volumes v WHERE v.deleted = 0 AND v.status = 'active'"
        " AND NOT EXISTS (SELECT 1 FROM volume_attachments a WHERE a.volume_id = v.id)"
    )
    for row in idle_rows:
        volume = await volumes_service.row_to_volume(db, row)
        duration = volume.configuration.auto_cleanup_duration
        if not duration:
            continue
        anchor = from_iso(row["last_job_processed_at"]) or from_iso(row["created_at"])
        if (now_utc() - anchor).total_seconds() < duration:
            continue
        project_row = await db.fetchone(
            "SELECT * FROM projects WHERE id = ?", (row["project_id"],)
        )
        logger.info("volume %s idle past %ss; deleting", row["name"], duration)
        try:
            await volumes_service.delete_volumes(db, project_row, [row["name"]])
        except Exception as e:
            logger.warning("volume %s auto-cleanup failed: %s", row["name"], e)


# =====================================================================================
# process_gateways (parity: reference process_gateways.py — provision the ingress
# appliance, then keep its service registry in sync every pass)


async def process_gateways(db: Database, batch: Optional[int] = None) -> None:
    from dstack_tpu.core.models.configurations import GatewayConfiguration
    from dstack_tpu.core.models.gateways import GatewayStatus
    from dstack_tpu.server.services import gateways as gateways_service

    rows = await db.fetchall(
        "SELECT * FROM gateways WHERE status IN ('submitted', 'provisioning') LIMIT ?",
        (batch or settings.PROCESS_BATCH_SIZE,),
    )
    for row in rows:
        project_row = await db.fetchone(
            "SELECT * FROM projects WHERE id = ?", (row["project_id"],)
        )
        conf = GatewayConfiguration.model_validate(loads(row["configuration"]))
        token = new_id()
        try:
            compute = await backends_service.get_compute(db, project_row, conf.backend)
            create = getattr(compute, "create_gateway", None)
            if create is None:
                raise BackendError(f"backend {conf.backend} has no gateway support")
            pd = await create(conf, token)
        except Exception as e:
            logger.warning("gateway %s provisioning failed: %s", row["name"], e)
            await db.execute(
                "UPDATE gateways SET status = 'failed', status_message = ? WHERE id = ?",
                (str(e)[:500], row["id"]),
            )
            continue
        backend_port = 8000
        if pd.backend_data:
            try:
                backend_port = json.loads(pd.backend_data).get("port", 8000)
            except ValueError:
                pass
        await db.execute(
            "UPDATE gateways SET status = ?, ip_address = ?, hostname = ?,"
            " provisioning_data = ?, last_processed_at = ? WHERE id = ?",
            (
                GatewayStatus.RUNNING.value,
                pd.ip_address,
                conf.domain,
                json.dumps(
                    {
                        "instance_id": pd.instance_id,
                        "token": token,
                        "port": backend_port,
                        "backend_data": pd.backend_data,
                    }
                ),
                to_iso(now_utc()),
                row["id"],
            ),
        )
        logger.info("gateway %s running at %s:%s", row["name"], pd.ip_address, backend_port)

    # Sync running services into every running gateway's registry.
    running = await db.fetchall("SELECT * FROM gateways WHERE status = 'running'")
    for row in running:
        project_row = await db.fetchone(
            "SELECT * FROM projects WHERE id = ?", (row["project_id"],)
        )
        await gateways_service.sync_services_to_gateway(db, project_row, row)
        await db.execute(
            "UPDATE gateways SET last_processed_at = ? WHERE id = ?",
            (to_iso(now_utc()), row["id"]),
        )
