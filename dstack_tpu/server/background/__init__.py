"""Background processing loops (parity: reference server/background/__init__.py:32-100
APScheduler — re-built as plain asyncio tasks; no executor pools needed)."""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Dict, List

from aiohttp import web

from dstack_tpu.server import settings

logger = logging.getLogger(__name__)

# Wake events by loop name, registered by add_periodic. wake() sets one to cut
# a loop's current sleep short — the submit->assign fast path: a freshly
# submitted run is picked up by process_submitted_jobs on the next scheduler
# tick instead of up to a full interval later. Module-level (not per
# scheduler) so services code can nudge without holding the scheduler; the
# live server runs one scheduler, and in tests the latest registration wins.
_WAKE_EVENTS: Dict[str, asyncio.Event] = {}


def wake(name: str) -> None:
    """Nudge the named periodic loop to start its next pass now. No-op when
    the loop isn't running (unit tests calling services directly, shutdown);
    idempotent while a nudge is already pending (Event.set)."""
    ev = _WAKE_EVENTS.get(name)
    if ev is not None:
        ev.set()


async def _wait_with_notify(
    event: asyncio.Event, interval: float, poll: Callable[[], Awaitable]
) -> None:
    """Sleep out `interval` in short ticks, returning early on the in-process
    wake event OR when the cross-replica notify stamp (services/leases.py
    notify) advances past what it read at sleep start. The baseline read
    means a stamp written BEFORE this sleep began is treated as consumed —
    the pass that just finished either saw that submit's rows or the next
    interval pass will; only stamps landing during the sleep cut it short."""
    from dstack_tpu.server import settings as _settings

    loop_time = asyncio.get_event_loop().time
    deadline = loop_time() + interval
    tick = max(_settings.SCHEDULER_NOTIFY_POLL, 0.005)
    baseline = await poll()
    while True:
        remaining = deadline - loop_time()
        if remaining <= 0:
            raise asyncio.TimeoutError
        try:
            await asyncio.wait_for(event.wait(), timeout=min(tick, remaining))
            return
        except asyncio.TimeoutError:
            pass
        try:
            stamp = await poll()
        except Exception:
            logger.debug("notify poll failed; falling back to interval sleep",
                         exc_info=True)
            continue
        if stamp is not None and stamp != baseline:
            return


class BackgroundScheduler:
    def __init__(self) -> None:
        self._tasks: List[asyncio.Task] = []
        self._names: List[str] = []

    def add_periodic(
        self,
        fn: Callable[[], Awaitable[None]],
        interval: float,
        name: str,
        notify_poll: Callable[[], Awaitable] = None,
    ) -> None:
        """``notify_poll`` (an async () -> Optional[str] returning the loop's
        cross-replica notify stamp) turns the fixed-interval sleep into a
        short-tick poll: submits on OTHER replicas — invisible to the
        in-process wake() event — start a pass next tick."""
        from dstack_tpu.core import tracing

        event = asyncio.Event()
        _WAKE_EVENTS[name] = event

        async def loop() -> None:
            import time

            expected = None  # when the NEXT pass should start (fixed-rate anchor)
            while True:
                now = time.monotonic()
                # Loop lag: how far behind schedule this pass starts. The
                # anchor is set BEFORE the pass runs, so a pass that overruns
                # its interval shows up as lag on the next pass (an anchor
                # taken after fn() would hide exactly the overload this gauge
                # exists to catch). A wake() nudge starts a pass EARLY, which
                # max(0, ...) reads as zero lag — on schedule, not behind it.
                lag = max(0.0, now - expected) if expected is not None else 0.0
                tracing.set_gauge(
                    "dstack_tpu_background_loop_lag_seconds", {"task": name}, lag
                )
                expected = now + interval
                # Cleared before fn() runs: a nudge landing DURING the pass
                # (a submit racing the DB query) leaves the event set, so the
                # wait below returns immediately and the next pass serves it
                # — no lost wakeup.
                event.clear()
                try:
                    await fn()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    logger.exception("background task %s failed", name)
                try:
                    if notify_poll is not None:
                        await _wait_with_notify(event, interval, notify_poll)
                    else:
                        await asyncio.wait_for(event.wait(), timeout=interval)
                except asyncio.TimeoutError:
                    pass

        self._tasks.append(asyncio.create_task(loop(), name=f"bg:{name}"))
        self._names.append(name)

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        for name in self._names:
            _WAKE_EVENTS.pop(name, None)
        self._names.clear()


def start_background_tasks(app: web.Application) -> BackgroundScheduler:
    """Registers the processing loops; intervals/batches per settings (BASELINE.md)."""
    from dstack_tpu.server.background import tasks

    db = app["db"]
    sched = BackgroundScheduler()
    sched.add_periodic(
        lambda: tasks.process_runs(db), settings.PROCESS_RUNS_INTERVAL, "process_runs"
    )
    # The submitted pass additionally polls the cross-replica notify stamp
    # (leases.notify, written by submit_run): a submit landing on replica A
    # wakes THIS replica's pass next short-tick instead of next full interval.
    # Gate on the poll setting so 0 restores the plain fixed-interval sleep.
    from dstack_tpu.server.services import leases as _leases

    submitted_poll = None
    if settings.SCHEDULER_NOTIFY_POLL > 0:
        submitted_poll = lambda: _leases.last_notify(db, "process_submitted_jobs")
    sched.add_periodic(
        lambda: tasks.process_submitted_jobs(db),
        settings.PROCESS_SUBMITTED_JOBS_INTERVAL,
        "process_submitted_jobs",
        notify_poll=submitted_poll,
    )
    sched.add_periodic(
        lambda: tasks.process_running_jobs(db),
        settings.PROCESS_RUNNING_JOBS_INTERVAL,
        "process_running_jobs",
    )
    sched.add_periodic(
        lambda: tasks.process_terminating_jobs(db),
        settings.PROCESS_TERMINATING_JOBS_INTERVAL,
        "process_terminating_jobs",
    )
    sched.add_periodic(
        lambda: tasks.process_instances(db),
        settings.PROCESS_INSTANCES_INTERVAL,
        "process_instances",
    )
    sched.add_periodic(
        lambda: tasks.process_metrics(db),
        settings.PROCESS_METRICS_INTERVAL,
        "process_metrics",
    )
    # Probes/stats-checkpoint and the scaling decisions run as separate loops
    # (scaling reacts on a tighter cadence than the heavier probe pass);
    # run_autoscaler=False stops the services pass from ALSO scaling — the
    # dedicated loop is the single cadence in the live server.
    sched.add_periodic(
        lambda: tasks.process_services(db, run_autoscaler=False),
        settings.PROCESS_SERVICES_INTERVAL,
        "process_services",
    )
    sched.add_periodic(
        lambda: tasks.process_autoscaler(db),
        settings.PROCESS_AUTOSCALER_INTERVAL,
        "process_autoscaler",
    )
    sched.add_periodic(
        lambda: tasks.process_volumes(db),
        settings.PROCESS_VOLUMES_INTERVAL,
        "process_volumes",
    )
    sched.add_periodic(
        lambda: tasks.process_gateways(db),
        settings.PROCESS_GATEWAYS_INTERVAL,
        "process_gateways",
    )
    return sched
