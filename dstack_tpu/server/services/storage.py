"""Blob storage for code archives (and any future large objects).

Parity: reference server/services/storage/ (S3/GCS blob offload for code blobs;
default keeps blobs in the DB). Configure with DSTACK_TPU_STORAGE:
  - unset              -> blobs stay in sqlite (codes.blob)
  - file:///some/dir   -> local filesystem store
  - gs://bucket[/pref] -> GCS over the JSON API, reusing the SDK-free gcp auth
                          (backends/gcp/auth.py); transport injectable for tests.
"""

from __future__ import annotations

import abc
import logging
import os
from pathlib import Path
from typing import Optional

logger = logging.getLogger(__name__)


class Storage(abc.ABC):
    @abc.abstractmethod
    async def put(self, key: str, blob: bytes) -> None: ...

    @abc.abstractmethod
    async def get(self, key: str) -> Optional[bytes]: ...

    @abc.abstractmethod
    async def delete(self, key: str) -> None: ...


class FileStorage(Storage):
    """Blobs as files under a root dir (one level of hash-prefix sharding)."""

    def __init__(self, root: str) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, key: str) -> Path:
        safe = key.replace("/", "_")
        return self.root / safe[:2] / safe

    async def put(self, key: str, blob: bytes) -> None:
        import asyncio

        path = self._path(key)

        def _write() -> None:
            path.parent.mkdir(parents=True, exist_ok=True)
            tmp = path.with_suffix(".tmp")
            tmp.write_bytes(blob)
            tmp.replace(path)

        await asyncio.to_thread(_write)

    async def get(self, key: str) -> Optional[bytes]:
        import asyncio

        path = self._path(key)

        def _read() -> Optional[bytes]:
            try:
                return path.read_bytes()
            except FileNotFoundError:
                return None

        return await asyncio.to_thread(_read)

    async def delete(self, key: str) -> None:
        import asyncio

        def _rm() -> None:
            try:
                self._path(key).unlink()
            except FileNotFoundError:
                pass

        await asyncio.to_thread(_rm)


class StorageError(Exception):
    pass


class GcsStorage(Storage):
    """GCS JSON API (media upload/download/delete), SDK-free like the gcp backend.

    ``request`` is injectable for tests: async (method, url, params, data) ->
    (status, body_bytes); the default speaks aiohttp with a bearer token from
    backends/gcp/auth.py (ambient metadata creds unless GOOGLE_APPLICATION* is
    configured)."""

    API = "https://storage.googleapis.com"

    def __init__(self, bucket: str, prefix: str = "", request=None) -> None:
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self._request = request or self._aiohttp_request
        self._tokens = None

    def _name(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    def _object(self, key: str) -> str:
        from urllib.parse import quote

        return quote(self._name(key), safe="")

    async def _aiohttp_request(self, method, url, params, data):
        import aiohttp

        if self._tokens is None:
            from dstack_tpu.backends.gcp.auth import token_provider_from_creds

            self._tokens = token_provider_from_creds(None)
        token = await self._tokens.get_token()
        async with aiohttp.ClientSession() as session:
            async with session.request(
                method,
                url,
                params=params,
                data=data,
                headers={"Authorization": f"Bearer {token}"},
                timeout=aiohttp.ClientTimeout(total=60),
            ) as resp:
                return resp.status, await resp.read()

    async def put(self, key: str, blob: bytes) -> None:
        status, body = await self._request(
            "POST",
            f"{self.API}/upload/storage/v1/b/{self.bucket}/o",
            {"uploadType": "media", "name": self._name(key)},
            blob,
        )
        if status >= 400:
            raise StorageError(f"gcs put {key}: HTTP {status}: {body[:200]!r}")

    async def get(self, key: str) -> Optional[bytes]:
        status, body = await self._request(
            "GET",
            f"{self.API}/storage/v1/b/{self.bucket}/o/{self._object(key)}",
            {"alt": "media"},
            None,
        )
        if status == 404:
            return None
        if status >= 400:
            raise StorageError(f"gcs get {key}: HTTP {status}: {body[:200]!r}")
        return body

    async def delete(self, key: str) -> None:
        status, body = await self._request(
            "DELETE",
            f"{self.API}/storage/v1/b/{self.bucket}/o/{self._object(key)}",
            None,
            None,
        )
        if status >= 400 and status != 404:
            raise StorageError(f"gcs delete {key}: HTTP {status}: {body[:200]!r}")


_storage: Optional[Storage] = None
_configured = False


def get_storage() -> Optional[Storage]:
    """The configured blob store, or None (= keep blobs in the DB)."""
    global _storage, _configured
    if _configured:
        return _storage
    _configured = True
    url = os.getenv("DSTACK_TPU_STORAGE", "")
    if not url:
        _storage = None
    elif url.startswith("file://"):
        _storage = FileStorage(url[len("file://"):])
    elif url.startswith("gs://"):
        rest = url[len("gs://"):]
        bucket, _, prefix = rest.partition("/")
        _storage = GcsStorage(bucket, prefix)
    else:
        logger.warning("unsupported DSTACK_TPU_STORAGE %r; using DB blobs", url)
        _storage = None
    if _storage is not None:
        logger.info("blob storage: %s", url)
    return _storage


def set_storage(storage: Optional[Storage]) -> None:
    global _storage, _configured
    _storage = storage
    _configured = True
