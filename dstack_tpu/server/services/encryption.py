"""At-rest encryption of secrets/tokens.

Parity: reference server/services/encryption/ (identity + AES keys, key rotation
encryption/__init__.py:70-83). Default is the identity codec (plaintext, tagged);
AES-256-GCM is used when a key is configured. Values are tagged with the key name so
rotation can decrypt old rows while encrypting new ones with the head key.

Wire format: ``enc:<codec>:<key-name>:<base64 payload>``.
"""

from __future__ import annotations

import base64
import os
from typing import Dict, List, Optional, Tuple

_PREFIX = "enc"


class EncryptionKey:
    NAME = "identity"

    def encrypt(self, plaintext: str) -> str:
        raise NotImplementedError

    def decrypt(self, payload: str) -> str:
        raise NotImplementedError


class IdentityKey(EncryptionKey):
    NAME = "identity"

    def __init__(self, name: str = "noname"):
        self.name = name

    def encrypt(self, plaintext: str) -> str:
        return base64.b64encode(plaintext.encode()).decode()

    def decrypt(self, payload: str) -> str:
        return base64.b64decode(payload).decode()


class AesGcmKey(EncryptionKey):
    NAME = "aes"

    def __init__(self, secret_b64: str, name: str = "default"):
        try:
            from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        except ImportError as e:  # pragma: no cover
            raise RuntimeError("aes encryption requires the `cryptography` package") from e
        self._aesgcm = AESGCM(base64.b64decode(secret_b64))
        self.name = name

    def encrypt(self, plaintext: str) -> str:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM  # noqa: F401

        nonce = os.urandom(12)
        ct = self._aesgcm.encrypt(nonce, plaintext.encode(), None)
        return base64.b64encode(nonce + ct).decode()

    def decrypt(self, payload: str) -> str:
        raw = base64.b64decode(payload)
        return self._aesgcm.decrypt(raw[:12], raw[12:], None).decode()


# Head key encrypts; all keys can decrypt (rotation).
_keys: List[EncryptionKey] = [IdentityKey()]


def configure_keys(specs: List[dict]) -> None:
    """specs: [{type: aes, secret: <b64 32 bytes>, name: k1} | {type: identity}]."""
    keys: List[EncryptionKey] = []
    for spec in specs:
        t = spec.get("type", "identity")
        if t == "aes":
            keys.append(AesGcmKey(spec["secret"], spec.get("name", "default")))
        elif t == "identity":
            keys.append(IdentityKey(spec.get("name", "noname")))
        else:
            raise ValueError(f"unknown encryption key type {t!r}")
    if not keys:
        keys = [IdentityKey()]
    # Always keep a decrypt-only identity key: rows written before AES was configured
    # are tagged enc:identity:* and must stay readable (the head key still encrypts).
    if not any(isinstance(k, IdentityKey) for k in keys):
        keys.append(IdentityKey())
    global _keys
    _keys = keys


def reset_keys() -> None:
    global _keys
    _keys = [IdentityKey()]


def encrypt(plaintext: str) -> str:
    key = _keys[0]
    return f"{_PREFIX}:{key.NAME}:{key.name}:{key.encrypt(plaintext)}"


def decrypt(value: str) -> str:
    if not value.startswith(_PREFIX + ":"):
        return value  # legacy plaintext rows
    _, codec, key_name, payload = value.split(":", 3)
    for key in _keys:
        if key.NAME == codec and (key.name == key_name or codec == "identity"):
            return key.decrypt(payload)
    # Fall back to any key of the right codec (rotated name mismatch).
    for key in _keys:
        if key.NAME == codec:
            try:
                return key.decrypt(payload)
            except Exception:
                continue
    raise ValueError(f"no encryption key can decrypt codec={codec} name={key_name}")
