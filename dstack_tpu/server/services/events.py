"""Run lifecycle events: the persistent half of the tracing layer.

Every run/job status transition (``services/runs.py``, ``services/jobs``,
``background/tasks.py``) appends one ``run_events`` row — timestamp, actor,
old→new status, reason, and the scheduler's current trace id — so "where did
my run spend its time?" is answerable after the fact, not just while a
debugger is attached. Derived phase durations (queue wait, provision, pull,
time-to-running) are computed from the timeline here, and the job-level phase
transitions feed the in-process Prometheus histograms
(``dstack_tpu_run_queue_wait_seconds`` / ``..._provision_duration_seconds``)
at write time, so ``/metrics`` carries distributions without re-reading the
table on scrape.

The single writer is ``record_event_tx``, called inside a ``db.run(...)``
transaction closure so the event commits atomically with the transition it
describes — a crash can't record a move that didn't land, or vice versa.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from dstack_tpu.core import tracing
from dstack_tpu.server.db import Database, new_id
from dstack_tpu.utils.common import from_iso, now_utc, to_iso

# Histogram family fed when a job LEAVES the keyed status; the observed value
# is the time spent in that status (from the previous event for the same job,
# falling back to the job's submitted_at for the first transition).
_PHASE_HISTOGRAMS = {
    "submitted": "dstack_tpu_run_queue_wait_seconds",
    "provisioning": "dstack_tpu_run_provision_duration_seconds",
    "pulling": "dstack_tpu_run_pull_duration_seconds",
}

# Human-facing phase names derived from a job timeline (CLI + get_events API).
PHASES = ("queue", "provision", "pull", "run")


def record_event_tx(
    conn,
    run_id: str,
    new_status: str,
    old_status: Optional[str] = None,
    job_id: Optional[str] = None,
    actor: str = "server",
    reason: Optional[str] = None,
    message: Optional[str] = None,
) -> None:
    """Append one event inside an open transaction (sqlite3 connection or the
    postgres adapter — both expose .execute with qmark SQL)."""
    now = now_utc()
    if job_id is not None and new_status == "running":
        # Cold-start tracking for autoscaled services: a replica the
        # autoscaler submitted (scale-up, and especially scale-FROM-ZERO)
        # reaching `running` closes the loop — observe submit->running into
        # the cold-start histogram, labeled by whether the service was at
        # zero (that's the latency a scale-to-zero policy trades away).
        first_sub = conn.execute(
            "SELECT timestamp, actor, reason, seq FROM run_events WHERE job_id = ?"
            " AND new_status = 'submitted' ORDER BY seq LIMIT 1",
            (job_id,),
        ).fetchone()
        if first_sub is not None and first_sub["actor"] == "autoscaler":
            elapsed = (now - from_iso(first_sub["timestamp"])).total_seconds()
            if elapsed >= 0:
                tracing.observe(
                    "dstack_tpu_service_cold_start_seconds",
                    elapsed,
                    {"from_zero": str(first_sub["reason"] == "scale_from_zero").lower()},
                )
        if first_sub is not None and first_sub["reason"] == "gang_retry":
            # Preemption rescue closing the loop: a gang-retried replica back
            # at `running` is the run making progress again. Time-to-recover
            # is anchored at the moment the failure was DETECTED (the prior
            # submission's first job leaving the running set), not at the
            # resubmit — teardown, backoff, and re-placement all count.
            # Lead job only: a gang's N hosts reach running N times but the
            # replica recovered once (the run_step_seconds lesson).
            lead = conn.execute(
                "SELECT job_num FROM jobs WHERE id = ?", (job_id,)
            ).fetchone()
            if lead is not None and lead["job_num"] != 0:
                first_sub = None
        if first_sub is not None and first_sub["reason"] == "gang_retry":
            anchor = conn.execute(
                "SELECT timestamp FROM run_events WHERE run_id = ?"
                " AND new_status IN ('terminating', 'failed', 'aborted')"
                " AND job_id IS NOT NULL AND seq < ?"
                " ORDER BY seq DESC LIMIT 1",
                (run_id, first_sub["seq"]),
            ).fetchone()
            base_ts = anchor["timestamp"] if anchor is not None else first_sub["timestamp"]
            elapsed = (now - from_iso(base_ts)).total_seconds()
            if elapsed >= 0:
                name_row = conn.execute(
                    "SELECT run_name FROM runs WHERE id = ?", (run_id,)
                ).fetchone()
                tracing.observe(
                    "dstack_tpu_run_recovery_seconds",
                    elapsed,
                    {"run": name_row["run_name"] if name_row is not None else ""},
                )
    if job_id is not None and old_status in _PHASE_HISTOGRAMS:
        prev = conn.execute(
            "SELECT timestamp FROM run_events WHERE job_id = ?"
            " ORDER BY seq DESC LIMIT 1",
            (job_id,),
        ).fetchone()
        anchor = prev["timestamp"] if prev is not None else None
        if anchor is None:
            row = conn.execute(
                "SELECT submitted_at FROM jobs WHERE id = ?", (job_id,)
            ).fetchone()
            anchor = row["submitted_at"] if row is not None else None
        if anchor:
            elapsed = (now - from_iso(anchor)).total_seconds()
            if elapsed >= 0:
                tracing.observe(_PHASE_HISTOGRAMS[old_status], elapsed)
    # seq orders the timeline deterministically when ISO timestamps collide
    # (several events in one transaction). Per-run MAX+1 inside the same
    # transaction — unlike an in-process counter it survives server restarts,
    # so a run spanning a restart still reads back in order.
    seq_row = conn.execute(
        "SELECT COALESCE(MAX(seq), 0) + 1 AS s FROM run_events WHERE run_id = ?",
        (run_id,),
    ).fetchone()
    conn.execute(
        "INSERT INTO run_events (id, run_id, job_id, timestamp, actor, old_status,"
        " new_status, reason, message, trace_id, seq)"
        " VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
        (
            new_id(),
            run_id,
            job_id,
            to_iso(now),
            actor,
            old_status,
            new_status,
            reason,
            message,
            tracing.current_trace_id(),
            seq_row["s"],
        ),
    )


async def list_run_events(db: Database, run_id: str) -> List[dict]:
    """The run's full timeline, oldest first."""
    rows = await db.fetchall(
        "SELECT * FROM run_events WHERE run_id = ? ORDER BY seq", (run_id,)
    )
    return [
        {
            "timestamp": r["timestamp"],
            "actor": r["actor"],
            "job_id": r["job_id"],
            "old_status": r["old_status"],
            "new_status": r["new_status"],
            "reason": r["reason"],
            "message": r["message"],
            "trace_id": r["trace_id"],
        }
        for r in rows
    ]


def compute_phases(events: List[dict]) -> Dict[str, Optional[float]]:
    """Derived per-phase durations (seconds) from a run's timeline.

    queue      = first submitted -> first job leaving 'submitted'
    provision  = first provisioning -> first job leaving 'provisioning'
    pull       = first pulling -> first job reaching 'running'
    run        = first 'running' -> the run's terminal event
    total      = first event -> last event (None while the run is live)

    Phases a run never entered (e.g. pull for a failed placement) are None.
    Derivations use the FIRST job to cross each edge: a gang's phases are the
    critical path of its slowest predecessor edge, and the first crossing is
    when the run as a whole left the phase."""

    def ts(ev) -> float:
        return from_iso(ev["timestamp"]).timestamp()

    def first(pred) -> Optional[dict]:
        for ev in events:
            if pred(ev):
                return ev
        return None

    out: Dict[str, Optional[float]] = {p: None for p in PHASES}
    out["total"] = None
    if not events:
        return out
    start = first(lambda e: e["new_status"] == "submitted") or events[0]
    left_queue = first(lambda e: e["job_id"] and e["old_status"] == "submitted")
    if left_queue is not None:
        out["queue"] = max(0.0, ts(left_queue) - ts(start))
    entered_prov = first(lambda e: e["job_id"] and e["new_status"] == "provisioning")
    left_prov = first(lambda e: e["job_id"] and e["old_status"] == "provisioning")
    if entered_prov is not None and left_prov is not None:
        out["provision"] = max(0.0, ts(left_prov) - ts(entered_prov))
    entered_pull = first(lambda e: e["job_id"] and e["new_status"] == "pulling")
    running = first(lambda e: e["new_status"] == "running")
    if entered_pull is not None and running is not None:
        out["pull"] = max(0.0, ts(running) - ts(entered_pull))
    terminal = first(
        lambda e: not e["job_id"]
        and e["new_status"] in ("terminated", "failed", "done")
    )
    if running is not None and terminal is not None:
        out["run"] = max(0.0, ts(terminal) - ts(running))
    if terminal is not None:
        out["total"] = max(0.0, ts(terminal) - ts(start))
    return out
