"""HTTP request metrics middleware (the tracing/request-duration layer).

Parity: reference server/app.py:81-89 + 227-271 (per-request duration metrics /
Sentry tracing). In-process counters keyed by (method, route template, status),
rendered into the Prometheus exposition (services/prometheus.py)."""

from __future__ import annotations

import time
from typing import Dict, List, Tuple

from aiohttp import web

_counts: Dict[Tuple[str, str, int], int] = {}
_duration_sums: Dict[Tuple[str, str, int], float] = {}


def record(method: str, route: str, status: int, seconds: float) -> None:
    key = (method, route, status)
    _counts[key] = _counts.get(key, 0) + 1
    _duration_sums[key] = _duration_sums.get(key, 0.0) + seconds


def snapshot() -> List[Tuple[Tuple[str, str, int], int, float]]:
    return [(k, _counts[k], _duration_sums.get(k, 0.0)) for k in sorted(_counts)]


def reset() -> None:
    _counts.clear()
    _duration_sums.clear()


@web.middleware
async def request_metrics_middleware(request: web.Request, handler):
    start = time.monotonic()
    status = 500
    try:
        response = await handler(request)
        status = response.status
        return response
    except web.HTTPException as e:
        status = e.status
        raise
    finally:
        # Unmatched requests (404 spam, scanners) share ONE label value: using
        # the raw path would mint a new (method, route, status) series per
        # probe and let anyone blow up the /metrics exposition.
        resource = request.match_info.route.resource
        route = resource.canonical if resource is not None else "unmatched"
        record(request.method, route, status, time.monotonic() - start)
