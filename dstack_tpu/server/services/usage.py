"""Fleet accounting and scheduling explainability (ISSUE 19).

Two halves:

1. **The chip-seconds ledger** (``usage_samples``): ``meter()`` attributes
   chip-seconds, dollars, and goodput-weighted chip-seconds to
   (project, user, run) from job/instance lifecycle rows — one row per run
   per UTC-hour bucket, accrued incrementally. The pass is O(live jobs):
   one join over live (or recently finished) jobs, one grouped cursor
   fetch, one grouped provisioning-anchor fetch, one workload-points fetch
   for the goodput weight. Accrual windows come from the lifecycle rows
   themselves (provisioning start → finished_at/now), not from tick
   wall-clock deltas, so a job that starts and finishes between two ticks
   still bills its full window and a restart resumes from the persisted
   ``last_sampled_at`` cursor without double counting. Single-writer: the
   pass runs inside the server's process_metrics loop; multi-replica
   deployments shard runs by lease before this matters.

2. **The pending-reason registry**: the submitted-jobs pass records why a
   run failed to place this pass (offer count + rejection-reason
   breakdown). The registry renders as ``dstack_tpu_run_pending_reason``
   gauges and backs the ``ps -v`` WAITING column (via runs.status_message);
   entries die on successful placement, terminal transition, run/project
   delete, and — defensively — when ``meter()`` notices the run is no
   longer waiting.

The placement-reason taxonomy (docs/guides/observability.md):
``no_offers`` (no candidate offers matched), ``no_capacity`` (offers
existed but every tried backend was out of stock), ``breaker_open``
(matching offers sit behind a backend whose circuit is open),
``slice_busy`` (every idle pool slice was claimed by a concurrent
placement), ``quota_reserved`` (reserved for fair-share quotas —
ROADMAP item 3; never emitted yet).
"""

from __future__ import annotations

import datetime
import json
import logging
from typing import Dict, List, Optional

from dstack_tpu.server import settings
from dstack_tpu.server.db import Database
from dstack_tpu.utils.common import from_iso, now_utc, to_iso

logger = logging.getLogger(__name__)

# Rejection reasons a placement pass can report, in precedence order for the
# single "primary" reason (ties in the per-slice counts break this way).
PENDING_REASONS = (
    "breaker_open",
    "no_capacity",
    "slice_busy",
    "quota_reserved",
    "no_offers",
)

# Job statuses that occupy chips (instance assigned, slice alive or coming up).
_ACCRUING_STATUSES = ("provisioning", "pulling", "running", "terminating")

# run_name -> {"run_id", "project", "reason", "reasons", "offers", "since"}
_pending: Dict[str, dict] = {}


def reset() -> None:
    """Test hook: drop all in-memory pending-reason state."""
    _pending.clear()


# =====================================================================================
# Pending-reason registry (scheduling explainability)


def set_pending(
    run_name: str, run_id: str, project: str, offers: int, reasons: Dict[str, int]
) -> str:
    """Record why `run_name` failed to place this pass; returns the primary
    reason (highest per-slice count, precedence order breaking ties)."""
    breakdown = {k: v for k, v in reasons.items() if v}
    primary = "no_offers"
    best = -1
    for key in PENDING_REASONS:
        n = breakdown.get(key, 0)
        if n > best:
            primary, best = key, n
    _pending[run_name] = {
        "run_id": run_id,
        "project": project,
        "reason": primary,
        "reasons": breakdown,
        "offers": offers,
        "since": to_iso(now_utc()),
    }
    return primary


def clear_pending(run_name: str) -> None:
    _pending.pop(run_name, None)


def forget_run(run_name: str) -> None:
    """Run deleted: its pending-reason series must not outlive it."""
    _pending.pop(run_name, None)


def forget_project(project_name: str) -> None:
    """Project deleted: sweep every pending entry it owned."""
    for name in [n for n, e in _pending.items() if e["project"] == project_name]:
        del _pending[name]


def pending_snapshot() -> List[dict]:
    """Current waiting runs for /metrics: one entry per (run, reason)."""
    return [
        {"run": name, "reason": entry["reason"], "project": entry["project"]}
        for name, entry in sorted(_pending.items())
    ]


# =====================================================================================
# Chip-seconds metering


def job_chips(instance_type_json) -> int:
    """Per-worker chip count from an instance_type (JSON string or parsed
    dict). The stored resources.tpu is slice-wide (chips across all hosts),
    and one job occupies one host — same derivation as chips_per_host."""
    if not instance_type_json:
        return 0
    if isinstance(instance_type_json, dict):
        itype = instance_type_json
    else:
        try:
            itype = json.loads(instance_type_json)
        except ValueError:
            return 0
    tpu = (itype.get("resources") or {}).get("tpu") or {}
    chips = int(tpu.get("chips") or 0)
    hosts = int(tpu.get("hosts") or 1)
    return chips // max(1, hosts) if chips else 0


def _bucket(ts: datetime.datetime) -> str:
    return to_iso(ts.replace(minute=0, second=0, microsecond=0))


async def _goodput_ratios(db: Database, run_ids: List[str]) -> Dict[str, float]:
    """Current goodput ratio per run (lead lineage, step/mark kinds — the
    /metrics gauge query), defaulting absent/unknown ledgers to 1.0 so runs
    without telemetry weigh goodput chip-seconds at face value."""
    from dstack_tpu.server.services.metrics import compute_goodput

    rows = await db.fetch_in(
        "SELECT j.run_id, w.data FROM workload_metrics_points w"
        " JOIN jobs j ON j.id = w.job_id"
        " WHERE j.job_num = 0 AND j.replica_num = 0"
        "   AND w.kind IN ('step', 'mark') AND j.run_id IN ({in})"
        " ORDER BY w.timestamp ASC",
        run_ids,
    )
    points: Dict[str, List[dict]] = {}
    for r in rows:
        try:
            points.setdefault(r["run_id"], []).append(json.loads(r["data"]))
        except ValueError:
            continue
    ratios: Dict[str, float] = {}
    for run_id, pts in points.items():
        ledger = compute_goodput(pts)
        if ledger["ratio"] is not None:
            ratios[run_id] = float(ledger["ratio"])
    return ratios


async def meter(db: Database, now: Optional[datetime.datetime] = None) -> int:
    """One metering tick: fold every live job's accrual window since the
    run's cursor into the ledger. Returns the number of runs touched."""
    now = now or now_utc()
    cutoff = to_iso(now - datetime.timedelta(seconds=settings.USAGE_FINISHED_GRACE))
    # Chips and price come from the job's own provisioning data, not the
    # instances join: a finished job's instance_id is already NULL (the slice
    # returned to the pool), but its JPD keeps the instance_type it occupied.
    rows = await db.fetchall(
        "SELECT j.id AS job_id, j.run_id, j.status, j.finished_at,"
        "       j.job_provisioning_data, r.project_id, r.user_id, r.run_name"
        " FROM jobs j"
        " JOIN runs r ON r.id = j.run_id"
        " WHERE r.deleted = 0 AND j.job_provisioning_data IS NOT NULL"
        "   AND (j.status IN ('provisioning', 'pulling', 'running', 'terminating')"
        "        OR (j.finished_at IS NOT NULL AND j.finished_at >= ?))",
        (cutoff,),
    )
    if _pending:
        await _prune_pending(db)
    if not rows:
        return 0

    by_run: Dict[str, List] = {}
    for r in rows:
        by_run.setdefault(r["run_id"], []).append(r)
    run_ids = list(by_run)

    cursor_rows = await db.fetch_in(
        "SELECT run_id, MAX(last_sampled_at) AS cursor FROM usage_samples"
        " WHERE run_id IN ({in}) GROUP BY run_id",
        run_ids,
    )
    cursors = {r["run_id"]: from_iso(r["cursor"]) for r in cursor_rows if r["cursor"]}

    # When each job started occupying its slice: the first provisioning event.
    anchor_rows = await db.fetch_in(
        "SELECT job_id, MIN(timestamp) AS ts FROM run_events"
        " WHERE job_id IS NOT NULL AND new_status = 'provisioning'"
        "   AND run_id IN ({in}) GROUP BY job_id",
        run_ids,
    )
    anchors = {r["job_id"]: from_iso(r["ts"]) for r in anchor_rows if r["ts"]}

    ratios = await _goodput_ratios(db, run_ids)

    bucket = _bucket(now)
    now_iso = to_iso(now)
    touched = 0
    for run_id, job_rows in by_run.items():
        cursor = cursors.get(run_id)
        chip_s = 0.0
        dollars = 0.0
        live = False
        for j in job_rows:
            start = anchors.get(j["job_id"])
            if start is None:
                continue
            try:
                jpd = json.loads(j["job_provisioning_data"])
            except (TypeError, ValueError):
                continue
            if j["status"] in _ACCRUING_STATUSES:
                live = True
                end = now
            else:
                end = from_iso(j["finished_at"]) if j["finished_at"] else now
            lo = max(start, cursor) if cursor is not None else start
            dt = (min(end, now) - lo).total_seconds()
            if dt <= 0:
                continue
            chip_s += job_chips(jpd.get("instance_type")) * dt
            # Every worker's JPD carries the whole slice's price; bill it on
            # worker 0 only so a multi-host gang counts its slice $/hr once.
            if int(jpd.get("worker_num") or 0) == 0:
                dollars += float(jpd.get("price") or 0.0) * dt / 3600.0
        if chip_s <= 0 and dollars <= 0 and not live:
            continue
        ratio = ratios.get(run_id, 1.0)
        await db.execute(
            "INSERT INTO usage_samples (run_id, project_id, user_id, bucket,"
            " chip_seconds, dollars, goodput_chip_seconds, last_sampled_at)"
            " VALUES (?, ?, ?, ?, ?, ?, ?, ?)"
            " ON CONFLICT (run_id, bucket) DO UPDATE SET"
            " chip_seconds = usage_samples.chip_seconds + excluded.chip_seconds,"
            " dollars = usage_samples.dollars + excluded.dollars,"
            " goodput_chip_seconds = usage_samples.goodput_chip_seconds"
            "   + excluded.goodput_chip_seconds,"
            " last_sampled_at = excluded.last_sampled_at",
            (
                run_id,
                job_rows[0]["project_id"],
                job_rows[0]["user_id"],
                bucket,
                chip_s,
                dollars,
                chip_s * ratio,
                now_iso,
            ),
        )
        touched += 1
    return touched


async def _prune_pending(db: Database) -> None:
    """Drop registry entries whose run is no longer waiting to place (stopped,
    finished, or deleted outside the placement pass)."""
    rows = await db.fetchall(
        "SELECT run_name FROM runs WHERE deleted = 0"
        " AND status IN ('pending', 'submitted')"
    )
    waiting = {r["run_name"] for r in rows}
    for name in [n for n in _pending if n not in waiting]:
        del _pending[name]


async def sweep_run(db: Database, run_id: str, run_name: str) -> None:
    """Run deleted: ledger rows and pending-reason series die with it."""
    await db.execute("DELETE FROM usage_samples WHERE run_id = ?", (run_id,))
    forget_run(run_name)


async def sweep_project(db: Database, project_id: str, project_name: str) -> None:
    """Project deleted: per-project ledger rows and pending entries go too
    (the per-project /metrics series disappear on the next scrape)."""
    await db.execute("DELETE FROM usage_samples WHERE project_id = ?", (project_id,))
    forget_project(project_name)


# =====================================================================================
# Aggregation (the /usage/get API and the fleet header)


async def fleet_summary(db: Database) -> dict:
    """One-line fleet accounting: chips by state, queued runs, $/hr burn.
    `allocated` = busy workers, `provisioning` = pending+provisioning,
    matching the dstack_tpu_fleet_chips states."""
    rows = await db.fetchall(
        "SELECT status, instance_type, price FROM instances"
        " WHERE status IN ('pending', 'provisioning', 'idle', 'busy')"
    )
    chips = {"allocated": 0, "idle": 0, "provisioning": 0}
    burn = 0.0
    for r in rows:
        state = {"busy": "allocated", "idle": "idle"}.get(r["status"], "provisioning")
        chips[state] += job_chips(r["instance_type"])
        burn += float(r["price"] or 0.0)
    queued = await db.fetchone(
        "SELECT COUNT(*) AS n FROM runs WHERE deleted = 0"
        " AND status IN ('pending', 'submitted')"
    )
    return {
        "total_chips": sum(chips.values()),
        "allocated_chips": chips["allocated"],
        "idle_chips": chips["idle"],
        "provisioning_chips": chips["provisioning"],
        "queued_runs": int(queued["n"]),
        "dollars_per_hour": burn,
    }


async def get_usage(
    db: Database, project_rows: List, since: Optional[str] = None
) -> dict:
    """Ledger readout for the given projects: per-run rows (chip-seconds,
    dollars, goodput-weighted chip-seconds, queue wait), per-project totals,
    and the fleet summary. `since` is an ISO timestamp compared against the
    hour buckets (lexical compare works: both are UTC ISO strings)."""
    projects = {p["id"]: p["name"] for p in project_rows}
    result = {
        "runs": [],
        "projects": [],
        "fleet": await fleet_summary(db),
        "since": since,
    }
    if not projects:
        return result
    params: List = list(projects)
    q = (
        "SELECT run_id, project_id, SUM(chip_seconds) AS chip_seconds,"
        " SUM(dollars) AS dollars,"
        " SUM(goodput_chip_seconds) AS goodput_chip_seconds"
        f" FROM usage_samples WHERE project_id IN ({','.join('?' for _ in projects)})"
    )
    if since:
        q += " AND bucket >= ?"
        params.append(since)
    q += " GROUP BY run_id, project_id"
    sample_rows = await db.fetchall(q, params)
    if not sample_rows:
        return result

    run_ids = [r["run_id"] for r in sample_rows]
    run_rows = await db.fetch_in(
        "SELECT r.id, r.run_name, r.status, r.submitted_at, u.username"
        " FROM runs r LEFT JOIN users u ON u.id = r.user_id"
        " WHERE r.id IN ({in})",
        run_ids,
    )
    runs = {r["id"]: r for r in run_rows}
    # Queue wait per run: submission -> the first job entering provisioning.
    placed_rows = await db.fetch_in(
        "SELECT run_id, MIN(timestamp) AS ts FROM run_events"
        " WHERE job_id IS NOT NULL AND new_status = 'provisioning'"
        "   AND run_id IN ({in}) GROUP BY run_id",
        run_ids,
    )
    placed = {r["run_id"]: r["ts"] for r in placed_rows}

    totals: Dict[str, dict] = {}
    for s in sample_rows:
        run = runs.get(s["run_id"])
        project = projects.get(s["project_id"], "")
        queue_wait = None
        if run is not None and placed.get(s["run_id"]) and run["submitted_at"]:
            queue_wait = max(
                0.0,
                (
                    from_iso(placed[s["run_id"]]) - from_iso(run["submitted_at"])
                ).total_seconds(),
            )
        result["runs"].append(
            {
                "project": project,
                "run_name": run["run_name"] if run is not None else s["run_id"],
                "user": run["username"] if run is not None else None,
                "status": run["status"] if run is not None else "deleted",
                "chip_seconds": float(s["chip_seconds"] or 0.0),
                "dollars": float(s["dollars"] or 0.0),
                "goodput_chip_seconds": float(s["goodput_chip_seconds"] or 0.0),
                "queue_wait_s": queue_wait,
            }
        )
        t = totals.setdefault(
            project,
            {"project": project, "chip_seconds": 0.0, "dollars": 0.0,
             "goodput_chip_seconds": 0.0, "runs": 0},
        )
        t["chip_seconds"] += float(s["chip_seconds"] or 0.0)
        t["dollars"] += float(s["dollars"] or 0.0)
        t["goodput_chip_seconds"] += float(s["goodput_chip_seconds"] or 0.0)
        t["runs"] += 1
    result["runs"].sort(key=lambda r: (r["project"], -r["chip_seconds"]))
    result["projects"] = sorted(totals.values(), key=lambda t: -t["chip_seconds"])
    return result
