"""Runs service: plan/apply/submit/stop/delete + row<->wire conversion.

Parity: reference server/services/runs.py (get_plan:277, apply_plan:377, submit_run:452,
stop_runs:552). The async FSM driving submitted->running->done lives in
server/background/tasks (M3 of the build plan)."""

from __future__ import annotations

import json
import logging
from typing import Dict, List, Optional, Tuple

from dstack_tpu.core.errors import (
    ResourceExistsError,
    ResourceNotExistsError,
    ServerClientError,
)
from dstack_tpu.core.models.runs import (
    Job,
    JobProvisioningData,
    JobSpec,
    JobStatus,
    JobSubmission,
    JobTerminationReason,
    Run,
    RunPlan,
    RunSpec,
    RunStatus,
    RunTerminationReason,
)
from dstack_tpu.core.models.services import ServiceSpec
from dstack_tpu.server import settings
from dstack_tpu.server.db import Database, dumps, loads, new_id
from dstack_tpu.server.services.jobs.configurators import get_job_specs
from dstack_tpu.utils.common import from_iso, now_utc, to_iso
from dstack_tpu.utils.random_names import generate_name

logger = logging.getLogger(__name__)


def row_to_job_submission(row) -> JobSubmission:
    jpd = loads(row["job_provisioning_data"])
    return JobSubmission(
        id=row["id"],
        submission_num=row["submission_num"],
        submitted_at=from_iso(row["submitted_at"]),
        last_processed_at=from_iso(row["last_processed_at"]),
        finished_at=from_iso(row["finished_at"]),
        status=JobStatus(row["status"]),
        termination_reason=(
            JobTerminationReason(row["termination_reason"]) if row["termination_reason"] else None
        ),
        termination_reason_message=row["termination_reason_message"],
        exit_status=row["exit_status"],
        job_provisioning_data=JobProvisioningData.model_validate(jpd) if jpd else None,
        inactivity_secs=row["inactivity_secs"],
    )


async def rows_to_runs(db: Database, run_rows: List) -> List[Run]:
    """Batch conversion: 3 queries total instead of 3 per run (avoids N+1 through the
    single DB worker)."""
    if not run_rows:
        return []
    user_ids = sorted({r["user_id"] for r in run_rows})
    project_ids = sorted({r["project_id"] for r in run_rows})
    run_ids = [r["id"] for r in run_rows]

    def q(ids):
        return ",".join("?" for _ in ids)

    users = {
        r["id"]: r["username"]
        for r in await db.fetchall(f"SELECT id, username FROM users WHERE id IN ({q(user_ids)})", user_ids)
    }
    projects = {
        r["id"]: r["name"]
        for r in await db.fetchall(f"SELECT id, name FROM projects WHERE id IN ({q(project_ids)})", project_ids)
    }
    job_rows = await db.fetchall(
        f"SELECT * FROM jobs WHERE run_id IN ({q(run_ids)})"
        " ORDER BY run_id, replica_num, job_num, submission_num",
        run_ids,
    )
    jobs_by_run: dict = {}
    for jr in job_rows:
        jobs_by_run.setdefault(jr["run_id"], []).append(jr)
    from dstack_tpu.server.services import leases as leases_service

    owners = await leases_service.owners(db, run_ids)
    return [
        _build_run(
            r,
            username=users.get(r["user_id"], "?"),
            project_name=projects.get(r["project_id"], "?"),
            job_rows=jobs_by_run.get(r["id"], []),
            owner=owners.get(r["id"]),
        )
        for r in run_rows
    ]


async def run_model_to_run(db: Database, run_row) -> Run:
    return (await rows_to_runs(db, [run_row]))[0]


def _build_run(
    run_row, username: str, project_name: str, job_rows: List,
    owner: Optional[str] = None,
) -> Run:
    by_key: dict = {}
    for jr in job_rows:
        key = (jr["replica_num"], jr["job_num"])
        if key not in by_key:
            by_key[key] = Job(job_spec=JobSpec.model_validate(loads(jr["job_spec"])))
        by_key[key].job_submissions.append(row_to_job_submission(jr))
    jobs = list(by_key.values())
    service_spec = loads(run_row["service_spec"])
    cost = 0.0
    for job in jobs:
        for sub in job.job_submissions:
            if sub.job_provisioning_data is not None and sub.finished_at is not None:
                cost += sub.job_provisioning_data.price * max(
                    0.0, (sub.finished_at - sub.submitted_at).total_seconds() / 3600
                )
    run = Run(
        id=run_row["id"],
        project_name=project_name,
        user=username,
        submitted_at=from_iso(run_row["submitted_at"]),
        last_processed_at=from_iso(run_row["last_processed_at"]),
        status=RunStatus(run_row["status"]),
        status_message=run_row["status_message"],
        termination_reason=(
            RunTerminationReason(run_row["termination_reason"])
            if run_row["termination_reason"]
            else None
        ),
        run_spec=RunSpec.model_validate(loads(run_row["run_spec"])),
        jobs=jobs,
        cost=cost,
        service=ServiceSpec.model_validate(service_spec) if service_spec else None,
        owner=owner,
    )
    run.error = _run_error(run)
    return run


def _run_error(run: Run) -> Optional[str]:
    if run.termination_reason == RunTerminationReason.RETRY_LIMIT_EXCEEDED:
        return "retry limit exceeded"
    if run.termination_reason == RunTerminationReason.SERVER_ERROR:
        return "server error"
    return None


def _configured_name(run_spec: RunSpec):
    """`name:` inside the configuration names the run when run_name isn't set
    explicitly (reference configurations/__init__.py BaseRunConfiguration.name)."""
    return getattr(run_spec.configuration, "name", None)


def _apply_plugin_policies(project_row, user_row, run_spec: RunSpec) -> RunSpec:
    from dstack_tpu.server.services import plugins as plugins_service

    return plugins_service.apply_policies(
        user_row["username"], project_row["name"], run_spec
    )


async def get_run_plan(db: Database, project_row, user_row, run_spec: RunSpec) -> RunPlan:
    run_spec = _apply_plugin_policies(project_row, user_row, run_spec)
    effective_name = run_spec.run_name or _configured_name(run_spec) or generate_name()
    plan_spec = run_spec.model_copy(deep=True)
    plan_spec.run_name = effective_name
    job_specs = get_job_specs(plan_spec)

    # Offer fan-in (backends configured for the project; populated in M3+).
    from dstack_tpu.server.services import offers as offers_service

    profile = plan_spec.merged_profile()
    offer_list = await offers_service.get_offers_by_requirements(
        db, project_row, job_specs[0].requirements, profile
    )

    # Plan-time image introspection (reference services/docker.py:34-70): a bad
    # image or credential fails HERE, not after a slice is provisioned. The
    # default TPU image is baked (never pulled from a registry) — skip it.
    image_config = None
    image = getattr(plan_spec.configuration, "image", None)
    if image and settings.VALIDATE_IMAGES:
        from dstack_tpu.core.services import docker_registry

        username = password = None
        auth = getattr(plan_spec.configuration, "registry_auth", None)
        if auth is not None:
            from dstack_tpu.server.services import secrets as secrets_service
            from dstack_tpu.utils.interpolator import extract_references, interpolate_env

            vals = {"username": auth.username or "", "password": auth.password or ""}
            refs = extract_references(vals.values(), "secrets")
            if refs:
                store = await secrets_service.get_secrets(db, project_row["id"])
                vals = interpolate_env(
                    vals, {"secrets": {k: store[k] for k in refs if k in store}},
                    missing_ok=True,
                )
            username, password = vals["username"] or None, vals["password"] or None
        icfg = await docker_registry.get_image_config_cached(image, username, password)
        image_config = icfg.model_dump(mode="json")

    current = None
    action = "create"
    existing = await db.fetchone(
        "SELECT * FROM runs WHERE project_id = ? AND run_name = ? AND deleted = 0",
        (project_row["id"], run_spec.run_name),
    ) if run_spec.run_name else None
    if existing is not None:
        current = await run_model_to_run(db, existing)
        can_update = False
        if not current.status.is_finished():
            try:
                check_can_update_run_spec(current.run_spec, plan_spec)
                can_update = True
            except ServerClientError:
                pass
        action = "update" if can_update else "create"

    return RunPlan(
        project_name=project_row["name"],
        user=user_row["username"],
        run_spec=plan_spec,
        effective_run_name=effective_name,
        job_plans=job_specs,
        offers=[o.model_dump(mode="json") for o in offer_list[:50]],
        total_offers=len(offer_list),
        max_offer_price=max((o.price for o in offer_list), default=None),
        current_resource=current,
        action=action,
        image_config=image_config,
    )


async def submit_run(db: Database, project_row, user_row, run_spec: RunSpec) -> Run:
    run_spec = _apply_plugin_policies(project_row, user_row, run_spec)
    if not run_spec.run_name:
        run_spec = run_spec.model_copy(deep=True)
        run_spec.run_name = _configured_name(run_spec) or generate_name()
    _validate_run_name(run_spec.run_name)

    existing = await db.fetchone(
        "SELECT * FROM runs WHERE project_id = ? AND run_name = ? AND deleted = 0",
        (project_row["id"], run_spec.run_name),
    )
    if existing is not None and not RunStatus(existing["status"]).is_finished():
        raise ResourceExistsError(
            f"run {run_spec.run_name} already exists and is {existing['status']}"
        )

    # Referenced volumes must exist up front (fail fast; activation is async).
    from dstack_tpu.core.models.configurations import VolumeMountPoint

    for mount in getattr(run_spec.configuration, "volumes", []) or []:
        if isinstance(mount, VolumeMountPoint):
            from dstack_tpu.server.services import volumes as volumes_service

            vrow = await volumes_service.get_volume_row(db, project_row["id"], mount.name)
            if vrow is None:
                raise ResourceNotExistsError(
                    f"volume {mount.name} does not exist; create it first"
                )

    run_id = new_id()
    now = to_iso(now_utc())
    replicas = 1
    conf = run_spec.configuration
    service_spec_json = None
    if conf.type == "service":
        replicas = conf.replicas.min or 0
        from dstack_tpu.core.models.services import ServiceSpec

        service_spec_json = ServiceSpec(
            url=f"/proxy/services/{project_row['name']}/{run_spec.run_name}/",
            model=conf.model,
        ).model_dump_json()

    # Validate/configure all job specs before writing anything, then insert the run and
    # its jobs in one transaction so a failure can't leave an orphan 'submitted' run.
    all_specs = [
        (replica_num, job_spec)
        for replica_num in range(replicas)
        for job_spec in get_job_specs(run_spec, replica_num=replica_num)
    ]
    project_id = project_row["id"]
    user_id = user_row["id"]
    run_spec_json = run_spec.model_dump_json()
    run_name = run_spec.run_name

    from dstack_tpu.server.services import events as events_service

    def _tx(conn) -> None:
        if existing is not None:
            # Finished runs with the same name are soft-deleted on resubmit.
            conn.execute("UPDATE runs SET deleted = 1 WHERE id = ?", (existing["id"],))
        conn.execute(
            "INSERT INTO runs (id, project_id, user_id, run_name, submitted_at, status,"
            " run_spec, service_spec, desired_replica_count) VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)",
            (
                run_id, project_id, user_id, run_name, now, RunStatus.SUBMITTED.value,
                run_spec_json, service_spec_json, replicas,
            ),
        )
        events_service.record_event_tx(
            conn, run_id, RunStatus.SUBMITTED.value, actor="user"
        )
        for _, job_spec in all_specs:
            job_id = new_id()
            conn.execute(
                "INSERT INTO jobs (id, project_id, run_id, run_name, job_num, replica_num,"
                " submission_num, job_spec, status, submitted_at)"
                " VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
                (
                    job_id,
                    project_id,
                    run_id,
                    run_name,
                    job_spec.job_num,
                    job_spec.replica_num,
                    0,
                    job_spec.model_dump_json(),
                    JobStatus.SUBMITTED.value,
                    now,
                ),
            )
            events_service.record_event_tx(
                conn, run_id, JobStatus.SUBMITTED.value, job_id=job_id, actor="user"
            )

    await db.run(_tx)
    # Nudge the scheduler: the new jobs are visible the moment the transaction
    # commits, so the submitted-jobs pass runs now instead of up to a full
    # PROCESS_SUBMITTED_JOBS_INTERVAL later (bench_scheduler measures the
    # submit->assign latency this removes). No-op without a running scheduler.
    from dstack_tpu.server import background

    background.wake("process_submitted_jobs")
    # Cross-replica nudge: wake() only reaches loops in THIS process, so stamp
    # the shared run_leases notify row too — other replicas' submitted passes
    # poll it and start next short-tick instead of next interval.
    from dstack_tpu.server.services import leases as leases_service

    await leases_service.notify(db, "process_submitted_jobs")
    from dstack_tpu.server.services import proxy as proxy_service

    if existing is not None:
        # The old (soft-deleted) run's proxy state goes with it; the route for
        # this run name must rebuild against the fresh run id.
        proxy_service.forget_run(existing["id"], run_name)
    proxy_service.route_table.invalidate(project_row["name"], run_name)
    run_row = await db.fetchone("SELECT * FROM runs WHERE id = ?", (run_id,))
    return await run_model_to_run(db, run_row)


async def create_job(
    db: Database,
    project_id: str,
    run_id: str,
    run_name: str,
    job_spec: JobSpec,
    submission_num: int = 0,
) -> str:
    job_id = new_id()
    await db.execute(
        "INSERT INTO jobs (id, project_id, run_id, run_name, job_num, replica_num,"
        " submission_num, job_spec, status, submitted_at)"
        " VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
        (
            job_id,
            project_id,
            run_id,
            run_name,
            job_spec.job_num,
            job_spec.replica_num,
            submission_num,
            job_spec.model_dump_json(),
            JobStatus.SUBMITTED.value,
            to_iso(now_utc()),
        ),
    )
    return job_id


async def list_runs(
    db: Database,
    project_id: Optional[str] = None,
    project_ids: Optional[List[str]] = None,
    only_active: bool = False,
    limit: int = 1000,
    prev_submitted_at: Optional[str] = None,
    prev_run_id: Optional[str] = None,
) -> List[Run]:
    """Keyset pagination (reference schemas/runs.py:16-18 — prev_submitted_at
    + prev_run_id cursor, newest first): pass the last row's values to get
    the next page; the (submitted_at, id) pair totally orders rows even when
    timestamps collide."""
    sql = "SELECT * FROM runs WHERE deleted = 0"
    params: list = []
    if project_id is not None:
        sql += " AND project_id = ?"
        params.append(project_id)
    if project_ids is not None:
        if not project_ids:
            return []
        sql += f" AND project_id IN ({','.join('?' for _ in project_ids)})"
        params.extend(project_ids)
    if only_active:
        sql += " AND status NOT IN ('terminated', 'failed', 'done')"
    if prev_submitted_at is not None:
        # Normalize to the canonical storage format (UTC isoformat) so the
        # lexicographic comparison is a correct time comparison whatever
        # offset/precision the client echoed back.
        try:
            prev_submitted_at = to_iso(from_iso(prev_submitted_at))
        except (TypeError, ValueError):  # non-string JSON raises TypeError
            raise ServerClientError("prev_submitted_at must be an ISO timestamp")
        if prev_run_id is not None:
            sql += " AND (submitted_at < ? OR (submitted_at = ? AND id < ?))"
            params.extend([prev_submitted_at, prev_submitted_at, str(prev_run_id)])
        else:
            sql += " AND submitted_at < ?"
            params.append(prev_submitted_at)
    sql += " ORDER BY submitted_at DESC, id DESC LIMIT ?"
    params.append(limit)
    rows = await db.fetchall(sql, params)
    return await rows_to_runs(db, rows)


async def get_run(db: Database, project_row, run_name: str) -> Run:
    row = await db.fetchone(
        "SELECT * FROM runs WHERE project_id = ? AND run_name = ? AND deleted = 0",
        (project_row["id"], run_name),
    )
    if row is None:
        raise ResourceNotExistsError(f"run {run_name} not found")
    return await run_model_to_run(db, row)


async def stop_runs(db: Database, project_row, run_names: List[str], abort: bool = False) -> None:
    reason = RunTerminationReason.ABORTED_BY_USER if abort else RunTerminationReason.STOPPED_BY_USER
    for name in run_names:
        row = await db.fetchone(
            "SELECT * FROM runs WHERE project_id = ? AND run_name = ? AND deleted = 0",
            (project_row["id"], name),
        )
        if row is None:
            raise ResourceNotExistsError(f"run {name} not found")
        if RunStatus(row["status"]).is_finished():
            continue
        from dstack_tpu.server.services import events as events_service

        old_status = row["status"]

        def _tx(conn, row=row, old_status=old_status) -> None:
            conn.execute(
                "UPDATE runs SET status = ?, termination_reason = ? WHERE id = ?",
                (RunStatus.TERMINATING.value, reason.value, row["id"]),
            )
            events_service.record_event_tx(
                conn,
                row["id"],
                RunStatus.TERMINATING.value,
                old_status=old_status,
                actor="user",
                reason=reason.value,
            )

        await db.run(_tx)


async def delete_runs(db: Database, project_row, run_names: List[str]) -> None:
    for name in run_names:
        row = await db.fetchone(
            "SELECT * FROM runs WHERE project_id = ? AND run_name = ? AND deleted = 0",
            (project_row["id"], name),
        )
        if row is None:
            raise ResourceNotExistsError(f"run {name} not found")
        if not RunStatus(row["status"]).is_finished():
            raise ServerClientError(f"run {name} is {row['status']}; stop it first")
        await db.execute("UPDATE runs SET deleted = 1 WHERE id = ?", (row["id"],))
        # The timeline goes with the run: events for deleted runs are
        # unreachable (get_events 404s) and would otherwise accumulate forever.
        await db.execute("DELETE FROM run_events WHERE run_id = ?", (row["id"],))
        # Workload telemetry too — both the DB points and the in-memory
        # per-run step-time histogram series (the proxy-latency precedent:
        # per-run label sets must die with the run or /metrics leaks).
        await db.execute(
            "DELETE FROM workload_metrics_points WHERE job_id IN"
            " (SELECT id FROM jobs WHERE run_id = ?)",
            (row["id"],),
        )
        from dstack_tpu.core import tracing
        from dstack_tpu.server.services.metrics import STEP_HISTOGRAM

        tracing.drop_series(STEP_HISTOGRAM, {"run": row["run_name"]})
        # Sweep ALL the proxy's per-run state (route entry, rr cursor, stats
        # window, rate-limit buckets): deleted runs must not leak memory.
        from dstack_tpu.server.services import proxy as proxy_service

        proxy_service.forget_run(row["id"], row["run_name"])
        # And the run's scheduler lease (finished runs normally release at
        # finalize; this catches leases orphaned by a crash).
        from dstack_tpu.server.services import leases as leases_service

        await leases_service.release_runs(db, [row["id"]])
        # Gang-health detector state (straggler hysteresis counters) dies
        # with the run; its /metrics snapshot self-heals on the next pass.
        from dstack_tpu.server.services import gang_health as gang_health_service

        gang_health_service.forget_run(row["id"])
        # Fleet accounting: the run's ledger rows and pending-reason series
        # go with it (the per-project chip-seconds counter resets, which
        # rate() tolerates).
        from dstack_tpu.server.services import usage as usage_service

        await usage_service.sweep_run(db, row["id"], row["run_name"])


def _validate_run_name(name: str) -> None:
    if not name or not all(c.isalnum() or c in "-_" for c in name):
        raise ServerClientError(f"invalid run name {name!r}")


# =====================================================================================
# Replica scaling (parity: reference runs.py:995 scale_run_replicas)


def _latest_by_replica(job_rows) -> Dict[int, List]:
    """replica_num -> latest-submission job rows (ordered by job_num)."""
    latest: Dict[tuple, dict] = {}
    for r in job_rows:
        key = (r["replica_num"], r["job_num"])
        cur = latest.get(key)
        if cur is None or r["submission_num"] > cur["submission_num"]:
            latest[key] = r
    replicas: Dict[int, List] = {}
    for (replica_num, _), r in sorted(latest.items()):
        replicas.setdefault(replica_num, []).append(r)
    return replicas


def classify_replicas(job_rows) -> Tuple[List[Tuple[int, int, List]], List[Tuple[int, List]]]:
    """(active, inactive): active carries (importance, replica_num, rows) — submitted=0,
    provisioning/pulling=1, running=2 (reference runs.py:1007-1024)."""
    active, inactive = [], []
    for replica_num, rows in _latest_by_replica(job_rows).items():
        statuses = {JobStatus(r["status"]) for r in rows}
        if JobStatus.TERMINATING in statuses or any(s.is_finished() for s in statuses):
            inactive.append((replica_num, rows))
        elif JobStatus.SUBMITTED in statuses:
            active.append((0, replica_num, rows))
        elif statuses & {JobStatus.PROVISIONING, JobStatus.PULLING}:
            active.append((1, replica_num, rows))
        else:
            active.append((2, replica_num, rows))
    # Most important first (stable by replica_num): scale-down takes from the tail.
    active.sort(key=lambda t: (-t[0], t[1]))
    return active, inactive


async def scale_run_replicas(
    db: Database, run_row, diff: int, actor: str = "autoscaler"
) -> None:
    """Add (+diff) or remove (-diff) service replicas.

    Scale-down marks the least-important replicas' jobs TERMINATING with reason
    SCALED_DOWN (the run FSM ignores such replicas); scale-up resubmits inactive
    replicas first, then mints new replica_nums. Inserts are per-replica-atomic
    like the gang-retry path. `actor` labels the run_events rows — manual
    replica changes (update_run) must not masquerade as autoscaler actions,
    and only autoscaler scale-ups feed the cold-start histogram
    (services/events)."""
    if diff == 0:
        return
    job_rows = await db.fetchall("SELECT * FROM jobs WHERE run_id = ?", (run_row["id"],))
    active, inactive = classify_replicas(job_rows)
    run_spec = RunSpec.model_validate(loads(run_row["run_spec"]))
    logger.info(
        "run %s: scaling %s by %d (active=%d)",
        run_row["run_name"], "up" if diff > 0 else "down", abs(diff), len(active),
    )

    if diff < 0:
        from dstack_tpu.server.services.jobs import terminate_job

        for _, _, rows in reversed(active[diff:]):
            for r in rows:
                await terminate_job(
                    db, r, JobTerminationReason.SCALED_DOWN,
                    f"scaled down by {actor}", actor=actor,
                )
    else:
        now = to_iso(now_utc())
        scheduled = 0
        used_nums = set(_latest_by_replica(job_rows))
        # Scale-from-zero is its own event flavor: the elapsed time from this
        # event to the replica's `running` is the service's COLD START — the
        # number a scale-to-zero policy is judged by (services/events observes
        # it into dstack_tpu_service_cold_start_seconds, autoscaler actor only).
        if actor == "autoscaler":
            scale_reason = "scale_from_zero" if not active else "scaled_up"
        else:
            scale_reason = "manual_scale"

        async def _insert_replica(replica_num: int, specs, submission_num: int) -> None:
            from dstack_tpu.server.services import events as events_service

            rows = [
                (
                    new_id(),
                    run_row["project_id"],
                    run_row["id"],
                    run_row["run_name"],
                    s.job_num,
                    replica_num,
                    submission_num,
                    s.model_dump_json(),
                    now,
                )
                for s in specs
            ]

            def _tx(conn) -> None:
                conn.executemany(
                    "INSERT INTO jobs (id, project_id, run_id, run_name, job_num,"
                    " replica_num, submission_num, job_spec, status, submitted_at)"
                    " VALUES (?, ?, ?, ?, ?, ?, ?, ?, 'submitted', ?)",
                    rows,
                )
                for r in rows:
                    events_service.record_event_tx(
                        conn, run_row["id"], "submitted", job_id=r[0],
                        actor=actor, reason=scale_reason,
                    )

            await db.run(_tx)

        # Revive previously scaled-down/finished replicas first (fresh submission).
        for replica_num, rows in inactive:
            if scheduled >= diff:
                break
            if any(not JobStatus(r["status"]).is_finished() for r in rows):
                continue  # still terminating; pick a new num instead
            specs = get_job_specs(run_spec, replica_num=replica_num)
            await _insert_replica(replica_num, specs, rows[0]["submission_num"] + 1)
            scheduled += 1
        next_num = max(used_nums, default=-1) + 1
        while scheduled < diff:
            specs = get_job_specs(run_spec, replica_num=next_num)
            await _insert_replica(next_num, specs, 0)
            next_num += 1
            scheduled += 1

    from dstack_tpu.server.services import proxy as proxy_service

    proxy_service.route_table.invalidate_run(run_row["id"])


# =====================================================================================
# In-place update (parity: reference runs.py:896-944 _check_can_update_run_spec —
# only fields that don't require re-provisioning may change on a live run)

_UPDATABLE_SPEC_FIELDS = ["configuration", "repo_data"]
_CONF_UPDATABLE_FIELDS: List[str] = []
_TYPE_SPECIFIC_CONF_UPDATABLE_FIELDS = {
    # Service capacity/routing knobs redeploy via replica scaling, not re-provision.
    "service": ["replicas", "scaling", "strip_prefix", "rate_limits"],
    "dev-environment": ["inactivity_duration"],
}


def _changed_fields(a, b) -> List[str]:
    da, db_ = a.model_dump(mode="json"), b.model_dump(mode="json")
    return sorted(k for k in set(da) | set(db_) if da.get(k) != db_.get(k))


def check_can_update_run_spec(current: RunSpec, new: RunSpec) -> None:
    changed = _changed_fields(current, new)
    for key in changed:
        if key not in _UPDATABLE_SPEC_FIELDS:
            raise ServerClientError(
                f"cannot update fields {changed} in place; only {_UPDATABLE_SPEC_FIELDS}"
                " may change on a live run (stop and re-apply for the rest)"
            )
    cur_conf, new_conf = current.configuration, new.configuration
    if cur_conf.type != new_conf.type:
        raise ServerClientError(
            f"configuration type changed {cur_conf.type} -> {new_conf.type}; cannot update"
        )
    allowed = _CONF_UPDATABLE_FIELDS + _TYPE_SPECIFIC_CONF_UPDATABLE_FIELDS.get(
        new_conf.type, []
    )
    conf_changed = _changed_fields(cur_conf, new_conf)
    for key in conf_changed:
        if key not in allowed:
            raise ServerClientError(
                f"cannot update configuration fields {conf_changed} in place;"
                f" a {new_conf.type} run allows only {allowed}"
            )


async def update_run(db: Database, project_row, user_row, run_spec: RunSpec) -> Run:
    """Apply an updated spec to a live run (reference update_run runs.py:915)."""
    row = await db.fetchone(
        "SELECT * FROM runs WHERE project_id = ? AND run_name = ? AND deleted = 0",
        (project_row["id"], run_spec.run_name),
    )
    if row is None:
        raise ResourceNotExistsError(f"run {run_spec.run_name} not found")
    if RunStatus(row["status"]).is_finished():
        raise ServerClientError(
            f"run {run_spec.run_name} is {row['status']}; submit a new run instead"
        )
    current = RunSpec.model_validate(loads(row["run_spec"]))
    check_can_update_run_spec(current, run_spec)
    await db.execute(
        "UPDATE runs SET run_spec = ? WHERE id = ?",
        (run_spec.model_dump_json(), row["id"]),
    )
    from dstack_tpu.server.services import proxy as proxy_service

    proxy_service.route_table.invalidate_run(row["id"])  # rate_limits may have changed
    conf = run_spec.configuration
    if conf.type == "service" and conf.scaling is None:
        # Manual replica count: converge now (autoscaled services converge via
        # process_services reading the updated spec).
        target = conf.replicas.min or 0
        job_rows = await db.fetchall("SELECT * FROM jobs WHERE run_id = ?", (row["id"],))
        active, _ = classify_replicas(job_rows)
        if target != len(active):
            await scale_run_replicas(db, row, target - len(active), actor="user")
        await db.execute(
            "UPDATE runs SET desired_replica_count = ? WHERE id = ?", (target, row["id"])
        )
    row = await db.fetchone("SELECT * FROM runs WHERE id = ?", (row["id"],))
    logger.info("run %s updated in place", run_spec.run_name)
    return await run_model_to_run(db, row)
