"""Project management (parity: reference server/services/projects.py)."""

from __future__ import annotations

from typing import List

from dstack_tpu.core.errors import ResourceExistsError, ResourceNotExistsError
from dstack_tpu.core.models.users import Member, Project, ProjectRole
from dstack_tpu.server.db import Database, new_id
from dstack_tpu.server.services.users import row_to_user
from dstack_tpu.utils.common import from_iso, now_utc, to_iso


async def get_project_row(db: Database, project_name: str):
    row = await db.fetchone(
        "SELECT * FROM projects WHERE name = ? AND deleted = 0", (project_name,)
    )
    if row is None:
        raise ResourceNotExistsError(f"project {project_name} not found")
    return row


async def create_project(db: Database, owner_row, project_name: str) -> Project:
    existing = await db.fetchone(
        "SELECT id FROM projects WHERE name = ? AND deleted = 0", (project_name,)
    )
    if existing is not None:
        raise ResourceExistsError(f"project {project_name} exists")
    pid = new_id()
    owner_id = owner_row["id"]
    created = to_iso(now_utc())

    def _tx(conn) -> None:
        conn.execute(
            "INSERT INTO projects (id, name, owner_id, created_at) VALUES (?, ?, ?, ?)",
            (pid, project_name, owner_id, created),
        )
        conn.execute(
            "INSERT INTO members (project_id, user_id, project_role) VALUES (?, ?, ?)",
            (pid, owner_id, ProjectRole.ADMIN.value),
        )

    await db.run(_tx)
    return await get_project(db, project_name)


async def get_project(db: Database, project_name: str) -> Project:
    row = await get_project_row(db, project_name)
    owner = await db.fetchone("SELECT * FROM users WHERE id = ?", (row["owner_id"],))
    member_rows = await db.fetchall(
        "SELECT m.project_role, u.* FROM members m JOIN users u ON u.id = m.user_id"
        " WHERE m.project_id = ?",
        (row["id"],),
    )
    return Project(
        id=row["id"],
        project_name=row["name"],
        owner=row_to_user(owner),
        created_at=from_iso(row["created_at"]),
        members=[
            Member(user=row_to_user(m), project_role=ProjectRole(m["project_role"]))
            for m in member_rows
        ],
    )


async def list_user_projects(db: Database, user_row) -> List[Project]:
    if user_row["global_role"] == "admin":
        rows = await db.fetchall("SELECT name FROM projects WHERE deleted = 0 ORDER BY name")
    else:
        rows = await db.fetchall(
            "SELECT p.name FROM projects p JOIN members m ON m.project_id = p.id"
            " WHERE m.user_id = ? AND p.deleted = 0 ORDER BY p.name",
            (user_row["id"],),
        )
    return [await get_project(db, r["name"]) for r in rows]


async def set_members(db: Database, project_name: str, members: List[dict]) -> Project:
    row = await get_project_row(db, project_name)
    # Resolve all usernames before mutating so a bad entry can't wipe the member list.
    resolved = []
    for m in members:
        user = await db.fetchone("SELECT id FROM users WHERE username = ?", (m["username"],))
        if user is None:
            raise ResourceNotExistsError(f"user {m['username']} not found")
        resolved.append((user["id"], m.get("project_role", "user")))
    project_id = row["id"]

    def _tx(conn) -> None:
        conn.execute("DELETE FROM members WHERE project_id = ?", (project_id,))
        for user_id, role in resolved:
            conn.execute(
                "INSERT INTO members (project_id, user_id, project_role)"
                " VALUES (?, ?, ?) ON CONFLICT (project_id, user_id)"
                " DO UPDATE SET project_role = excluded.project_role",
                (project_id, user_id, role),
            )

    await db.run(_tx)
    return await get_project(db, project_name)


async def delete_projects(db: Database, names: List[str]) -> None:
    for name in names:
        row = await get_project_row(db, name)
        await db.execute("UPDATE projects SET deleted = 1 WHERE id = ?", (row["id"],))
        # Fleet accounting: the project's ledger rows and any pending-reason
        # entries die with it, so per-project /metrics series disappear on
        # the next scrape instead of freezing at their last value.
        from dstack_tpu.server.services import usage as usage_service

        await usage_service.sweep_project(db, row["id"], name)
