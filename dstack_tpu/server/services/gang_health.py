"""Gang-wide health: cross-host step skew, straggler detection, per-host
fleet gauges (ISSUE 15 — the layer PR 11 deliberately left out).

PR 11's aggregation is lead-lineage-only: a 16-host gang is observable as
exactly one host, so a slow host dragging every synchronous collective, or a
sick DCN link, is invisible until goodput silently decays. This module joins
``workload_metrics_points`` across ALL jobs of a run on every collection pass
and derives what the lead stream can't show:

* **Skew** — per-host median step time over the trailing window; the run's
  skew ratio is slowest-host median / gang median. In synchronous training
  every host's step stretches to the slowest host, so on a healthy gang the
  ratio sits near 1.0; sustained growth is a straggler even before the rule
  below names one (reported medians can diverge because step TIME is measured
  locally: the straggler's compute runs long while the victims' fence —
  ``collective_wait_s`` — absorbs the lag).
* **Stragglers** — a robust rule with hysteresis: a host whose window median
  exceeds ``k``·(gang median) for ``M`` consecutive windows is flagged
  (``straggler_detected`` run_event naming the host); a flagged host must sit
  below the lower ``clear_k`` threshold for ``M`` consecutive windows to
  clear (``straggler_cleared``), so a host flapping around the threshold
  can't spam events. Single-host runs never flag — there is no gang to skew
  against. A host that leaves the sample entirely (gang shrink via elastic
  restart, agent death) is cleared with ``reason="departed"`` so the gauge
  can't stick at 1 for a host that no longer exists.
* **Per-host attribution** — last step, median step time, collective/input
  wait, and the agent's host-hardware sample (``kind="host"`` points: cpu,
  memory, network — runner/src/executor.cpp) per host, surfaced through
  ``/runs/get_metrics`` (``hosts`` + ``skew``), ``dstack-tpu metrics``'s
  per-host table, ``dstack-tpu top``, and the ``/metrics`` families
  ``dstack_tpu_run_step_skew_ratio``, ``dstack_tpu_run_straggler{host}``,
  ``dstack_tpu_host_{cpu_percent,mem_bytes,collective_wait_seconds}``.

The goodput ledger and the ``run_step_seconds`` histogram stay lead-only
(services/metrics.py) — joining hosts here must not multiply productive time.

Detector state (consecutive-window counters, flagged set) is in-process and
per-run; a server restart resets hysteresis counters, which at worst delays a
re-flag by ``M`` windows. The exported gauge snapshot is rebuilt whole on
every pass, so runs that finish (or hosts that depart) drop out of
``/metrics`` without a separate sweep.
"""

from __future__ import annotations

import dataclasses
import datetime
import json
import logging
import statistics
from typing import Dict, List, Optional, Set, Tuple

from dstack_tpu.server import settings
from dstack_tpu.server.db import Database
from dstack_tpu.utils.common import now_utc, to_iso

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# The pure straggler rule (unit-tested as a function of numbers, no DB)


@dataclasses.dataclass
class HostStats:
    """One host's view of the trailing window (input to the rule)."""

    host: str
    median_step_s: Optional[float] = None  # None: no step points this window
    last_step: Optional[int] = None
    steps: int = 0
    collective_wait_s: Optional[float] = None  # window mean
    input_wait_s: Optional[float] = None  # window mean
    mfu: Optional[float] = None  # latest
    cpu_percent: Optional[float] = None  # latest agent host sample
    mem_bytes: Optional[float] = None
    last_ts: Optional[str] = None


@dataclasses.dataclass
class RunState:
    """Per-run hysteresis state carried across collection passes. ``flagged``
    is seeded from the run's straggler run_events on first sight, so a server
    restart (or a lease moving the run to another replica) resumes with the
    durable flag set instead of re-emitting ``straggler_detected`` for a host
    the timeline already flagged."""

    over: Dict[str, int] = dataclasses.field(default_factory=dict)
    under: Dict[str, int] = dataclasses.field(default_factory=dict)
    flagged: Set[str] = dataclasses.field(default_factory=set)
    # High-water marks for the exported telemetry-loss counters: the summed
    # per-job emitter counters can DECREASE (a job finishes, a resubmitted
    # emitter restarts at 0), and a Prometheus counter must not — rate()
    # would read the dip as a reset and double-count history.
    dropped_hwm: int = 0
    write_errors_hwm: int = 0


@dataclasses.dataclass
class Verdict:
    """One pass's decisions for a run."""

    skew_ratio: Optional[float] = None
    gang_median_s: Optional[float] = None
    slowest_host: Optional[str] = None
    detected: List[Tuple[str, str]] = dataclasses.field(default_factory=list)
    cleared: List[Tuple[str, str]] = dataclasses.field(default_factory=list)
    ratios: Dict[str, float] = dataclasses.field(default_factory=dict)


def compute_skew(medians: Dict[str, float]) -> Optional[Dict]:
    """The ONE skew definition (rule, gauge, and API all read this): gang
    median = median of per-host window medians; ratio = slowest / gang
    median. None when fewer than 2 hosts reported or the median is
    degenerate."""
    if len(medians) < 2:
        return None
    gang_median = statistics.median(medians.values())
    if gang_median <= 0:
        return None
    slowest = max(medians, key=medians.get)
    return {
        "ratio": medians[slowest] / gang_median,
        "gang_median_s": gang_median,
        "slowest_host": slowest,
        "ratios": {host: m / gang_median for host, m in medians.items()},
    }


def evaluate_stragglers(
    hosts: List[HostStats],
    state: RunState,
    k: Optional[float] = None,
    clear_k: Optional[float] = None,
    windows: Optional[int] = None,
) -> Verdict:
    """Advance the detector one window. Mutates ``state``; returns the pass's
    skew + detect/clear transitions (each with a human message).

    Robustness properties the tests pin down:

    * single-host runs (or windows where <2 hosts reported steps) never flag
      and decay nothing — a transient collection gap must not clear a real
      straggler, so counters simply freeze until data returns;
    * hysteresis: flagging needs ``windows`` CONSECUTIVE over-threshold
      windows, clearing needs ``windows`` consecutive under-``clear_k``
      windows, and one healthy window resets the over-counter (and vice
      versa) — a host flapping across ``k`` emits nothing;
    * gang shrink: hosts absent from ``hosts`` entirely (elastic restart
      dropped them) are forgotten; if flagged, they clear with
      reason ``departed``.
    """
    k = k if k is not None else settings.STRAGGLER_K
    clear_k = clear_k if clear_k is not None else settings.STRAGGLER_CLEAR_K
    windows = windows if windows is not None else settings.STRAGGLER_WINDOWS
    verdict = Verdict()

    present = {h.host for h in hosts}
    # Gang shrink / host departure: forget state, clear stuck flags.
    for host in list(state.flagged):
        if host not in present:
            state.flagged.discard(host)
            verdict.cleared.append(
                (host, f"host {host} left the gang (elastic restart or agent loss)")
            )
    for d in (state.over, state.under):
        for host in list(d):
            if host not in present:
                del d[host]

    reporting = [h for h in hosts if h.median_step_s and h.median_step_s > 0]
    medians = {h.host: h.median_step_s for h in reporting}
    skew = compute_skew(medians)
    if skew is None:
        return verdict  # nothing to skew against; counters freeze
    gang_median = skew["gang_median_s"]
    verdict.gang_median_s = gang_median
    verdict.skew_ratio = skew["ratio"]
    verdict.slowest_host = skew["slowest_host"]
    verdict.ratios = skew["ratios"]

    for host, ratio in verdict.ratios.items():
        if host in state.flagged:
            if ratio < clear_k:
                state.under[host] = state.under.get(host, 0) + 1
                if state.under[host] >= windows:
                    state.flagged.discard(host)
                    state.under[host] = 0
                    verdict.cleared.append(
                        (
                            host,
                            f"host {host} back to {ratio:.2f}x gang median"
                            f" ({medians[host]:.3f}s vs {gang_median:.3f}s)"
                            f" for {windows} windows",
                        )
                    )
            else:
                state.under[host] = 0
        else:
            if ratio > k:
                state.over[host] = state.over.get(host, 0) + 1
                if state.over[host] >= windows:
                    state.flagged.add(host)
                    state.over[host] = 0
                    state.under[host] = 0
                    verdict.detected.append(
                        (
                            host,
                            f"host {host} median step {medians[host]:.3f}s is"
                            f" {ratio:.2f}x the gang median {gang_median:.3f}s"
                            f" for {windows} consecutive windows",
                        )
                    )
            else:
                state.over[host] = 0
    return verdict


# ---------------------------------------------------------------------------
# Window summarization (points -> HostStats)


def summarize_host(host: str, points: List[dict]) -> HostStats:
    """Fold one host's window of step + host-sample points into HostStats."""
    stats = HostStats(host=host)
    step_times: List[float] = []
    coll: List[float] = []
    inp: List[float] = []
    for p in points:
        kind = p.get("kind")
        if kind == "step":
            try:
                st = float(p.get("step_time_s") or 0.0)
            except (TypeError, ValueError):
                continue
            if st > 0:
                step_times.append(st)
            num = p.get("step")
            if isinstance(num, (int, float)):
                stats.last_step = int(num)
            for field, acc in (("collective_wait_s", coll), ("input_wait_s", inp)):
                v = p.get(field)
                if isinstance(v, (int, float)):
                    acc.append(float(v))
            if p.get("mfu") is not None:
                try:
                    stats.mfu = float(p["mfu"])
                except (TypeError, ValueError):
                    pass
            stats.last_ts = p.get("ts") or stats.last_ts
        elif kind == "host":
            for field, attr in (
                ("cpu_percent", "cpu_percent"),
                ("mem_used_bytes", "mem_bytes"),
            ):
                v = p.get(field)
                if isinstance(v, (int, float)):
                    setattr(stats, attr, float(v))
    if step_times:
        stats.median_step_s = statistics.median(step_times)
        stats.steps = len(step_times)
    if coll:
        stats.collective_wait_s = sum(coll) / len(coll)
    if inp:
        stats.input_wait_s = sum(inp) / len(inp)
    return stats


def _host_labels(by_job: Dict[str, Tuple[dict, List[dict]]]) -> Dict[str, str]:
    """Per-job host labels: the emitter-stamped hostname when present, else
    the job lineage. Hostnames that COLLIDE across jobs (local/test gangs run
    several "hosts" on one box) get the lineage appended, so every label is
    unique — a straggler flag must name exactly one stream."""
    raw: Dict[str, str] = {}
    for job_id, (job_row, points) in by_job.items():
        label = None
        for p in reversed(points):
            h = p.get("host")
            if isinstance(h, str) and h:
                label = h
                break
        raw[job_id] = label or f"job{job_row['replica_num']}-{job_row['job_num']}"
    counts: Dict[str, int] = {}
    for label in raw.values():
        counts[label] = counts.get(label, 0) + 1
    labels: Dict[str, str] = {}
    for job_id, (job_row, _points) in by_job.items():
        label = raw[job_id]
        if counts[label] > 1:
            label = f"{label}/{job_row['replica_num']}-{job_row['job_num']}"
        labels[job_id] = label
    return labels


async def _window_points_by_run(
    db: Database, run_ids: List[str], window_s: float
) -> Dict[str, Dict[str, Tuple[dict, List[dict]]]]:
    """The trailing window of step/host points for every RUNNING job of the
    given runs, plus each job's emitter counters (unwindowed — emitter points
    only appear when the counters advance). ONE windowed query for the whole
    batch (the enforce_utilization_policies N+1 lesson from PR 11 — a pass
    over hundreds of live runs must not issue hundreds of queries). Returns
    {run_id: {job_id: (job_row_like, points)}}."""
    if not run_ids:
        return {}
    window_start = to_iso(now_utc() - datetime.timedelta(seconds=window_s))
    # fetch_in binds `params` before the {in} values: the ? placeholder must
    # precede the IN clause in the SQL.
    rows = await db.fetch_in(
        "SELECT w.timestamp, w.kind, w.data, j.run_id, j.id AS job_id,"
        "       j.job_num, j.replica_num"
        " FROM workload_metrics_points w JOIN jobs j ON j.id = w.job_id"
        " WHERE ((w.kind IN ('step', 'host') AND w.timestamp >= ?)"
        "        OR w.kind = 'emitter')"
        "   AND j.status = 'running' AND j.run_id IN ({in})"
        " ORDER BY w.timestamp ASC",
        run_ids,
        (window_start,),
    )
    by_run: Dict[str, Dict[str, Tuple[dict, List[dict]]]] = {}
    for r in rows:
        try:
            point = json.loads(r["data"])
        except ValueError:
            continue
        point["kind"] = r["kind"]
        entry = by_run.setdefault(r["run_id"], {}).setdefault(
            r["job_id"],
            ({"job_num": r["job_num"], "replica_num": r["replica_num"]}, []),
        )
        entry[1].append(point)
    return by_run


async def _run_window_points(
    db: Database, run_id: str, window_s: float
) -> Dict[str, Tuple[dict, List[dict]]]:
    """Single-run window (the on-demand API path)."""
    by_run = await _window_points_by_run(db, [run_id], window_s)
    return by_run.get(run_id, {})


async def _flagged_from_events(db: Database, run_id: str) -> Set[str]:
    """The durable straggler flag set: fold the run's straggler_detected /
    straggler_cleared timeline (reason = host). This is what seeds a fresh
    RunState — in-process hysteresis counters die with the process, but a
    flag the timeline raised must not be re-raised after a restart or a
    lease handoff."""
    rows = await db.fetchall(
        "SELECT new_status, reason FROM run_events WHERE run_id = ?"
        " AND new_status IN ('straggler_detected', 'straggler_cleared')"
        " ORDER BY seq ASC",
        (run_id,),
    )
    flagged: Set[str] = set()
    for r in rows:
        if not r["reason"]:
            continue
        if r["new_status"] == "straggler_detected":
            flagged.add(r["reason"])
        else:
            flagged.discard(r["reason"])
    return flagged


def _emitter_counters(points: List[dict]) -> Tuple[int, int]:
    """(dropped, write_errors) — the emitter reports cumulative counters, so
    the latest (max) value per job is the truth."""
    dropped = write_errors = 0
    for p in points:
        if p.get("kind") != "emitter":
            continue
        try:
            dropped = max(dropped, int(p.get("dropped") or 0))
            write_errors = max(write_errors, int(p.get("write_errors") or 0))
        except (TypeError, ValueError):
            continue
    return dropped, write_errors


# ---------------------------------------------------------------------------
# The collection-pass check + exported gauge snapshot

# run_id -> RunState, pruned to the live-run set every pass.
_states: Dict[str, RunState] = {}
# Rebuilt whole each pass: [{run, skew, hosts: {host: {...}}, dropped, ...}].
_snapshot: List[dict] = []


def reset() -> None:
    """Test hook: forget all detector state and gauges."""
    _states.clear()
    _snapshot.clear()


def forget_run(run_id: str) -> None:
    """Run deleted: drop its detector state (the gauge snapshot self-heals
    on the next pass)."""
    _states.pop(run_id, None)


def snapshot() -> List[dict]:
    """The latest pass's per-run gang view (rendered by prometheus.py)."""
    return list(_snapshot)


def state_for(run_id: str) -> RunState:
    return _states.setdefault(run_id, RunState())


async def check_gang_health(db: Database) -> int:
    """One pass over every live run THIS replica owns: summarize per-host
    windows, advance the straggler rule, persist detect/clear run_events,
    rebuild the gauge snapshot. Returns the number of runs examined. Runs
    with a single host still land in the snapshot (per-host CLI table +
    emitter drop counters work for solo runs) — they just can never flag.

    Lease scoping: with run leases enabled (PR 14), only the replica whose
    scheduler owns a run advances its detector — N replicas each running the
    metrics pass must not emit N copies of every straggler event or race
    their hysteresis counters. Unleased runs (leases disabled, or a gap
    between lease sweeps) are processed by whoever gets there; the durable
    flag seed below keeps a handoff from re-raising existing flags."""
    from dstack_tpu.server.services import leases as leases_service

    runs = await db.fetchall(
        "SELECT r.id, r.run_name, r.status FROM runs r"
        " WHERE r.deleted = 0 AND r.id IN"
        " (SELECT DISTINCT run_id FROM jobs WHERE status = 'running')"
    )
    if settings.RUN_LEASES_ENABLED and runs:
        lease_owners = await leases_service.owners(db, [r["id"] for r in runs])
        me = leases_service.replica_id()
        runs = [
            r for r in runs if lease_owners.get(r["id"], me) == me
        ]
    fresh_snapshot: List[dict] = []
    live_ids = set()
    windows = await _window_points_by_run(
        db, [r["id"] for r in runs], settings.GANG_WINDOW_SECONDS
    )
    for run in runs:
        live_ids.add(run["id"])
        by_job = windows.get(run["id"])
        if not by_job:
            continue
        labels = _host_labels(by_job)
        host_stats: List[HostStats] = []
        dropped_total = write_errors_total = 0
        for job_id, (job_row, points) in by_job.items():
            host_stats.append(summarize_host(labels[job_id], points))
            d, w = _emitter_counters(points)
            dropped_total += d
            write_errors_total += w
        if run["id"] not in _states:
            # First sight of this run in THIS process: seed the flag set
            # from the durable timeline (restart / lease-handoff continuity).
            seeded = state_for(run["id"])
            seeded.flagged = await _flagged_from_events(db, run["id"])
        state = state_for(run["id"])
        # Monotonic export: the summed per-job counters can dip when a job
        # finishes or a fresh emitter restarts at zero.
        state.dropped_hwm = max(state.dropped_hwm, dropped_total)
        state.write_errors_hwm = max(state.write_errors_hwm, write_errors_total)
        dropped_total = state.dropped_hwm
        write_errors_total = state.write_errors_hwm
        verdict = evaluate_stragglers(host_stats, state)
        for host, message in verdict.detected:
            await _record_straggler_event(
                db, run["id"], "straggler_detected", run["status"], host, message
            )
            logger.warning(
                "run %s: straggler detected: %s", run["run_name"], message
            )
        for host, message in verdict.cleared:
            await _record_straggler_event(
                db, run["id"], "straggler_cleared", run["status"], host, message
            )
            logger.info("run %s: straggler cleared: %s", run["run_name"], message)
        fresh_snapshot.append(
            {
                "run": run["run_name"],
                "run_id": run["id"],
                "skew_ratio": verdict.skew_ratio,
                "gang_median_s": verdict.gang_median_s,
                "slowest_host": verdict.slowest_host,
                "flagged": sorted(state.flagged),
                "hosts": [dataclasses.asdict(h) for h in host_stats],
                "dropped": dropped_total,
                "write_errors": write_errors_total,
            }
        )
    for run_id in list(_states):
        if run_id not in live_ids:
            del _states[run_id]
    _snapshot[:] = fresh_snapshot
    return len(runs)


async def _record_straggler_event(
    db: Database, run_id: str, event: str, run_status: str, host: str, message: str
) -> None:
    """One straggler_detected/straggler_cleared run_event; ``reason`` carries
    the offending host so `dstack-tpu events` (and greps) name it directly."""
    from dstack_tpu.server.services import events as events_service

    def _tx(conn) -> None:
        events_service.record_event_tx(
            conn,
            run_id,
            event,
            old_status=run_status,
            actor="gang_health",
            reason=host,
            message=message,
        )

    await db.run(_tx)


# ---------------------------------------------------------------------------
# API summary (the `hosts` + `skew` blocks of /runs/get_metrics)


async def get_run_gang_metrics(db: Database, run_id: str) -> Dict:
    """Per-host table + skew for one run, on demand (the API/CLI path; the
    collection-pass snapshot serves /metrics so a scrape costs no query).
    Straggler flags come from the pass-maintained state when this replica
    owns the run, and from the durable run_events timeline otherwise — a
    lease-sharded deployment must answer the same no matter which replica
    the proxy routed the API call to."""
    by_job = await _run_window_points(db, run_id, settings.GANG_WINDOW_SECONDS)
    state = _states.get(run_id)
    if state is not None:
        flagged = state.flagged
    else:
        flagged = await _flagged_from_events(db, run_id)
    labels = _host_labels(by_job)
    hosts: List[Dict] = []
    medians: Dict[str, float] = {}
    for job_id, (job_row, points) in sorted(
        by_job.items(), key=lambda e: (e[1][0]["replica_num"], e[1][0]["job_num"])
    ):
        label = labels[job_id]
        stats = summarize_host(label, points)
        row = dataclasses.asdict(stats)
        row["replica_num"] = job_row["replica_num"]
        row["job_num"] = job_row["job_num"]
        row["straggler"] = label in flagged
        hosts.append(row)
        if stats.median_step_s:
            medians[label] = stats.median_step_s
    skew = compute_skew(medians)
    if skew is not None:
        skew = {
            "ratio": round(skew["ratio"], 4),
            "gang_median_s": round(skew["gang_median_s"], 6),
            "slowest_host": skew["slowest_host"],
        }
    return {"hosts": hosts, "skew": skew, "stragglers": sorted(flagged)}
