"""Cache-aware replica routing for the service proxy (the fleet-wide prefix
cache).

Serving replicas each hold a private KV prefix cache (workloads/serve.py
``PrefixCache``); blind round-robin sprays requests sharing a prompt prefix
across the fleet, so every replica pays the prefill for the same prefix and
N caches hold N copies. This module makes the proxy's replica pick
cache-aware:

- **Prefix key**: hash the first ``prefix_block`` prompt tokens (or the same
  count of raw prompt bytes pre-tokenization — the engine's tokenizer is
  byte-level, so the spaces agree) out of the request body. Requests without
  an extractable key — non-engine services, non-JSON bodies — fall back to
  round-robin.
- **Rendezvous (HRW) ring**: every (key, endpoint) pair is scored with a
  keyed blake2b; the highest-scoring ready endpoint owns the bucket. HRW
  gives minimal disruption by construction — a joining replica steals ~1/N
  of the buckets, a leaving one redistributes only its own — with no token
  ring to rebalance and no state to replicate.
- **Sticky assignments**: each observed bucket's winner is memoized (bounded
  LRU). Membership changes re-pin exactly the buckets whose recomputed
  winner changed, which is what makes the ~1/N property observable — and
  what the probe-flip hygiene hook (``drop_endpoint``) clears when a replica
  goes not-ready, together with its ring slot.
- **Load spill**: when the preferred replica's last-reported engine queue
  depth (the ``X-Dstack-Queue-Depth`` header the proxy already records)
  exceeds ``DSTACK_TPU_PROXY_SPILL_QUEUE_DEPTH``, the request spills to the
  least-loaded ready replica — a hot prefix must not hotspot one replica
  into timeout while its peers idle.

Everything here is in-process memory keyed by run id — the proxy's
zero-DB-queries-per-request invariant holds; a server restart merely starts
with a cold ring (first requests re-pin buckets via HRW, deterministically).
Decisions are counted per (run, policy, outcome) and rendered on /metrics as
``dstack_tpu_proxy_routing_decisions_total``.
"""

from __future__ import annotations

import collections
import hashlib
import json
import time
from typing import Dict, List, Optional, Tuple

from dstack_tpu.server import settings

Endpoint = Tuple[str, int]

POLICIES = ("prefix", "round_robin")
# preferred = the prefix-hash owner took the request; spilled = owner was
# over the queue-depth bound, least-loaded took it; fallback = round-robin
# (configured policy, keyless request, or a retry past the owner).
OUTCOMES = ("preferred", "spilled", "fallback")


def active_policy() -> str:
    """The configured routing policy, read per call so tests/bench can flip
    ``settings.PROXY_ROUTING_POLICY`` at runtime."""
    policy = settings.PROXY_ROUTING_POLICY
    return policy if policy in POLICIES else "prefix"


def prefix_key(body: Optional[bytes],
               prefix_block: Optional[int] = None) -> Optional[bytes]:
    """The routable prefix of a /generate-shaped JSON body, or None when the
    request has no extractable prompt (route it round-robin).

    Token lists hash the first ``prefix_block`` ids — the same space the
    engine's PrefixCache blocks live in, so equal hash keys mean shareable KV.
    Raw text prompts hash the same count of leading bytes (pre-tokenization;
    the serve tokenizer is byte-level so the prefixes coincide)."""
    if not body:
        return None
    try:
        payload = json.loads(body)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(payload, dict):
        return None
    n = prefix_block if prefix_block is not None else settings.PROXY_ROUTING_PREFIX_BLOCK
    tokens = payload.get("prompt_tokens")
    if isinstance(tokens, list) and tokens and all(
        isinstance(t, int) and not isinstance(t, bool) for t in tokens
    ):
        return ("t:" + ",".join(str(t) for t in tokens[:n])).encode()
    prompt = payload.get("prompt")
    if isinstance(prompt, str) and prompt:
        return b"s:" + prompt.encode("utf-8")[:n]
    return None


def _score(key: bytes, endpoint: Endpoint) -> int:
    h = hashlib.blake2b(
        key + b"|" + f"{endpoint[0]}:{endpoint[1]}".encode(), digest_size=8
    )
    return int.from_bytes(h.digest(), "big")


def rendezvous(key: bytes, endpoints: List[Endpoint]) -> Endpoint:
    """Highest-random-weight owner of ``key`` among ``endpoints``."""
    return max(endpoints, key=lambda ep: _score(key, ep))


class PrefixRing:
    """Per-run rendezvous ring + sticky bucket assignments (bounded LRU)."""

    def __init__(self, max_assignments: Optional[int] = None) -> None:
        self.endpoints: List[Endpoint] = []
        self.assignments: "collections.OrderedDict[bytes, Endpoint]" = (
            collections.OrderedDict()
        )
        self.max_assignments = (
            max_assignments
            if max_assignments is not None
            else settings.PROXY_ROUTING_STICKY_MAX
        )
        self.moved = 0  # sticky buckets re-pinned by membership changes

    def set_endpoints(self, endpoints: List[Endpoint]) -> None:
        """Sync ring membership; re-pins only the sticky buckets whose HRW
        winner changed (~1/N on a join, exactly the dead endpoint's share on
        a leave)."""
        eps = sorted(set(endpoints))
        if eps == self.endpoints:
            return
        self.endpoints = eps
        for key in list(self.assignments):
            new = rendezvous(key, eps) if eps else None
            if new != self.assignments[key]:
                self.moved += 1
                if new is None:
                    del self.assignments[key]
                else:
                    self.assignments[key] = new

    def drop_endpoint(self, endpoint: Endpoint) -> None:
        if endpoint in self.endpoints:
            self.set_endpoints([e for e in self.endpoints if e != endpoint])

    def pick(self, key: bytes) -> Optional[Endpoint]:
        if not self.endpoints:
            return None
        ep = self.assignments.get(key)
        if ep is None:
            ep = rendezvous(key, self.endpoints)
        self.assignments[key] = ep
        self.assignments.move_to_end(key)
        while len(self.assignments) > self.max_assignments:
            self.assignments.popitem(last=False)
        return ep


class RoutingState:
    """All mutable routing state, per process (mirrors proxy.stats): rings,
    per-endpoint queue-depth samples, and the decision counters /metrics
    renders. Single-threaded event-loop access — no locks."""

    def __init__(self) -> None:
        self._rings: Dict[str, PrefixRing] = {}
        # (run_id, endpoint) -> (ts, last reported engine queue depth).
        self._depths: Dict[Tuple[str, Endpoint], Tuple[float, float]] = {}
        # (run_name, policy, outcome) -> count. Keyed by run NAME because
        # that is the /metrics label (run ids are internal).
        self._decisions: Dict[Tuple[str, str, str], int] = {}

    def ring(self, run_id: str) -> PrefixRing:
        ring = self._rings.get(run_id)
        if ring is None:
            ring = self._rings[run_id] = PrefixRing()
        return ring

    # -- queue depth (per endpoint — the spill signal) ---------------------

    def record_queue_depth(
        self, run_id: str, endpoint: Endpoint, depth: float
    ) -> None:
        self._depths[(run_id, endpoint)] = (time.monotonic(), float(depth))

    def endpoint_depth(
        self, run_id: str, endpoint: Endpoint, window: float = 30.0
    ) -> Optional[float]:
        sample = self._depths.get((run_id, endpoint))
        if sample is None or time.monotonic() - sample[0] > window:
            return None
        return sample[1]

    def least_loaded(
        self, run_id: str, endpoints: List[Endpoint]
    ) -> Optional[Endpoint]:
        """Endpoint with the lowest known queue depth; an endpoint that never
        reported (fresh replica) counts as empty — spill should discover it."""
        if not endpoints:
            return None
        return min(
            endpoints, key=lambda ep: self.endpoint_depth(run_id, ep) or 0.0
        )

    # -- decision counters --------------------------------------------------

    def record_decision(self, run_name: str, policy: str, outcome: str) -> None:
        key = (run_name, policy, outcome)
        self._decisions[key] = self._decisions.get(key, 0) + 1

    def decisions(self) -> Dict[Tuple[str, str, str], int]:
        return dict(self._decisions)

    def decisions_for(self, run_name: str) -> Dict[Tuple[str, str], int]:
        return {
            (policy, outcome): n
            for (run, policy, outcome), n in self._decisions.items()
            if run == run_name
        }

    # -- hygiene ------------------------------------------------------------

    def drop_endpoint(self, run_id: str, endpoint: Endpoint) -> None:
        """Probe flipped a replica to not-ready: drop it from the ring AND
        its sticky assignments now — waiting out the route TTL would keep
        hashing hot prefixes at a dead replica."""
        ring = self._rings.get(run_id)
        if ring is not None:
            ring.drop_endpoint(endpoint)
        self._depths.pop((run_id, endpoint), None)

    def invalidate_run(self, run_id: str) -> None:
        """Membership changed but the endpoint is unresolvable (tunnel down):
        reset the whole ring; the next request re-pins from live endpoints."""
        self._rings.pop(run_id, None)
        for key in [k for k in self._depths if k[0] == run_id]:
            del self._depths[key]

    def forget_run(self, run_id: str, run_name: Optional[str] = None) -> None:
        self.invalidate_run(run_id)
        if run_name:
            for key in [k for k in self._decisions if k[0] == run_name]:
                del self._decisions[key]

    def reset(self) -> None:
        self._rings.clear()
        self._depths.clear()
        self._decisions.clear()


state = RoutingState()


def choose(
    run_id: str,
    run_name: str,
    pool: List[Endpoint],
    all_endpoints: List[Endpoint],
    key: Optional[bytes],
    cursor: int,
    retrying: bool = False,
) -> Optional[Endpoint]:
    """Pick one endpoint from ``pool`` (the proxy's untried,
    breaker-preferred candidates) and record the decision.

    ``all_endpoints`` is the run's full ready set — ring membership follows
    it, not the shrinking retry pool, so one failed forward doesn't re-pin
    every sticky bucket. Round-robin (``cursor``) is both the configured
    alternative policy and the fallback for keyless requests, retries, and
    owners that dropped out of the pool."""
    if not pool:
        return None
    policy = active_policy()
    if policy == "round_robin" or key is None:
        state.record_decision(run_name, policy, "fallback")
        return pool[cursor % len(pool)]

    ring = state.ring(run_id)
    ring.set_endpoints(all_endpoints)
    preferred = ring.pick(key)
    if preferred is None or retrying or preferred not in pool:
        state.record_decision(run_name, policy, "fallback")
        return pool[cursor % len(pool)]
    depth = state.endpoint_depth(run_id, preferred)
    if depth is not None and depth > settings.PROXY_SPILL_QUEUE_DEPTH:
        spill = state.least_loaded(run_id, pool)
        if spill is not None and spill != preferred:
            state.record_decision(run_name, policy, "spilled")
            return spill
    state.record_decision(run_name, policy, "preferred")
    return preferred


# Module-level conveniences mirroring proxy.stats' style.

def drop_endpoint(run_id: str, endpoint: Endpoint) -> None:
    state.drop_endpoint(run_id, endpoint)


def invalidate_run(run_id: str) -> None:
    state.invalidate_run(run_id)


def forget_run(run_id: str, run_name: Optional[str] = None) -> None:
    state.forget_run(run_id, run_name)
