"""Job metrics: collection from runner agents, query API, TTL sweep.

Parity: reference server/services/metrics.py (get_job_metrics derives
cpu_usage_percent from consecutive cpu_usage_micro samples) +
background/tasks/process_metrics.py (collect/delete loops). TPU re-design: the
``tpu`` column stores the agent's TPU sample (duty-cycle %, HBM bytes — scraped
from the runtime metrics endpoint by the C++ agent, runner/src/executor.cpp) in
place of the reference's per-GPU DCGM rows.
"""

from __future__ import annotations

import asyncio
import datetime
import json
import logging
from typing import Optional

from dstack_tpu.core.models.metrics import JobMetrics, MetricPoint
from dstack_tpu.server import settings
from dstack_tpu.server.db import Database
from dstack_tpu.server.services.jobs import job_jpd, job_jrd
from dstack_tpu.server.services.runner.client import get_runner_client
from dstack_tpu.utils.common import from_iso, now_utc, to_iso

logger = logging.getLogger(__name__)

MAX_JOBS_PER_PASS = 100
COLLECT_CONCURRENCY = 10


async def collect_job_metrics(db: Database) -> int:
    """One collection pass: sample every running job's agent. Returns #points."""
    rows = await db.fetchall(
        "SELECT * FROM jobs WHERE status = 'running'"
        " ORDER BY last_processed_at ASC LIMIT ?",
        (MAX_JOBS_PER_PASS,),
    )
    if not rows:
        return 0
    sem = asyncio.Semaphore(COLLECT_CONCURRENCY)

    async def _one(row) -> int:
        async with sem:
            try:
                jpd = job_jpd(row)
                if jpd is None or jpd.hostname is None:
                    return 0
                client = get_runner_client(jpd, job_jrd(row))
                sample = await client.metrics()
            except Exception as e:  # a dead tunnel must not kill the whole pass
                logger.debug("metrics: job %s unreachable: %s", row["id"], e)
                return 0
            if not sample:
                return 0
            tpu = sample.get("tpu")
            await db.execute(
                "INSERT INTO job_metrics_points"
                " (job_id, timestamp, cpu_usage_micro, memory_usage_bytes,"
                "  memory_working_set_bytes, tpu)"
                " VALUES (?, ?, ?, ?, ?, ?)",
                (
                    row["id"],
                    sample.get("timestamp") or to_iso(now_utc()),
                    int(sample.get("cpu_usage_micro") or 0),
                    int(sample.get("memory_usage_bytes") or 0),
                    int(sample.get("memory_working_set_bytes") or sample.get("memory_usage_bytes") or 0),
                    json.dumps(tpu) if tpu else None,
                ),
            )
            return 1

    results = await asyncio.gather(*(_one(r) for r in rows))
    return sum(results)


async def enforce_utilization_policies(db: Database) -> None:
    """Terminate runs whose TPU duty-cycle stayed below the policy's threshold for
    the whole window (reference process_running_jobs.py:764 _check_gpu_utilization —
    GPU util there, TPU duty-cycle here). A gang dies whole, so enforcement is
    run-level: any breaching job marks the run terminating; process_runs tears it
    down. Decided from job_metrics_points so it composes with the collection loop."""
    from dstack_tpu.core.models.runs import RunTerminationReason
    from dstack_tpu.server.services.jobs import job_spec as load_job_spec

    rows = await db.fetchall(
        "SELECT j.* FROM jobs j JOIN runs r ON r.id = j.run_id"
        " WHERE j.status = 'running' AND r.status NOT IN"
        " ('terminating', 'terminated', 'failed', 'done')"
    )
    breached_runs = {}
    for row in rows:
        spec = load_job_spec(row)
        policy = spec.utilization_policy
        if policy is None or row["run_id"] in breached_runs:
            continue
        window_start = to_iso(
            now_utc() - datetime.timedelta(seconds=policy.time_window)
        )
        points = await db.fetchall(
            "SELECT * FROM job_metrics_points WHERE job_id = ? AND timestamp >= ?"
            " ORDER BY timestamp",
            (row["id"], window_start),
        )
        if not points:
            continue
        # The whole window must be covered by samples AND below threshold; a job
        # that just started is not killable yet.
        first_ts = from_iso(points[0]["timestamp"])
        if (now_utc() - first_ts).total_seconds() < policy.time_window * 0.9:
            continue
        duties = []
        for p in points:
            tpu = json.loads(p["tpu"]) if p["tpu"] else {}
            duty = tpu.get("duty_cycle_percent")
            if duty is None:
                duties = []  # no TPU signal -> never kill on missing data
                break
            duties.append(duty)
        if duties and max(duties) < policy.min_tpu_utilization:
            breached_runs[row["run_id"]] = (max(duties), policy)
    for run_id, (duty, policy) in breached_runs.items():
        logger.info(
            "run %s: TPU duty %.1f%% < %s%% for %ss; terminating per utilization policy",
            run_id, duty, policy.min_tpu_utilization, policy.time_window,
        )
        await db.execute(
            "UPDATE runs SET status = 'terminating', termination_reason = ?"
            " WHERE id = ? AND status NOT IN ('terminated', 'failed', 'done')",
            (RunTerminationReason.TERMINATED_DUE_TO_UTILIZATION_POLICY.value, run_id),
        )


async def sweep_metrics(db: Database) -> None:
    """TTL delete (reference keeps separate running/finished TTLs; one TTL here —
    finished jobs' points age out the same way)."""
    cutoff = to_iso(now_utc() - datetime.timedelta(seconds=settings.METRICS_TTL_SECONDS))
    await db.execute("DELETE FROM job_metrics_points WHERE timestamp < ?", (cutoff,))


async def get_job_metrics(
    db: Database,
    job_id: str,
    limit: int = 100,
    after: Optional[str] = None,
    before: Optional[str] = None,
) -> JobMetrics:
    """Latest-first points. cpu_usage_percent needs consecutive samples, so one
    extra row is fetched beyond `limit` and consumed by the delta computation
    (reference services/metrics.py:35-50)."""
    q = "SELECT * FROM job_metrics_points WHERE job_id = ?"
    args: list = [job_id]
    if after:
        q += " AND timestamp >= ?"
        args.append(after)
    if before:
        q += " AND timestamp < ?"
        args.append(before)
    q += " ORDER BY timestamp DESC LIMIT ?"
    args.append(min(limit, 1000) + 1)
    rows = await db.fetchall(q, tuple(args))

    points = []
    for i in range(len(rows) - 1):
        cur, prev = rows[i], rows[i + 1]
        t_cur, t_prev = from_iso(cur["timestamp"]), from_iso(prev["timestamp"])
        window_micro = max(1, int((t_cur - t_prev).total_seconds() * 1_000_000))
        cpu_pct = (
            max(0, cur["cpu_usage_micro"] - prev["cpu_usage_micro"]) / window_micro * 100.0
        )
        tpu = json.loads(cur["tpu"]) if cur["tpu"] else {}
        points.append(
            MetricPoint(
                timestamp=t_cur,
                cpu_usage_percent=round(cpu_pct, 2),
                memory_usage_bytes=cur["memory_usage_bytes"],
                memory_working_set_bytes=cur["memory_working_set_bytes"],
                tpu_duty_cycle_percent=tpu.get("duty_cycle_percent"),
                tpu_hbm_usage_bytes=tpu.get("hbm_usage_bytes"),
                tpu_tensorcore_util_percent=tpu.get("tensorcore_util_percent"),
            )
        )
    return JobMetrics(points=points[:limit])
