"""Job + workload metrics: collection from runner agents, goodput accounting,
query API, on-demand profiler fan-out, TTL sweep.

Parity: reference server/services/metrics.py (get_job_metrics derives
cpu_usage_percent from consecutive cpu_usage_micro samples) +
background/tasks/process_metrics.py (collect/delete loops). TPU re-design: the
``tpu`` column stores the agent's TPU sample (duty-cycle %, HBM bytes — scraped
from the runtime metrics endpoint by the C++ agent, runner/src/executor.cpp) in
place of the reference's per-GPU DCGM rows.

Beyond the reference: the agent's sample also carries ``workload`` — telemetry
points the job's own emitter (workloads/telemetry.py) appended to a sidecar
file the agent tails. Those land in ``workload_metrics_points`` and power the
run-level surfaces: per-step throughput/MFU/loss, serving-engine gauges, and
the **goodput ledger** — productive step time over wall clock, with the
non-productive remainder attributed to compile, input wait, and restarts
(the headline metric for ROADMAP item 3's preemption work).
"""

from __future__ import annotations

import asyncio
import datetime
import json
import logging
from typing import Dict, List, Optional

from dstack_tpu.core import tracing
from dstack_tpu.core.errors import ResourceNotExistsError, ServerClientError
from dstack_tpu.core.models.metrics import JobMetrics, MetricPoint
from dstack_tpu.server import settings
from dstack_tpu.server.db import Database
from dstack_tpu.server.services.jobs import job_jpd, job_jrd
from dstack_tpu.server.services.runner.client import RunnerError, get_runner_client
from dstack_tpu.utils.common import from_iso, now_utc, to_iso

logger = logging.getLogger(__name__)

MAX_JOBS_PER_PASS = 100
COLLECT_CONCURRENCY = 10
# Histogram family fed at ingestion time from workload step points (rendered
# by services/prometheus.py; per-run series dropped on run delete).
STEP_HISTOGRAM = "dstack_tpu_run_step_seconds"


async def collect_job_metrics(db: Database) -> int:
    """One collection pass: sample running jobs' agents. Returns #jobs sampled.

    Rotation: jobs are picked oldest-``metrics_sampled_at`` first and the
    cursor advances for every job PICKED (reachable or not) before sampling.
    Ordering by the scheduler's ``last_processed_at`` — which this loop never
    advanced — meant that with more than MAX_JOBS_PER_PASS running jobs the
    same subset was sampled every pass and the rest starved forever; a
    metrics-owned cursor makes each pass sample the least-recently-sampled
    slice of the fleet."""
    rows = await db.fetchall(
        "SELECT * FROM jobs WHERE status = 'running'"
        " ORDER BY COALESCE(metrics_sampled_at, '') ASC LIMIT ?",
        (MAX_JOBS_PER_PASS,),
    )
    if not rows:
        return 0
    # Advance the cursor up front: an unreachable agent must rotate to the
    # back of the line like everyone else, not wedge its position.
    now_iso = to_iso(now_utc())
    await db.executemany(
        "UPDATE jobs SET metrics_sampled_at = ? WHERE id = ?",
        [(now_iso, r["id"]) for r in rows],
    )
    sem = asyncio.Semaphore(COLLECT_CONCURRENCY)

    async def _one(row) -> int:
        async with sem:
            try:
                jpd = job_jpd(row)
                if jpd is None or jpd.hostname is None:
                    return 0
                client = get_runner_client(jpd, job_jrd(row))
                sample = await client.metrics()
            except Exception as e:  # a dead tunnel must not kill the whole pass
                logger.debug("metrics: job %s unreachable: %s", row["id"], e)
                return 0
            if not sample:
                return 0
            tpu = sample.get("tpu")
            await db.execute(
                "INSERT INTO job_metrics_points"
                " (job_id, timestamp, cpu_usage_micro, memory_usage_bytes,"
                "  memory_working_set_bytes, tpu)"
                " VALUES (?, ?, ?, ?, ?, ?)",
                (
                    row["id"],
                    sample.get("timestamp") or to_iso(now_utc()),
                    int(sample.get("cpu_usage_micro") or 0),
                    int(sample.get("memory_usage_bytes") or 0),
                    int(sample.get("memory_working_set_bytes") or sample.get("memory_usage_bytes") or 0),
                    json.dumps(tpu) if tpu else None,
                ),
            )
            await store_workload_points(db, row, sample.get("workload"))
            return 1

    results = await asyncio.gather(*(_one(r) for r in rows))
    return sum(results)


async def store_workload_points(db: Database, job_row, points) -> int:
    """Persist one agent sample's workload telemetry batch; step points also
    feed the run step-time histogram at write time (the run_events idiom —
    /metrics renders distributions without a query per scrape)."""
    if not points:
        return 0
    now_iso = to_iso(now_utc())
    rows = []
    for p in points:
        if not isinstance(p, dict):
            continue
        kind = p.get("kind")
        if not isinstance(kind, str) or not kind:
            continue
        ts = p.get("ts")
        if not isinstance(ts, str) or not ts:
            ts = now_iso
        rows.append((job_row["id"], ts, kind, json.dumps(p)))
        # Lead lineage only: a gang's N hosts ship N identical step streams,
        # and observing all of them would N-fold the run's histogram counts.
        if kind == "step" and job_row["job_num"] == 0 and job_row["replica_num"] == 0:
            try:
                tracing.observe(
                    STEP_HISTOGRAM,
                    float(p.get("step_time_s") or 0.0),
                    {"run": job_row["run_name"]},
                )
            except (TypeError, ValueError):
                pass
    if rows:
        await db.executemany(
            "INSERT INTO workload_metrics_points (job_id, timestamp, kind, data)"
            " VALUES (?, ?, ?, ?)",
            rows,
        )
    return len(rows)


# ---------------------------------------------------------------------------
# Goodput ledger


def compute_goodput(points: List[dict]) -> Dict[str, Optional[float]]:
    """The goodput ledger over one job-lineage's telemetry points.

    ``ratio`` = productive step time / wall clock, where wall is the span from
    the first to the last point. *Productive* means **net forward progress**:
    a step whose number is not past the furthest step already seen (a restart
    that resumed from an old checkpoint — or from step 0 — re-doing work) is
    ``rework_s``, not productive; this is exactly the wall-clock waste the
    preemption benches measure. The non-productive remainder is attributed:

    * ``compile_s``    — time inside compile_start→compile_end marks (the
      compile_end's own measured ``compile_s`` wins when present, because the
      bracketing marks include the first step's execution).
    * ``input_wait_s`` — the step points' reported time blocked on the input
      pipeline (counted OUT of productive: a step stalled on data is not
      productive hardware time).
    * ``restart_s``    — downtime between the last point of one process and
      the next process's ``run_start``/``restart`` mark (preemption →
      reschedule → re-init shows up exactly here).
    * ``checkpoint_s`` — train-thread stalls inside checkpoint_start→
      checkpoint_end marks (the end mark's measured ``blocked_s`` wins; the
      async storage write deliberately does NOT count — only the time the
      step loop actually stood still).
    * ``rework_s``     — step time spent re-running steps a previous attempt
      had already completed (restart-from-behind-the-frontier).
    * ``other_s``      — whatever remains (eval pauses, emitter gaps).

    Returns ratio=None when there is no wall clock to divide by (fewer than
    two points) or no step points at all (e.g. a serving engine)."""
    zeros = {
        "ratio": None, "wall_s": 0.0, "productive_s": 0.0, "compile_s": 0.0,
        "input_wait_s": 0.0, "restart_s": 0.0, "checkpoint_s": 0.0,
        "rework_s": 0.0, "other_s": 0.0, "steps": 0,
    }
    parsed = []
    for p in points:
        try:
            parsed.append((from_iso(p["ts"]), p))
        except (KeyError, TypeError, ValueError):
            continue
    if not parsed:
        return zeros
    parsed.sort(key=lambda tp: tp[0])
    first_ts, last_ts = parsed[0][0], parsed[-1][0]
    wall = (last_ts - first_ts).total_seconds()

    productive = input_wait = compile_s = restart = checkpoint_s = rework = 0.0
    steps = 0
    frontier: Optional[float] = None  # furthest step number seen so far
    compile_open: Optional[datetime.datetime] = None
    checkpoint_open: Optional[datetime.datetime] = None
    prev_ts: Optional[datetime.datetime] = None
    for t, p in parsed:
        kind = p.get("kind")
        if kind == "step":
            try:
                step_time = float(p.get("step_time_s") or 0.0)
                wait = float(p.get("input_wait_s") or 0.0)
            except (TypeError, ValueError):
                continue
            step_num = p.get("step")
            redone = (
                isinstance(step_num, (int, float))
                and frontier is not None
                and step_num <= frontier
            )
            if redone:
                # Forward progress already reached this step once; re-doing
                # it is wasted hardware time, not goodput.
                rework += step_time
            else:
                productive += step_time
                input_wait += wait
                steps += 1
                if isinstance(step_num, (int, float)):
                    frontier = max(frontier or 0.0, float(step_num))
        elif kind == "mark":
            event = p.get("event")
            if event == "compile_start":
                compile_open = t
            elif event == "compile_end":
                try:
                    measured = float(p.get("compile_s"))
                except (TypeError, ValueError):
                    measured = None
                if measured is not None:
                    compile_s += measured
                elif compile_open is not None:
                    compile_s += (t - compile_open).total_seconds()
                compile_open = None
            elif event == "checkpoint_start":
                checkpoint_open = t
            elif event == "checkpoint_end":
                try:
                    measured = float(p.get("blocked_s"))
                except (TypeError, ValueError):
                    measured = None
                if measured is not None:
                    checkpoint_s += measured
                elif checkpoint_open is not None:
                    checkpoint_s += (t - checkpoint_open).total_seconds()
                checkpoint_open = None
            elif event in ("run_start", "restart") and prev_ts is not None:
                restart += max(0.0, (t - prev_ts).total_seconds())
        prev_ts = t
    if compile_open is not None:  # still compiling at the window's edge
        compile_s += (last_ts - compile_open).total_seconds()
    if checkpoint_open is not None:  # mid-checkpoint at the window's edge
        checkpoint_s += (last_ts - checkpoint_open).total_seconds()

    productive = max(0.0, productive - input_wait)
    attributed = productive + compile_s + input_wait + restart + checkpoint_s + rework
    out = {
        "wall_s": round(wall, 4),
        "productive_s": round(productive, 4),
        "compile_s": round(compile_s, 4),
        "input_wait_s": round(input_wait, 4),
        "restart_s": round(restart, 4),
        "checkpoint_s": round(checkpoint_s, 4),
        "rework_s": round(rework, 4),
        "other_s": round(max(0.0, wall - attributed), 4),
        "steps": steps,
        "ratio": None,
    }
    if wall > 0 and steps > 0:
        out["ratio"] = round(min(1.0, productive / wall), 4)
    return out


async def get_run_workload_metrics(
    db: Database, run_id: str, limit: int = 50
) -> Dict:
    """Run-level workload telemetry: latest step/engine points, recent step
    series, and the goodput ledger. The ledger and step series come from the
    run's LEAD lineage (job_num 0, replica 0, every submission — so restarts
    show up as restart_s) to avoid summing a gang's N identical hosts; the
    engine point is the freshest across all replicas."""
    rows = await db.fetchall(
        "SELECT w.timestamp, w.kind, w.data, j.job_num, j.replica_num"
        " FROM workload_metrics_points w JOIN jobs j ON j.id = w.job_id"
        " WHERE j.run_id = ? ORDER BY w.timestamp ASC",
        (run_id,),
    )
    lead_points: List[dict] = []
    latest_engine: Optional[dict] = None
    latest_profile: Optional[dict] = None
    dropped = 0
    for r in rows:
        try:
            point = json.loads(r["data"])
        except ValueError:
            continue
        kind = r["kind"]
        if kind == "engine":
            latest_engine = point
        if kind == "mark" and str(point.get("event", "")).startswith("profile"):
            latest_profile = point
        if kind == "emitter":
            try:
                dropped = max(dropped, int(point.get("dropped") or 0))
            except (TypeError, ValueError):
                pass
        # The ledger reads step/mark kinds ONLY (matching the /metrics gauge
        # query): the agent appends a kind="host" hardware point to every
        # sample, and letting those into compute_goodput stretches the wall
        # clock and fills restart gaps — a host point right before run_start
        # bills pull/startup as restart_s, and host points DURING a real
        # preemption's downtime erase the restart_s PR 12 measures.
        if (
            r["job_num"] == 0
            and r["replica_num"] == 0
            and kind in ("step", "mark")
        ):
            lead_points.append(point)
    step_points = [p for p in lead_points if p.get("kind") == "step"]
    # Per-host view (ISSUE 15): the lead lineage represents the run for the
    # ledger/series above, but skew and straggler attribution need every
    # host — gang_health joins the trailing window across ALL running jobs.
    from dstack_tpu.server.services import gang_health

    gang = await gang_health.get_run_gang_metrics(db, run_id)
    return {
        "goodput": compute_goodput(lead_points),
        "latest": step_points[-1] if step_points else None,
        "engine": latest_engine,
        "profile": latest_profile,
        "dropped": dropped,
        "points": step_points[-max(0, min(limit, 1000)):],
        "hosts": gang["hosts"],
        "skew": gang["skew"],
        "stragglers": gang["stragglers"],
    }


async def request_profile(
    db: Database, project_row, run_name: str, seconds: float
) -> Dict:
    """`dstack-tpu profile <run>`: fan the capture request out to the run's
    lead running job's agent, which publishes it to the live workload via the
    telemetry control file. Returns the agent's ack (artifact dir + request
    id); completion is observable as a ``profile_end`` mark in the run's
    workload metrics."""
    run_row = await db.fetchone(
        "SELECT id, run_name FROM runs WHERE project_id = ? AND run_name = ?"
        " AND deleted = 0",
        (project_row["id"], run_name),
    )
    if run_row is None:
        raise ResourceNotExistsError(f"run {run_name} not found")
    job_row = await db.fetchone(
        "SELECT * FROM jobs WHERE run_id = ? AND status = 'running'"
        " ORDER BY replica_num ASC, job_num ASC, submission_num DESC LIMIT 1",
        (run_row["id"],),
    )
    if job_row is None:
        raise ServerClientError(f"run {run_name} has no running job to profile")
    jpd = job_jpd(job_row)
    if jpd is None or jpd.hostname is None:
        raise ServerClientError(f"run {run_name}'s job is not reachable yet")
    client = get_runner_client(jpd, job_jrd(job_row))
    try:
        ack = await client.profile(seconds)
    except RunnerError as e:
        raise ServerClientError(f"profiler request failed: {e}") from e
    return {
        "run_name": run_row["run_name"],
        "job_num": job_row["job_num"],
        "replica_num": job_row["replica_num"],
        **(ack or {}),
    }


# ---------------------------------------------------------------------------
# Utilization policy enforcement


async def enforce_utilization_policies(db: Database) -> None:
    """Terminate runs whose TPU duty-cycle stayed below the policy's threshold for
    the whole window (reference process_running_jobs.py:764 _check_gpu_utilization —
    GPU util there, TPU duty-cycle here). A gang dies whole, so enforcement is
    run-level: any breaching job marks the run terminating; process_runs tears it
    down. Decided from job_metrics_points so it composes with the collection loop.

    One grouped window query covers every candidate job (the PR 1/PR 3 IN-clause
    idiom) — the per-job fetch this replaces issued N queries per pass and
    scaled linearly with fleet size."""
    from dstack_tpu.core.models.runs import RunTerminationReason
    from dstack_tpu.server.services.jobs import job_spec as load_job_spec

    rows = await db.fetchall(
        "SELECT j.* FROM jobs j JOIN runs r ON r.id = j.run_id"
        " WHERE j.status = 'running' AND r.status NOT IN"
        " ('terminating', 'terminated', 'failed', 'done')"
    )
    candidates = []  # every policy-bearing running job (any breaching job kills its run)
    max_window = 0
    for row in rows:
        spec = load_job_spec(row)
        policy = spec.utilization_policy
        if policy is None:
            continue
        candidates.append((row, policy))
        max_window = max(max_window, policy.time_window)
    if not candidates:
        return
    now = now_utc()
    window_start = to_iso(now - datetime.timedelta(seconds=max_window))
    point_rows = await db.fetch_in(
        "SELECT job_id, timestamp, tpu FROM job_metrics_points"
        " WHERE timestamp >= ? AND job_id IN ({in})"
        " ORDER BY timestamp",
        [row["id"] for row, _ in candidates],
        (window_start,),
    )
    by_job: Dict[str, List] = {}
    for p in point_rows:
        by_job.setdefault(p["job_id"], []).append(p)

    breached_runs = {}
    for row, policy in candidates:
        if row["run_id"] in breached_runs:
            continue
        job_window_start = to_iso(now - datetime.timedelta(seconds=policy.time_window))
        points = [
            p for p in by_job.get(row["id"], []) if p["timestamp"] >= job_window_start
        ]
        if not points:
            continue
        # The whole window must be covered by samples AND below threshold; a job
        # that just started is not killable yet.
        first_ts = from_iso(points[0]["timestamp"])
        if (now - first_ts).total_seconds() < policy.time_window * 0.9:
            continue
        duties = []
        for p in points:
            tpu = json.loads(p["tpu"]) if p["tpu"] else {}
            duty = tpu.get("duty_cycle_percent")
            if duty is None:
                duties = []  # no TPU signal -> never kill on missing data
                break
            duties.append(duty)
        if duties and max(duties) < policy.min_tpu_utilization:
            breached_runs[row["run_id"]] = (max(duties), policy)
    for run_id, (duty, policy) in breached_runs.items():
        logger.info(
            "run %s: TPU duty %.1f%% < %s%% for %ss; terminating per utilization policy",
            run_id, duty, policy.min_tpu_utilization, policy.time_window,
        )
        await db.execute(
            "UPDATE runs SET status = 'terminating', termination_reason = ?"
            " WHERE id = ? AND status NOT IN ('terminated', 'failed', 'done')",
            (RunTerminationReason.TERMINATED_DUE_TO_UTILIZATION_POLICY.value, run_id),
        )


async def sweep_metrics(db: Database) -> None:
    """TTL delete (reference keeps separate running/finished TTLs; one TTL here —
    finished jobs' points age out the same way). Workload telemetry shares the
    TTL: the goodput window IS the retention window."""
    cutoff = to_iso(now_utc() - datetime.timedelta(seconds=settings.METRICS_TTL_SECONDS))
    await db.execute("DELETE FROM job_metrics_points WHERE timestamp < ?", (cutoff,))
    await db.execute("DELETE FROM workload_metrics_points WHERE timestamp < ?", (cutoff,))


async def get_job_metrics(
    db: Database,
    job_id: str,
    limit: int = 100,
    after: Optional[str] = None,
    before: Optional[str] = None,
) -> JobMetrics:
    """Latest-first points. cpu_usage_percent needs consecutive samples, so one
    extra row is fetched beyond `limit` and consumed by the delta computation
    (reference services/metrics.py:35-50)."""
    q = "SELECT * FROM job_metrics_points WHERE job_id = ?"
    args: list = [job_id]
    if after:
        q += " AND timestamp >= ?"
        args.append(after)
    if before:
        q += " AND timestamp < ?"
        args.append(before)
    q += " ORDER BY timestamp DESC LIMIT ?"
    args.append(min(limit, 1000) + 1)
    rows = await db.fetchall(q, tuple(args))

    points = []
    for i in range(len(rows) - 1):
        cur, prev = rows[i], rows[i + 1]
        t_cur, t_prev = from_iso(cur["timestamp"]), from_iso(prev["timestamp"])
        window_micro = max(1, int((t_cur - t_prev).total_seconds() * 1_000_000))
        cpu_pct = (
            max(0, cur["cpu_usage_micro"] - prev["cpu_usage_micro"]) / window_micro * 100.0
        )
        tpu = json.loads(cur["tpu"]) if cur["tpu"] else {}
        points.append(
            MetricPoint(
                timestamp=t_cur,
                cpu_usage_percent=round(cpu_pct, 2),
                memory_usage_bytes=cur["memory_usage_bytes"],
                memory_working_set_bytes=cur["memory_working_set_bytes"],
                tpu_duty_cycle_percent=tpu.get("duty_cycle_percent"),
                tpu_hbm_usage_bytes=tpu.get("hbm_usage_bytes"),
                tpu_tensorcore_util_percent=tpu.get("tensorcore_util_percent"),
            )
        )
    return JobMetrics(points=points[:limit])
