"""Replica-count decisions for autoscaled services.

Two control loops share this module (``background/tasks.process_autoscaler``):

- ``metric: rps`` — the reference's RPS autoscaler (autoscalers.py:60-110):
  target replicas = ceil(window RPS / per-replica target).
- ``metric: latency`` — the serving-engine loop: scale on the windowed **p90**
  latency the proxy records (TTFT for streamed token responses) and on the
  **engine queue depth** replicas report via ``X-Dstack-Queue-Depth``.
  Latency over target, or backlog over ``queue_depth_target`` per replica,
  adds a replica; p90 under ``LATENCY_DOWN_FACTOR * target`` with a drained
  queue removes one. Step (+-1) scaling, not proportional: latency is a lagging
  nonlinear signal and a proportional controller on it oscillates.

Both scale to zero when ``replicas.min == 0`` and the window shows no demand,
and both scale from zero the moment demand appears (``ServiceStats.record``
counts admitted requests even when no replica is up — that IS the wake
signal). ``decide`` is pure: every branch is unit-testable from synthetic
windows without a server.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

from dstack_tpu.core.models.services import ScalingMetric, ScalingSpec

# Scale down only when p90 sits comfortably under target: between
# DOWN_FACTOR*target and target is the hysteresis dead band that keeps the
# controller from flapping around the setpoint.
LATENCY_DOWN_FACTOR = 0.5


@dataclasses.dataclass(frozen=True)
class Signals:
    """One service's windowed demand signals, gathered by the caller from
    ``proxy.stats`` (all in-memory; the pass touches the DB only to scale)."""

    rps: float = 0.0
    p50: Optional[float] = None
    p90: Optional[float] = None
    queue_depth: Optional[float] = None  # max reported over the gauge window
    # Requests currently held open through the proxy. A long-running token
    # stream stops tripping the RPS window after 60s but is still demand —
    # without this, scale-to-zero would cut live streams.
    inflight: int = 0

    @property
    def idle(self) -> bool:
        return self.rps <= 0.0 and not self.queue_depth and self.inflight <= 0


def decide(
    scaling: ScalingSpec,
    replicas_min: int,
    replicas_max: int,
    active: int,
    sig: Signals,
) -> int:
    """Target replica count for one service (clamped to [min, max])."""
    if scaling.metric == ScalingMetric.RPS:
        target = math.ceil(sig.rps / scaling.target)
        if target == 0 and sig.inflight > 0:
            # A stream held open longer than the RPS window is still demand:
            # never scale an rps service to zero out from under it.
            target = 1
    else:
        target = _latency_target(scaling, active, sig)
    return min(max(target, replicas_min), replicas_max)


def _latency_target(scaling: ScalingSpec, active: int, sig: Signals) -> int:
    if sig.idle:
        return 0  # no demand in the window: clamp decides (min=0 -> zero)
    if active == 0:
        return 1  # demand against zero replicas: wake one up, no delay math
    per_replica_queue = (sig.queue_depth or 0.0) / max(active, 1)
    qd_target = scaling.queue_depth_target
    if (sig.p90 is not None and sig.p90 > scaling.target) or (
        qd_target is not None and per_replica_queue > qd_target
    ):
        return active + 1
    if (
        sig.p90 is not None
        and sig.p90 < LATENCY_DOWN_FACTOR * scaling.target
        and per_replica_queue <= (qd_target or 1) / 2
    ):
        # Comfortable latency shrinks the fleet but never below ONE while
        # demand is present — zero is reserved for the idle path above, else
        # a lightly-loaded scale-to-zero service would cycle kill/cold-start
        # every scale_down_delay.
        return max(active - 1, 1)
    return active
