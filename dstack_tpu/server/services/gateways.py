"""Gateways service: CRUD + provisioning FSM + service sync.

Parity: reference server/services/gateways.py + background process_gateways.
The appliance itself is dstack_tpu/gateway/app.py (replaces the reference's
nginx+python gateway pair); this module provisions it through the backend
(a GCE VM on gcp, a subprocess on local — same pattern as runner agents) and
pushes every running service's replica endpoints to its registry each pass.
"""

from __future__ import annotations

import json
import logging
import time
import uuid as uuid_mod
from typing import Dict, List, Optional, Tuple

import aiohttp

from dstack_tpu.core.errors import (
    ResourceExistsError,
    ResourceNotExistsError,
    ServerClientError,
)
from dstack_tpu.core.models.configurations import GatewayConfiguration
from dstack_tpu.core.models.gateways import (
    Gateway,
    GatewayProvisioningData,
    GatewayStatus,
)
from dstack_tpu.server.db import Database, loads, new_id
from dstack_tpu.utils.common import from_iso, now_utc, to_iso

logger = logging.getLogger(__name__)


def row_to_gateway(row, project_name: str = "") -> Gateway:
    pd = loads(row["provisioning_data"])
    return Gateway(
        id=uuid_mod.UUID(row["id"]),
        name=row["name"],
        project_name=project_name,
        configuration=GatewayConfiguration.model_validate(loads(row["configuration"])),
        created_at=from_iso(row["created_at"]),
        status=GatewayStatus(row["status"]),
        status_message=row["status_message"],
        ip_address=row["ip_address"],
        hostname=row["hostname"],
        default=bool(row["is_default"]),
    )


async def get_gateway_row(db: Database, project_id: str, name: str):
    return await db.fetchone(
        "SELECT * FROM gateways WHERE project_id = ? AND name = ?", (project_id, name)
    )


async def list_gateways(db: Database, project_row) -> List[Gateway]:
    rows = await db.fetchall(
        "SELECT * FROM gateways WHERE project_id = ? ORDER BY created_at",
        (project_row["id"],),
    )
    return [row_to_gateway(r, project_row["name"]) for r in rows]


async def create_gateway(
    db: Database, project_row, conf: GatewayConfiguration
) -> Gateway:
    name = conf.name or f"gateway-{new_id()[:8]}"
    if await get_gateway_row(db, project_row["id"], name) is not None:
        raise ResourceExistsError(f"gateway {name} already exists")
    first = await db.fetchone(
        "SELECT COUNT(*) AS n FROM gateways WHERE project_id = ?", (project_row["id"],)
    )
    await db.execute(
        "INSERT INTO gateways (id, project_id, name, status, configuration, created_at,"
        " is_default) VALUES (?, ?, ?, ?, ?, ?, ?)",
        (
            new_id(),
            project_row["id"],
            name,
            GatewayStatus.SUBMITTED.value,
            conf.model_dump_json(),
            to_iso(now_utc()),
            1 if first["n"] == 0 else 0,  # first gateway becomes the default
        ),
    )
    row = await get_gateway_row(db, project_row["id"], name)
    return row_to_gateway(row, project_row["name"])


async def delete_gateways(db: Database, project_row, names: List[str]) -> None:
    from dstack_tpu.server.services import backends as backends_service

    for name in names:
        row = await get_gateway_row(db, project_row["id"], name)
        if row is None:
            raise ResourceNotExistsError(f"gateway {name} not found")
        pd = loads(row["provisioning_data"])
        if pd:
            conf = GatewayConfiguration.model_validate(loads(row["configuration"]))
            try:
                compute = await backends_service.get_compute(db, project_row, conf.backend)
                terminate = getattr(compute, "terminate_gateway", None)
                if terminate is not None:
                    await terminate(pd.get("instance_id"), conf.region, pd.get("backend_data"))
            except ResourceNotExistsError:
                pass  # backend no longer configured; forget the row
        await db.execute("DELETE FROM gateways WHERE id = ?", (row["id"],))
        # Its pulled request window must stop feeding the autoscaler.
        from dstack_tpu.server.services import proxy as proxy_service

        proxy_service.stats.drop_external(f"gw:{row['id']}")


def gateway_token(row) -> Optional[str]:
    pd = loads(row["provisioning_data"])
    return (pd or {}).get("token")


def gateway_endpoint(row) -> Optional[str]:
    pd = loads(row["provisioning_data"]) or {}
    ip = row["ip_address"]
    port = pd.get("port", 8000)
    if not ip:
        return None
    return f"http://{ip}:{port}"


def stats_rows_from_payload(
    payload,
    run_ids: Dict[str, str],
    project_name: str,
    now: Optional[float] = None,
) -> List[Tuple[str, int, int]]:
    """(run_id, bucket, count) rows from an appliance's /api/registry/stats.

    Bucket keys are the APPLIANCE's wall clock; they are rebased by the clock
    delta (`now` - the payload's own `now`) so a skewed, e.g. NTP-less,
    gateway VM can neither silently age its demand out of the scaling window
    nor future-date it."""
    now = time.time() if now is None else now
    skew = 0.0
    if isinstance(payload, dict):
        appliance_now = payload.get("now")
        services = payload.get("services") or []
        if isinstance(appliance_now, (int, float)):
            skew = now - appliance_now
    else:  # older appliance: bare list, assume clocks agree
        services = payload
    rows: List[Tuple[str, int, int]] = []
    for svc in services:
        run_id = run_ids.get(svc.get("run_name"))
        if run_id is None or svc.get("project") != project_name:
            continue
        for bucket, count in (svc.get("buckets") or {}).items():
            rows.append((run_id, int(int(bucket) + skew), int(count)))
    return rows


async def sync_services_to_gateway(db: Database, project_row, gateway_row) -> None:
    """Push every running service's replica endpoints to the appliance registry;
    unregister services that no longer run. Idempotent per pass."""
    from dstack_tpu.core.models.runs import RunSpec
    from dstack_tpu.core.models.services import ServiceSpec
    from dstack_tpu.server.services import proxy as proxy_service

    endpoint = gateway_endpoint(gateway_row)
    token = gateway_token(gateway_row)
    if endpoint is None or token is None:
        return
    conf = GatewayConfiguration.model_validate(loads(gateway_row["configuration"]))

    run_rows = await db.fetchall(
        "SELECT * FROM runs WHERE project_id = ? AND deleted = 0"
        " AND service_spec IS NOT NULL AND status IN ('running', 'provisioning')",
        (project_row["id"],),
    )
    desired = {}
    for run_row in run_rows:
        run_spec = RunSpec.model_validate(loads(run_row["run_spec"]))
        service_conf = run_spec.configuration
        if getattr(service_conf, "gateway", None) is False:
            continue  # explicitly in-server-proxy only
        service_spec = ServiceSpec.model_validate(loads(run_row["service_spec"]))
        replicas = await proxy_service.list_service_replicas(
            db, project_row["id"], run_row["run_name"], ready_only=True
        )
        entry = {
            "project": project_row["name"],
            "run_name": run_row["run_name"],
            "domain": (
                f"{run_row['run_name']}.{conf.domain}" if conf.domain else None
            ),
            "model": (
                service_spec.model.model_dump(mode="json") if service_spec.model else None
            ),
            "replicas": [
                {"host": jpd.internal_ip or jpd.hostname, "port": port}
                for _, jpd, _, port in replicas
            ],
            "rate_limits": [
                l.model_dump(mode="json")
                for l in getattr(service_conf, "rate_limits", []) or []
            ],
        }
        desired[run_row["run_name"]] = entry

    run_ids = {row["run_name"]: row["id"] for row in run_rows}
    headers = {"Authorization": f"Bearer {token}"}
    timeout = aiohttp.ClientTimeout(total=10)
    try:
        async with aiohttp.ClientSession(timeout=timeout) as session:
            async with session.get(
                f"{endpoint}/api/registry/services", headers=headers
            ) as resp:
                current = {
                    e["run_name"]: e
                    for e in await resp.json()
                    if e["project"] == project_row["name"]
                }
            for run_name, entry in desired.items():
                if current.get(run_name) != entry:
                    async with session.post(
                        f"{endpoint}/api/registry/register", json=entry, headers=headers
                    ) as resp:
                        resp.raise_for_status()
            for run_name in set(current) - set(desired):
                async with session.post(
                    f"{endpoint}/api/registry/unregister",
                    json={"project": project_row["name"], "run_name": run_name},
                    headers=headers,
                ) as resp:
                    resp.raise_for_status()
            # Pull the appliance's request buckets so gateway-routed traffic
            # feeds the RPS autoscaler like in-server proxy traffic does (the
            # reference's server pulls its gateway's access-log stats the same
            # way). Each pull replaces this gateway's window — no double count.
            async with session.get(
                f"{endpoint}/api/registry/stats", headers=headers
            ) as resp:
                if resp.status == 200:
                    stats_rows = stats_rows_from_payload(
                        await resp.json(), run_ids, project_row["name"]
                    )
                    proxy_service.stats.set_external(
                        f"gw:{gateway_row['id']}", stats_rows
                    )
    except (aiohttp.ClientError, OSError) as e:
        logger.warning("gateway %s sync failed: %s", gateway_row["name"], e)
