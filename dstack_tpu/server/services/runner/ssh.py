"""Runner reachability: SSH local-forward tunnels for cloud instances.

Parity: reference server/services/runner/ssh.py:24-114 (``runner_ssh_tunnel``
decorator). Shape differs: instead of wrapping every client call, this module keeps a
per-worker tunnel pool and hands the RunnerClient a lazily-resolved base endpoint —
one persistent ``ssh -N -L`` child per slice worker, reused across scheduler passes
(the reference re-establishes tunnels per call batch).

Local/mock instances bypass SSH entirely; with no ssh client on the host the layer
degrades to direct HTTP (dev containers).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional, Tuple

from dstack_tpu.backends.gcp.startup import RUNNER_PORT
from dstack_tpu.core import tracing
from dstack_tpu.core.errors import SSHError
from dstack_tpu.core.models.runs import JobProvisioningData, JobRuntimeData
from dstack_tpu.core.services.ssh.tunnel import (
    Forward,
    SSHTunnel,
    allocate_local_port,
    ssh_binary,
)
from dstack_tpu.server import settings

logger = logging.getLogger(__name__)

_DIRECT_BACKENDS = {"local", "mock"}

_pool: Dict[str, SSHTunnel] = {}
_pool_lock: Optional[asyncio.Lock] = None
# Per-key locks so tunnel establishment (up to CONNECT_TIMEOUT against a dead
# host) to one worker never serializes runner traffic to every other instance
# (ADVICE r2). The global lock only guards the dicts, never an open().
_key_locks: Dict[str, asyncio.Lock] = {}


def _lock() -> asyncio.Lock:
    global _pool_lock
    if _pool_lock is None:
        _pool_lock = asyncio.Lock()
    return _pool_lock


async def _key_lock(key: str) -> asyncio.Lock:
    async with _lock():
        lock = _key_locks.get(key)
        if lock is None:
            lock = _key_locks[key] = asyncio.Lock()
        return lock


def tunnel_required(jpd: JobProvisioningData) -> bool:
    if jpd.backend in _DIRECT_BACKENDS:
        return False
    if not settings.SSH_TUNNELS_ENABLED:
        return False
    return ssh_binary() is not None


def _runner_port(jpd: JobProvisioningData, jrd: Optional[JobRuntimeData]) -> int:
    if jrd is not None and jrd.runner_port:
        return jrd.runner_port
    if jpd.backend_data:
        try:
            import json

            port = json.loads(jpd.backend_data).get("runner_port")
            if port:
                return int(port)
        except (ValueError, TypeError):
            pass
    return RUNNER_PORT


def _key(jpd: JobProvisioningData) -> str:
    return f"{jpd.instance_id}:{jpd.worker_num}"


async def tunneled_endpoint(
    jpd: JobProvisioningData, jrd: Optional[JobRuntimeData]
) -> Tuple[str, int]:
    """(host, port) the RunnerClient should hit: the local end of a live tunnel."""
    remote_port = _runner_port(jpd, jrd)
    key = _key(jpd)
    async with await _key_lock(key):
        async with _lock():
            tunnel = _pool.get(key)
        if tunnel is not None and tunnel.is_open:
            return "127.0.0.1", tunnel.forwards[0].local_port
        if tunnel is not None:
            await tunnel.close()
            async with _lock():
                _pool.pop(key, None)
        local_port = allocate_local_port()
        tunnel = SSHTunnel(
            hostname=jpd.hostname or "",
            username=jpd.username or "root",
            port=jpd.ssh_port or 22,
            identity_file=settings.SSH_IDENTITY_FILE or _server_identity(),
            proxy=jpd.ssh_proxy,
            forwards=[Forward(local_port, "127.0.0.1", remote_port)],
        )
        with tracing.span(
            "ssh.tunnel_open",
            histogram="dstack_tpu_ssh_tunnel_open_seconds",
            host=jpd.hostname,
        ):
            await tunnel.open()  # slow path: only this key's callers wait
        async with _lock():
            _pool[key] = tunnel
        logger.debug("tunnel up: %s -> %s:%s (local %s)", key, jpd.hostname, remote_port, local_port)
        return "127.0.0.1", local_port


async def tunneled_app_endpoint(jpd: JobProvisioningData, remote_port: int) -> Tuple[str, int]:
    """Like tunneled_endpoint but for an arbitrary app port on the worker (service
    sockets, dev-env servers). One tunnel per (worker, port), pooled the same way."""
    key = f"{_key(jpd)}:app{remote_port}"
    async with await _key_lock(key):
        async with _lock():
            tunnel = _pool.get(key)
        if tunnel is not None and tunnel.is_open:
            return "127.0.0.1", tunnel.forwards[0].local_port
        if tunnel is not None:
            await tunnel.close()
            async with _lock():
                _pool.pop(key, None)
        local_port = allocate_local_port()
        tunnel = SSHTunnel(
            hostname=jpd.hostname or "",
            username=jpd.username or "root",
            port=jpd.ssh_port or 22,
            identity_file=settings.SSH_IDENTITY_FILE or _server_identity(),
            proxy=jpd.ssh_proxy,
            forwards=[Forward(local_port, "127.0.0.1", remote_port)],
        )
        with tracing.span(
            "ssh.app_tunnel_open",
            histogram="dstack_tpu_ssh_tunnel_open_seconds",
            host=jpd.hostname,
        ):
            await tunnel.open()
        async with _lock():
            _pool[key] = tunnel
        logger.debug("app tunnel up: %s (local %s)", key, local_port)
        return "127.0.0.1", local_port


async def close_tunnel(jpd: JobProvisioningData) -> None:
    """Close the worker's runner tunnel AND any app-port tunnels riding it."""
    base = _key(jpd)
    async with _lock():
        keys = [k for k in _pool if k == base or k.startswith(base + ":app")]
        tunnels = [_pool.pop(k) for k in keys]
        for k in keys:
            _key_locks.pop(k, None)
    for tunnel in tunnels:
        await tunnel.close()


async def reap_tunnels(live_keys) -> None:
    """Close tunnels whose worker is gone (terminated outside the normal teardown
    path — crashes, manual deletes). `live_keys` is the set of
    ``instance_id:worker_num`` for every non-terminated instance; app-port
    tunnels follow their worker's fate."""
    async with _lock():
        doomed = [k for k in _pool if k.split(":app", 1)[0] not in live_keys]
        tunnels = [_pool.pop(k) for k in doomed]
        for k in doomed:
            _key_locks.pop(k, None)
    for t in tunnels:
        await t.close()
    if doomed:
        logger.info("reaped %d stale tunnel(s)", len(doomed))


async def close_all_tunnels() -> None:
    async with _lock():
        tunnels = list(_pool.values())
        _pool.clear()
        _key_locks.clear()
    for t in tunnels:
        await t.close()


def _server_identity() -> Optional[str]:
    try:
        from dstack_tpu.utils.ssh_keys import get_server_ssh_keypair

        identity, _ = get_server_ssh_keypair(settings.SERVER_DIR)
        return identity
    except Exception:  # keygen failure must not take down the scheduler
        logger.exception("failed to materialize server ssh identity")
        return None
