"""HTTP client for the dstack-tpu runner agent.

Parity: reference server/services/runner/client.py (RunnerClient:49-134). The runner API
is our own design (see runner/ C++ agent): submit carries the job spec AND cluster info
in one call; pull streams both state events and log lines from a single monotonically
increasing offset, so the server needs no websocket.

For cloud instances the client talks through an SSH tunnel (services/runner/ssh.py);
for the local backend it connects directly to 127.0.0.1:<runner_port>.

Every request rides the unified resilience layer (services/resilience): an
explicit per-request timeout (DSTACK_TPU_RUNNER_REQUEST_TIMEOUT), transport
retries with jittered backoff (DSTACK_TPU_RUNNER_CALL_ATTEMPTS), and a per-
agent circuit breaker keyed ``runner:<endpoint>``. Healthchecks bypass both
retry and breaker accounting — an unreachable agent is the NORMAL state while
a slice provisions, and must not open the breaker the first submit needs.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Dict, List, Optional

import aiohttp

from dstack_tpu.core import faults, tracing
from dstack_tpu.core.errors import SSHError
from dstack_tpu.core.models.runs import ClusterInfo, JobRuntimeData, JobSpec


class RunnerError(Exception):
    """Base for runner conversations that did not produce a result."""

    def __init__(self, msg: str = "", status: Optional[int] = None):
        super().__init__(msg)
        self.status = status


class RunnerRequestError(RunnerError):
    """The agent answered with a 4xx: the request was wrong, the agent is fine
    (never retried; counts as breaker SUCCESS — the target is reachable)."""


class RunnerUnavailableError(RunnerError):
    """Transport failure, timeout, or agent 5xx: the target may be down
    (retried; counts as a breaker failure)."""


class RunnerClient:
    """Async HTTP client; one instance per (host, port) conversation.

    ``endpoint_resolver`` defers endpoint resolution to first use: cloud instances
    resolve to the local end of an SSH tunnel (services/runner/ssh.py), which can only
    be established from an async context."""

    def __init__(
        self,
        hostname: Optional[str] = None,
        port: Optional[int] = None,
        endpoint_resolver=None,
    ):
        self.base = f"http://{hostname}:{port}" if hostname is not None else None
        self._resolver = endpoint_resolver

    async def _ensure_base(self) -> str:
        if self.base is None:
            host, port = await self._resolver()
            self.base = f"http://{host}:{port}"
        return self.base

    async def _request_once(
        self,
        method: str,
        path: str,
        payload: Optional[dict] = None,
        data: Optional[bytes] = None,
        params: Optional[dict] = None,
    ) -> Any:
        from dstack_tpu.server import settings

        try:
            await faults.check("runner.request", detail=f"{self.base}{path}")
            timeout = aiohttp.ClientTimeout(total=settings.RUNNER_REQUEST_TIMEOUT)
            async with aiohttp.ClientSession(timeout=timeout) as session:
                kwargs: dict = {}
                if payload is not None:
                    kwargs["json"] = payload
                if data is not None:
                    kwargs["data"] = data
                if params is not None:
                    kwargs["params"] = params
                # Trace propagation: the scheduler's current trace id rides
                # every agent call, and the agent echoes it into its own log
                # lines — a run_event's trace_id greps straight into the
                # agent log on the host (runner/src/main.cpp).
                trace_id = tracing.current_trace_id()
                if trace_id:
                    kwargs["headers"] = {"X-Dstack-Trace-Id": trace_id}
                async with session.request(method, self.base + path, **kwargs) as resp:
                    body = await resp.read()
                    if resp.status >= 500:
                        raise RunnerUnavailableError(
                            f"{path} -> {resp.status}: {body[:200]!r}", status=resp.status
                        )
                    if resp.status >= 400:
                        raise RunnerRequestError(
                            f"{path} -> {resp.status}: {body[:200]!r}", status=resp.status
                        )
                    if not body:
                        return None
                    return json.loads(body)
        except (
            aiohttp.ClientError,
            asyncio.TimeoutError,
            OSError,
            faults.FaultInjected,
        ) as e:
            raise RunnerUnavailableError(f"{path}: {e}") from e

    async def _request(
        self,
        method: str,
        path: str,
        payload: Optional[dict] = None,
        data: Optional[bytes] = None,
        params: Optional[dict] = None,
        retry: bool = True,
        breaker: bool = True,
    ) -> Any:
        from dstack_tpu.server import settings
        from dstack_tpu.server.services import resilience

        try:
            base = await self._ensure_base()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError, SSHError) as e:
            # Endpoint resolution (SSH tunnel setup, local port allocation)
            # failing is the same story as the request failing: the agent is
            # unreachable — keep the RunnerError contract callers rely on.
            raise RunnerUnavailableError(f"{path}: {e}") from e
        try:
            return await resilience.with_retry(
                lambda: self._request_once(method, path, payload, data, params),
                target=f"runner:{base}" if breaker else None,
                op=path,
                attempts=settings.RUNNER_CALL_ATTEMPTS if retry else 1,
                base_delay=0.2,
                max_delay=2.0,
                retry_on=(RunnerUnavailableError,),
                treat_as_success=(RunnerRequestError,),
            )
        except resilience.BreakerOpenError as e:
            raise RunnerUnavailableError(f"{path}: {e}") from e

    async def healthcheck(self) -> Optional[dict]:
        # Single attempt, no breaker: failing healthchecks are the expected
        # state of a provisioning slice, not a fault signal.
        try:
            return await self._request(
                "GET", "/api/healthcheck", retry=False, breaker=False
            )
        except RunnerError:
            return None

    async def submit(
        self,
        job_spec: JobSpec,
        cluster_info: ClusterInfo,
        run_spec: Optional[dict] = None,
        secrets: Optional[Dict[str, str]] = None,
    ) -> None:
        await self._request(
            "POST",
            "/api/submit",
            payload={
                "job_spec": job_spec.model_dump(mode="json"),
                "cluster_info": cluster_info.model_dump(mode="json"),
                "run_spec": run_spec or {},
                "secrets": secrets or {},
            },
        )

    async def upload_code(self, code: bytes) -> None:
        await self._request("POST", "/api/upload_code", data=code)

    async def run_job(self) -> None:
        await self._request("POST", "/api/run")

    async def pull(self, offset: int = 0) -> dict:
        """Returns {"job_states": [{"state","termination_reason","exit_status","ts"}...],
        "logs": [{"ts","message"}...], "offset": int, "has_more": bool}."""
        return await self._request("GET", "/api/pull", params={"offset": str(offset)})

    async def stop(self, abort: bool = False) -> None:
        # Best-effort teardown: one attempt (callers already tolerate failure;
        # retrying a stop only delays releasing the slice).
        await self._request("POST", "/api/stop", payload={"abort": abort}, retry=False)

    async def metrics(self) -> Optional[dict]:
        try:
            return await self._request("GET", "/api/metrics")
        except RunnerError:
            return None

    async def profile(self, seconds: float = 5.0) -> dict:
        """Request an on-demand profiler capture from the live workload.
        Unlike metrics(), errors PROPAGATE: the caller is an interactive
        `dstack-tpu profile` request that must hear "no running job"."""
        return await self._request("POST", "/api/profile", payload={"seconds": seconds})


def get_runner_client(jpd, jrd: Optional[JobRuntimeData]) -> RunnerClient:
    """Resolve how to reach a job's runner.

    Local/mock instances expose the runner directly on a host port recorded in
    JobRuntimeData; cloud instances are reached via a pooled SSH local-forward
    (services/runner/ssh.py), resolved lazily on the client's first request."""
    from dstack_tpu.server.services.runner import ssh as runner_ssh

    if jpd is not None and runner_ssh.tunnel_required(jpd):
        return RunnerClient(
            endpoint_resolver=lambda: runner_ssh.tunneled_endpoint(jpd, jrd)
        )
    port = None
    if jrd is not None and jrd.runner_port:
        port = jrd.runner_port
    if port is None and jpd is not None and jpd.backend_data:
        try:
            port = json.loads(jpd.backend_data).get("runner_port")
        except ValueError:
            port = None
    if port is None:
        port = 10999
    return RunnerClient(jpd.hostname or "127.0.0.1", port)
