"""User management (parity: reference server/services/users.py)."""

from __future__ import annotations

from typing import List, Optional

from dstack_tpu.core.errors import ResourceExistsError, ResourceNotExistsError
from dstack_tpu.core.models.users import GlobalRole, User, UserWithCreds
from dstack_tpu.server.db import Database, new_id
from dstack_tpu.server.security import generate_token
from dstack_tpu.utils.common import from_iso, now_utc, to_iso


def row_to_user(row) -> User:
    return User(
        id=row["id"],
        username=row["username"],
        global_role=GlobalRole(row["global_role"]),
        email=row["email"],
        active=bool(row["active"]),
        created_at=from_iso(row["created_at"]),
    )


def row_to_user_with_creds(row) -> UserWithCreds:
    u = row_to_user(row)
    return UserWithCreds(**u.model_dump(), creds={"token": row["token"]})


async def get_or_create_admin_user(db: Database, token: Optional[str] = None):
    row = await db.fetchone("SELECT * FROM users WHERE username = 'admin'")
    if row is not None:
        if token and row["token"] != token:
            await db.execute("UPDATE users SET token = ? WHERE id = ?", (token, row["id"]))
            row = await db.fetchone("SELECT * FROM users WHERE id = ?", (row["id"],))
        return row, False
    await create_user(db, "admin", GlobalRole.ADMIN, token=token)
    return await db.fetchone("SELECT * FROM users WHERE username = 'admin'"), True


async def create_user(
    db: Database,
    username: str,
    global_role: GlobalRole = GlobalRole.USER,
    email: Optional[str] = None,
    token: Optional[str] = None,
) -> UserWithCreds:
    existing = await db.fetchone("SELECT id FROM users WHERE username = ?", (username,))
    if existing is not None:
        raise ResourceExistsError(f"user {username} exists")
    uid = new_id()
    await db.execute(
        "INSERT INTO users (id, username, global_role, email, token, active, created_at)"
        " VALUES (?, ?, ?, ?, ?, 1, ?)",
        (uid, username, global_role.value, email, token or generate_token(), to_iso(now_utc())),
    )
    row = await db.fetchone("SELECT * FROM users WHERE id = ?", (uid,))
    return row_to_user_with_creds(row)


async def list_users(db: Database) -> List[User]:
    rows = await db.fetchall("SELECT * FROM users ORDER BY username")
    return [row_to_user(r) for r in rows]


async def get_user_by_name(db: Database, username: str):
    row = await db.fetchone("SELECT * FROM users WHERE username = ?", (username,))
    if row is None:
        raise ResourceNotExistsError(f"user {username} not found")
    return row


async def refresh_token(db: Database, username: str) -> UserWithCreds:
    row = await get_user_by_name(db, username)
    await db.execute("UPDATE users SET token = ? WHERE id = ?", (generate_token(), row["id"]))
    return row_to_user_with_creds(await get_user_by_name(db, username))


async def update_user(
    db: Database,
    username: str,
    global_role: Optional[GlobalRole] = None,
    email: Optional[str] = None,
) -> User:
    """Partial update: omitted fields keep their current values."""
    row = await get_user_by_name(db, username)
    await db.execute(
        "UPDATE users SET global_role = ?, email = ? WHERE id = ?",
        (
            global_role.value if global_role is not None else row["global_role"],
            email if email is not None else row["email"],
            row["id"],
        ),
    )
    return row_to_user(await get_user_by_name(db, username))


async def delete_users(db: Database, usernames: List[str]) -> None:
    """Hard-delete when unreferenced; otherwise deactivate (projects/runs keep valid
    foreign keys to the user row)."""
    rows = [await get_user_by_name(db, name) for name in usernames]
    for row in rows:
        uid = row["id"]
        owns = await db.fetchone("SELECT 1 FROM projects WHERE owner_id = ? LIMIT 1", (uid,))
        has_runs = await db.fetchone("SELECT 1 FROM runs WHERE user_id = ? LIMIT 1", (uid,))

        def _tx(conn, uid=uid, referenced=bool(owns or has_runs)) -> None:
            conn.execute("DELETE FROM members WHERE user_id = ?", (uid,))
            if referenced:
                conn.execute(
                    "UPDATE users SET active = 0, token = ? WHERE id = ?",
                    (generate_token(), uid),
                )
            else:
                conn.execute("DELETE FROM users WHERE id = ?", (uid,))

        await db.run(_tx)
