"""Attach bridge: TCP-over-WebSocket port forwarding through the control plane.

Parity: reference `dstack attach` (cli/commands/attach.py:28,
api/_public/runs.py:244-351) forwards ports by SSHing from the client straight to
the instance with the user's key. TPU re-design: the client rarely holds instance
keys — but the control plane already maintains SSH tunnels to every worker, so
attach rides them: the CLI opens local listeners and pipes each accepted
connection over one WebSocket to the server, which pipes it on to the worker's
port (directly for local workers, over the pooled app tunnel for cloud ones).

Bridge activity doubles as the dev-environment inactivity signal (the reference
tracks SSH connections in the shim, runner/internal/shim/connections.go): open
bridges hold inactivity at zero, and the clock starts at the last disconnect.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, Optional

from aiohttp import WSMsgType, web

from dstack_tpu.server.db import Database
from dstack_tpu.server.services.jobs import job_jpd, job_jrd, job_spec as load_job_spec
from dstack_tpu.server.services.runner import ssh as runner_ssh

logger = logging.getLogger(__name__)


class ActivityRegistry:
    """Per-run attach-connection bookkeeping, in-memory (a server restart resets
    the inactivity clock — same trade-off the scale-delay derivation makes)."""

    def __init__(self) -> None:
        self._active: Dict[str, int] = {}
        self._last_disconnect: Dict[str, float] = {}

    def on_connect(self, run_id: str) -> None:
        self._active[run_id] = self._active.get(run_id, 0) + 1

    def on_disconnect(self, run_id: str) -> None:
        n = self._active.get(run_id, 0)
        self._active[run_id] = max(0, n - 1)
        if self._active[run_id] == 0:
            self._last_disconnect[run_id] = time.monotonic()

    def inactivity_secs(self, run_id: str) -> Optional[int]:
        """0 while attached; seconds since last detach; None if never attached."""
        if self._active.get(run_id, 0) > 0:
            return 0
        last = self._last_disconnect.get(run_id)
        if last is None:
            return None
        return int(time.monotonic() - last)

    def reset(self) -> None:
        self._active.clear()
        self._last_disconnect.clear()


activity = ActivityRegistry()


async def resolve_job_endpoint(
    db: Database, run_row, port: int, replica_num: int = 0, job_num: int = 0
):
    """(host, port) reaching `port` on the chosen worker, honoring ports_mapping."""
    row = await db.fetchone(
        "SELECT * FROM jobs WHERE run_id = ? AND replica_num = ? AND job_num = ?"
        "   AND status = 'running'"
        " ORDER BY submission_num DESC LIMIT 1",
        (run_row["id"], replica_num, job_num),
    )
    if row is None:
        return None
    jpd = job_jpd(row)
    if jpd is None or jpd.hostname is None:
        return None
    jrd = job_jrd(row)
    effective = port
    if jrd is not None and jrd.ports_mapping:
        effective = jrd.ports_mapping.get(port, port)
    if runner_ssh.tunnel_required(jpd):
        return await runner_ssh.tunneled_app_endpoint(jpd, effective)
    return jpd.hostname, effective


async def ws_bridge(request: web.Request, db: Database, run_row, port: int) -> web.StreamResponse:
    """Upgrade to WS and pipe bytes bidirectionally to the worker port."""
    endpoint = await resolve_job_endpoint(
        db,
        run_row,
        port,
        replica_num=int(request.query.get("replica", 0)),
        job_num=int(request.query.get("job", 0)),
    )
    if endpoint is None:
        raise web.HTTPServiceUnavailable(text="no running job to attach to")
    host, eport = endpoint
    try:
        reader, writer = await asyncio.open_connection(host, eport)
    except OSError as e:
        raise web.HTTPBadGateway(text=f"worker port {port} unreachable: {e}")

    ws = web.WebSocketResponse(heartbeat=30)
    await ws.prepare(request)
    activity.on_connect(run_row["id"])

    async def tcp_to_ws() -> None:
        try:
            while True:
                data = await reader.read(64 * 1024)
                if not data:
                    break
                await ws.send_bytes(data)
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            if not ws.closed:
                await ws.close()

    pump = asyncio.ensure_future(tcp_to_ws())
    try:
        async for msg in ws:
            if msg.type == WSMsgType.BINARY:
                writer.write(msg.data)
                await writer.drain()
            elif msg.type in (WSMsgType.CLOSE, WSMsgType.ERROR):
                break
    finally:
        activity.on_disconnect(run_row["id"])
        pump.cancel()
        try:
            writer.close()
            await writer.wait_closed()
        except (ConnectionError, OSError):
            pass
    return ws
