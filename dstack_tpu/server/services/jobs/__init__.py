"""Job FSM helpers shared by the background loops.

Parity: reference server/services/jobs/__init__.py (job_model_to_job_submission:109,
process_terminating_job:209)."""

from __future__ import annotations

import logging
from typing import List, Optional

from dstack_tpu.core.models.runs import (
    ClusterInfo,
    JobProvisioningData,
    JobRuntimeData,
    JobSpec,
    JobStatus,
    JobTerminationReason,
)
from dstack_tpu.server.db import Database, loads
from dstack_tpu.utils.common import now_utc, to_iso

logger = logging.getLogger(__name__)

DEFAULT_JAX_COORDINATOR_PORT = 8476
DEFAULT_MEGASCALE_PORT = 8081


def job_jpd(row) -> Optional[JobProvisioningData]:
    data = loads(row["job_provisioning_data"])
    return JobProvisioningData.model_validate(data) if data else None


def job_jrd(row) -> Optional[JobRuntimeData]:
    data = loads(row["job_runtime_data"])
    return JobRuntimeData.model_validate(data) if data else None


def job_spec(row) -> JobSpec:
    return JobSpec.model_validate(loads(row["job_spec"]))


async def set_job_status(
    db: Database,
    job_row,
    status: JobStatus,
    reason: Optional[JobTerminationReason] = None,
    reason_message: Optional[str] = None,
    exit_status: Optional[int] = None,
    actor: str = "server",
) -> None:
    from dstack_tpu.server.services import events as events_service

    now = to_iso(now_utc())
    finished = now if status.is_finished() else None
    try:
        run_id = job_row["run_id"]
    except (KeyError, IndexError):
        run_id = None
    old_status = job_row["status"]

    def _tx(conn) -> None:
        conn.execute(
            "UPDATE jobs SET status = ?,"
            " termination_reason = COALESCE(?, termination_reason),"
            " termination_reason_message = COALESCE(?, termination_reason_message),"
            " exit_status = COALESCE(?, exit_status),"
            " last_processed_at = ?, finished_at = COALESCE(finished_at, ?)"
            " WHERE id = ?",
            (
                status.value,
                reason.value if reason else None,
                reason_message,
                exit_status,
                now,
                finished,
                job_row["id"],
            ),
        )
        # The lifecycle event commits atomically with the transition it
        # describes: a crash can't record a move that didn't land (or vice
        # versa). Same-status touches are not transitions and stay silent.
        if run_id and old_status != status.value:
            events_service.record_event_tx(
                conn,
                run_id,
                status.value,
                old_status=old_status,
                job_id=job_row["id"],
                actor=actor,
                reason=reason.value if reason else None,
                message=reason_message,
            )

    await db.run(_tx)
    # Every job transition drops the run's cached proxy route (no-op for runs
    # never proxied). Import is deferred: proxy imports this module.
    from dstack_tpu.server.services import proxy as proxy_service

    if run_id:
        proxy_service.route_table.invalidate_run(run_id)


async def touch_jobs(db: Database, job_rows: List) -> None:
    """Bump last_processed_at for a set of jobs in one executemany round trip
    (was: one UPDATE per job from the scheduler's park-for-next-pass paths)."""
    if not job_rows:
        return
    now = to_iso(now_utc())
    await db.executemany(
        "UPDATE jobs SET last_processed_at = ? WHERE id = ?",
        [(now, r["id"]) for r in job_rows],
    )


async def terminate_job(
    db: Database,
    job_row,
    reason: JobTerminationReason,
    reason_message: Optional[str] = None,
    actor: str = "server",
) -> None:
    """Move an active job into TERMINATING; process_terminating_jobs finishes it."""
    if JobStatus(job_row["status"]).is_finished():
        return
    await set_job_status(
        db, job_row, JobStatus.TERMINATING, reason, reason_message, actor=actor
    )


def build_cluster_info(
    specs_and_jpds: List[tuple],
    num_slices: int = 1,
) -> List[ClusterInfo]:
    """Cluster contract for one replica: one ClusterInfo per job (SURVEY §2.6).

    `specs_and_jpds` is [(JobSpec, JobProvisioningData)] ordered by job_num; jobs are
    grouped into slices of jpd.hosts_per_slice workers. The JAX coordinator is worker 0
    of slice 0; MegaScale coordination (multislice) also anchors there."""
    if not specs_and_jpds:
        return []
    ips = [jpd.internal_ip or jpd.hostname or "" for _, jpd in specs_and_jpds]
    master_ip = ips[0]
    hosts_per_slice = specs_and_jpds[0][1].hosts_per_slice or 1
    first = specs_and_jpds[0][1]
    tpu = first.instance_type.resources.tpu
    infos: List[ClusterInfo] = []
    for (spec, jpd), ip in zip(specs_and_jpds, ips):
        slice_idx = spec.job_num // hosts_per_slice
        worker_id = spec.job_num % hosts_per_slice
        slice_ips = ips[slice_idx * hosts_per_slice : (slice_idx + 1) * hosts_per_slice]
        infos.append(
            ClusterInfo(
                master_node_ip=master_ip,
                node_ips=ips,
                nodes_num=len(specs_and_jpds),
                node_rank=spec.job_num,
                tpu_worker_id=worker_id,
                tpu_worker_hostnames=slice_ips,
                tpu_topology=(tpu.topology if tpu else None),
                tpu_generation=(tpu.generation if tpu else None),
                chips_per_host=(tpu.chips // max(1, tpu.hosts) if tpu and tpu.chips else 0),
                num_slices=num_slices,
                slice_id=slice_idx,
                coordinator_address=f"{master_ip}:{DEFAULT_JAX_COORDINATOR_PORT}",
                megascale_coordinator_address=(
                    f"{master_ip}:{DEFAULT_MEGASCALE_PORT}" if num_slices > 1 else None
                ),
            )
        )
    return infos
