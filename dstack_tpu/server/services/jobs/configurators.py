"""RunSpec -> per-job JobSpecs.

Parity: reference server/services/jobs/configurators/{base,task,service,dev}.py
(base.py:60-279). TPU twist: a replica spans all hosts of the requested slice, so
jobs_per_replica = slice hosts and every job of a replica is gang-scheduled onto the
same slice (reference's `nodes: N` maps N jobs to N independent VMs instead)."""

from __future__ import annotations

from typing import List

from dstack_tpu.core.errors import ServerClientError
from dstack_tpu.core.models.configurations import (
    DEFAULT_IDE_PORT,
    DEFAULT_TPU_IMAGE,
    DevEnvironmentConfiguration,
    InstanceMountPoint,
    ServiceConfiguration,
    TaskConfiguration,
    VolumeMountPoint,
)
from dstack_tpu.core.models.profiles import Profile
from dstack_tpu.core.models.runs import JobSpec, Requirements, RunSpec

# jax-free string composition (workloads/xla_flags.py): the comm/compute-overlap
# XLA defaults every orchestrated TPU job receives unless it opts out.
from dstack_tpu.workloads.xla_flags import overlap_env

# Pinned openvscode-server release installed at dev-env start when the image
# ships no IDE and the host has egress (reference configurators/dev.py:35).
OPENVSCODE_VERSION = "1.97.2"

DEFAULT_STOP_DURATION = 300
DEFAULT_MAX_DURATION = {"task": None, "service": None, "dev-environment": 72 * 3600}


def _requirements(run_spec: RunSpec, profile: Profile) -> Requirements:
    spot = None
    if profile.spot_policy is not None:
        spot = {"spot": True, "on-demand": False, "auto": None}[profile.spot_policy.value]
    return Requirements(
        resources=run_spec.configuration.resources,
        max_price=profile.max_price,
        spot=spot,
        reservation=profile.reservation,
    )


def _env(run_spec: RunSpec) -> dict:
    try:
        return run_spec.configuration.env.as_dict()
    except ValueError as e:
        raise ServerClientError(str(e))


def get_job_specs(run_spec: RunSpec, replica_num: int = 0) -> List[JobSpec]:
    """All jobs for one replica. Multi-host slices produce one job per slice host."""
    conf = run_spec.configuration
    profile = run_spec.merged_profile()
    run_name = run_spec.run_name or "run"

    if conf.resources.tpu is not None:
        # One job per slice host; multislice (`tpu.count > 1`) multiplies the gang.
        num_slices = conf.resources.tpu.count.min or 1
        jobs_per_replica = conf.resources.tpu.hosts * num_slices
    elif isinstance(conf, TaskConfiguration) and conf.nodes > 0:
        jobs_per_replica = conf.nodes
    else:
        jobs_per_replica = 1

    if isinstance(conf, TaskConfiguration) and conf.nodes > 0 and conf.resources.tpu is not None:
        if conf.nodes != jobs_per_replica:
            raise ServerClientError(
                f"`nodes: {conf.nodes}` conflicts with the {conf.resources.tpu.pretty()} "
                f"request ({jobs_per_replica} hosts); omit `nodes` to derive it"
            )

    from dstack_tpu.core.models.common import parse_duration

    env = _env(run_spec)
    if conf.resources.tpu is not None:
        # TPU jobs get the comm/compute-overlap compiler defaults (latency-
        # hiding scheduler + async collectives). overlap_env merges flag-by-
        # flag UNDER the user's own XLA_FLAGS/LIBTPU_INIT_ARGS (their flags
        # win by name) and returns {} when DSTACK_TPU_OVERLAP_FLAGS=0.
        additions = overlap_env(env)
        if additions:
            env = {**env, **additions}
        elif not conf.image:
            # Opted out on the DEFAULT image: pin the vars (user values or
            # empty) so its baked ENV can't re-apply the flags the user just
            # disabled — container env overrides image env. Custom images are
            # left alone: their baked ENV is the user's own choice.
            env.setdefault("XLA_FLAGS", "")
            env.setdefault("LIBTPU_INIT_ARGS", "")
    elif not conf.image:
        # NON-TPU job on the default TPU image: the baked flags are libtpu-
        # registered and would abort any CPU-backed XLA at init, so neutralize
        # them at the container level (user env still wins via setdefault).
        env.setdefault("XLA_FLAGS", "")
        env.setdefault("LIBTPU_INIT_ARGS", "")

    commands = _build_commands(conf)
    stop_duration = (
        parse_duration(profile.stop_duration)
        if "stop_duration" in profile.model_fields_set
        else DEFAULT_STOP_DURATION
    )
    max_duration = (
        parse_duration(profile.max_duration)
        if "max_duration" in profile.model_fields_set
        else DEFAULT_MAX_DURATION[conf.type]
    )

    specs = []
    for job_num in range(jobs_per_replica):
        specs.append(
            JobSpec(
                replica_num=replica_num,
                job_num=job_num,
                job_name=f"{run_name}-{job_num}-{replica_num}",
                jobs_per_replica=jobs_per_replica,
                commands=commands,
                env=env,
                image_name=conf.image or DEFAULT_TPU_IMAGE,
                registry_auth=conf.registry_auth,
                privileged=conf.privileged,
                home_dir=conf.home_dir,
                working_dir=conf.working_dir,
                repo_dir=conf.repo_dir,
                max_duration=max_duration,
                stop_duration=stop_duration,
                utilization_policy=profile.utilization_policy,
                retry=profile.retry,
                requirements=_requirements(run_spec, profile),
                app_ports=_app_ports(conf),
                volumes=[
                    {"name": m.name, "path": m.path}
                    for m in conf.volumes
                    if isinstance(m, VolumeMountPoint)
                ],
                instance_mounts=[
                    {"instance_path": m.instance_path, "path": m.path}
                    for m in conf.volumes
                    if isinstance(m, InstanceMountPoint)
                ],
                # The primary app socket: the service's port, or the dev env's IDE
                # backend. Gets a DSTACK_SERVICE_PORT assignment at submit time.
                service_port=(
                    conf.port.container_port
                    if isinstance(conf, ServiceConfiguration)
                    else DEFAULT_IDE_PORT
                    if isinstance(conf, DevEnvironmentConfiguration)
                    else None
                ),
            )
        )
    return specs


def _pkg_root() -> str:
    """Shell-quoted directory containing the ``dstack_tpu`` package the server
    itself imports (the repo root on a checkout, site-packages on a wheel)."""
    import shlex
    from pathlib import Path

    import dstack_tpu

    return shlex.quote(str(Path(dstack_tpu.__file__).resolve().parent.parent))


def _build_commands(conf) -> List[str]:
    if isinstance(conf, DevEnvironmentConfiguration):
        # init, then an IDE backend on the assigned port. Four-tier chain
        # (reference configurators/dev.py:35 get_install_commands() downloads
        # openvscode-server unconditionally — which needs egress at job start):
        #   1. code-server already in the image (docker/tpu bakes it)
        #   2. install openvscode-server once (reference parity; needs curl+egress)
        #   3. the repo's stdlib web IDE (dstack_tpu/ide.py — always works
        #      air-gapped wherever the package is importable)
        #   4. bare workspace listing (attach always has a socket)
        # The server keeps the env alive and IS the attach target.
        ovs = OPENVSCODE_VERSION
        return [
            *conf.init,
            f"echo 'dev environment ready ({conf.ide.value})'",
            'if command -v code-server >/dev/null 2>&1; then\n'
            '  echo "ide: code-server on port $DSTACK_SERVICE_PORT"\n'
            '  exec code-server --bind-addr "127.0.0.1:$DSTACK_SERVICE_PORT" --auth none\n'
            "fi",
            # Extract into a temp dir and promote atomically: an interrupted
            # download must not leave a half-install that [ -x ] mistakes for
            # complete (wedging the env until ~/.dstack-ide is deleted).
            'if [ ! -x "$HOME/.dstack-ide/bin/openvscode-server" ]'
            " && command -v curl >/dev/null 2>&1; then\n"
            '  rm -rf "$HOME/.dstack-ide.tmp" && mkdir -p "$HOME/.dstack-ide.tmp"\n'
            f'  if curl -fsSL --max-time 120 "https://github.com/gitpod-io/openvscode-server/releases/download/openvscode-server-v{ovs}/openvscode-server-v{ovs}-linux-x64.tar.gz"'
            ' | tar -xz -C "$HOME/.dstack-ide.tmp" --strip-components=1; then\n'
            '    rm -rf "$HOME/.dstack-ide" && mv "$HOME/.dstack-ide.tmp" "$HOME/.dstack-ide"\n'
            "  else\n"
            '    rm -rf "$HOME/.dstack-ide.tmp"\n'
            '    echo "ide: openvscode-server download failed; trying fallbacks"\n'
            "  fi\n"
            "fi",
            'if [ -x "$HOME/.dstack-ide/bin/openvscode-server" ]; then\n'
            '  echo "ide: openvscode-server on port $DSTACK_SERVICE_PORT"\n'
            '  exec "$HOME/.dstack-ide/bin/openvscode-server" --host 127.0.0.1'
            ' --port "$DSTACK_SERVICE_PORT" --without-connection-token\n'
            "fi",
            # The package root the SERVER runs from rides along on PYTHONPATH:
            # local/test runs execute jobs on the same filesystem where
            # dstack_tpu is a repo checkout, not an installed wheel, and the
            # runner's job cwd is its own base dir — without the prefix the
            # import probe fails and every air-gapped dev env lands on the
            # bare http.server tier. On remote hosts the path simply doesn't
            # exist and the probe decides on the image's own install.
            f'if env PYTHONPATH={_pkg_root()}:"$PYTHONPATH"'
            ' python3 -c "import dstack_tpu.ide" >/dev/null 2>&1; then\n'
            '  echo "ide: dstack-tpu web IDE on port $DSTACK_SERVICE_PORT"\n'
            f'  exec env PYTHONPATH={_pkg_root()}:"$PYTHONPATH"'
            ' python3 -m dstack_tpu.ide --port "$DSTACK_SERVICE_PORT" --root .\n'
            "fi",
            'echo "ide: serving workspace over http on port $DSTACK_SERVICE_PORT"',
            'exec python3 -m http.server "$DSTACK_SERVICE_PORT" --bind 127.0.0.1',
        ]
    if conf.entrypoint:
        # An explicit entrypoint overrides image defaults; commands become its body.
        return [conf.entrypoint, *conf.commands]
    # Empty commands with an image: the agent runs the image's own entrypoint
    # (no Cmd override in the container create).
    return list(conf.commands)


def _app_ports(conf) -> List[int]:
    if isinstance(conf, TaskConfiguration):
        return [p.container_port for p in conf.ports]
    if isinstance(conf, ServiceConfiguration):
        return [conf.port.container_port]
    return []
