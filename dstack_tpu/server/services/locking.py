"""In-process resource locking for the background loops.

Parity: reference server/services/locking.py (sqlite lockset / postgres advisory locks).
This server is single-process (sqlite single-writer model), so named asyncio locks are
sufficient and cheaper: they serialize FSM transitions on one resource (a run, an
instance slice) without DB round-trips — both across concurrently-running background
loops AND across the concurrent work items each loop now fans out (background/tasks
bounded-gather passes). The contract for every scheduler work item is
lock(f"run:{run_id}") -> re-fetch fresh rows -> act: the keyed lock serializes
same-resource passes, and the fresh re-read under the lock is what makes an
overlapping pass a no-op instead of a double placement.
"""

from __future__ import annotations

import asyncio
from typing import Dict


class Locker:
    def __init__(self) -> None:
        self._locks: Dict[str, asyncio.Lock] = {}
        self._waiters: Dict[str, int] = {}

    def lock(self, name: str) -> "_LockCtx":
        return _LockCtx(self, name)

    def locked(self, name: str) -> bool:
        """True while any task holds the named lock (tests/diagnostics only —
        by the time a caller branches on it, the answer may be stale)."""
        lock = self._locks.get(name)
        return lock is not None and lock.locked()

    def _acquire_obj(self, name: str) -> asyncio.Lock:
        lock = self._locks.get(name)
        if lock is None:
            lock = asyncio.Lock()
            self._locks[name] = lock
        self._waiters[name] = self._waiters.get(name, 0) + 1
        return lock

    def _release_obj(self, name: str) -> None:
        # Drop the lock object once nobody holds or waits on it (unbounded resource
        # names: run ids come and go).
        n = self._waiters.get(name, 0) - 1
        if n <= 0:
            self._waiters.pop(name, None)
            self._locks.pop(name, None)
        else:
            self._waiters[name] = n


class _LockCtx:
    def __init__(self, locker: Locker, name: str) -> None:
        self._locker = locker
        self._name = name
        self._lock: asyncio.Lock = None  # type: ignore[assignment]

    async def __aenter__(self) -> None:
        self._lock = self._locker._acquire_obj(self._name)
        try:
            await self._lock.acquire()
        except BaseException:
            # Cancelled while waiting: __aexit__ won't run, so drop our waiter
            # refcount here or the per-name entry leaks forever.
            self._locker._release_obj(self._name)
            raise

    async def __aexit__(self, *exc) -> None:
        self._lock.release()
        self._locker._release_obj(self._name)


_locker = Locker()


def get_locker() -> Locker:
    return _locker
