"""Server-side plugin loading + policy application.

Parity: reference server/services/plugins.py:59 (load_plugins / apply_plugin_policies).
Import paths come from config.yml ``plugins:`` or DSTACK_TPU_PLUGINS instead of
packaging entrypoints: explicit > discoverable for a control plane."""

from __future__ import annotations

import importlib
import logging
from typing import List

from dstack_tpu.core.errors import ServerClientError
from dstack_tpu.plugins import ApplyPolicy, Plugin

logger = logging.getLogger(__name__)

_plugins: List[Plugin] = []


def load_plugins(import_paths: List[str]) -> List[str]:
    """Load `module.path:ClassName` plugins; returns the names that loaded.
    A broken plugin is skipped with a warning — one bad plugin must not take
    the control plane down."""
    _plugins.clear()
    loaded = []
    for path in import_paths:
        module_path, _, class_name = path.partition(":")
        try:
            module = importlib.import_module(module_path)
            cls = getattr(module, class_name)
            if not (isinstance(cls, type) and issubclass(cls, Plugin)):
                raise TypeError(f"{path} is not a dstack_tpu.plugins.Plugin subclass")
            _plugins.append(cls())
            loaded.append(path)
        except Exception as e:
            logger.warning("failed to load plugin %s: %s", path, e)
    return loaded


def reset_plugins() -> None:
    _plugins.clear()


def apply_policies(user: str, project: str, spec):
    """Run every loaded policy over the spec; ValueError => client error."""
    for plugin in _plugins:
        for policy in plugin.get_apply_policies():
            try:
                spec = policy.on_apply(user, project, spec)
            except ValueError as e:
                raise ServerClientError(str(e) or "rejected by plugin policy")
    return spec
