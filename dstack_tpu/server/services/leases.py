"""Lease-based run ownership: the multi-replica scheduling contract.

Generalizes the PR 1 conditional slice-claim from one placement decision to
the whole run lifecycle. Every run-keyed scheduler pass (submitted / running /
terminating jobs, runs) first claims the runs it is about to process; a claim
succeeds when the run is unleased, already ours (renewal), or the holder's
lease expired (reclaim). N server replicas sharing one database therefore each
own a disjoint partition of runs with no coordinator: the partition is just
whoever claimed first, rebalanced by the TTL when a replica dies.

All claim logic is conditional SQL inside one transaction, so it is correct
under both sqlite (single writer thread) and postgres (row-level locking):
two replicas racing for an expired lease resolve by UPDATE rowcount, exactly
like ``mark_slice_busy_tx``.

Reclaiming an expired lease means the previous owner died (or stalled past the
TTL) with the run possibly mid-provision: the new owner *reconciles* before
scheduling — re-probe the runner of every in-flight job, re-derive the FSM
position from the rows (which are transactionally consistent — every transition
commits atomically with its run_event), and emit a ``reconciled`` run_event so
the timeline records the ownership change and what was found. Nothing is
rolled back: the job FSM is re-entrant by design (each pass re-fetches fresh
rows), so reconciliation is observation + adoption, not repair.
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import logging
import os
import socket
import uuid
from typing import Iterable, List, Optional, Sequence, Set, Tuple

from dstack_tpu.server import settings
from dstack_tpu.server.db import Database, in_clause
from dstack_tpu.utils.common import now_utc, to_iso

logger = logging.getLogger(__name__)

# Job states that mean "the control plane has work in flight for this run"
# (provisioned capacity, a submitted agent, or a live workload).
IN_FLIGHT_JOB_STATUSES = ("provisioning", "pulling", "running")
_ACTIVE_RUN_FILTER = "status NOT IN ('terminated', 'failed', 'done')"

# The bench/chaos harness runs several logical replicas inside one process:
# the contextvar override scopes a replica identity to an asyncio task.
_replica_override: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "dstack_tpu_replica_id", default=None
)
_process_replica_id: Optional[str] = None


def replica_id() -> str:
    """This scheduler's lease identity: DSTACK_TPU_REPLICA_ID, else a
    host-pid-rand string minted once per process (a restarted server is a NEW
    replica; its previous incarnation's leases age out via the TTL)."""
    override = _replica_override.get()
    if override is not None:
        return override
    global _process_replica_id
    if settings.REPLICA_ID:
        return settings.REPLICA_ID
    if _process_replica_id is None:
        _process_replica_id = (
            f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:6]}"
        )
    return _process_replica_id


@contextlib.contextmanager
def as_replica(rid: str):
    """Scope a replica identity to the current task (chaos harness / tests)."""
    token = _replica_override.set(rid)
    try:
        yield
    finally:
        _replica_override.reset(token)


def _expiry(now) -> str:
    from datetime import timedelta

    return to_iso(now + timedelta(seconds=settings.LEASE_TTL))


def claim_runs_tx(
    conn, run_ids: Sequence[str], owner: str
) -> Tuple[Set[str], Set[str]]:
    """Claim/renew leases inside an open transaction. Returns
    ``(owned, reclaimed)``: run ids this owner now holds, and the subset taken
    over from an expired holder (those runs need reconciliation — their
    previous owner died mid-work)."""
    now = now_utc()
    now_s, exp_s = to_iso(now), _expiry(now)
    owned: Set[str] = set()
    reclaimed: Set[str] = set()
    for run_id in run_ids:
        # Renewal first: the common steady-state case is one UPDATE, no read.
        cur = conn.execute(
            "UPDATE run_leases SET heartbeat_at = ?, expires_at = ?"
            " WHERE run_id = ? AND owner = ?",
            (now_s, exp_s, run_id, owner),
        )
        if cur.rowcount == 1:
            owned.add(run_id)
            continue
        # Fresh claim: INSERT-if-absent settles races via the primary key.
        cur = conn.execute(
            "INSERT INTO run_leases (run_id, owner, acquired_at, heartbeat_at,"
            " expires_at) VALUES (?, ?, ?, ?, ?) ON CONFLICT (run_id) DO NOTHING",
            (run_id, owner, now_s, now_s, exp_s),
        )
        if cur.rowcount == 1:
            owned.add(run_id)
            continue
        # Held by someone else: take over only if their lease expired. The
        # conditional UPDATE is the whole consensus — a racing replica's
        # transaction sees rowcount 0 and moves on.
        cur = conn.execute(
            "UPDATE run_leases SET owner = ?, acquired_at = ?, heartbeat_at = ?,"
            " expires_at = ?, reclaims = reclaims + 1"
            " WHERE run_id = ? AND owner != ? AND expires_at < ?",
            (owner, now_s, now_s, exp_s, run_id, owner, now_s),
        )
        if cur.rowcount == 1:
            owned.add(run_id)
            reclaimed.add(run_id)
    return owned, reclaimed


async def claim_runs(
    db: Database, run_ids: Iterable[str]
) -> Tuple[Set[str], Set[str]]:
    """Claim (or renew) leases on `run_ids` for this replica; one transaction.
    With leases disabled everything is owned and nothing is ever reclaimed."""
    run_ids = list(dict.fromkeys(run_ids))
    if not run_ids:
        return set(), set()
    if not settings.RUN_LEASES_ENABLED:
        return set(run_ids), set()
    owner = replica_id()
    result = await db.run(lambda conn: claim_runs_tx(conn, run_ids, owner))
    owned, reclaimed = result
    if reclaimed:
        logger.info(
            "replica %s reclaimed %d expired run lease(s): %s",
            owner, len(reclaimed), ", ".join(sorted(reclaimed)),
        )
    return owned, reclaimed


def release_tx(conn, run_id: str) -> None:
    """Drop a run's lease inside the transaction that finalizes the run, so
    ownership ends atomically with the terminal transition."""
    conn.execute("DELETE FROM run_leases WHERE run_id = ?", (run_id,))


async def release_runs(db: Database, run_ids: Iterable[str]) -> None:
    run_ids = list(run_ids)
    if not run_ids:
        return
    await db.execute(
        f"DELETE FROM run_leases WHERE run_id IN ({in_clause(run_ids)})", run_ids
    )


async def sweep(db: Database) -> None:
    """Drop leases whose run is finished, deleted, or gone — the table must
    track only live scheduling work (finalize already releases; this catches
    crashes between the terminal transition and the release). Notify
    sentinel rows are not leases and survive the sweep."""
    await db.execute(
        "DELETE FROM run_leases WHERE run_id NOT IN"
        f" (SELECT id FROM runs WHERE deleted = 0 AND {_ACTIVE_RUN_FILTER})"
        f" AND run_id NOT LIKE '{NOTIFY_PREFIX}%'"
    )


# -- cross-replica notify ---------------------------------------------------
#
# background.wake() is an in-process asyncio.Event: a submit on replica A
# never reaches replica B's loops. The DB-visible half rides the run_leases
# table (the one piece of shared scheduler state every replica already
# watches): notify() stamps a sentinel row, and a loop registered with a
# notify poll (background.add_periodic) slices its interval sleep into short
# ticks that compare the stamp against what it saw when the sleep began —
# submit on A, assign on B next tick, not next interval.

NOTIFY_PREFIX = "notify:"


def notify_tx(conn, name: str) -> None:
    now_s = to_iso(now_utc())
    conn.execute(
        "INSERT INTO run_leases (run_id, owner, acquired_at, heartbeat_at,"
        " expires_at, notify_at) VALUES (?, ?, ?, ?, ?, ?)"
        " ON CONFLICT (run_id) DO UPDATE SET"
        " owner = excluded.owner, notify_at = excluded.notify_at",
        (NOTIFY_PREFIX + name, replica_id(), now_s, now_s, now_s, now_s),
    )


async def notify(db: Database, name: str) -> None:
    """Stamp the named loop's cross-replica notify sentinel. Cheap (one
    upsert), idempotent, and safe to call with no scheduler running — the
    stamp just waits for the next poller. ISO stamps carry microseconds, so
    back-to-back submits always advance the value a sleeping poller compares
    against."""
    await db.run(lambda conn: notify_tx(conn, name))


async def last_notify(db: Database, name: str) -> Optional[str]:
    """The named loop's latest notify stamp (None before the first one)."""
    row = await db.fetchone(
        "SELECT notify_at FROM run_leases WHERE run_id = ?",
        (NOTIFY_PREFIX + name,),
    )
    return row["notify_at"] if row is not None else None


async def owners(db: Database, run_ids: Sequence[str]) -> dict:
    """run_id -> owner for the given runs (ps/API surface)."""
    if not run_ids:
        return {}
    rows = await db.fetch_in(
        "SELECT run_id, owner FROM run_leases WHERE run_id IN ({in})", run_ids
    )
    return {r["run_id"]: r["owner"] for r in rows}


async def reconcile_run(db: Database, run_id: str, reason: str = "lease_reclaimed") -> None:
    """Adopt an orphaned run: re-probe the runner of every in-flight job,
    re-derive the FSM position from the rows, and emit a ``reconciled``
    run_event recording both. The FSM itself needs no repair — every
    transition commits atomically with its event, so the rows ARE the
    position; what a dead replica loses is only the work of its interrupted
    pass, which the next pass redoes from the fresh rows."""
    from dstack_tpu.server.services import events as events_service
    from dstack_tpu.server.services.jobs import job_jpd, job_jrd

    run_row = await db.fetchone("SELECT * FROM runs WHERE id = ?", (run_id,))
    if run_row is None:
        return
    job_rows = await db.fetch_in(
        "SELECT * FROM jobs WHERE run_id = ? AND status IN ({in})",
        IN_FLIGHT_JOB_STATUSES,
        params=(run_id,),
    )
    async def _probe(row) -> Optional[bool]:
        jpd = job_jpd(row)
        if jpd is None or jpd.hostname is None:
            return None  # still resolving its endpoint; nothing to probe yet
        try:
            # Late import: background.tasks imports this module, and tests/
            # bench monkeypatch tasks.get_runner_client — resolve through it
            # so reconciliation probes the same (possibly faked) agents.
            from dstack_tpu.server.background import tasks as _tasks

            client = _tasks.get_runner_client(jpd, job_jrd(row))
            return await client.healthcheck() is not None
        except Exception:
            return False

    # Probes fan out: a gang of dead agents must cost one healthcheck
    # timeout, not hosts-per-gang of them (the adopting replica is a live
    # scheduler — reconciliation can't stall its passes for minutes).
    outcomes = await asyncio.gather(*(_probe(row) for row in job_rows))
    probed_ok = sum(1 for o in outcomes if o is True)
    probed_bad = sum(1 for o in outcomes if o is False)
    message = (
        f"adopted by {replica_id()}: {len(job_rows)} in-flight job(s),"
        f" {probed_ok} reachable, {probed_bad} unreachable"
    )

    def _tx(conn) -> None:
        events_service.record_event_tx(
            conn,
            run_id,
            "reconciled",
            old_status=run_row["status"],
            actor="scheduler",
            reason=reason,
            message=message,
        )

    await db.run(_tx)
    logger.info("run %s reconciled (%s): %s", run_row["run_name"], reason, message)


async def startup_reconcile(db: Database) -> int:
    """Crash-safe startup: adopt active runs with in-flight jobs whose lease is
    missing, expired, or (with a pinned DSTACK_TPU_REPLICA_ID) left over from
    this replica's previous incarnation — killing a replica mid-provision loses
    nothing but the interrupted pass. Returns the number of runs adopted."""
    if not settings.RUN_LEASES_ENABLED:
        return 0
    rows = await db.fetchall(
        f"SELECT r.id FROM runs r WHERE r.deleted = 0 AND r.{_ACTIVE_RUN_FILTER}"
        " AND EXISTS (SELECT 1 FROM jobs j WHERE j.run_id = r.id AND j.status IN"
        f" ({','.join(repr(s) for s in IN_FLIGHT_JOB_STATUSES)}))"
    )
    candidate_ids = [r["id"] for r in rows]
    if not candidate_ids:
        return 0
    me = replica_id()
    now_s = to_iso(now_utc())
    lease_rows = await db.fetch_in(
        "SELECT run_id, owner, expires_at FROM run_leases WHERE run_id IN ({in})",
        candidate_ids,
    )
    leases = {r["run_id"]: r for r in lease_rows}
    orphans = [
        rid
        for rid in candidate_ids
        if rid not in leases
        or leases[rid]["owner"] == me
        or leases[rid]["expires_at"] < now_s
    ]
    if not orphans:
        return 0
    owned, _ = await claim_runs(db, orphans)
    for rid in sorted(owned):
        try:
            await reconcile_run(db, rid, reason="startup")
        except Exception:
            logger.exception("startup reconciliation of run %s failed", rid)
    return len(owned)
