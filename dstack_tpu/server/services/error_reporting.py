"""Env-gated error reporting for the control plane.

Parity: reference server/app.py:81-89 — `sentry_sdk.init` when SENTRY_DSN is
set, tagging release + deployment environment. Two tiers here:

- ``DSTACK_TPU_SENTRY_DSN``: init sentry_sdk when the package is importable
  (it is not bundled; setting the var without it logs a warning and degrades).
- ``DSTACK_TPU_ERROR_REPORT_URL``: SDK-free tier in the repo's house style —
  a logging handler that ships every ERROR-or-worse record (message +
  traceback + release) as a JSON POST from a background thread, so any
  `logger.exception` in the middleware, services, or background loops reaches
  the operator's webhook/collector without blocking the event loop.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
import traceback
import urllib.request
from typing import Optional

logger = logging.getLogger(__name__)


class ReportHandler(logging.Handler):
    """Queue + drain thread: emit() never blocks, delivery failures are
    dropped silently (error reporting must never take the server down)."""

    def __init__(self, url: str, max_queue: int = 256, timeout: float = 5.0):
        super().__init__(level=logging.ERROR)
        self.url = url
        self.timeout = timeout
        self.delivered = 0
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._thread = threading.Thread(
            target=self._pump, name="error-report", daemon=True
        )
        self._thread.start()

    def emit(self, record: logging.LogRecord) -> None:
        import dstack_tpu

        tb = None
        if record.exc_info and record.exc_info[0] is not None:
            tb = "".join(traceback.format_exception(*record.exc_info))
        payload = {
            "logger": record.name,
            "level": record.levelname,
            "message": record.getMessage(),
            "traceback": tb,
            "release": dstack_tpu.__version__,
            "environment": os.getenv("DSTACK_TPU_DEPLOYMENT_ENV", "production"),
            "timestamp": time.time(),
        }
        try:
            self._queue.put_nowait(payload)
        except queue.Full:
            pass  # shed under a log storm; reporting must not amplify it

    def _pump(self) -> None:
        while True:
            payload = self._queue.get()
            if payload is None:
                return
            try:
                req = urllib.request.Request(
                    self.url,
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=self.timeout):
                    self.delivered += 1
            except Exception:
                pass

    def drain(self, deadline: float = 2.0) -> None:
        """Best effort flush (tests / shutdown)."""
        end = time.time() + deadline
        while not self._queue.empty() and time.time() < end:
            time.sleep(0.02)

    def stop(self) -> None:
        try:
            self._queue.put_nowait(None)
        except queue.Full:
            pass


_handler: Optional[ReportHandler] = None


def setup() -> Optional[str]:
    """Install the configured reporter; returns which tier activated."""
    global _handler
    dsn = os.getenv("DSTACK_TPU_SENTRY_DSN")
    if dsn:
        try:
            import sentry_sdk  # type: ignore

            import dstack_tpu

            sentry_sdk.init(
                dsn=dsn,
                release=dstack_tpu.__version__,
                environment=os.getenv("DSTACK_TPU_DEPLOYMENT_ENV", "production"),
            )
            logger.info("error reporting: sentry enabled")
            return "sentry"
        except ImportError:
            logger.warning(
                "DSTACK_TPU_SENTRY_DSN is set but sentry_sdk is not installed;"
                " falling back to DSTACK_TPU_ERROR_REPORT_URL if configured"
            )
        except Exception:
            # A typo'd DSN (sentry raises BadDsn) must not stop the control
            # plane from booting over a non-essential reporting feature.
            logger.exception("sentry init failed; continuing without it")
    url = os.getenv("DSTACK_TPU_ERROR_REPORT_URL")
    if url:
        if _handler is None:
            _handler = ReportHandler(url)
            logging.getLogger().addHandler(_handler)
        logger.info("error reporting: POSTing ERROR records to %s", url)
        return "http"
    return None


def teardown() -> None:
    global _handler
    if _handler is not None:
        logging.getLogger().removeHandler(_handler)
        _handler.stop()
        _handler = None
