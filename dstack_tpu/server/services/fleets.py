"""Fleets service: declarative fleet CRUD + run auto-fleets.

Parity: reference server/services/fleets.py (get_plan:232, create_fleet:338). A fleet is
a named pool of slices; cloud fleets declare `nodes` x a slice resource spec, SSH fleets
enumerate user hosts. Runs auto-create a fleet per run when none is targeted (reference
process_submitted_jobs.py:490)."""

from __future__ import annotations

import uuid
from typing import List, Optional

from dstack_tpu.core.errors import (
    ResourceExistsError,
    ResourceNotExistsError,
    ServerClientError,
)
from dstack_tpu.core.models.fleets import (
    ApplyFleetPlanInput,
    Fleet,
    FleetPlan,
    FleetSpec,
    FleetStatus,
)
from dstack_tpu.core.models.instances import InstanceStatus
from dstack_tpu.server.db import Database, loads, new_id
from dstack_tpu.server.services import instances as instances_service
from dstack_tpu.utils.common import from_iso, now_utc, to_iso


def fleet_profile(conf):
    """Scheduling profile implied by a FleetConfiguration's inline fields."""
    from dstack_tpu.core.models.profiles import Profile

    return Profile.model_validate(
        {
            k: v
            for k, v in dict(
                backends=conf.backends,
                regions=conf.regions,
                availability_zones=conf.availability_zones,
                instance_types=conf.instance_types,
                spot_policy=conf.spot_policy,
                max_price=conf.max_price,
                reservation=conf.reservation,
            ).items()
            if v is not None
        }
    )


async def row_to_fleet(db: Database, row, project_name: str = "") -> Fleet:
    instance_rows = await db.fetchall(
        "SELECT * FROM instances WHERE fleet_id = ? AND deleted = 0 ORDER BY instance_num",
        (row["id"],),
    )
    return Fleet(
        id=uuid.UUID(row["id"]),
        name=row["name"],
        project_name=project_name,
        spec=FleetSpec.model_validate(loads(row["spec"])),
        created_at=from_iso(row["created_at"]),
        status=FleetStatus(row["status"]),
        status_message=row["status_message"],
        instances=[
            instances_service.row_to_instance(r, project_name, fleet_name=row["name"])
            for r in instance_rows
        ],
    )


async def get_fleet_row(db: Database, project_id: str, name: str):
    return await db.fetchone(
        "SELECT * FROM fleets WHERE project_id = ? AND name = ? AND deleted = 0",
        (project_id, name),
    )


async def list_fleets(db: Database, project_row) -> List[Fleet]:
    rows = await db.fetchall(
        "SELECT * FROM fleets WHERE project_id = ? AND deleted = 0 ORDER BY created_at",
        (project_row["id"],),
    )
    return [await row_to_fleet(db, r, project_row["name"]) for r in rows]


async def get_fleet(db: Database, project_row, name: str) -> Fleet:
    row = await get_fleet_row(db, project_row["id"], name)
    if row is None:
        raise ResourceNotExistsError(f"fleet {name} not found")
    return await row_to_fleet(db, row, project_row["name"])


async def get_plan(db: Database, project_row, user_row, spec: FleetSpec) -> FleetPlan:
    from dstack_tpu.server.services import offers as offers_service
    from dstack_tpu.core.models.runs import Requirements

    conf = spec.configuration
    effective_name = conf.name or f"fleet-{new_id()[:8]}"
    offers = []
    total = 0
    max_price = None
    if conf.ssh_config is None and conf.resources is not None:
        req = Requirements(resources=conf.resources, spot=None)
        offer_list = await offers_service.get_offers_by_requirements(
            db, project_row, req, fleet_profile(conf)
        )
        offers = [o.model_dump(mode="json") for o in offer_list[:50]]
        total = len(offer_list)
        max_price = max((o.price for o in offer_list), default=None)
    current = None
    action = "create"
    row = await get_fleet_row(db, project_row["id"], effective_name) if conf.name else None
    if row is not None:
        current = await row_to_fleet(db, row, project_row["name"])
        action = "update"
    return FleetPlan(
        project_name=project_row["name"],
        user=user_row["username"],
        spec=spec,
        effective_name=effective_name,
        current_resource=current,
        offers=offers,
        total_offers=total,
        max_offer_price=max_price,
        action=action,
    )


async def create_fleet(db: Database, project_row, user_row, spec: FleetSpec) -> Fleet:
    conf = spec.configuration
    name = conf.name or f"fleet-{new_id()[:8]}"
    existing = await get_fleet_row(db, project_row["id"], name)
    if existing is not None:
        raise ResourceExistsError(f"fleet {name} already exists")
    fleet_id = new_id()
    now = to_iso(now_utc())
    await db.execute(
        "INSERT INTO fleets (id, project_id, name, status, spec, created_at, auto_created)"
        " VALUES (?, ?, ?, ?, ?, ?, 0)",
        (fleet_id, project_row["id"], name, FleetStatus.SUBMITTED.value, spec.model_dump_json(), now),
    )
    if conf.ssh_config is not None:
        # SSH fleet: one instance row per user-supplied host, provisioned by
        # process_instances (shim upload over SSH).
        for num, host in enumerate(conf.ssh_config.hosts):
            await db.execute(
                "INSERT INTO instances (id, project_id, fleet_id, name, instance_num,"
                " status, created_at, backend, remote_connection_info)"
                " VALUES (?, ?, ?, ?, ?, ?, ?, 'ssh', ?)",
                (
                    new_id(),
                    project_row["id"],
                    fleet_id,
                    f"{name}-{num}",
                    num,
                    InstanceStatus.PENDING.value,
                    now,
                    host.model_dump_json(),
                ),
            )
    else:
        # Cloud fleet: `nodes` pending markers; process_fleets provisions slices.
        nodes = conf.nodes.min or 0
        for num in range(nodes):
            await db.execute(
                "INSERT INTO instances (id, project_id, fleet_id, name, instance_num,"
                " status, created_at) VALUES (?, ?, ?, ?, ?, ?, ?)",
                (
                    new_id(),
                    project_row["id"],
                    fleet_id,
                    f"{name}-{num}",
                    num,
                    InstanceStatus.PENDING.value,
                    now,
                ),
            )
    row = await db.fetchone("SELECT * FROM fleets WHERE id = ?", (fleet_id,))
    return await row_to_fleet(db, row, project_row["name"])


async def apply_plan(db: Database, project_row, user_row, plan: ApplyFleetPlanInput) -> Fleet:
    conf = plan.spec.configuration
    if conf.name:
        existing = await get_fleet_row(db, project_row["id"], conf.name)
        if existing is not None:
            if not plan.force and loads(existing["spec"]) == loads(plan.spec.model_dump_json()):
                return await row_to_fleet(db, existing, project_row["name"])
            await _soft_delete_fleet(db, existing)
    return await create_fleet(db, project_row, user_row, plan.spec)


async def delete_fleets(db: Database, project_row, names: List[str]) -> None:
    for name in names:
        row = await get_fleet_row(db, project_row["id"], name)
        if row is None:
            raise ResourceNotExistsError(f"fleet {name} not found")
        busy = await db.fetchone(
            "SELECT COUNT(*) AS n FROM instances WHERE fleet_id = ? AND deleted = 0"
            " AND busy_blocks > 0",
            (row["id"],),
        )
        if busy["n"] > 0:
            raise ServerClientError(f"fleet {name} has busy instances; stop runs first")
        await db.execute(
            "UPDATE fleets SET status = ? WHERE id = ?",
            (FleetStatus.TERMINATING.value, row["id"]),
        )
        await db.execute(
            "UPDATE instances SET status = 'terminating', termination_reason = 'fleet deleted'"
            " WHERE fleet_id = ? AND deleted = 0 AND status NOT IN ('terminating', 'terminated')",
            (row["id"],),
        )


async def _soft_delete_fleet(db: Database, row) -> None:
    await db.execute("UPDATE fleets SET deleted = 1 WHERE id = ?", (row["id"],))


def get_or_create_auto_fleet_tx(conn, project_id: str, run_name: str) -> str:
    """Synchronous core of get_or_create_auto_fleet, composable inside one db.run()
    transaction with the slice-row inserts it precedes."""
    row = conn.execute(
        "SELECT id FROM fleets WHERE project_id = ? AND name = ? AND deleted = 0",
        (project_id, run_name),
    ).fetchone()
    if row is not None:
        return row["id"]
    fleet_id = new_id()
    spec = FleetSpec.model_validate({"configuration": {"type": "fleet", "name": run_name}})
    conn.execute(
        "INSERT INTO fleets (id, project_id, name, status, spec, created_at, auto_created)"
        " VALUES (?, ?, ?, 'active', ?, ?, 1)",
        (fleet_id, project_id, run_name, spec.model_dump_json(), to_iso(now_utc())),
    )
    return fleet_id
