"""Offer fan-in across project backends (parity: reference server/services/offers.py:
get_offers_by_requirements:26-154), fronted by a small TTL cache.

The scheduler's placement loop re-queries offers once per gang; under load most of
those queries are identical (N submissions of the same instance shape in one project),
so the fan-in to every backend is memoized for OFFER_CACHE_TTL seconds keyed on
(project, requirements, profile fingerprint). A backend config change invalidates the
project's entries immediately via the reset_compute_cache path in services/backends."""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional, Tuple

from dstack_tpu.core.models.instances import InstanceOffer
from dstack_tpu.core.models.profiles import Profile, SpotPolicy
from dstack_tpu.core.models.runs import Requirements
from dstack_tpu.server import settings
from dstack_tpu.server.db import Database
from dstack_tpu.server.services import backends as backends_service

logger = logging.getLogger(__name__)

# (project_id, requirements fingerprint, profile fingerprint) -> (monotonic ts, offers)
_offer_cache: Dict[Tuple[str, str, str], Tuple[float, List[InstanceOffer]]] = {}
# Same key -> the fan-in currently resolving it: concurrent cold-cache misses
# (the scheduler fans out up to SCHEDULER_CONCURRENCY placements at once) await
# one backend query instead of issuing N identical ones.
_inflight: Dict[Tuple[str, str, str], "asyncio.Task"] = {}
_OFFER_CACHE_MAX_ENTRIES = 512


def invalidate_offer_cache(project_id: Optional[str] = None) -> None:
    """Drop cached offers — for one project (its backend config changed) or all.
    In-flight queries are detached (not cancelled): current awaiters get their
    result, but it is no longer cached, so the next caller re-queries."""
    keys = [
        k
        for k in set(_offer_cache) | set(_inflight)
        if project_id is None or k[0] == project_id
    ]
    for key in keys:
        _offer_cache.pop(key, None)
        _inflight.pop(key, None)


def _cache_get(key) -> Optional[List[InstanceOffer]]:
    hit = _offer_cache.get(key)
    if hit is None:
        return None
    ts, offers = hit
    if time.monotonic() - ts > settings.OFFER_CACHE_TTL:
        _offer_cache.pop(key, None)
        return None
    return offers


def _cache_put(key, offers: List[InstanceOffer]) -> None:
    if len(_offer_cache) >= _OFFER_CACHE_MAX_ENTRIES:
        # Unbounded distinct shapes would leak; drop expired first, then oldest.
        now = time.monotonic()
        for k in [
            k for k, (ts, _) in _offer_cache.items()
            if now - ts > settings.OFFER_CACHE_TTL
        ]:
            _offer_cache.pop(k, None)
        while len(_offer_cache) >= _OFFER_CACHE_MAX_ENTRIES:
            _offer_cache.pop(next(iter(_offer_cache)), None)
    _offer_cache[key] = (time.monotonic(), offers)


async def get_offers_by_requirements(
    db: Database,
    project_row,
    requirements: Requirements,
    profile: Optional[Profile] = None,
) -> List[InstanceOffer]:
    profile = profile or Profile()
    if settings.OFFER_CACHE_TTL <= 0:
        return await _query_offers(db, project_row, requirements, profile)
    key = (
        project_row["id"],
        requirements.model_dump_json(),
        profile.model_dump_json(),
    )
    cached = _cache_get(key)
    if cached is not None:
        # Shallow copy: callers filter/slice their view without corrupting
        # the cached list (InstanceOffer objects themselves are not mutated).
        return list(cached)
    fut = _inflight.get(key)
    if fut is not None:
        return list(await asyncio.shield(fut))
    fut = asyncio.ensure_future(_query_offers(db, project_row, requirements, profile))
    _inflight[key] = fut
    try:
        offers = await asyncio.shield(fut)
        if _inflight.get(key) is fut:  # not invalidated while querying
            _cache_put(key, offers)
    finally:
        if _inflight.get(key) is fut:
            _inflight.pop(key, None)
    return list(offers)


async def _query_offers(
    db: Database,
    project_row,
    requirements: Requirements,
    profile: Profile,
) -> List[InstanceOffer]:
    from dstack_tpu.core import tracing

    with tracing.span(
        "offers.query",
        histogram="dstack_tpu_offer_query_seconds",
        project=project_row["name"],
    ):
        return await _query_offers_inner(db, project_row, requirements, profile)


async def _query_offers_inner(
    db: Database,
    project_row,
    requirements: Requirements,
    profile: Profile,
) -> List[InstanceOffer]:
    computes = await backends_service.get_project_computes(db, project_row)
    if profile.backends:
        computes = [(t, c) for t, c in computes if t in profile.backends]

    req = requirements
    if profile.spot_policy == SpotPolicy.SPOT:
        req = requirements.model_copy(update={"spot": True})
    elif profile.spot_policy == SpotPolicy.ONDEMAND:
        req = requirements.model_copy(update={"spot": False})

    results = await asyncio.gather(
        *(c.get_offers(req, regions=profile.regions) for _, c in computes),
        return_exceptions=True,
    )
    offers: List[InstanceOffer] = []
    for (backend_type, _), result in zip(computes, results):
        if isinstance(result, BaseException):
            logger.warning("backend %s offers failed: %s", backend_type, result)
            continue
        offers.extend(result)
    if profile.max_price is not None:
        offers = [o for o in offers if o.price <= profile.max_price]
    if profile.instance_types:
        offers = [o for o in offers if o.instance.name in profile.instance_types]
    return sorted(offers, key=lambda o: (o.price, o.backend, o.region))
