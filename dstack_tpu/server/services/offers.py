"""Offer fan-in across project backends (parity: reference server/services/offers.py:
get_offers_by_requirements:26-154)."""

from __future__ import annotations

import asyncio
import logging
from typing import List, Optional

from dstack_tpu.core.models.instances import InstanceOffer
from dstack_tpu.core.models.profiles import Profile, SpotPolicy
from dstack_tpu.core.models.runs import Requirements
from dstack_tpu.server.db import Database
from dstack_tpu.server.services import backends as backends_service

logger = logging.getLogger(__name__)


async def get_offers_by_requirements(
    db: Database,
    project_row,
    requirements: Requirements,
    profile: Optional[Profile] = None,
) -> List[InstanceOffer]:
    profile = profile or Profile()
    computes = await backends_service.get_project_computes(db, project_row)
    if profile.backends:
        computes = [(t, c) for t, c in computes if t in profile.backends]

    req = requirements
    if profile.spot_policy == SpotPolicy.SPOT:
        req = requirements.model_copy(update={"spot": True})
    elif profile.spot_policy == SpotPolicy.ONDEMAND:
        req = requirements.model_copy(update={"spot": False})

    results = await asyncio.gather(
        *(c.get_offers(req, regions=profile.regions) for _, c in computes),
        return_exceptions=True,
    )
    offers: List[InstanceOffer] = []
    for (backend_type, _), result in zip(computes, results):
        if isinstance(result, BaseException):
            logger.warning("backend %s offers failed: %s", backend_type, result)
            continue
        offers.extend(result)
    if profile.max_price is not None:
        offers = [o for o in offers if o.price <= profile.max_price]
    if profile.instance_types:
        offers = [o for o in offers if o.instance.name in profile.instance_types]
    return sorted(offers, key=lambda o: (o.price, o.backend, o.region))
