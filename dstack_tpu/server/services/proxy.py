"""In-server service proxy + per-service request stats (the autoscaler's input).

Parity: reference server/services/proxy/ — routes
``/proxy/services/{project}/{run}/...`` to replica app sockets over the instance
tunnels (proxy/lib/service_connection.py:158), balancing across running replicas;
request counts per window feed the RPS autoscaler (autoscalers.py:60-110).
TPU re-design: replica app ports ride the same per-worker SSH tunnel pool the
runner protocol uses (one extra forward per service port), and on the shared-host
local backend each replica gets an ephemeral port assigned at submit time
(jobs' ``ports_mapping``) so replicas never collide.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import time
from typing import Deque, Dict, List, Optional, Tuple

from aiohttp import web

from dstack_tpu.core import tracing
from dstack_tpu.core.models.runs import JobProvisioningData, JobRuntimeData
from dstack_tpu.server import settings
from dstack_tpu.server.db import Database, loads
from dstack_tpu.server.services.jobs import job_jpd, job_jrd, job_spec as load_job_spec
from dstack_tpu.server.services.locking import get_locker
from dstack_tpu.server.services.runner import ssh as runner_ssh
from dstack_tpu.server.services import routing

logger = logging.getLogger(__name__)

from dstack_tpu.core.services.stats_window import (  # noqa: F401 (re-export)
    STATS_BUCKET,
    STATS_WINDOW,
)


def _wall_offset() -> float:
    """monotonic + offset = wall clock; lets buckets survive process restarts."""
    return time.time() - time.monotonic()


class ServiceStats:
    """Per-run request timestamps. The hot path (record) is in-memory; the
    window is periodically persisted as coarse wall-clock buckets
    (``flush_rows``) and re-primed from them at startup (``prime``) so a server
    restart does not zero the autoscaler's knowledge — the reference gets the
    same durability by tailing nginx access logs
    (proxy/gateway/services/stats.py:41-148)."""

    def __init__(self) -> None:
        self._requests: Dict[str, Deque[float]] = {}
        # (ts, seconds) per completed proxied request — or TTFT for streamed
        # responses: the latency autoscaler's signal (p50/p90, not just RPS).
        self._latencies: Dict[str, Deque[Tuple[float, float]]] = {}
        # (ts, depth) engine-backlog gauge samples, reported by serving
        # replicas via the X-Dstack-Queue-Depth response header and recorded
        # by the proxy in-memory (zero DB cost on the hot path).
        self._queue_depths: Dict[str, Deque[Tuple[float, float]]] = {}
        # Requests currently being forwarded (held-open SSE streams included):
        # the demand signal that stops the autoscaler from scaling a service
        # to zero mid-generation — a long stream leaves no trace in the RPS
        # window after 60s, but it is very much still demand.
        self._inflight: Dict[str, int] = {}
        # run_id -> {gauge name -> (ts, value)}: last-value engine gauges
        # reported by serving replicas via response headers (prefix-cache hit
        # ratio, speculative accept ratio — ENGINE_GAUGE_HEADERS), rendered
        # per service on /metrics.
        self._engine_gauges: Dict[str, Dict[str, Tuple[float, float]]] = {}
        # (run_id, bucket) -> count at last persist; lets each checkpoint write
        # only buckets that changed instead of re-upserting the whole window.
        self.persisted: Dict[Tuple[str, int], int] = {}
        # source (e.g. "gw:<id>") -> {(run_id, wall_bucket): count} — request
        # counts pulled from gateway appliances. Each pull REPLACES its
        # source's map (the appliance keeps the authoritative window), so
        # repeated polls never double-count; not persisted here — a server
        # restart re-pulls from the appliances.
        self._external: Dict[str, Dict[Tuple[str, int], int]] = {}

    def record(self, run_id: str, ts: Optional[float] = None) -> None:
        dq = self._requests.setdefault(run_id, collections.deque())
        dq.append(ts if ts is not None else time.monotonic())
        self._trim(dq)

    def record_latency(self, run_id: str, seconds: float) -> None:
        dq = self._latencies.setdefault(run_id, collections.deque())
        dq.append((time.monotonic(), seconds))
        cutoff = time.monotonic() - STATS_WINDOW
        while dq and dq[0][0] < cutoff:
            dq.popleft()

    def avg_latency(self, run_id: str, window: float = 60.0) -> Optional[float]:
        """Mean end-to-end proxied latency (seconds) over `window`, or None
        when no request completed in it."""
        dq = self._latencies.get(run_id)
        if not dq:
            return None
        cutoff = time.monotonic() - window
        samples = [lat for ts, lat in dq if ts >= cutoff]
        if not samples:
            return None
        return sum(samples) / len(samples)

    def latency_quantiles(
        self, run_id: str, window: float = 60.0
    ) -> Optional[Dict[str, float]]:
        """{"p50", "p90", "mean", "count"} over `window`, or None when no
        request completed in it — the latency autoscaler's primary signal
        (p90 catches the tail the mean hides)."""
        dq = self._latencies.get(run_id)
        if not dq:
            return None
        cutoff = time.monotonic() - window
        samples = sorted(lat for ts, lat in dq if ts >= cutoff)
        if not samples:
            return None
        from dstack_tpu.utils.common import nearest_rank

        return {
            "p50": nearest_rank(samples, 0.50),
            "p90": nearest_rank(samples, 0.90),
            "mean": sum(samples) / len(samples),
            "count": len(samples),
        }

    def record_queue_depth(self, run_id: str, depth: float) -> None:
        dq = self._queue_depths.setdefault(run_id, collections.deque())
        dq.append((time.monotonic(), float(depth)))
        cutoff = time.monotonic() - STATS_WINDOW
        while dq and dq[0][0] < cutoff:
            dq.popleft()

    def queue_depth(self, run_id: str, window: float = 30.0) -> Optional[float]:
        """Max engine queue depth reported over `window` (None = no reports).
        Max, not mean: a backlog spike is exactly what scale-up must see."""
        dq = self._queue_depths.get(run_id)
        if not dq:
            return None
        cutoff = time.monotonic() - window
        samples = [d for ts, d in dq if ts >= cutoff]
        if not samples:
            return None
        return max(samples)

    def record_engine_gauge(self, run_id: str, name: str, value: float) -> None:
        self._engine_gauges.setdefault(run_id, {})[name] = (
            time.monotonic(), float(value)
        )

    def engine_gauges(
        self, run_id: str, window: float = STATS_WINDOW
    ) -> Dict[str, float]:
        """Latest engine-reported gauge per name, or {} when none was seen in
        `window` (a dead replica's stale ratio must age out of /metrics)."""
        cutoff = time.monotonic() - window
        return {
            name: value
            for name, (ts, value) in self._engine_gauges.get(run_id, {}).items()
            if ts >= cutoff
        }

    def record_inflight(self, run_id: str, delta: int) -> None:
        n = self._inflight.get(run_id, 0) + delta
        if n <= 0:
            self._inflight.pop(run_id, None)
        else:
            self._inflight[run_id] = n

    def inflight(self, run_id: str) -> int:
        """Requests currently held open through the proxy for this run."""
        return self._inflight.get(run_id, 0)

    def run_ids(self) -> List[str]:
        """Runs with any window state (requests or latencies) — the public
        surface for exporters; the internal deque layout is not a contract."""
        return sorted(set(self._requests) | set(self._latencies))

    def drop_run(self, run_id: str) -> None:
        """Forget a deleted run's window so per-run state can't grow unbounded."""
        self._requests.pop(run_id, None)
        self._latencies.pop(run_id, None)
        self._queue_depths.pop(run_id, None)
        self._inflight.pop(run_id, None)
        self._engine_gauges.pop(run_id, None)
        for key in [k for k in self.persisted if k[0] == run_id]:
            del self.persisted[key]
        for source_map in self._external.values():
            for key in [k for k in source_map if k[0] == run_id]:
                del source_map[key]

    def set_external(self, source: str, rows) -> None:
        """Replace `source`'s pulled window: rows of (run_id, bucket, count)."""
        self._external[source] = {
            (run_id, int(bucket)): int(count) for run_id, bucket, count in rows
        }

    def drop_external(self, source: str) -> None:
        self._external.pop(source, None)

    def rps(self, run_id: str, window: float = 60.0) -> float:
        n = 0.0
        dq = self._requests.get(run_id)
        if dq:
            self._trim(dq)
            cutoff = time.monotonic() - window
            n += sum(1 for t in dq if t >= cutoff)
        now = time.time()
        wall_cutoff = now - window
        for source_map in self._external.values():
            for (rid, bucket), count in source_map.items():
                if rid != run_id:
                    continue
                # Weight a bucket by how much of its ELAPSED span overlaps the
                # window, so the pulled path matches the deque path's accuracy:
                # a whole trailing-edge bucket would inflate a 60s window by up
                # to STATS_BUCKET/window, while the in-progress bucket's
                # requests all arrived within the window and count fully.
                elapsed = min(bucket + STATS_BUCKET, now) - bucket
                overlap = min(bucket + STATS_BUCKET, now) - max(bucket, wall_cutoff)
                if overlap > 0 and elapsed > 0:
                    n += count * min(overlap / elapsed, 1.0)
        return n / window

    def flush_rows(self) -> List[Tuple[str, int, int]]:
        """(run_id, bucket_epoch, count) rows for the retained window, bucketed
        on the wall clock so another process can reconstruct them."""
        off = _wall_offset()
        out: List[Tuple[str, int, int]] = []
        for run_id, dq in self._requests.items():
            self._trim(dq)
            counts: Dict[int, int] = {}
            for t in dq:
                b = int((t + off) // STATS_BUCKET) * int(STATS_BUCKET)
                counts[b] = counts.get(b, 0) + 1
            out.extend((run_id, b, c) for b, c in sorted(counts.items()))
        return out

    def prime(self, rows) -> None:
        """Rebuild the window from persisted buckets (server restart). Buckets
        older than the window are dropped; each deque is re-sorted so trimming
        stays correct against requests recorded before the prime."""
        off = _wall_offset()
        cutoff = time.monotonic() - STATS_WINDOW
        touched = set()
        for run_id, bucket, count in rows:
            # Mid-bucket placement: a boundary-exact timestamp would re-bucket
            # one slot earlier under ms-scale wall/monotonic jitter, and the
            # shifted row would duplicate the original on the next flush.
            ts = float(bucket) + STATS_BUCKET / 2 - off  # wall -> our monotonic
            if ts < cutoff:
                continue
            dq = self._requests.setdefault(run_id, collections.deque())
            dq.extend([ts] * min(int(count), 100_000))
            touched.add(run_id)
        for run_id in touched:
            self._requests[run_id] = collections.deque(sorted(self._requests[run_id]))

    def _trim(self, dq: Deque[float]) -> None:
        cutoff = time.monotonic() - STATS_WINDOW
        while dq and dq[0] < cutoff:
            dq.popleft()

    def reset(self) -> None:
        self._requests.clear()
        self._latencies.clear()
        self._queue_depths.clear()
        self._inflight.clear()
        self._engine_gauges.clear()
        self.persisted.clear()
        self._external.clear()


stats = ServiceStats()


async def persist_stats(db: Database) -> None:
    """Write the window's changed buckets; expired buckets are swept."""
    rows = stats.flush_rows()
    cutoff = int(time.time() - STATS_WINDOW)
    changed = [(r, b, c) for r, b, c in rows if stats.persisted.get((r, b)) != c]
    if not changed:
        return

    def _tx(conn) -> None:
        conn.execute("DELETE FROM service_stats WHERE bucket < ?", (cutoff,))
        conn.executemany(
            "INSERT INTO service_stats (run_id, bucket, count) VALUES (?, ?, ?)"
            " ON CONFLICT (run_id, bucket) DO UPDATE SET count = excluded.count",
            changed,
        )

    await db.run(_tx)
    for r, b, c in changed:
        stats.persisted[(r, b)] = c
    for key in [k for k in stats.persisted if k[1] < cutoff]:
        del stats.persisted[key]


async def prime_stats(db: Database) -> None:
    """Load the persisted window into the in-process stats (server startup)."""
    rows = await db.fetchall(
        "SELECT run_id, bucket, count FROM service_stats WHERE bucket >= ?",
        (int(time.time() - STATS_WINDOW),),
    )
    stats.prime([(r["run_id"], r["bucket"], r["count"]) for r in rows])

from dstack_tpu.core.services.rate_limit import RateLimiter

rate_limiter = RateLimiter()

# Round-robin cursor per run (swept by forget_run with the rest of the
# per-run state when a run is deleted).
_rr: Dict[str, int] = {}


class RouteEntry:
    """Everything the data plane needs to forward one request, resolved once:
    run identity, parsed configuration bits (auth flag, rate limits), and the
    ready replicas' endpoints AFTER ports_mapping/tunnel resolution — so the
    steady-state request path is an in-memory lookup, zero DB round trips.

    Endpoints are populated lazily, on the first ADMITTED request
    (proxy_request), never at resolve time: an unauthenticated request must
    not cause replica listing or SSH tunnel establishment."""

    __slots__ = (
        "key", "run_id", "project_id", "conf", "limits", "auth", "is_service",
        "endpoints", "n_running", "n_ready", "built_at",
    )

    def __init__(self, key, run_id, project_id, conf) -> None:
        self.key: Tuple[str, str] = key  # (project_name, run_name)
        self.run_id: str = run_id
        self.project_id: str = project_id
        self.conf = conf
        self.limits: List[dict] = [
            l.model_dump(mode="json") for l in getattr(conf, "rate_limits", []) or []
        ]
        self.auth: bool = getattr(conf, "auth", True)
        self.is_service: bool = getattr(conf, "type", None) == "service"
        # None = not yet populated (post-auth, first admitted request).
        self.endpoints: Optional[List[Tuple[str, int]]] = None
        self.n_running: int = 0  # running replicas (ready or not)
        self.n_ready: int = 0    # passed (or not yet given) a readiness probe
        self.built_at: float = time.monotonic()


class RouteTable:
    """Per-run route cache for the service proxy. Entries are invalidated on
    job/run state transitions (set_job_status, scaling, probe flips, run
    deletion) and bounded by a TTL fallback (DSTACK_TPU_PROXY_ROUTE_CACHE_TTL)
    so a missed invalidation hook can only serve stale routes briefly."""

    def __init__(self) -> None:
        self._entries: Dict[Tuple[str, str], RouteEntry] = {}
        self._run_index: Dict[str, Tuple[str, str]] = {}
        # PER-RUN invalidation sequence, keyed only for runs whose endpoint
        # resolution has ever started (mark_build). Fences the awaited part of
        # a build: an invalidation of THIS run mid-resolve discards the result
        # from the cache; unrelated runs' transitions don't touch it. Swept by
        # forget_run along with the other per-run state.
        self._run_seq: Dict[str, int] = {}

    @property
    def ttl(self) -> float:
        return settings.PROXY_ROUTE_CACHE_TTL

    def mark_build(self, run_id: str) -> int:
        """Start fencing `run_id`: returns the current sequence; compare with
        run_seq() after awaited work to detect a concurrent invalidation."""
        return self._run_seq.setdefault(run_id, 0)

    def run_seq(self, run_id: str) -> int:
        return self._run_seq.get(run_id, 0)

    def _bump(self, run_id: str) -> None:
        if run_id in self._run_seq:
            self._run_seq[run_id] += 1

    def get(self, project_name: str, run_name: str) -> Optional[RouteEntry]:
        if self.ttl <= 0:
            return None
        entry = self._entries.get((project_name, run_name))
        if entry is None:
            return None
        if time.monotonic() - entry.built_at > self.ttl:
            self.invalidate(project_name, run_name)
            return None
        return entry

    def put(self, entry: RouteEntry) -> None:
        if self.ttl <= 0:
            return
        self._entries[entry.key] = entry
        self._run_index[entry.run_id] = entry.key

    def invalidate(self, project_name: str, run_name: str) -> None:
        entry = self._entries.pop((project_name, run_name), None)
        if entry is not None:
            self._run_index.pop(entry.run_id, None)
            self._bump(entry.run_id)

    def invalidate_run(self, run_id: str) -> None:
        """Drop the route of the run that just changed state. Cheap no-op for
        runs that were never proxied — every scheduler transition calls this."""
        self._bump(run_id)
        key = self._run_index.pop(run_id, None)
        if key is not None:
            self._entries.pop(key, None)

    def forget_seq(self, run_id: str) -> None:
        self._run_seq.pop(run_id, None)

    def clear(self) -> None:
        for run_id in self._run_seq:
            self._run_seq[run_id] += 1
        self._entries.clear()
        self._run_index.clear()


route_table = RouteTable()


def forget_run(run_id: str, run_name: Optional[str] = None) -> None:
    """Run deleted: drop ALL its per-run proxy state (route entry, build fence,
    round-robin cursor, stats window, rate-limit buckets, latency histogram
    series) so none of it grows unbounded."""
    route_table.invalidate_run(run_id)
    route_table.forget_seq(run_id)
    _rr.pop(run_id, None)
    routing.forget_run(run_id, run_name)
    stats.drop_run(run_id)
    rate_limiter.drop_scope(run_id)
    if run_name:
        tracing.drop_series(
            "dstack_tpu_service_request_latency_seconds", {"run": run_name}
        )
        tracing.drop_series("dstack_tpu_service_ttft_seconds", {"run": run_name})


async def resolve_route(db: Database, project_name: str, run_name: str) -> RouteEntry:
    """Cached route lookup; on miss, rebuilds the identity/spec half of the
    entry (two fetches + one spec validation — the same pre-auth cost the
    legacy path paid). Replica endpoints are NOT resolved here: that happens
    post-auth in proxy_request, so unauthenticated traffic can't drive tunnel
    establishment. Raises 404 for unknown project/run (negatives not cached)."""
    entry = route_table.get(project_name, run_name)
    if entry is not None:
        return entry

    # No fence needed here: after the run row lands there is no await before
    # put(), so an invalidation can't interleave (single-threaded loop), and
    # the DB reads themselves always reflect post-transition state.
    project_row = await db.fetchone(
        "SELECT * FROM projects WHERE name = ? AND deleted = 0", (project_name,)
    )
    if project_row is None:
        raise web.HTTPNotFound(text=f"no project {project_name}")
    run_row = await db.fetchone(
        "SELECT * FROM runs WHERE project_id = ? AND run_name = ? AND deleted = 0",
        (project_row["id"], run_name),
    )
    if run_row is None:
        raise web.HTTPNotFound(text=f"no run {run_name}")

    from dstack_tpu.core.models.runs import RunSpec

    conf = RunSpec.model_validate(loads(run_row["run_spec"])).configuration
    entry = RouteEntry(
        (project_name, run_name), run_row["id"], project_row["id"], conf
    )
    route_table.put(entry)
    return entry


async def _populate_endpoints(db: Database, entry: RouteEntry) -> None:
    """Resolve the entry's ready-replica endpoints (ports_mapping + tunnels).
    Runs once per cached entry, on the first admitted request; if THIS run's
    state transitioned mid-resolve (per-run fence — unrelated runs' churn
    doesn't count), the result serves this request only and the entry is
    dropped so the next request rebuilds fresh."""
    seq = route_table.mark_build(entry.run_id)
    replicas = await list_service_replicas(db, entry.project_id, entry.key[1])
    entry.n_running = len(replicas)
    ready = [
        (jpd, port)
        for _, jpd, jrd, port in replicas
        if jrd is None or jrd.probe_ready is not False
    ]
    entry.n_ready = len(ready)
    endpoints: List[Tuple[str, int]] = []
    for jpd, port in ready:
        try:
            endpoints.append(await replica_endpoint(jpd, port))
        except Exception as e:
            logger.warning("proxy: tunnel to %s failed: %s", jpd.hostname, e)
    entry.endpoints = endpoints
    if seq != route_table.run_seq(entry.run_id):
        route_table.invalidate(*entry.key)

async def list_service_replicas(
    db: Database, project_id: str, run_name: str, ready_only: bool = False
) -> List[Tuple[dict, JobProvisioningData, Optional[JobRuntimeData], int]]:
    """(job_row, jpd, jrd, effective_port) for every RUNNING replica of a service.

    The service socket lives on job 0 of each replica (the slice's worker 0 for
    multi-host services). With ready_only, replicas whose last readiness probe
    failed are dropped — but an un-probed replica (probe_ready None) stays in,
    so traffic flows before the first probe pass."""
    rows = await db.fetchall(
        "SELECT j.* FROM jobs j JOIN runs r ON r.id = j.run_id"
        " WHERE r.project_id = ? AND r.run_name = ? AND r.deleted = 0"
        "   AND j.status = 'running' AND j.job_num = 0",
        (project_id, run_name),
    )
    out = []
    for row in rows:
        spec = load_job_spec(row)
        if spec.service_port is None:
            continue
        jpd = job_jpd(row)
        if jpd is None or jpd.hostname is None:
            continue
        jrd = job_jrd(row)
        if ready_only and jrd is not None and jrd.probe_ready is False:
            continue
        port = spec.service_port
        if jrd is not None and jrd.ports_mapping:
            port = jrd.ports_mapping.get(spec.service_port, port)
        out.append((row, jpd, jrd, port))
    return out


async def collect_service_traces(
    db: Database,
    project_id: str,
    run_name: str,
    request_id: Optional[str] = None,
    trace_id: Optional[str] = None,
    limit: int = 20,
) -> dict:
    """Fan the flight-recorder query (GET /debug/traces) across every running
    replica of a service and merge the results newest-first. A replica that
    fails to answer is reported, not fatal — the debug surface must work
    mid-incident, exactly when some replica is likely sick."""
    import aiohttp

    from dstack_tpu.core.services.http_forward import get_session

    replicas = await list_service_replicas(db, project_id, run_name)
    params = {"limit": str(max(int(limit), 1))}
    if request_id:
        params["request"] = request_id
    if trace_id:
        params["trace"] = trace_id

    async def _fetch_one(jpd: JobProvisioningData, port: int) -> dict:
        try:
            host, eport = await replica_endpoint(jpd, port)
            url = f"http://{host}:{eport}/debug/traces"
            timeout = aiohttp.ClientTimeout(total=5.0)
            async with get_session().get(url, params=params, timeout=timeout) as r:
                if r.status != 200:
                    return {"error": f"HTTP {r.status}", "traces": []}
                return await r.json()
        except (aiohttp.ClientError, OSError, asyncio.TimeoutError, ValueError) as e:
            return {"error": str(e) or type(e).__name__, "traces": []}

    results = await asyncio.gather(
        *(_fetch_one(jpd, port) for _, jpd, _, port in replicas)
    )
    traces: List[dict] = []
    errors: List[dict] = []
    for (row, jpd, _, _), payload in zip(replicas, results):
        replica_num = load_job_spec(row).replica_num
        if payload.get("error"):
            errors.append({"replica": replica_num, "error": payload["error"]})
            continue
        for t in payload.get("traces", []):
            t = dict(t)
            t.setdefault("replica", str(payload.get("replica", replica_num)))
            traces.append(t)
    # Newest-first across the fleet; finished_at is wall-clock, good enough to
    # interleave replicas (per-replica order is already newest-first).
    traces.sort(key=lambda t: t.get("finished_at", 0.0), reverse=True)
    return {
        "run_name": run_name,
        "replicas_queried": len(replicas),
        "errors": errors,
        "traces": traces[: max(int(limit), 1)],
    }


async def probe_service_replicas(db: Database, project_id: str, run_name: str) -> None:
    """Readiness probe per replica socket; outcome lands in
    job_runtime_data.probe_ready (reference service probes/nginx health checks).

    Probes run concurrently (one slow replica must not stall the pass), bound by
    one deadline that covers tunnel establishment too. An `ssh -L` forward
    accepts locally even when the remote connect fails and then closes the
    channel — so after connecting we read briefly: immediate EOF = not ready,
    open-and-quiet (or data) = ready. Writes re-read the row under the run lock
    and change ONLY probe_ready, so they never clobber the pull loop's
    concurrent jrd updates. A flip also refreshes the route table: the next
    request rebuilds its replica endpoints instead of waiting out the TTL."""
    replicas = await list_service_replicas(db, project_id, run_name)
    if not replicas:
        return

    async def _probe_one(
        jpd: JobProvisioningData, port: int
    ) -> Tuple[bool, Optional[Tuple[str, int]]]:
        # The resolved endpoint rides along with the verdict: a flip to
        # not-ready must evict exactly that endpoint from the routing ring
        # (None when resolution itself failed — then the whole ring resets).
        resolved: Dict[str, Tuple[str, int]] = {}

        async def _connect_and_check() -> bool:
            host, eport = await replica_endpoint(jpd, port)
            resolved["ep"] = (host, eport)
            reader, writer = await asyncio.open_connection(host, eport)
            try:
                try:
                    data = await asyncio.wait_for(reader.read(1), timeout=0.5)
                except asyncio.TimeoutError:
                    return True  # open and quiet: a listening app socket
                return bool(data)  # data = alive; EOF = tunnel-relayed refusal
            finally:
                writer.close()
                try:
                    await writer.wait_closed()
                except (ConnectionError, OSError):
                    pass

        try:
            ready = await asyncio.wait_for(_connect_and_check(), timeout=5.0)
        except Exception:
            ready = False  # tunnel failures, refused/timed-out connects alike
        return ready, resolved.get("ep")

    outcomes = await asyncio.gather(
        *(_probe_one(jpd, port) for _, jpd, _, port in replicas)
    )
    for (row, _, _, _), (ready, endpoint) in zip(replicas, outcomes):
        async with get_locker().lock(f"run:{row['run_id']}"):
            fresh = await db.fetchone("SELECT * FROM jobs WHERE id = ?", (row["id"],))
            if fresh is None:
                continue
            jrd = job_jrd(fresh) or JobRuntimeData()
            if jrd.probe_ready != ready:
                logger.info(
                    "service %s replica job %s probe flip: %s -> %s",
                    run_name, fresh["id"],
                    "ready" if jrd.probe_ready else
                    ("unprobed" if jrd.probe_ready is None else "not-ready"),
                    "ready" if ready else "not-ready",
                )
                jrd.probe_ready = ready
                await db.execute(
                    "UPDATE jobs SET job_runtime_data = ? WHERE id = ?",
                    (jrd.model_dump_json(), fresh["id"]),
                )
                route_table.invalidate_run(row["run_id"])
                if not ready:
                    # Routing-ring hygiene: evict the dead replica's bucket
                    # assignments now — prefix affinity must not keep hashing
                    # hot prompts at it until the route TTL runs out.
                    if endpoint is not None:
                        routing.drop_endpoint(row["run_id"], endpoint)
                    else:
                        routing.invalidate_run(row["run_id"])


async def replica_endpoint(jpd: JobProvisioningData, port: int) -> Tuple[str, int]:
    if runner_ssh.tunnel_required(jpd):
        return await runner_ssh.tunneled_app_endpoint(jpd, port)
    return jpd.hostname or "127.0.0.1", port


async def proxy_request(
    request: web.Request,
    db: Database,
    entry: RouteEntry,
    tail: str,
    body: bytes = None,
) -> web.StreamResponse:
    """Forward one HTTP request to a replica; admitted requests are recorded for
    autoscaling (even when no replica is up, so scale-from-zero sees demand).
    `entry` is the resolved route (resolve_route) — the steady-state hot path
    touches only in-memory state before the upstream forward."""
    run_name = entry.key[1]
    if route_table.ttl <= 0:
        # Cache disabled = the pre-fast-path behavior, including its
        # per-request existence guard (with caching on, the deletion hooks
        # own this: forget_run drops the route the moment the run goes).
        run_row = await db.fetchone(
            "SELECT id FROM runs WHERE id = ? AND deleted = 0", (entry.run_id,)
        )
        if run_row is None:
            raise web.HTTPNotFound(text=f"no service run {run_name}")
    # rate_limits: token buckets per configured prefix (reference nginx
    # limit_req). Throttled requests are rejected BEFORE autoscaler accounting —
    # throttled demand must not drive scale-up it can never reach.
    if entry.limits and not rate_limiter.check(entry.run_id, "/" + tail, entry.limits):
        raise web.HTTPTooManyRequests(text="rate limit exceeded")
    stats.record(entry.run_id)

    if entry.endpoints is None:
        await _populate_endpoints(db, entry)
    if not entry.endpoints:
        if entry.n_ready:
            # Replicas looked ready but no tunnel resolved at build time; drop
            # the entry so the next request retries establishment.
            route_table.invalidate(*entry.key)
            raise web.HTTPBadGateway(text="replica unreachable")
        raise web.HTTPServiceUnavailable(
            text=(
                f"service {run_name} replicas are starting (readiness probe pending)"
                if entry.n_running
                else f"service {run_name} has no running replicas"
            )
        )
    cursor = _rr.get(entry.run_id, 0)
    _rr[entry.run_id] = cursor + 1
    # Routing key, computed once per request (services/routing.py): the hash
    # of the prompt's leading tokens/bytes. None (no prompt / non-JSON body)
    # routes round-robin via the cursor above. request.read() caches — the
    # forward path reads the same buffered bytes, so this adds no extra copy.
    if body is None:
        body = await request.read()
    pkey = routing.prefix_key(body)

    from dstack_tpu.core import faults
    from dstack_tpu.core.services.http_forward import forward
    from dstack_tpu.server.services import resilience

    def _pick(endpoints, tried) -> Optional[Tuple[str, int]]:
        """Pick among untried endpoints, preferring ones whose circuit is
        closed; if every candidate's breaker is open, use them anyway —
        degraded service beats refusing outright. Which candidate wins is the
        routing policy's call: prefix-hash affinity with load spill, or the
        round-robin cursor (services/routing.py)."""
        candidates = [ep for ep in endpoints or [] if ep not in tried]
        if not candidates:
            return None
        closed = [
            ep for ep in candidates
            if not resilience.is_open(f"replica:{ep[0]}:{ep[1]}")
        ]
        pool = closed or candidates
        return routing.choose(
            entry.run_id, run_name, pool, endpoints or [], pkey, cursor,
            retrying=bool(tried),
        )

    # One trace id per proxied request, honored end to end: reuse the client's
    # header when present (a caller correlating across services), otherwise
    # mint one. The same id is stamped on the upstream request (the replica's
    # flight recorder keys its entry by it) and echoed back to the client, so
    # `dstack-tpu trace <run>` can go from a slow proxy-side latency straight
    # to the engine stage that caused it.
    trace_id = request.headers.get(tracing.TRACE_HEADER) or tracing.new_trace()

    t0 = time.monotonic()
    started = False  # headers/chunks already relayed: retrying is impossible

    def _on_first_chunk(upstream) -> None:
        # Streamed/SSE responses: the first body chunk is the first token —
        # record TTFT as the latency sample (the full stream duration would
        # poison the autoscaler signal) plus the engine backlog it reported.
        nonlocal started
        started = True
        elapsed = time.monotonic() - t0
        stats.record_latency(entry.run_id, elapsed)
        tracing.observe(
            "dstack_tpu_service_ttft_seconds", elapsed, {"run": run_name}
        )
        _record_queue_depth(entry.run_id, upstream.headers, endpoint=picked)

    stats.record_inflight(entry.run_id, +1)
    try:
        tried: List[Tuple[str, int]] = []
        while True:
            picked = _pick(entry.endpoints, tried)
            if picked is None:
                # Nothing left to try: drop the (re-resolved) entry so the
                # next request rebuilds from live state instead of a route
                # whose only endpoints just failed.
                route_table.invalidate(*entry.key)
                raise web.HTTPBadGateway(text="replica unreachable")
            host, local_port = picked
            target = f"replica:{host}:{local_port}"
            try:
                try:
                    await faults.check("proxy.forward", detail=f"{host}:{local_port}")
                except faults.FaultInjected as e:
                    raise web.HTTPBadGateway(text=f"fault injected: {e}")
                resp = await forward(
                    request, host, local_port, tail, body=body,
                    on_first_chunk=_on_first_chunk,
                    extra_headers={tracing.TRACE_HEADER: trace_id},
                )
                resilience.record_success(target)
                break
            except web.HTTPBadGateway:
                # The endpoint went dark (replica died, tunnel dropped):
                # count it against the replica's breaker and rebuild the route
                # — the 502 hook invalidated it, so the re-resolve reads fresh
                # replica state.
                resilience.record_failure(target)
                route_table.invalidate(*entry.key)
                tried.append(picked)
                if started or len(tried) >= 2:
                    raise
                # One retry against a DIFFERENT ready replica from the
                # refreshed table; nothing was written downstream yet, so the
                # request is safely replayable.
                entry = await resolve_route(db, entry.key[0], entry.key[1])
                if entry.endpoints is None:
                    await _populate_endpoints(db, entry)
    finally:
        stats.record_inflight(entry.run_id, -1)
    if isinstance(resp, web.Response):
        # Buffered (known-length) responses only: for streamed/SSE output
        # forward() returns after the WHOLE stream — TTFT was recorded by the
        # first-chunk hook above instead.
        elapsed = time.monotonic() - t0
        stats.record_latency(entry.run_id, elapsed)
        # Latency distribution for /metrics (fixed-bucket histogram, rendered
        # by services/prometheus). Purely in-memory: the steady-state hot path
        # stays at zero DB queries per request.
        tracing.observe(
            "dstack_tpu_service_request_latency_seconds", elapsed, {"run": run_name}
        )
        _record_queue_depth(entry.run_id, resp.headers, endpoint=picked)
    # Replicas running the dstack serve app echo the trace header themselves
    # (it flows back through forward's header copy); for non-dstack upstreams
    # stamp it here so the client always learns the id its request ran under.
    if tracing.TRACE_HEADER not in resp.headers:
        resp.headers[tracing.TRACE_HEADER] = trace_id
    return resp


QUEUE_DEPTH_HEADER = "X-Dstack-Queue-Depth"

# Tier-2 engine gauges riding the same response-header channel as the queue
# depth: recorded last-value in-memory (zero DB cost on the hot path) and
# rendered per service on /metrics as dstack_tpu_service_<name>.
ENGINE_GAUGE_HEADERS = {
    "X-Dstack-Prefix-Hit-Rate": "prefix_cache_hit_ratio",
    "X-Dstack-Spec-Accept-Rate": "spec_accept_ratio",
}


def _record_queue_depth(run_id: str, headers, endpoint=None) -> None:
    """Serving replicas report engine backlog (and tier-2 engine gauges) on
    every response; an absent or malformed header is simply not a sample.
    With ``endpoint``, the depth is also recorded per replica — the routing
    policy's spill signal (services/routing.py)."""
    raw = headers.get(QUEUE_DEPTH_HEADER)
    if raw is not None:
        try:
            depth = float(raw)
        except (TypeError, ValueError):
            depth = None
        if depth is not None:
            stats.record_queue_depth(run_id, depth)
            if endpoint is not None:
                routing.state.record_queue_depth(run_id, endpoint, depth)
    for header, name in ENGINE_GAUGE_HEADERS.items():
        raw = headers.get(header)
        if raw is None:
            continue
        try:
            stats.record_engine_gauge(run_id, name, float(raw))
        except (TypeError, ValueError):
            pass
