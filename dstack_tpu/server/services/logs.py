"""Log storage: pluggable, file-tree backed by default.

Parity: reference server/services/logs/ (base ABC logs/base.py:47, FileLogStorage
logs/filelog.py). Layout: <LOGS_DIR>/<project_id>/<run_name>/<job id>.jsonl — one JSON
line per log event, append-only, so polling readers can seek by line offset."""

from __future__ import annotations

import abc
import json
import os
from pathlib import Path
from typing import List, Optional

from dstack_tpu.core.models.logs import LogEvent
from dstack_tpu.server import settings


class LogStorage(abc.ABC):
    @abc.abstractmethod
    def write_logs(self, project_id: str, run_name: str, job_id: str, events: List[LogEvent]) -> None: ...

    @abc.abstractmethod
    def poll_logs(
        self,
        project_id: str,
        run_name: str,
        job_id: str,
        start_line: int = 0,
        limit: int = 1000,
    ) -> List[LogEvent]: ...


class FileLogStorage(LogStorage):
    def __init__(self, root: Optional[str] = None):
        self.root = Path(root) if root else settings.LOGS_DIR

    def _path(self, project_id: str, run_name: str, job_id: str) -> Path:
        return self.root / project_id / run_name / f"{job_id}.jsonl"

    def write_logs(self, project_id: str, run_name: str, job_id: str, events: List[LogEvent]) -> None:
        if not events:
            return
        path = self._path(project_id, run_name, job_id)
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            for ev in events:
                f.write(ev.model_dump_json() + "\n")

    def poll_logs(
        self,
        project_id: str,
        run_name: str,
        job_id: str,
        start_line: int = 0,
        limit: int = 1000,
    ) -> List[LogEvent]:
        path = self._path(project_id, run_name, job_id)
        if not path.exists():
            return []
        out: List[LogEvent] = []
        with open(path, "r", encoding="utf-8") as f:
            for i, line in enumerate(f):
                if i < start_line:
                    continue
                if len(out) >= limit:
                    break
                line = line.strip()
                if line:
                    out.append(LogEvent.model_validate(json.loads(line)))
        return out


_storage: Optional[LogStorage] = None


def get_log_storage() -> LogStorage:
    global _storage
    if _storage is None:
        _storage = FileLogStorage()
    return _storage


def set_log_storage(storage: Optional[LogStorage]) -> None:
    global _storage
    _storage = storage
