"""Log storage: pluggable — file tree by default, Cloud Logging as the cloud sink.

Parity: reference server/services/logs/ (base ABC logs/base.py:47, FileLogStorage
logs/filelog.py, GCPLogStorage logs/gcp.py:165). File layout:
<LOGS_DIR>/<project_id>/<run_name>/<job id>.jsonl — one JSON line per log event,
append-only, so polling readers can seek by line offset. Select the sink with
DSTACK_TPU_LOG_STORAGE (unset/`file` | `gcp:<gcp-project-id>`)."""

from __future__ import annotations

import abc
import json
import os
from pathlib import Path
from typing import List, Optional

import pydantic

from dstack_tpu.core.models.logs import LogEvent
from dstack_tpu.server import settings


class LogStorage(abc.ABC):
    @abc.abstractmethod
    def write_logs(self, project_id: str, run_name: str, job_id: str, events: List[LogEvent]) -> None: ...

    @abc.abstractmethod
    def poll_logs(
        self,
        project_id: str,
        run_name: str,
        job_id: str,
        start_line: int = 0,
        limit: int = 1000,
    ) -> List[LogEvent]: ...


class FileLogStorage(LogStorage):
    def __init__(self, root: Optional[str] = None):
        self.root = Path(root) if root else settings.LOGS_DIR
        # Per-stream (line_count, byte_offset) memo of the furthest point a
        # poll has consumed, so tail-polling seeks straight to the new bytes
        # instead of re-reading the file from line 0 every call. A shrunk file
        # resets the memo up front; a same-or-larger replacement is caught by
        # the decode-error rescan fallback in poll_logs.
        # Bounded: least-recently-polled streams are evicted past the cap, so
        # long-dead jobs' memos don't accumulate forever (eviction only costs
        # that stream one full rescan if it is ever polled again).
        self._offsets: dict = {}

    _OFFSETS_CAP = 4096

    def _path(self, project_id: str, run_name: str, job_id: str) -> Path:
        return self.root / project_id / run_name / f"{job_id}.jsonl"

    def write_logs(self, project_id: str, run_name: str, job_id: str, events: List[LogEvent]) -> None:
        if not events:
            return
        path = self._path(project_id, run_name, job_id)
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            for ev in events:
                f.write(ev.model_dump_json() + "\n")

    def poll_logs(
        self,
        project_id: str,
        run_name: str,
        job_id: str,
        start_line: int = 0,
        limit: int = 1000,
    ) -> List[LogEvent]:
        path = self._path(project_id, run_name, job_id)
        key = (project_id, run_name, job_id)
        try:
            size = path.stat().st_size
        except OSError:
            self._offsets.pop(key, None)
            return []
        line_i, byte_off = self._offsets.get(key, (0, 0))
        if byte_off > size or line_i > start_line:
            # File shrank (rotated/truncated) or the caller rewound behind the
            # memo: fall back to a full scan and rebuild the memo.
            line_i, byte_off = 0, 0
        try:
            out, line_i, byte_off = self._scan(path, start_line, limit, line_i, byte_off)
        except (ValueError, pydantic.ValidationError):
            if (line_i, byte_off) == (0, 0):
                raise  # genuinely corrupt file: same failure as a memo-less scan
            # The file was replaced by one of equal-or-larger size (shrink
            # detection can't see that): the memo'd seek landed mid-line.
            # Rescan from the top; only this recovery pass pays the full read.
            out, line_i, byte_off = self._scan(path, start_line, limit, 0, 0)
        # Re-insert at the back: dict order doubles as the LRU for eviction.
        self._offsets.pop(key, None)
        self._offsets[key] = (line_i, byte_off)
        while len(self._offsets) > self._OFFSETS_CAP:
            self._offsets.pop(next(iter(self._offsets)))
        return out

    @staticmethod
    def _scan(path: Path, start_line: int, limit: int, line_i: int, byte_off: int):
        out: List[LogEvent] = []
        # Binary mode so byte offsets are exact (text mode counts decoded chars).
        with open(path, "rb") as f:
            f.seek(byte_off)
            for raw in f:
                if not raw.endswith(b"\n"):
                    break  # partial trailing write; re-read it next poll
                if line_i >= start_line:
                    if len(out) >= limit:
                        break
                    stripped = raw.strip()
                    if stripped:
                        out.append(
                            LogEvent.model_validate(json.loads(stripped.decode("utf-8")))
                        )
                line_i += 1
                byte_off += len(raw)
        return out, line_i, byte_off


class GcpLogStorage(LogStorage):
    """Cloud Logging sink over the JSON API (entries.write / entries.list) —
    SDK-free like the gcp backend; ``request`` is injectable for tests
    (sync (method, url, json) -> (status, dict)). Log name:
    projects/<p>/logs/dstack-tpu-run-logs; labels carry project/run/job plus a
    per-event line number so polling stays offset-based like the file sink."""

    LOG_ID = "dstack-tpu-run-logs"
    API = "https://logging.googleapis.com/v2"

    def __init__(self, gcp_project: str, request=None) -> None:
        self.gcp_project = gcp_project
        self._request = request or self._requests_request
        self._tokens = None
        # Next line number per stream (restart => re-derived from a list call).
        self._lines: dict = {}

    def _requests_request(self, method: str, url: str, payload: dict):
        import requests as _requests

        if self._tokens is None:
            from dstack_tpu.backends.gcp.auth import token_provider_from_creds

            self._tokens = token_provider_from_creds(None)
        import asyncio

        token = asyncio.run(self._tokens.get_token())
        resp = _requests.request(
            method, url, json=payload,
            headers={"Authorization": f"Bearer {token}"}, timeout=30,
        )
        try:
            return resp.status_code, resp.json()
        except ValueError:
            return resp.status_code, {}

    def _stream_key(self, project_id: str, run_name: str, job_id: str) -> str:
        return f"{project_id}/{run_name}/{job_id}"

    def write_logs(self, project_id: str, run_name: str, job_id: str, events: List[LogEvent]) -> None:
        if not events:
            return
        key = self._stream_key(project_id, run_name, job_id)
        next_line = self._lines.get(key, 0)
        entries = []
        for i, ev in enumerate(events):
            entries.append(
                {
                    "logName": f"projects/{self.gcp_project}/logs/{self.LOG_ID}",
                    "resource": {"type": "global"},
                    "timestamp": ev.timestamp.isoformat() if ev.timestamp else None,
                    "labels": {
                        "project_id": project_id,
                        "run_name": run_name,
                        "job_id": job_id,
                        # Zero-padded so the poller can range-filter server-side:
                        # label comparisons are lexicographic strings.
                        "line": f"{next_line + i:012d}",
                    },
                    "jsonPayload": {"message": ev.message, "source": ev.log_source.value},
                }
            )
        status, body = self._request(
            "POST", f"{self.API}/entries:write", {"entries": entries}
        )
        if status >= 400:
            raise RuntimeError(f"Cloud Logging write failed: HTTP {status}: {body}")
        self._lines[key] = next_line + len(events)

    def poll_logs(
        self,
        project_id: str,
        run_name: str,
        job_id: str,
        start_line: int = 0,
        limit: int = 1000,
    ) -> List[LogEvent]:
        flt = (
            f'logName="projects/{self.gcp_project}/logs/{self.LOG_ID}"'
            f' AND labels.project_id="{project_id}"'
            f' AND labels.run_name="{run_name}" AND labels.job_id="{job_id}"'
            # Lexicographic >= on the zero-padded line label skips already-read
            # entries server-side, keeping a tail-poll O(new lines) instead of
            # re-paging the whole stream every call.
            f' AND labels.line>="{start_line:012d}"'
        )
        out: List[LogEvent] = []
        page_token: Optional[str] = None
        # Follow nextPageToken until the window is filled or the sink is
        # exhausted (a single page caps at 1000, which would permanently stall
        # polling for jobs past 1000 lines).
        while len(out) < limit:
            req = {
                "resourceNames": [f"projects/{self.gcp_project}"],
                "filter": flt,
                "orderBy": "timestamp asc",
                "pageSize": 1000,
            }
            if page_token:
                req["pageToken"] = page_token
            status, body = self._request("POST", f"{self.API}/entries:list", req)
            if status >= 400:
                raise RuntimeError(f"Cloud Logging list failed: HTTP {status}: {body}")
            for entry in body.get("entries", []):
                line = int(entry.get("labels", {}).get("line", 0))
                if line < start_line or len(out) >= limit:
                    continue
                payload = entry.get("jsonPayload", {})
                out.append(
                    LogEvent(
                        timestamp=entry.get("timestamp"),
                        message=payload.get("message", ""),
                        log_source=payload.get("source") or "stdout",
                    )
                )
            page_token = body.get("nextPageToken")
            if not page_token:
                break
        return out


_storage: Optional[LogStorage] = None


def get_log_storage() -> LogStorage:
    global _storage
    if _storage is None:
        spec = os.getenv("DSTACK_TPU_LOG_STORAGE", "")
        if spec.startswith("gcp:"):
            _storage = GcpLogStorage(spec.split(":", 1)[1])
        else:
            _storage = FileLogStorage()
    return _storage


def set_log_storage(storage: Optional[LogStorage]) -> None:
    global _storage
    _storage = storage
