"""Volumes service: network data-disk CRUD + attach tracking.

Parity: reference server/services/volumes.py (455 LoC). TPU twist: a volume attaches to
every host of a slice (reference gcp/compute.py:1003-1016 TPU data disks)."""

from __future__ import annotations

import uuid
from typing import List, Optional

from dstack_tpu.core.errors import (
    ResourceExistsError,
    ResourceNotExistsError,
    ServerClientError,
)
from dstack_tpu.core.models.configurations import VolumeConfiguration
from dstack_tpu.core.models.volumes import (
    Volume,
    VolumeAttachment,
    VolumeProvisioningData,
    VolumeStatus,
)
from dstack_tpu.server.db import Database, loads, new_id
from dstack_tpu.utils.common import from_iso, now_utc, to_iso


async def row_to_volume(db: Database, row, project_name: str = "") -> Volume:
    att_rows = await db.fetchall(
        "SELECT va.*, i.name AS instance_name FROM volume_attachments va"
        " JOIN instances i ON i.id = va.instance_id WHERE va.volume_id = ?",
        (row["id"],),
    )
    user = None
    if row["user_id"]:
        urow = await db.fetchone("SELECT username FROM users WHERE id = ?", (row["user_id"],))
        user = urow["username"] if urow else None
    pd = loads(row["provisioning_data"])
    return Volume(
        id=uuid.UUID(row["id"]),
        name=row["name"],
        project_name=project_name,
        user=user,
        configuration=VolumeConfiguration.model_validate(loads(row["configuration"])),
        external=bool(row["external"]),
        created_at=from_iso(row["created_at"]),
        last_job_processed_at=from_iso(row["last_job_processed_at"]),
        status=VolumeStatus(row["status"]),
        status_message=row["status_message"],
        volume_id=row["volume_id"],
        provisioning_data=VolumeProvisioningData.model_validate(pd) if pd else None,
        attachments=[
            VolumeAttachment(
                instance_id=uuid.UUID(a["instance_id"]),
                instance_name=a["instance_name"],
                device_name=(loads(a["attachment_data"]) or {}).get("device_name"),
            )
            for a in att_rows
        ],
    )


async def get_volume_row(db: Database, project_id: str, name: str):
    return await db.fetchone(
        "SELECT * FROM volumes WHERE project_id = ? AND name = ? AND deleted = 0",
        (project_id, name),
    )


async def list_volumes(db: Database, project_row) -> List[Volume]:
    rows = await db.fetchall(
        "SELECT * FROM volumes WHERE project_id = ? AND deleted = 0 ORDER BY created_at",
        (project_row["id"],),
    )
    return [await row_to_volume(db, r, project_row["name"]) for r in rows]


async def get_volume(db: Database, project_row, name: str) -> Volume:
    row = await get_volume_row(db, project_row["id"], name)
    if row is None:
        raise ResourceNotExistsError(f"volume {name} not found")
    return await row_to_volume(db, row, project_row["name"])


async def create_volume(db: Database, project_row, user_row, conf: VolumeConfiguration) -> Volume:
    name = conf.name or f"volume-{new_id()[:8]}"
    if await get_volume_row(db, project_row["id"], name) is not None:
        raise ResourceExistsError(f"volume {name} already exists")
    external = conf.volume_id is not None
    await db.execute(
        "INSERT INTO volumes (id, project_id, user_id, name, status, configuration,"
        " external, created_at, volume_id) VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)",
        (
            new_id(),
            project_row["id"],
            user_row["id"],
            name,
            VolumeStatus.SUBMITTED.value,
            conf.model_dump_json(),
            1 if external else 0,
            to_iso(now_utc()),
            conf.volume_id,
        ),
    )
    row = await get_volume_row(db, project_row["id"], name)
    return await row_to_volume(db, row, project_row["name"])


async def delete_volumes(db: Database, project_row, names: List[str]) -> None:
    for name in names:
        row = await get_volume_row(db, project_row["id"], name)
        if row is None:
            raise ResourceNotExistsError(f"volume {name} not found")
        attached = await db.fetchone(
            "SELECT COUNT(*) AS n FROM volume_attachments WHERE volume_id = ?", (row["id"],)
        )
        if attached["n"] > 0:
            raise ServerClientError(f"volume {name} is attached; detach it first")
        # External (registered) disks are not destroyed in the cloud, only forgotten.
        if not row["external"] and row["status"] == "active":
            from dstack_tpu.server.services import backends as backends_service

            conf = VolumeConfiguration.model_validate(loads(row["configuration"]))
            try:
                compute = await backends_service.get_compute(db, project_row, conf.backend)
            except ResourceNotExistsError:
                compute = None  # backend no longer configured; forget the row
            delete_fn = getattr(compute, "delete_volume", None)
            if delete_fn is not None:
                volume = await row_to_volume(db, row, project_row["name"])
                try:
                    await delete_fn(volume)
                except NotImplementedError:
                    pass  # backend has no volume support; real errors propagate
        await db.execute("UPDATE volumes SET deleted = 1 WHERE id = ?", (row["id"],))
