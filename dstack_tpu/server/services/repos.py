"""Repos service: repo registration + code blob upload.

Parity: reference server/services/repos.py (362 LoC) + CodeModel. The client tars the
working tree (<= MAX_CODE_SIZE, reference settings.py:92) and uploads it keyed by
content hash; the scheduler hands the blob to the runner at submit time."""

from __future__ import annotations

import hashlib
from typing import List, Optional

from dstack_tpu.core.errors import ResourceNotExistsError, ServerClientError
from dstack_tpu.server import settings
from dstack_tpu.server.db import Database, dumps, loads, new_id


async def init_repo(db: Database, project_row, repo_name: str, repo_info: Optional[dict] = None) -> dict:
    row = await db.fetchone(
        "SELECT * FROM repos WHERE project_id = ? AND name = ?",
        (project_row["id"], repo_name),
    )
    if row is None:
        await db.execute(
            "INSERT INTO repos (id, project_id, name, type, info) VALUES (?, ?, ?, ?, ?)",
            (new_id(), project_row["id"], repo_name, "local", dumps(repo_info or {})),
        )
        row = await db.fetchone(
            "SELECT * FROM repos WHERE project_id = ? AND name = ?",
            (project_row["id"], repo_name),
        )
    return {"repo_id": row["name"], "repo_info": loads(row["info"])}


async def list_repos(db: Database, project_row) -> List[dict]:
    rows = await db.fetchall(
        "SELECT * FROM repos WHERE project_id = ? ORDER BY name", (project_row["id"],)
    )
    return [{"repo_id": r["name"], "repo_info": loads(r["info"])} for r in rows]


async def upload_code(db: Database, project_row, repo_name: str, blob: bytes) -> str:
    """Store a code tarball; returns its content hash (idempotent)."""
    if len(blob) > settings.MAX_CODE_SIZE:
        raise ServerClientError(
            f"code archive is {len(blob)} bytes; max is {settings.MAX_CODE_SIZE}"
        )
    repo_row = await db.fetchone(
        "SELECT * FROM repos WHERE project_id = ? AND name = ?",
        (project_row["id"], repo_name),
    )
    if repo_row is None:
        raise ResourceNotExistsError(f"repo {repo_name} not found; run init first")
    blob_hash = hashlib.sha256(blob).hexdigest()
    # Blob offload: with DSTACK_TPU_STORAGE configured, the bytes live in the
    # object store and the DB keeps only the hash (reference services/storage/).
    from dstack_tpu.server.services import storage as storage_service

    store = storage_service.get_storage()
    stored_blob = blob
    if store is not None:
        await store.put(code_blob_key(project_row["id"], repo_name, blob_hash), blob)
        stored_blob = None
    await db.execute(
        "INSERT INTO codes (id, repo_id, blob_hash, blob) VALUES (?, ?, ?, ?)"
        " ON CONFLICT (repo_id, blob_hash) DO NOTHING",
        (new_id(), repo_row["id"], blob_hash, stored_blob),
    )
    return blob_hash


def code_blob_key(project_id: str, repo_name: str, blob_hash: str) -> str:
    return f"codes/{project_id}/{repo_name}/{blob_hash}"
