"""Prometheus text exposition for the control plane.

Parity: reference server/services/prometheus.py:31 (get_metrics: instance, run,
and per-job gauges rendered for scraping). Rendered by hand — the exposition
format is a dozen lines of text; no client library needed. TPU re-design: the
per-job hardware gauges are TPU duty-cycle / HBM (from the agents' runtime
scrape) instead of per-GPU DCGM series.
"""

from __future__ import annotations

import json
from typing import Dict, List, Tuple

from dstack_tpu.server.db import Database


def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(name: str, help_: str, type_: str, samples: List[Tuple[Dict[str, str], float]]) -> str:
    lines = [f"# HELP {name} {help_}", f"# TYPE {name} {type_}"]
    for labels, value in samples:
        if labels:
            inner = ",".join(f'{k}="{_esc(v)}"' for k, v in sorted(labels.items()))
            lines.append(f"{name}{{{inner}}} {value:g}")
        else:
            lines.append(f"{name} {value:g}")
    return "\n".join(lines)


async def render_metrics(db: Database) -> str:
    sections = []

    rows = await db.fetchall(
        "SELECT p.name AS project, r.status, COUNT(*) AS n FROM runs r"
        " JOIN projects p ON p.id = r.project_id"
        " WHERE r.deleted = 0 GROUP BY p.name, r.status"
    )
    sections.append(
        _fmt(
            "dstack_tpu_runs_total",
            "Runs by project and status",
            "gauge",
            [({"project": r["project"], "status": r["status"]}, float(r["n"])) for r in rows],
        )
    )

    rows = await db.fetchall(
        "SELECT backend, status, COUNT(*) AS n FROM instances"
        " WHERE status NOT IN ('terminated') GROUP BY backend, status"
    )
    sections.append(
        _fmt(
            "dstack_tpu_instances_total",
            "Slice worker instances by backend and status",
            "gauge",
            [({"backend": r["backend"] or "", "status": r["status"]}, float(r["n"])) for r in rows],
        )
    )

    rows = await db.fetchall(
        "SELECT instance_type, price FROM instances"
        " WHERE status IN ('idle', 'busy', 'provisioning')"
    )
    cost_by_type: Dict[str, float] = {}
    for r in rows:
        itype = json.loads(r["instance_type"]) if r["instance_type"] else {}
        name = itype.get("name") or ""
        cost_by_type[name] = cost_by_type.get(name, 0.0) + float(r["price"] or 0.0)
    sections.append(
        _fmt(
            "dstack_tpu_instance_price_dollars_per_hour",
            "Active provisioned capacity price by instance type",
            "gauge",
            [({"instance_type": k}, v) for k, v in sorted(cost_by_type.items())],
        )
    )

    # Per-running-job latest sample (cpu micro is a counter; TPU gauges as-is).
    rows = await db.fetchall(
        "SELECT j.run_name, j.job_num, j.replica_num, m.cpu_usage_micro,"
        "       m.memory_usage_bytes, m.tpu"
        " FROM jobs j JOIN job_metrics_points m ON m.job_id = j.id"
        " WHERE j.status = 'running'"
        "   AND m.timestamp = (SELECT MAX(timestamp) FROM job_metrics_points WHERE job_id = j.id)"
    )
    cpu, mem, duty, hbm = [], [], [], []
    for r in rows:
        labels = {
            "run": r["run_name"],
            "job": str(r["job_num"]),
            "replica": str(r["replica_num"]),
        }
        cpu.append((labels, float(r["cpu_usage_micro"]) / 1e6))
        mem.append((labels, float(r["memory_usage_bytes"])))
        tpu = json.loads(r["tpu"]) if r["tpu"] else {}
        if tpu.get("duty_cycle_percent") is not None:
            duty.append((labels, float(tpu["duty_cycle_percent"])))
        if tpu.get("hbm_usage_bytes") is not None:
            hbm.append((labels, float(tpu["hbm_usage_bytes"])))
    sections.append(
        _fmt("dstack_tpu_job_cpu_seconds_total", "Job CPU time consumed", "counter", cpu)
    )
    sections.append(
        _fmt("dstack_tpu_job_memory_usage_bytes", "Job resident memory", "gauge", mem)
    )
    sections.append(
        _fmt("dstack_tpu_job_tpu_duty_cycle_percent", "TPU duty cycle", "gauge", duty)
    )
    sections.append(
        _fmt("dstack_tpu_job_tpu_hbm_usage_bytes", "TPU HBM in use", "gauge", hbm)
    )

    # HTTP request metrics from the middleware (services/request_metrics.py).
    from dstack_tpu.server.services import request_metrics

    req_counts, req_durations = [], []
    for (method, route, status), count, dur in request_metrics.snapshot():
        labels = {"method": method, "route": route, "status": str(status)}
        req_counts.append((labels, float(count)))
        req_durations.append((labels, dur))
    sections.append(
        _fmt("dstack_tpu_http_requests_total", "API requests served", "counter", req_counts)
    )
    sections.append(
        _fmt(
            "dstack_tpu_http_request_duration_seconds_total",
            "Cumulative API request wall time",
            "counter",
            req_durations,
        )
    )

    # Service data-plane window (services/proxy.py ServiceStats): the same RPS
    # the autoscaler scales on, plus mean proxied latency over the last minute.
    from dstack_tpu.server.services import proxy as proxy_service

    run_ids = proxy_service.stats.run_ids()
    svc_rps, svc_latency = [], []
    if run_ids:
        rows = await db.fetch_in(
            "SELECT id, run_name FROM runs WHERE deleted = 0 AND id IN ({in})", run_ids
        )
        for r in rows:
            labels = {"run": r["run_name"]}
            svc_rps.append((labels, proxy_service.stats.rps(r["id"])))
            latency = proxy_service.stats.avg_latency(r["id"])
            if latency is not None:
                svc_latency.append((labels, latency))
    sections.append(
        _fmt(
            "dstack_tpu_service_requests_per_second",
            "Proxied service RPS over the trailing minute",
            "gauge",
            svc_rps,
        )
    )
    sections.append(
        _fmt(
            "dstack_tpu_service_request_latency_seconds",
            "Mean proxied request latency over the trailing minute",
            "gauge",
            svc_latency,
        )
    )

    return "\n".join(sections) + "\n"
