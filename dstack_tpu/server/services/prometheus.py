"""Prometheus text exposition for the control plane.

Parity: reference server/services/prometheus.py:31 (get_metrics: instance, run,
and per-job gauges rendered for scraping). Rendered by hand — the exposition
format is a dozen lines of text; no client library needed. TPU re-design: the
per-job hardware gauges are TPU duty-cycle / HBM (from the agents' runtime
scrape) instead of per-GPU DCGM series.

Beyond the gauges, the tracing layer's fixed-bucket histograms
(core/tracing.py — run phase durations, scheduler pass durations, runner/SSH
round trips, proxied request latency) render as real ``_bucket``/``_sum``/
``_count`` families, and each background loop exports its scheduling lag.
A strict exposition-parser test (tests/test_run_events.py) validates every
family emitted here, since hand-rendering is exactly where format drift creeps
in.
"""

from __future__ import annotations

import json
import os
import time
import weakref
from typing import Dict, List, Tuple

from dstack_tpu.core import tracing
from dstack_tpu.server.db import Database

# The workload gauges (mfu / tokens_per_sec / goodput ledger) re-derive from
# the full TTL window of step+mark points; a short per-Database cache keeps a
# tight scrape interval from recomputing N runs' ledgers on the event loop
# every 15 s. Collection itself runs every PROCESS_METRICS_INTERVAL (10 s),
# so a 5 s cache loses no freshness that exists to lose.
_WORKLOAD_GAUGE_CACHE_TTL = float(os.getenv("DSTACK_TPU_WORKLOAD_GAUGE_CACHE_TTL", "5"))
_workload_gauge_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _esc_help(v: str) -> str:
    # HELP text escapes only backslash and newline (labels also escape quotes).
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _sample(name: str, labels: Dict[str, str], value: float) -> str:
    if labels:
        inner = ",".join(f'{k}="{_esc(v)}"' for k, v in sorted(labels.items()))
        return f"{name}{{{inner}}} {value:g}"
    return f"{name} {value:g}"


def _fmt(name: str, help_: str, type_: str, samples: List[Tuple[Dict[str, str], float]]) -> str:
    lines = [f"# HELP {name} {_esc_help(help_)}", f"# TYPE {name} {type_}"]
    for labels, value in samples:
        lines.append(_sample(name, labels, value))
    return "\n".join(lines)


# Histogram families always advertised (HELP/TYPE) even before the first
# observation, so scrapers and dashboards can discover them from a cold server.
_HISTOGRAM_HELP = {
    "dstack_tpu_run_queue_wait_seconds": "Time jobs spent queued (submitted -> placement)",
    "dstack_tpu_run_provision_duration_seconds": "Time jobs spent provisioning (placement -> runner submit)",
    "dstack_tpu_run_pull_duration_seconds": "Time jobs spent pulling (runner submit -> running)",
    "dstack_tpu_scheduler_pass_duration_seconds": "Scheduler background pass wall time",
    "dstack_tpu_service_request_latency_seconds": "Proxied service request latency",
    "dstack_tpu_runner_call_seconds": "Runner agent round-trip time by op",
    "dstack_tpu_offer_query_seconds": "Offer fan-in query time across project backends",
    "dstack_tpu_backend_create_slice_seconds": "Cloud slice provisioning call time",
    "dstack_tpu_ssh_tunnel_open_seconds": "SSH tunnel establishment time",
    "dstack_tpu_run_step_seconds": "Workload-reported training step wall time by run",
    "dstack_tpu_run_recovery_seconds": "Preemption rescue time-to-recover (failure detected -> gang-retried replica running) by run",
    "dstack_tpu_service_ttft_seconds": "Proxy-observed time to first streamed chunk (TTFT) by run",
    # Serving-engine request-lifecycle families (workloads/serve.py
    # SERVE_HISTOGRAM_HELP — kept in sync by tests/test_metrics_lint.py; not
    # imported, the serve module pulls JAX). Observed in this process when an
    # engine runs in-process (tests, smoke); real replicas also expose them on
    # their own GET /metrics.
    "dstack_tpu_serve_queue_wait_seconds": "Engine admission-queue wait (request enqueued -> slot admitted) by replica",
    "dstack_tpu_serve_prefill_seconds": "Prefill span (first prefill chunk launched -> first token) by replica",
    "dstack_tpu_serve_ttft_seconds": "Engine-side time-to-first-token (enqueued -> first token) by replica",
    "dstack_tpu_serve_itl_seconds": "Inter-token latency between consecutive generated tokens by replica",
    "dstack_tpu_serve_decode_tokens_per_s": "Per-request decode throughput (generated tokens over the decode span) by replica",
    "dstack_tpu_serve_step_stage_seconds": "Engine step time split by stage (admit/prefill/decode) by replica",
}


def _fmt_histogram(name: str, help_: str) -> str:
    lines = [f"# HELP {name} {_esc_help(help_)}", f"# TYPE {name} histogram"]
    snap = tracing.histogram_snapshot(name)
    if snap is not None:
        buckets, series = snap
        for labels, cumulative, total, count in series:
            for le, c in zip([f"{b:g}" for b in buckets] + ["+Inf"], cumulative):
                lines.append(_sample(f"{name}_bucket", {**labels, "le": le}, c))
            lines.append(_sample(f"{name}_sum", labels, total))
            lines.append(_sample(f"{name}_count", labels, count))
    return "\n".join(lines)


async def render_metrics(db: Database) -> str:
    sections = []

    rows = await db.fetchall(
        "SELECT p.name AS project, r.status, COUNT(*) AS n FROM runs r"
        " JOIN projects p ON p.id = r.project_id"
        " WHERE r.deleted = 0 GROUP BY p.name, r.status"
    )
    sections.append(
        _fmt(
            "dstack_tpu_runs_total",
            "Runs by project and status",
            "gauge",
            [({"project": r["project"], "status": r["status"]}, float(r["n"])) for r in rows],
        )
    )

    rows = await db.fetchall(
        "SELECT backend, status, COUNT(*) AS n FROM instances"
        " WHERE status NOT IN ('terminated') GROUP BY backend, status"
    )
    sections.append(
        _fmt(
            "dstack_tpu_instances_total",
            "Slice worker instances by backend and status",
            "gauge",
            [({"backend": r["backend"] or "", "status": r["status"]}, float(r["n"])) for r in rows],
        )
    )

    rows = await db.fetchall(
        "SELECT instance_type, price FROM instances"
        " WHERE status IN ('idle', 'busy', 'provisioning')"
    )
    cost_by_type: Dict[str, float] = {}
    for r in rows:
        itype = json.loads(r["instance_type"]) if r["instance_type"] else {}
        name = itype.get("name") or ""
        cost_by_type[name] = cost_by_type.get(name, 0.0) + float(r["price"] or 0.0)
    sections.append(
        _fmt(
            "dstack_tpu_instance_price_dollars_per_hour",
            "Active provisioned capacity price by instance type",
            "gauge",
            [({"instance_type": k}, v) for k, v in sorted(cost_by_type.items())],
        )
    )

    # Fleet accounting (ISSUE 19, services/usage.py): chips by state, per-
    # project allocation/queue/usage, and the scheduler's pending reasons.
    # All families render cold; the per-project series die with their project
    # (delete_projects sweeps the ledger) and pending reasons live in the
    # usage registry, swept on placement/terminal/delete.
    from dstack_tpu.server.services import usage as usage_service

    rows = await db.fetchall(
        "SELECT status, instance_type FROM instances"
        " WHERE status IN ('pending', 'provisioning', 'idle', 'busy')"
    )
    fleet_chips = {"allocated": 0, "idle": 0, "provisioning": 0}
    for r in rows:
        state = {"busy": "allocated", "idle": "idle"}.get(r["status"], "provisioning")
        fleet_chips[state] += usage_service.job_chips(r["instance_type"])
    sections.append(
        _fmt(
            "dstack_tpu_fleet_chips",
            "TPU chips in the fleet by state (allocated = busy workers,"
            " provisioning includes pending)",
            "gauge",
            [({"state": k}, float(v)) for k, v in sorted(fleet_chips.items())],
        )
    )
    rows = await db.fetchall(
        "SELECT p.name AS project, i.instance_type FROM instances i"
        " JOIN projects p ON p.id = i.project_id"
        " WHERE i.status = 'busy' AND p.deleted = 0"
    )
    alloc_by_project: Dict[str, int] = {}
    for r in rows:
        alloc_by_project[r["project"]] = alloc_by_project.get(
            r["project"], 0
        ) + usage_service.job_chips(r["instance_type"])
    sections.append(
        _fmt(
            "dstack_tpu_project_allocated_chips",
            "TPU chips currently allocated (busy workers) by project",
            "gauge",
            [({"project": k}, float(v)) for k, v in sorted(alloc_by_project.items())],
        )
    )
    rows = await db.fetchall(
        "SELECT p.name AS project, COUNT(*) AS n FROM runs r"
        " JOIN projects p ON p.id = r.project_id"
        " WHERE r.deleted = 0 AND r.status IN ('pending', 'submitted')"
        " GROUP BY p.name"
    )
    sections.append(
        _fmt(
            "dstack_tpu_project_queued_runs",
            "Runs waiting for placement by project",
            "gauge",
            [({"project": r["project"]}, float(r["n"])) for r in rows],
        )
    )
    rows = await db.fetchall(
        "SELECT p.name AS project, SUM(u.chip_seconds) AS cs FROM usage_samples u"
        " JOIN projects p ON p.id = u.project_id"
        " WHERE p.deleted = 0 GROUP BY p.name"
    )
    sections.append(
        _fmt(
            "dstack_tpu_project_chip_seconds_total",
            "Chip-seconds attributed to the project's runs (ledger sum;"
            " resets when runs or the project are deleted)",
            "counter",
            [({"project": r["project"]}, float(r["cs"] or 0.0)) for r in rows],
        )
    )
    sections.append(
        _fmt(
            "dstack_tpu_run_pending_reason",
            "1 while the submitted run's latest placement pass failed for"
            " this reason (no_offers / no_capacity / breaker_open /"
            " slice_busy / quota_reserved)",
            "gauge",
            [
                ({"run": e["run"], "reason": e["reason"]}, 1.0)
                for e in usage_service.pending_snapshot()
            ],
        )
    )

    # Per-running-job latest sample (cpu micro is a counter; TPU gauges as-is).
    # One grouped join resolves every job's newest point: the correlated
    # MAX(timestamp) subquery this replaces re-scanned job_metrics_points once
    # per running job, so /metrics degraded linearly with fleet size.
    rows = await db.fetchall(
        "SELECT j.run_name, j.job_num, j.replica_num, m.cpu_usage_micro,"
        "       m.memory_usage_bytes, m.tpu"
        " FROM jobs j"
        " JOIN (SELECT job_id, MAX(timestamp) AS ts FROM job_metrics_points"
        "       GROUP BY job_id) latest ON latest.job_id = j.id"
        " JOIN job_metrics_points m ON m.job_id = j.id AND m.timestamp = latest.ts"
        " WHERE j.status = 'running'"
    )
    cpu, mem, duty, hbm = [], [], [], []
    for r in rows:
        labels = {
            "run": r["run_name"],
            "job": str(r["job_num"]),
            "replica": str(r["replica_num"]),
        }
        cpu.append((labels, float(r["cpu_usage_micro"]) / 1e6))
        mem.append((labels, float(r["memory_usage_bytes"])))
        tpu = json.loads(r["tpu"]) if r["tpu"] else {}
        if tpu.get("duty_cycle_percent") is not None:
            duty.append((labels, float(tpu["duty_cycle_percent"])))
        if tpu.get("hbm_usage_bytes") is not None:
            hbm.append((labels, float(tpu["hbm_usage_bytes"])))
    sections.append(
        _fmt("dstack_tpu_job_cpu_seconds_total", "Job CPU time consumed", "counter", cpu)
    )
    sections.append(
        _fmt("dstack_tpu_job_memory_usage_bytes", "Job resident memory", "gauge", mem)
    )
    sections.append(
        _fmt("dstack_tpu_job_tpu_duty_cycle_percent", "TPU duty cycle", "gauge", duty)
    )
    sections.append(
        _fmt("dstack_tpu_job_tpu_hbm_usage_bytes", "TPU HBM in use", "gauge", hbm)
    )

    # Workload telemetry (workload_metrics_points via the agents' sidecar
    # tails): per-running-run latest step gauges + the goodput ledger ratio.
    # The lead lineage (job 0 / replica 0) represents the run — a gang's N
    # hosts emit N copies of the same step stream (see services/metrics.py
    # get_run_workload_metrics). The run must be live (some job running) but
    # the points span EVERY lead submission: a preemption's prior lineage and
    # the restart gap are exactly what the goodput gauge exists to show.
    # Only step/mark kinds feed these families — engine/emitter rows are
    # skipped at the SQL layer (they can dominate a serving run's window).
    cached = _workload_gauge_cache.get(db)
    if cached is not None and time.monotonic() - cached[0] < _WORKLOAD_GAUGE_CACHE_TTL:
        mfu, tok_s, goodput = cached[1]
    else:
        rows = await db.fetchall(
            "SELECT j.run_name AS run, w.kind, w.data"
            " FROM workload_metrics_points w JOIN jobs j ON j.id = w.job_id"
            " WHERE j.job_num = 0 AND j.replica_num = 0"
            "   AND w.kind IN ('step', 'mark')"
            "   AND j.run_id IN (SELECT DISTINCT run_id FROM jobs WHERE status = 'running')"
            " ORDER BY w.timestamp ASC"
        )
        run_points: Dict[str, List[dict]] = {}
        for r in rows:
            try:
                run_points.setdefault(r["run"], []).append(json.loads(r["data"]))
            except ValueError:
                continue
        mfu, tok_s, goodput = [], [], []
        from dstack_tpu.server.services.metrics import compute_goodput

        for run_name in sorted(run_points):
            points = run_points[run_name]
            labels = {"run": run_name}
            steps = [p for p in points if p.get("kind") == "step"]
            if steps:
                latest = steps[-1]
                if latest.get("mfu") is not None:
                    mfu.append((labels, float(latest["mfu"])))
                if latest.get("tokens_per_sec") is not None:
                    tok_s.append((labels, float(latest["tokens_per_sec"])))
            ledger = compute_goodput(points)
            if ledger["ratio"] is not None:
                goodput.append((labels, float(ledger["ratio"])))
        _workload_gauge_cache[db] = (time.monotonic(), (mfu, tok_s, goodput))
    sections.append(
        _fmt(
            "dstack_tpu_run_mfu",
            "Latest workload-reported model FLOPs utilization (0-1) by run",
            "gauge",
            mfu,
        )
    )
    sections.append(
        _fmt(
            "dstack_tpu_run_tokens_per_sec",
            "Latest workload-reported training throughput by run",
            "gauge",
            tok_s,
        )
    )
    sections.append(
        _fmt(
            "dstack_tpu_run_goodput_ratio",
            "Productive step time over wall clock (goodput ledger) by run",
            "gauge",
            goodput,
        )
    )

    # Gang health (services/gang_health.py): per-run cross-host step skew,
    # straggler flags, and per-host hardware/wait attribution. Rendered from
    # the collection-pass snapshot — a scrape costs no query, and runs that
    # finish drop out when the next pass rebuilds it. The goodput/step
    # families above stay lead-lineage-only; these are the ONLY families that
    # fan out per host.
    from dstack_tpu.server.services import gang_health

    skew_samples, straggler_samples = [], []
    host_cpu, host_mem, host_coll = [], [], []
    dropped_samples, write_error_samples = [], []
    for entry in gang_health.snapshot():
        run_labels = {"run": entry["run"]}
        if entry.get("skew_ratio") is not None:
            skew_samples.append((run_labels, float(entry["skew_ratio"])))
        flagged = set(entry.get("flagged") or ())
        for host in entry.get("hosts") or ():
            labels = {"run": entry["run"], "host": host["host"]}
            straggler_samples.append((labels, 1.0 if host["host"] in flagged else 0.0))
            if host.get("cpu_percent") is not None:
                host_cpu.append((labels, float(host["cpu_percent"])))
            if host.get("mem_bytes") is not None:
                host_mem.append((labels, float(host["mem_bytes"])))
            if host.get("collective_wait_s") is not None:
                host_coll.append((labels, float(host["collective_wait_s"])))
        if entry.get("dropped"):
            dropped_samples.append((run_labels, float(entry["dropped"])))
        if entry.get("write_errors"):
            write_error_samples.append((run_labels, float(entry["write_errors"])))
    sections.append(
        _fmt(
            "dstack_tpu_run_step_skew_ratio",
            "Slowest-host median step time over the gang median (1.0 = healthy) by run",
            "gauge",
            skew_samples,
        )
    )
    sections.append(
        _fmt(
            "dstack_tpu_run_straggler",
            "1 while the host is flagged as the run's straggler (hysteresis rule)",
            "gauge",
            straggler_samples,
        )
    )
    sections.append(
        _fmt(
            "dstack_tpu_host_cpu_percent",
            "Host CPU utilization sampled by the runner agent, by run and host",
            "gauge",
            host_cpu,
        )
    )
    sections.append(
        _fmt(
            "dstack_tpu_host_mem_bytes",
            "Host memory in use sampled by the runner agent, by run and host",
            "gauge",
            host_mem,
        )
    )
    sections.append(
        _fmt(
            "dstack_tpu_host_collective_wait_seconds",
            "Mean per-step collective fence wait over the trailing window, by run and host",
            "gauge",
            host_coll,
        )
    )
    # Emitter self-reported loss: points dropped on buffer overflow and
    # sidecar flush failures, summed across the run's hosts (cumulative
    # per-process counters -> counter semantics; invisible outside the JSONL
    # stream before this).
    sections.append(
        _fmt(
            "dstack_tpu_run_telemetry_dropped_points_total",
            "Telemetry points dropped by the run's emitters (buffer overflow or failed flush)",
            "counter",
            dropped_samples,
        )
    )
    sections.append(
        _fmt(
            "dstack_tpu_run_telemetry_write_errors_total",
            "Sidecar flush failures reported by the run's emitters",
            "counter",
            write_error_samples,
        )
    )

    # HTTP request metrics from the middleware (services/request_metrics.py).
    from dstack_tpu.server.services import request_metrics

    req_counts, req_durations = [], []
    for (method, route, status), count, dur in request_metrics.snapshot():
        labels = {"method": method, "route": route, "status": str(status)}
        req_counts.append((labels, float(count)))
        req_durations.append((labels, dur))
    sections.append(
        _fmt("dstack_tpu_http_requests_total", "API requests served", "counter", req_counts)
    )
    sections.append(
        _fmt(
            "dstack_tpu_http_request_duration_seconds_total",
            "Cumulative API request wall time",
            "counter",
            req_durations,
        )
    )

    # Service data-plane window (services/proxy.py ServiceStats): the same RPS
    # the autoscaler scales on. Latency is no longer a mean-only gauge — the
    # dstack_tpu_service_request_latency_seconds HISTOGRAM below carries the
    # full distribution (the in-memory avg_latency window remains the
    # autoscaler's signal; only the exposition changed).
    from dstack_tpu.server.services import proxy as proxy_service

    run_ids = proxy_service.stats.run_ids()
    svc_rps = []
    if run_ids:
        rows = await db.fetch_in(
            "SELECT id, run_name FROM runs WHERE deleted = 0 AND id IN ({in})", run_ids
        )
        for r in rows:
            svc_rps.append(({"run": r["run_name"]}, proxy_service.stats.rps(r["id"])))
    run_names = {r["id"]: r["run_name"] for r in rows} if run_ids else {}
    sections.append(
        _fmt(
            "dstack_tpu_service_requests_per_second",
            "Proxied service RPS over the trailing minute",
            "gauge",
            svc_rps,
        )
    )

    # The proxy's sliding-window views, previously internal-only deques the
    # autoscaler read: max queue depth in the trailing window and the windowed
    # latency quantiles. The histogram families carry the full cumulative
    # distribution; these gauges are the autoscaler's actual decision inputs,
    # exported so a scale decision is explainable from /metrics alone.
    svc_qd, svc_lat_q = [], []
    for run_id, run_name in run_names.items():
        labels = {"run": run_name}
        depth = proxy_service.stats.queue_depth(run_id)
        if depth is not None:
            svc_qd.append((labels, float(depth)))
        quantiles = proxy_service.stats.latency_quantiles(run_id)
        if quantiles and quantiles.get("count"):
            for q in ("p50", "p90"):
                if quantiles.get(q) is not None:
                    svc_lat_q.append(({**labels, "quantile": q}, float(quantiles[q])))
    sections.append(
        _fmt(
            "dstack_tpu_service_queue_depth",
            "Max replica-reported engine queue depth over the trailing window, by run",
            "gauge",
            svc_qd,
        )
    )
    sections.append(
        _fmt(
            "dstack_tpu_service_latency_window_seconds",
            "Proxied request latency quantiles over the trailing window, by run",
            "gauge",
            svc_lat_q,
        )
    )

    # Tier-2 serving-engine gauges reported by replicas on response headers
    # (proxy ENGINE_GAUGE_HEADERS): prefix-cache hit ratio and speculative-
    # decode accept ratio, last value per run within the stats window.
    engine_families = {
        "prefix_cache_hit_ratio": (
            "dstack_tpu_service_prefix_cache_hit_ratio",
            "Fraction of admitted prompt tokens served from the engine's prefix cache",
        ),
        "spec_accept_ratio": (
            "dstack_tpu_service_spec_accept_ratio",
            "Fraction of speculative draft tokens accepted by the verify step",
        ),
    }
    engine_samples: Dict[str, List[Tuple[Dict[str, str], float]]] = {
        key: [] for key in engine_families
    }
    for run_id, run_name in run_names.items():
        for name, value in proxy_service.stats.engine_gauges(run_id).items():
            if name in engine_samples:
                engine_samples[name].append(({"run": run_name}, value))
    for key, (family, help_) in engine_families.items():
        sections.append(_fmt(family, help_, "gauge", engine_samples[key]))

    # Cache-aware routing decisions (services/routing.py): counted per run
    # name at decision time, so rendering needs no run-id join and the family
    # is visible even for runs whose stats window has aged out.
    from dstack_tpu.server.services import routing as routing_service

    sections.append(
        _fmt(
            "dstack_tpu_proxy_routing_decisions_total",
            "Replica routing decisions by run, policy, and outcome"
            " (preferred=prefix-hash owner took it, spilled=owner over the"
            " queue-depth bound, fallback=round-robin)",
            "counter",
            [
                ({"run": run, "policy": policy, "outcome": outcome}, float(n))
                for (run, policy, outcome), n in sorted(
                    routing_service.state.decisions().items()
                )
            ],
        )
    )

    # Control-plane fault-tolerance surfaces: who owns which runs (lease
    # sharding across server replicas) and which external targets are
    # circuit-broken. Both families render even when empty so dashboards can
    # discover them from a cold server.
    rows = await db.fetchall(
        "SELECT owner, COUNT(*) AS n FROM run_leases GROUP BY owner ORDER BY owner"
    )
    sections.append(
        _fmt(
            "dstack_tpu_run_leases",
            "Live run leases held, by owner replica",
            "gauge",
            [({"owner": r["owner"]}, float(r["n"])) for r in rows],
        )
    )
    from dstack_tpu.server.services import resilience

    sections.append(
        _fmt(
            "dstack_tpu_circuit_breaker_state",
            "Circuit breaker state by external target (0=closed, 1=half-open, 2=open)",
            "gauge",
            [({"target": t}, v) for t, v in resilience.snapshot()],
        )
    )

    # Background loop lag: how far behind schedule each processing loop started
    # its latest pass (0 = on time; sustained growth = an overloaded loop).
    sections.append(
        _fmt(
            "dstack_tpu_background_loop_lag_seconds",
            "Delay of the latest background pass behind its schedule",
            "gauge",
            tracing.gauge_snapshot("dstack_tpu_background_loop_lag_seconds"),
        )
    )

    # Tracing histograms: the advertised families first (stable discovery),
    # then any additional span histograms instrumentation has registered.
    rendered = set()
    for name, help_ in _HISTOGRAM_HELP.items():
        sections.append(_fmt_histogram(name, help_))
        rendered.add(name)
    for name in tracing.histogram_names():
        if name not in rendered:
            sections.append(_fmt_histogram(name, f"Span duration for {name}"))

    return "\n".join(sections) + "\n"
