"""Secrets service — implemented for real.

Parity-plus: the reference snapshot stubs secrets (routers/secrets.py:20-36 handlers
`pass`, `secrets = {}  # TODO` in process_running_jobs.py:178); here they are stored
encrypted at rest (services/encryption) and injected into job environments by
process_running_jobs."""

from __future__ import annotations

from typing import Dict, List

from dstack_tpu.core.errors import ResourceNotExistsError
from dstack_tpu.server.db import Database, new_id
from dstack_tpu.server.services import encryption


async def set_secret(db: Database, project_row, name: str, value: str) -> None:
    await db.execute(
        "INSERT INTO secrets (id, project_id, name, value) VALUES (?, ?, ?, ?)"
        " ON CONFLICT (project_id, name) DO UPDATE SET value = excluded.value",
        (new_id(), project_row["id"], name, encryption.encrypt(value)),
    )


async def list_secrets(db: Database, project_row) -> List[str]:
    rows = await db.fetchall(
        "SELECT name FROM secrets WHERE project_id = ? ORDER BY name", (project_row["id"],)
    )
    return [r["name"] for r in rows]


async def get_secrets(db: Database, project_id: str) -> Dict[str, str]:
    rows = await db.fetchall(
        "SELECT name, value FROM secrets WHERE project_id = ?", (project_id,)
    )
    return {r["name"]: encryption.decrypt(r["value"]) for r in rows}


async def delete_secrets(db: Database, project_row, names: List[str]) -> None:
    for name in names:
        n = await db.execute(
            "DELETE FROM secrets WHERE project_id = ? AND name = ?",
            (project_row["id"], name),
        )
        if n == 0:
            raise ResourceNotExistsError(f"secret {name} not found")
