"""Instance pool service: slice-aware creation, matching, release.

Parity: reference server/services/instances.py (filter_pool_instances:130,
create_instance_model:407). TPU twist (SURVEY §7 hard part (a)): one cloud *slice* backs
`hosts_per_slice` instance rows sharing `slice_id`; pool matching returns whole idle
slices, never individual workers, so gang placement is atomic."""

from __future__ import annotations

import json
import uuid
from typing import List, Optional

from dstack_tpu.core.models.instances import (
    Instance,
    InstanceOffer,
    InstanceStatus,
    InstanceType,
)
from dstack_tpu.core.models.runs import JobProvisioningData, Requirements
from dstack_tpu.server.db import Database, loads, new_id
from dstack_tpu.utils.common import from_iso, now_utc, to_iso


def row_to_instance(row, project_name: str = "", fleet_name: Optional[str] = None) -> Instance:
    itype = loads(row["instance_type"])
    return Instance(
        id=uuid.UUID(row["id"]),
        project_name=project_name,
        backend=row["backend"],
        instance_type=InstanceType.model_validate(itype) if itype else None,
        name=row["name"],
        fleet_id=uuid.UUID(row["fleet_id"]) if row["fleet_id"] else None,
        fleet_name=fleet_name,
        instance_num=row["instance_num"],
        hostname=_jpd_hostname(row),
        status=InstanceStatus(row["status"]),
        unreachable=bool(row["unreachable"]),
        termination_reason=row["termination_reason"],
        created=from_iso(row["created_at"]),
        region=row["region"],
        availability_zone=row["availability_zone"],
        price=row["price"],
        slice_id=row["slice_id"],
        slice_name=row["slice_name"],
        worker_num=row["worker_num"],
        hosts_per_slice=row["hosts_per_slice"],
        total_blocks=row["total_blocks"],
        busy_blocks=row["busy_blocks"],
    )


def _jpd_hostname(row) -> Optional[str]:
    jpd = loads(row["job_provisioning_data"])
    if jpd:
        return jpd.get("hostname")
    return None


def create_slice_instances_tx(
    conn,
    project_id: str,
    fleet_id: Optional[str],
    name_base: str,
    jpds: List[JobProvisioningData],
    offer: InstanceOffer,
    status: InstanceStatus = InstanceStatus.PROVISIONING,
    instance_num_start: int = 0,
) -> List[str]:
    """Synchronous core of create_slice_instances, composable inside one db.run()
    transaction so slice rows and their job assignments commit atomically (reference
    wraps each scheduler pass in a session transaction, process_submitted_jobs.py:193)."""
    now = to_iso(now_utc())
    ids: List[str] = []
    rows = []
    for jpd in jpds:
        iid = new_id()
        ids.append(iid)
        rows.append(
            (
                iid,
                project_id,
                fleet_id,
                f"{name_base}-{jpd.worker_num}" if jpd.hosts_per_slice > 1 else name_base,
                instance_num_start + jpd.worker_num,
                status.value,
                now,
                now,
                jpd.backend,
                jpd.region,
                jpd.availability_zone,
                jpd.price if jpd.worker_num == 0 else 0.0,  # price is per-slice; bill on worker 0
                jpd.instance_type.model_dump_json(),
                offer.model_dump_json(),
                jpd.model_dump_json(),
                jpd.slice_id,
                jpd.slice_name,
                jpd.worker_num,
                jpd.hosts_per_slice,
            )
        )
    conn.executemany(
        "INSERT INTO instances (id, project_id, fleet_id, name, instance_num, status,"
        " created_at, last_processed_at, backend, region, availability_zone, price,"
        " instance_type, offer, job_provisioning_data, slice_id, slice_name, worker_num,"
        " hosts_per_slice) VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
        rows,
    )
    return ids


async def create_slice_instances(
    db: Database,
    project_id: str,
    fleet_id: Optional[str],
    name_base: str,
    jpds: List[JobProvisioningData],
    offer: InstanceOffer,
    status: InstanceStatus = InstanceStatus.PROVISIONING,
    instance_num_start: int = 0,
) -> List[str]:
    """Insert one instance row per slice worker; all rows share slice_id. Returns ids in
    worker order."""
    return await db.run(
        lambda conn: create_slice_instances_tx(
            conn, project_id, fleet_id, name_base, jpds, offer, status, instance_num_start
        )
    )


async def find_idle_slices(
    db: Database,
    project_id: str,
    requirements: Requirements,
    slice_name: Optional[str],
    hosts_per_slice: int,
    fleet_ids: Optional[List[str]] = None,
    profile=None,
) -> List[List]:
    """Idle slices matching a job's requirements: every worker row idle, worker count
    complete, host resources sufficient (parity: reference filter_pool_instances
    instances.py:130). Returns a list of slices; each slice is its instance rows in
    worker order."""
    sql = (
        "SELECT * FROM instances WHERE project_id = ? AND deleted = 0"
        " AND status = 'idle' AND busy_blocks = 0 AND unreachable = 0"
    )
    params: list = [project_id]
    if slice_name is not None:
        sql += " AND slice_name = ?"
        params.append(slice_name)
    else:
        sql += " AND (slice_name IS NULL OR slice_name = '')"
    if fleet_ids:
        sql += f" AND fleet_id IN ({','.join('?' for _ in fleet_ids)})"
        params.extend(fleet_ids)
    sql += " ORDER BY slice_id, worker_num"
    rows = await db.fetchall(sql, params)

    by_slice: dict = {}
    for r in rows:
        by_slice.setdefault(r["slice_id"] or r["id"], []).append(r)
    result = []
    for workers in by_slice.values():
        if len(workers) != hosts_per_slice:
            continue
        if not _slice_matches(workers[0], requirements, profile):
            continue
        result.append(workers)
    return result


def _slice_matches(worker_row, requirements: Requirements, profile) -> bool:
    offer = loads(worker_row["offer"]) or {}
    if requirements.spot is not None and bool(offer.get("spot")) != requirements.spot:
        return False
    price = worker_row["price"] or 0.0
    if requirements.max_price is not None and price > requirements.max_price:
        return False
    if profile is not None:
        if profile.backends and worker_row["backend"] not in profile.backends:
            return False
        if profile.regions and worker_row["region"] not in profile.regions:
            return False
        if profile.max_price is not None and price > profile.max_price:
            return False
    itype = loads(worker_row["instance_type"]) or {}
    host = itype.get("resources") or {}
    res = requirements.resources
    if res.cpu.count.min is not None and (host.get("cpus") or 0) < res.cpu.count.min:
        return False
    if res.memory.min is not None and (host.get("memory_gb") or 0.0) < res.memory.min:
        return False
    if (
        res.disk is not None
        and res.disk.size.min is not None
        and (host.get("disk_gb") or 0.0) < res.disk.size.min
    ):
        return False
    return True


class SliceBusyError(Exception):
    """A slice believed idle was claimed by a concurrent placement; the enclosing
    transaction must roll back and the caller should try another slice."""


def mark_slice_busy_tx(conn, instance_ids: List[str]) -> None:
    """Claim a whole idle slice inside a placement transaction.

    Conditional on every worker still being idle: with concurrent scheduler
    passes (background/tasks fan-out), two placements can race for the same
    pool slice — the UPDATE's idle guard makes exactly one win, and the loser's
    transaction rolls back via SliceBusyError instead of double-assigning."""
    q = ",".join("?" for _ in instance_ids)
    cur = conn.execute(
        f"UPDATE instances SET status = 'busy', busy_blocks = 1, idle_since = NULL"
        f" WHERE id IN ({q}) AND status = 'idle' AND busy_blocks = 0",
        instance_ids,
    )
    if cur.rowcount != len(instance_ids):
        raise SliceBusyError(
            f"slice workers concurrently claimed ({cur.rowcount}/{len(instance_ids)} still idle)"
        )


async def release_instance(db: Database, instance_id: str) -> None:
    await db.execute(
        "UPDATE instances SET busy_blocks = 0, idle_since = ?,"
        " status = CASE WHEN status = 'busy' THEN 'idle' ELSE status END"
        " WHERE id = ?",
        (to_iso(now_utc()), instance_id),
    )


async def list_instances(
    db: Database,
    project_id: Optional[str] = None,
    statuses: Optional[List[str]] = None,
) -> List:
    sql = "SELECT * FROM instances WHERE deleted = 0"
    params: list = []
    if project_id is not None:
        sql += " AND project_id = ?"
        params.append(project_id)
    if statuses:
        sql += f" AND status IN ({','.join('?' for _ in statuses)})"
        params.extend(statuses)
    sql += " ORDER BY created_at, worker_num"
    return await db.fetchall(sql, params)
