"""Unified retry / timeout / circuit-breaker layer for external calls.

The control plane talks to three families of things it does not control —
runner agents, cloud backend APIs, and service replicas behind the proxy.
Before this module each call site handled failure ad-hoc (a bare try/except
here, an unbounded await there). Now one combinator owns the policy:

- ``with_retry(fn, ...)``: explicit per-attempt timeout and total deadline,
  jittered exponential backoff between attempts, and typed outcome routing
  (``retry_on`` / ``no_retry`` / ``treat_as_success`` — e.g. NoCapacityError
  is a *successful* conversation with a healthy backend, not a fault).
- Per-target circuit breakers: a target opens after
  ``settings.BREAKER_THRESHOLD`` consecutive failures, rejects calls for
  ``settings.BREAKER_COOLDOWN`` seconds, then half-opens exactly one probe;
  the probe's outcome closes or re-opens it. Targets are strings like
  ``runner:http://10.0.0.7:10999`` or ``backend:gcp`` — state is process-local
  (each replica learns about a dead dependency from its own traffic, which is
  the traffic the breaker protects).

Breaker state is exported on ``/metrics`` as
``dstack_tpu_circuit_breaker_state{target=...}`` (0 closed, 1 half-open,
2 open) so an open breaker is visible before anyone reads logs. Scheduler
passes consult ``is_open()`` to degrade gracefully — skip-and-requeue with a
reason'd run_event instead of burning a pass (and an offer's deadline) on a
dead backend.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Awaitable, Callable, Dict, List, Optional, Tuple, Type

from dstack_tpu.server import settings

logger = logging.getLogger(__name__)

_STATE_VALUES = {"closed": 0.0, "half_open": 1.0, "open": 2.0}


class BreakerOpenError(Exception):
    """The target's circuit is open; the call was rejected without being made."""

    def __init__(self, target: str, retry_in: float = 0.0):
        super().__init__(
            f"circuit breaker open for {target}"
            + (f" (probe in {retry_in:.1f}s)" if retry_in > 0 else "")
        )
        self.target = target
        self.retry_in = retry_in


class DeadlineExceededError(Exception):
    """with_retry ran out of total wall budget before an attempt succeeded."""


class _Breaker:
    __slots__ = ("target", "state", "failures", "opened_at", "probing", "probe_started_at")

    def __init__(self, target: str):
        self.target = target
        self.state = "closed"
        self.failures = 0
        self.opened_at = 0.0
        self.probing = False
        self.probe_started_at = 0.0


_breakers: Dict[str, _Breaker] = {}


def _set_state(b: _Breaker, state: str) -> None:
    b.state = state


def check(target: str) -> None:
    """Admission check; raises BreakerOpenError when the target is open. On a
    cooled-down open breaker, the FIRST caller through becomes the half-open
    probe (concurrent callers stay rejected until its outcome lands)."""
    b = _breakers.get(target)
    if b is None or b.state == "closed":
        return
    now = time.monotonic()
    if b.state == "open":
        elapsed = now - b.opened_at
        if elapsed < settings.BREAKER_COOLDOWN:
            raise BreakerOpenError(target, settings.BREAKER_COOLDOWN - elapsed)
        _set_state(b, "half_open")
        b.probing = False
    if b.state == "half_open":
        # A probe whose caller never reported back (cancelled task, crashed
        # pass) must not wedge the breaker: past one cooldown it is presumed
        # dead and the next caller becomes the probe.
        if b.probing and now - b.probe_started_at < settings.BREAKER_COOLDOWN:
            raise BreakerOpenError(target)
        b.probing = True
        b.probe_started_at = now


def abort_probe(target: str) -> None:
    """The in-flight half-open probe was cancelled before producing an
    outcome: hand the probe slot to the next caller instead of holding it."""
    b = _breakers.get(target)
    if b is not None and b.state == "half_open":
        b.probing = False


def record_success(target: str) -> None:
    b = _breakers.get(target)
    if b is None:
        return
    b.failures = 0
    b.probing = False
    if b.state != "closed":
        logger.info("circuit breaker %s closed (probe succeeded)", target)
        _set_state(b, "closed")


def record_failure(target: str) -> None:
    b = _breakers.get(target)
    if b is None:
        b = _breakers[target] = _Breaker(target)
    b.failures += 1
    if b.state == "half_open" or b.failures >= settings.BREAKER_THRESHOLD:
        b.opened_at = time.monotonic()
        b.probing = False
        if b.state != "open":
            logger.warning(
                "circuit breaker %s opened after %d consecutive failure(s)",
                target, b.failures,
            )
        _set_state(b, "open")


def is_open(target: str) -> bool:
    """True while the target rejects calls outright (cooldown not yet elapsed).
    A cooled-down breaker reads False so decision points (offer filtering,
    endpoint choice) route one probe call back at the target."""
    b = _breakers.get(target)
    return (
        b is not None
        and b.state == "open"
        and time.monotonic() - b.opened_at < settings.BREAKER_COOLDOWN
    )


def state(target: str) -> str:
    b = _breakers.get(target)
    return b.state if b is not None else "closed"


def snapshot() -> List[Tuple[str, float]]:
    """(target, numeric state) for /metrics."""
    return sorted((t, _STATE_VALUES[b.state]) for t, b in _breakers.items())


def reset() -> None:
    """Forget all breaker state (tests / bench rounds)."""
    _breakers.clear()


def backoff_delay(
    attempt: int, base: float, cap: float, rng: Optional[random.Random] = None
) -> float:
    """Jittered exponential backoff: min(base * 2^attempt, cap) scaled into
    [0.5, 1.0) so N callers failing together never retry in lockstep."""
    return min(base * (2 ** attempt), cap) * (0.5 + 0.5 * (rng or random).random())


async def with_retry(
    fn: Callable[[], Awaitable],
    *,
    target: Optional[str] = None,
    op: str = "",
    attempts: int = 3,
    timeout: Optional[float] = None,
    deadline: Optional[float] = None,
    base_delay: float = 0.2,
    max_delay: float = 5.0,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    no_retry: Tuple[Type[BaseException], ...] = (),
    treat_as_success: Tuple[Type[BaseException], ...] = (),
    rng: Optional[random.Random] = None,
):
    """Run ``fn()`` (a zero-arg coroutine factory) under the resilience policy.

    ``timeout`` bounds each attempt; ``deadline`` bounds the whole call
    including backoff sleeps. With ``target`` set, every attempt passes the
    breaker admission check and reports its outcome. Exception routing, in
    priority order: ``treat_as_success`` closes the breaker and re-raises
    (a definitive answer, not a fault); ``no_retry`` counts a failure and
    re-raises; ``retry_on`` counts a failure and retries while budget remains.
    CancelledError always propagates untouched.
    """
    start = time.monotonic()
    last: Optional[BaseException] = None
    for attempt in range(max(1, attempts)):
        if target is not None:
            check(target)  # BreakerOpenError propagates to the caller
        budget = timeout
        if deadline is not None:
            remaining = deadline - (time.monotonic() - start)
            if remaining <= 0:
                break
            budget = min(budget, remaining) if budget is not None else remaining
        try:
            coro = fn()
            result = await (
                asyncio.wait_for(coro, budget) if budget is not None else coro
            )
        except asyncio.CancelledError:
            # Cancellation is not a target outcome — release the half-open
            # probe slot (if this attempt held it) instead of wedging it.
            if target is not None:
                abort_probe(target)
            raise
        except BaseException as e:
            if isinstance(e, treat_as_success):
                if target is not None:
                    record_success(target)
                raise
            if isinstance(e, no_retry) or not isinstance(e, retry_on):
                if target is not None:
                    record_failure(target)
                raise
            if target is not None:
                record_failure(target)
            last = e
            if attempt + 1 < max(1, attempts):
                delay = backoff_delay(attempt, base_delay, max_delay, rng)
                if deadline is not None:
                    delay = min(delay, max(0.0, deadline - (time.monotonic() - start)))
                logger.debug(
                    "%s%s attempt %d/%d failed (%s); retrying in %.2fs",
                    target or "", f" {op}" if op else "", attempt + 1, attempts, e, delay,
                )
                await asyncio.sleep(delay)
            continue
        else:
            if target is not None:
                record_success(target)
            return result
    if last is not None:
        raise last
    raise DeadlineExceededError(
        f"{target or op or 'call'}: deadline of {deadline}s exhausted before any attempt"
    )
