"""Per-project backend registry (parity: reference server/services/backends/ +
core/backends/configurators.py)."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from dstack_tpu.backends.base import Compute
from dstack_tpu.backends.local import LocalCompute
from dstack_tpu.backends.mock import MockTpuCompute
from dstack_tpu.core.errors import ResourceNotExistsError, ServerClientError
from dstack_tpu.core.models.backends import BackendConfig, BackendType
from dstack_tpu.server import settings
from dstack_tpu.server.db import Database, dumps, loads, new_id

# Compute instances are lightweight; cache per (project_id, type).
_compute_cache: Dict[Tuple[str, str], Compute] = {}


def make_compute(backend_type: str, config: Optional[dict] = None) -> Compute:
    config = config or {}
    if backend_type == BackendType.LOCAL.value:
        return LocalCompute()
    if backend_type == BackendType.MOCK.value:
        return MockTpuCompute(regions=config.get("regions"))
    if backend_type == BackendType.GCP.value:
        from dstack_tpu.backends.gcp import GcpTpuCompute

        return GcpTpuCompute(config)
    raise ServerClientError(f"unsupported backend type {backend_type}")


async def create_backend(db: Database, project_row, config: BackendConfig) -> None:
    make_compute(config.type.value, config.model_dump())  # validates type
    await db.execute(
        "INSERT INTO backends (id, project_id, type, config) VALUES (?, ?, ?, ?)"
        " ON CONFLICT (project_id, type) DO UPDATE SET config = excluded.config",
        (
            new_id(),
            project_row["id"],
            config.type.value,
            config.model_dump_json(),
        ),
    )
    _compute_cache.pop((project_row["id"], config.type.value), None)
    _invalidate_offers(project_row["id"])


async def delete_backends(db: Database, project_row, types: List[str]) -> None:
    for t in types:
        await db.execute(
            "DELETE FROM backends WHERE project_id = ? AND type = ?", (project_row["id"], t)
        )
        _compute_cache.pop((project_row["id"], t), None)
    _invalidate_offers(project_row["id"])


async def list_backends(db: Database, project_row) -> List[BackendConfig]:
    rows = await db.fetchall(
        "SELECT * FROM backends WHERE project_id = ? ORDER BY type", (project_row["id"],)
    )
    configs = [BackendConfig.model_validate(loads(r["config"])) for r in rows]
    if settings.LOCAL_BACKEND_ENABLED and not any(c.type == BackendType.LOCAL for c in configs):
        configs.append(BackendConfig(type=BackendType.LOCAL))
    return configs


async def get_project_computes(db: Database, project_row) -> List[Tuple[str, Compute]]:
    """All (backend_type, Compute) pairs usable by the project."""
    out: List[Tuple[str, Compute]] = []
    for config in await list_backends(db, project_row):
        key = (project_row["id"], config.type.value)
        if key not in _compute_cache:
            _compute_cache[key] = make_compute(config.type.value, config.model_dump())
        out.append((config.type.value, _compute_cache[key]))
    return out


async def get_compute(db: Database, project_row, backend_type: str) -> Compute:
    for t, compute in await get_project_computes(db, project_row):
        if t == backend_type:
            return compute
    raise ResourceNotExistsError(f"backend {backend_type} not configured")


def _invalidate_offers(project_id: Optional[str] = None) -> None:
    # Late import: offers imports this module at the top level.
    from dstack_tpu.server.services import offers as offers_service

    offers_service.invalidate_offer_cache(project_id)


def reset_compute_cache() -> None:
    _compute_cache.clear()
    _invalidate_offers()
