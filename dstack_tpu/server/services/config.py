"""Server config manager: apply ``config.yml`` at startup.

Parity: reference server/services/config.py (ServerConfigManager — a declarative
``~/.dstack/server/config.yml`` naming projects, their backends, encryption
keys, and plugins, applied idempotently on boot). A default file is written on
first start so operators have something to edit.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import List, Optional

import yaml
from pydantic import Field

from dstack_tpu.core.models.backends import BackendConfig
from dstack_tpu.core.models.common import CoreModel
from dstack_tpu.server.db import Database

logger = logging.getLogger(__name__)


class ProjectConfig(CoreModel):
    name: str
    backends: List[BackendConfig] = Field(default_factory=list)


class EncryptionConfig(CoreModel):
    keys: List[dict] = Field(default_factory=list)


class ServerConfig(CoreModel):
    projects: List[ProjectConfig] = Field(default_factory=list)
    plugins: List[str] = Field(default_factory=list)
    encryption: Optional[EncryptionConfig] = None


_DEFAULT_CONFIG = """\
# dstack-tpu server configuration, applied at every startup.
#
# projects:
#   - name: main
#     backends:
#       - type: gcp
#         project_id: my-gcp-project
#         creds:
#           type: service_account
#           filename: /path/to/sa.json
#
# plugins:
#   - my_package.my_module:MyPlugin
projects: []
plugins: []
"""


def config_path(server_dir: Path) -> Path:
    return server_dir / "config.yml"


def load_config(server_dir: Path) -> ServerConfig:
    """Read config.yml; writes the commented default on first boot."""
    path = config_path(server_dir)
    if not path.exists():
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(_DEFAULT_CONFIG)
        logger.info("wrote default server config to %s", path)
        return ServerConfig()
    data = yaml.safe_load(path.read_text()) or {}
    return ServerConfig.model_validate(data)


async def apply_config(db: Database, admin_row, config: ServerConfig) -> None:
    """Idempotently converge projects + backends + plugins to the file."""
    from dstack_tpu.server.services import backends as backends_service
    from dstack_tpu.server.services import plugins as plugins_service
    from dstack_tpu.server.services import projects as projects_service

    for proj in config.projects:
        row = await db.fetchone(
            "SELECT * FROM projects WHERE name = ? AND deleted = 0", (proj.name,)
        )
        if row is None:
            await projects_service.create_project(db, admin_row, proj.name)
            row = await db.fetchone(
                "SELECT * FROM projects WHERE name = ? AND deleted = 0", (proj.name,)
            )
            logger.info("config: created project %s", proj.name)
        for backend in proj.backends:
            await backends_service.create_backend(db, row, backend)
        if proj.backends:
            logger.info(
                "config: project %s backends: %s",
                proj.name,
                [b.type.value for b in proj.backends],
            )

    if config.plugins:
        loaded = plugins_service.load_plugins(config.plugins)
        logger.info("config: loaded plugins: %s", loaded)
