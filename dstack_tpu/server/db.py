"""Async-friendly sqlite persistence.

Parity: reference server/db.py (async SQLAlchemy, WAL pragma db.py:35-40) — re-designed
on stdlib sqlite3: one writer connection in WAL mode, all statements funneled through a
single worker thread so the asyncio event loop never blocks and writes are serialized
(sqlite's own model). Schema migrations are ordered DDL scripts tracked in a version
table (alembic equivalent)."""

from __future__ import annotations

import asyncio
import json
import sqlite3
import threading
import queue
import uuid
from pathlib import Path
from typing import Any, Iterable, List, Optional

from dstack_tpu.server import migrations


class Database:
    """All access goes through execute()/fetchall()/fetchone() coroutines.

    A dedicated thread owns the sqlite3 connection; requests are queued, keeping the
    event loop responsive under the write-heavy scheduler loops.
    """

    def __init__(self, path: str = ":memory:"):
        self._path = path
        self._queue: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    async def connect(self) -> None:
        if self._thread is not None:
            return
        if self._path != ":memory:":
            Path(self._path).parent.mkdir(parents=True, exist_ok=True)
        loop = asyncio.get_running_loop()
        started: "asyncio.Future" = loop.create_future()
        self._thread = threading.Thread(
            target=self._worker, args=(loop, started), name="db-worker", daemon=True
        )
        self._thread.start()
        await started

    def _worker(self, loop: asyncio.AbstractEventLoop, started: "asyncio.Future") -> None:
        try:
            conn = sqlite3.connect(self._path)
            conn.row_factory = sqlite3.Row
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA busy_timeout=10000")
            conn.execute("PRAGMA foreign_keys=ON")
            conn.execute("PRAGMA synchronous=NORMAL")
            migrations.migrate(conn)
            loop.call_soon_threadsafe(started.set_result, None)
        except Exception as e:  # pragma: no cover
            loop.call_soon_threadsafe(started.set_exception, e)
            return
        while True:
            item = self._queue.get()
            if item is None:
                break
            fn, fut, fut_loop = item
            try:
                result = fn(conn)
                conn.commit()
            except Exception as e:
                conn.rollback()
                fut_loop.call_soon_threadsafe(_set_exc, fut, e)
            else:
                fut_loop.call_soon_threadsafe(_set_result, fut, result)
        conn.close()

    async def run(self, fn) -> Any:
        """Run `fn(conn)` on the DB thread inside a transaction; return its result."""
        loop = asyncio.get_running_loop()
        fut: "asyncio.Future" = loop.create_future()
        self._queue.put((fn, fut, loop))
        return await fut

    async def execute(self, sql: str, params: Iterable = ()) -> int:
        def _do(conn: sqlite3.Connection) -> int:
            cur = conn.execute(sql, tuple(params))
            return cur.rowcount

        return await self.run(_do)

    async def executemany(self, sql: str, rows: List[Iterable]) -> None:
        def _do(conn: sqlite3.Connection) -> None:
            conn.executemany(sql, [tuple(r) for r in rows])

        await self.run(_do)

    async def fetchall(self, sql: str, params: Iterable = ()) -> List[sqlite3.Row]:
        def _do(conn: sqlite3.Connection):
            return conn.execute(sql, tuple(params)).fetchall()

        return await self.run(_do)

    async def fetchone(self, sql: str, params: Iterable = ()) -> Optional[sqlite3.Row]:
        def _do(conn: sqlite3.Connection):
            return conn.execute(sql, tuple(params)).fetchone()

        return await self.run(_do)

    async def close(self) -> None:
        if self._thread is not None and not self._closed:
            self._closed = True
            self._queue.put(None)
            await asyncio.get_running_loop().run_in_executor(None, self._thread.join)
            self._thread = None


def _set_result(fut: "asyncio.Future", result: Any) -> None:
    if not fut.cancelled():
        fut.set_result(result)


def _set_exc(fut: "asyncio.Future", e: Exception) -> None:
    if not fut.cancelled():
        fut.set_exception(e)


def new_id() -> str:
    return str(uuid.uuid4())


def dumps(obj: Any) -> str:
    return json.dumps(obj, separators=(",", ":"), default=str)


def loads(s: Optional[str]) -> Any:
    if s is None:
        return None
    return json.loads(s)
