"""Async-friendly persistence with a sqlite/postgres dialect seam.

Parity: reference server/db.py (async SQLAlchemy over sqlite+aiosqlite OR
postgres+asyncpg, WAL pragma db.py:35-40) and services/locking.py (postgres
advisory locks for multi-replica HA init). Re-designed without an ORM: one
worker thread owns the connection, all statements are funneled through it so
the asyncio event loop never blocks and writes are serialized. The dialect
object hides everything engine-specific:

- placeholder style: services author qmark (`?`) SQL; the postgres dialect
  translates to `%s` outside string literals at execution time.
- DDL: migrations are authored once in portable DDL (TEXT/INTEGER/REAL +
  `ON CONFLICT` upserts, supported by both engines); the postgres dialect
  rewrites the few remaining divergences (BLOB -> BYTEA) and splits scripts
  into single statements (sqlite's executescript has no postgres analogue).
- advisory locks: `Database.advisory_lock(name)` guards multi-replica init
  sections (admin/user bootstrap, config apply). On sqlite it is a no-op —
  one process, one writer thread — while on postgres it takes a session
  advisory lock so N server replicas sharing one database elect a single
  initializer, like the reference's `with_for_update`+advisory-lock HA init
  (ref server/app.py:109-113).

The postgres driver (psycopg 3 or psycopg2) is not bundled in this image;
`Database("postgresql://...")` raises a clear error at connect() when no
driver is importable, and the postgres test module skips itself the same way.
Multi-replica deployment is documented in README.md (Run `dstack-tpu server`
N times against the same DSTACK_TPU_DB_URL; background schedulers coordinate
through transactions + advisory locks).
"""

from __future__ import annotations

import asyncio
import contextvars
import functools
import json
import re
import sqlite3
import threading
import queue
import uuid
from contextlib import asynccontextmanager
from pathlib import Path
from typing import Any, Iterable, List, Optional, Sequence

from dstack_tpu.server import migrations


# ---------------------------------------------------------------------------
# Dialects


@functools.lru_cache(maxsize=1024)
def translate_qmark(sql: str, marker: str = "%s") -> str:
    """Rewrite qmark placeholders to `marker`, leaving quoted literals alone.
    Memoized: the scheduler loops re-execute a small fixed set of statements."""
    out = []
    in_str = False
    i = 0
    while i < len(sql):
        ch = sql[i]
        if in_str:
            out.append(ch)
            if ch == "'":
                if i + 1 < len(sql) and sql[i + 1] == "'":  # escaped ''
                    out.append("'")
                    i += 1
                else:
                    in_str = False
        elif ch == "'":
            in_str = True
            out.append(ch)
        elif ch == "?":
            out.append(marker)
        else:
            out.append(ch)
        i += 1
    return "".join(out)


def split_script(script: str) -> List[str]:
    """Split a DDL script into statements on top-level semicolons (the repo's
    migration DDL keeps no semicolons inside string literals or bodies)."""
    statements, buf, in_str = [], [], False
    for ch in script:
        if ch == "'":
            in_str = not in_str
        if ch == ";" and not in_str:
            stmt = "".join(buf).strip()
            if stmt:
                statements.append(stmt)
            buf = []
        else:
            buf.append(ch)
    tail = "".join(buf).strip()
    if tail:
        statements.append(tail)
    return statements


class SqliteDialect:
    """Owns the sqlite3 connection; qmark SQL passes through untouched."""

    name = "sqlite"

    def __init__(self, path: str):
        self.path = path

    def connect(self):
        if self.path != ":memory:":
            Path(self.path).parent.mkdir(parents=True, exist_ok=True)
        conn = sqlite3.connect(self.path)
        conn.row_factory = sqlite3.Row
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA busy_timeout=10000")
        conn.execute("PRAGMA foreign_keys=ON")
        conn.execute("PRAGMA synchronous=NORMAL")
        migrations.migrate(conn)
        return conn

    def run_script(self, conn, script: str) -> None:
        conn.executescript(script)

    def tx_advisory_lock(self, conn, name: str) -> None:
        pass  # the single writer thread already serializes all transactions

    def session_lock(self, conn, name: str) -> None:
        pass

    def session_unlock(self, conn, name: str) -> None:
        pass


class PgRow:
    """dict+index row access matching what sqlite3.Row offers services."""

    __slots__ = ("_cols", "_vals")

    def __init__(self, cols: Sequence[str], vals: Sequence[Any]):
        self._cols = cols
        self._vals = vals

    def __getitem__(self, key):
        if isinstance(key, int):
            return self._vals[key]
        try:
            return self._vals[self._cols.index(key)]
        except ValueError:
            raise KeyError(key) from None

    def keys(self):
        return list(self._cols)

    def __iter__(self):
        return iter(self._vals)

    def __len__(self):
        return len(self._vals)

    def __repr__(self):  # pragma: no cover
        return f"PgRow({dict(zip(self._cols, self._vals))!r})"


class _PgCursor:
    """Cursor facade returning PgRow so service code is row-type agnostic."""

    def __init__(self, cursor):
        self._cur = cursor

    @property
    def rowcount(self) -> int:
        return self._cur.rowcount

    def _cols(self) -> List[str]:
        return [d[0] for d in (self._cur.description or [])]

    def fetchone(self) -> Optional[PgRow]:
        row = self._cur.fetchone()
        return None if row is None else PgRow(self._cols(), row)

    def fetchall(self) -> List[PgRow]:
        cols = None
        out = []
        for row in self._cur.fetchall():
            if cols is None:
                cols = self._cols()
            out.append(PgRow(cols, row))
        return out


class _PgConnection:
    """The connection object handed to db.run() closures under postgres: the
    same `.execute(qmark_sql, params)` surface the sqlite3 connection has."""

    def __init__(self, raw):
        self.raw = raw

    def execute(self, sql: str, params: Iterable = ()) -> _PgCursor:
        cur = self.raw.cursor()
        cur.execute(translate_qmark(sql), tuple(params))
        return _PgCursor(cur)

    def executemany(self, sql: str, rows: Iterable[Iterable]) -> None:
        cur = self.raw.cursor()
        cur.executemany(translate_qmark(sql), [tuple(r) for r in rows])

    def commit(self) -> None:
        self.raw.commit()

    def rollback(self) -> None:
        self.raw.rollback()

    def close(self) -> None:
        self.raw.close()


_PG_DDL_FIXUPS = [
    (re.compile(r"\bBLOB\b"), "BYTEA"),
    # sqlite INTEGER is 64-bit; postgres INTEGER is int4, which byte counters
    # (HBM/memory usage) and cumulative CPU-microsecond columns overflow.
    (re.compile(r"\bINTEGER\b"), "BIGINT"),
]


class PostgresDialect:
    """Talks to postgres via psycopg 3 or psycopg2, whichever imports."""

    name = "postgres"

    def __init__(self, dsn: str):
        self.dsn = dsn

    @staticmethod
    def _driver():
        try:
            import psycopg  # psycopg 3

            return psycopg, 3
        except ImportError:
            pass
        try:
            import psycopg2

            return psycopg2, 2
        except ImportError:
            raise RuntimeError(
                "postgres DSN configured but no driver available: install "
                "psycopg (v3) or psycopg2 on the server host"
            ) from None

    def connect(self) -> _PgConnection:
        driver, _version = self._driver()
        conn = _PgConnection(driver.connect(self.dsn))
        migrations.migrate(conn, dialect=self)
        return conn

    def fixup_ddl(self, script: str) -> str:
        for pattern, replacement in _PG_DDL_FIXUPS:
            script = pattern.sub(replacement, script)
        return script

    def run_script(self, conn: _PgConnection, script: str) -> None:
        for statement in split_script(self.fixup_ddl(script)):
            conn.execute(statement)

    # hashtext() maps the lock name onto postgres's bigint advisory-lock
    # keyspace; xact locks release at commit/rollback, session locks at
    # session_unlock or disconnect.
    def tx_advisory_lock(self, conn: _PgConnection, name: str) -> None:
        conn.execute("SELECT pg_advisory_xact_lock(hashtext(?))", (name,))

    def session_lock(self, conn: _PgConnection, name: str) -> None:
        conn.execute("SELECT pg_advisory_lock(hashtext(?))", (name,))

    def session_unlock(self, conn: _PgConnection, name: str) -> None:
        conn.execute("SELECT pg_advisory_unlock(hashtext(?))", (name,))


def make_dialect(url: str):
    if url.startswith(("postgres://", "postgresql://")):
        return PostgresDialect(url)
    if url.startswith("sqlite:///"):
        url = url[len("sqlite:///"):] or ":memory:"
    return SqliteDialect(url)


# ---------------------------------------------------------------------------
# Database


class Database:
    """All access goes through execute()/fetchall()/fetchone() coroutines.

    A dedicated thread owns the connection; requests are queued, keeping the
    event loop responsive under the write-heavy scheduler loops. `url` is a
    sqlite path (default) or a postgres:// DSN.
    """

    def __init__(self, url: str = ":memory:"):
        self.dialect = make_dialect(url)
        self._queue: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    async def connect(self) -> None:
        if self._thread is not None:
            return
        loop = asyncio.get_running_loop()
        started: "asyncio.Future" = loop.create_future()
        self._thread = threading.Thread(
            target=self._worker, args=(loop, started), name="db-worker", daemon=True
        )
        self._thread.start()
        await started

    def _worker(self, loop: asyncio.AbstractEventLoop, started: "asyncio.Future") -> None:
        try:
            conn = self.dialect.connect()
            loop.call_soon_threadsafe(started.set_result, None)
        except Exception as e:  # pragma: no cover
            loop.call_soon_threadsafe(started.set_exception, e)
            return
        while True:
            item = self._queue.get()
            if item is None:
                break
            fn, fut, fut_loop, ctx = item
            try:
                # Run under the submitter's contextvars so closures see the
                # caller's tracing context (trace ids in run_events rows).
                result = ctx.run(fn, conn)
                conn.commit()
            except Exception as e:
                conn.rollback()
                fut_loop.call_soon_threadsafe(_set_exc, fut, e)
            else:
                fut_loop.call_soon_threadsafe(_set_result, fut, result)
        conn.close()

    async def run(self, fn) -> Any:
        """Run `fn(conn)` on the DB thread inside a transaction; return its result."""
        loop = asyncio.get_running_loop()
        fut: "asyncio.Future" = loop.create_future()
        self._queue.put((fn, fut, loop, contextvars.copy_context()))
        return await fut

    async def execute(self, sql: str, params: Iterable = ()) -> int:
        def _do(conn) -> int:
            cur = conn.execute(sql, tuple(params))
            return cur.rowcount

        return await self.run(_do)

    async def executemany(self, sql: str, rows: List[Iterable]) -> None:
        def _do(conn) -> None:
            conn.executemany(sql, [tuple(r) for r in rows])

        await self.run(_do)

    async def fetchall(self, sql: str, params: Iterable = ()) -> List[Any]:
        def _do(conn):
            return conn.execute(sql, tuple(params)).fetchall()

        return await self.run(_do)

    async def fetchone(self, sql: str, params: Iterable = ()) -> Optional[Any]:
        def _do(conn):
            return conn.execute(sql, tuple(params)).fetchone()

        return await self.run(_do)

    async def fetch_in(
        self, sql_template: str, values: Sequence, params: Iterable = ()
    ) -> List[Any]:
        """Grouped ``IN (...)`` fetch — the scheduler's N+1 killer. `sql_template`
        holds one ``{in}`` slot that expands to placeholders for `values`
        (bound after `params`); empty `values` returns [] without touching the DB."""
        values = list(values)
        if not values:
            return []
        sql = sql_template.format(**{"in": in_clause(values)})
        return await self.fetchall(sql, [*params, *values])

    def tx_advisory_lock(self, conn, name: str) -> None:
        """Inside a db.run() closure: serialize a critical section across
        server replicas (transaction-scoped; released at commit/rollback)."""
        self.dialect.tx_advisory_lock(conn, name)

    @asynccontextmanager
    async def advisory_lock(self, name: str):
        """Serialize a multi-statement init section across server replicas
        sharing one postgres database (no-op on sqlite: single process owns
        the file). Usage: `async with db.advisory_lock("init"): ...`"""
        await self.run(lambda conn: self.dialect.session_lock(conn, name))
        try:
            yield
        finally:
            await self.run(lambda conn: self.dialect.session_unlock(conn, name))

    async def close(self) -> None:
        if self._thread is not None and not self._closed:
            self._closed = True
            self._queue.put(None)
            await asyncio.get_running_loop().run_in_executor(None, self._thread.join)
            self._thread = None


def _set_result(fut: "asyncio.Future", result: Any) -> None:
    if not fut.cancelled():
        fut.set_result(result)


def _set_exc(fut: "asyncio.Future", e: Exception) -> None:
    if not fut.cancelled():
        fut.set_exception(e)


def in_clause(values: Sequence) -> str:
    """Placeholders for an ``IN (...)`` clause: ``in_clause([a, b, c])`` -> ``"?,?,?"``."""
    return ",".join("?" for _ in values)


def new_id() -> str:
    return str(uuid.uuid4())


def dumps(obj: Any) -> str:
    return json.dumps(obj, separators=(",", ":"), default=str)


def loads(s: Optional[str]) -> Any:
    if s is None:
        return None
    return json.loads(s)
