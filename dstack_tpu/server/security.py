"""Token auth + role checks (parity: reference server/security/)."""

from __future__ import annotations

import secrets
from typing import Optional

from aiohttp import web

from dstack_tpu.core.errors import ForbiddenError, NotAuthenticatedError
from dstack_tpu.core.models.users import GlobalRole, ProjectRole


def generate_token() -> str:
    return secrets.token_hex(20)


def get_request_token(request: web.Request) -> Optional[str]:
    auth = request.headers.get("Authorization", "")
    if auth.lower().startswith("bearer "):
        return auth[7:].strip()
    # Browser WebSocket clients cannot set headers; accept ?token= on upgrade
    # requests only (the SPA's live log stream / attach bridge).
    if request.headers.get("Upgrade", "").lower() == "websocket":
        return request.query.get("token") or None
    return None


async def authenticate(request: web.Request):
    """Resolve the bearer token to a user row; raise if missing/invalid."""
    token = get_request_token(request)
    if not token:
        raise NotAuthenticatedError("missing token")
    db = request.app["db"]
    row = await db.fetchone("SELECT * FROM users WHERE token = ? AND active = 1", (token,))
    if row is None:
        raise NotAuthenticatedError("invalid token")
    return row


def is_global_admin(user_row) -> bool:
    return user_row["global_role"] == GlobalRole.ADMIN.value


async def get_project_member_role(db, project_id: str, user_id: str) -> Optional[str]:
    row = await db.fetchone(
        "SELECT project_role FROM members WHERE project_id = ? AND user_id = ?",
        (project_id, user_id),
    )
    return row["project_role"] if row else None


async def require_project_access(db, project_row, user_row, admin_only: bool = False) -> str:
    """Return the caller's effective role in the project or raise ForbiddenError."""
    if is_global_admin(user_row):
        return ProjectRole.ADMIN.value
    role = await get_project_member_role(db, project_row["id"], user_row["id"])
    if role is None:
        raise ForbiddenError("not a project member")
    if admin_only and role not in (ProjectRole.ADMIN.value, ProjectRole.MANAGER.value):
        raise ForbiddenError("project admin required")
    return role
