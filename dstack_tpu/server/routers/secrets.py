"""/api/project/{p}/secrets/* — real handlers (the reference stubs these,
routers/secrets.py:20-36)."""

from __future__ import annotations

from aiohttp import web

from dstack_tpu.server.routers._common import auth_project, body_dict, model_response, required
from dstack_tpu.server.services import secrets as secrets_service

routes = web.RouteTableDef()


@routes.post("/api/project/{project_name}/secrets/set")
async def set_secret(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request, admin_only=True)
    body = await body_dict(request)
    await secrets_service.set_secret(request.app["db"], project_row, required(body, "name"), required(body, "value"))
    return model_response(None)


@routes.post("/api/project/{project_name}/secrets/list")
async def list_secrets(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    names = await secrets_service.list_secrets(request.app["db"], project_row)
    return model_response([{"name": n} for n in names])


@routes.post("/api/project/{project_name}/secrets/delete")
async def delete(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request, admin_only=True)
    body = await body_dict(request)
    await secrets_service.delete_secrets(request.app["db"], project_row, required(body, "names"))
    return model_response(None)
