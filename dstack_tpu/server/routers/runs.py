"""/api/project/{p}/runs/* + /api/runs/list (parity: reference server/routers/runs.py)."""

from __future__ import annotations

from aiohttp import web

from dstack_tpu.core.models.runs import ApplyRunPlanInput, RunSpec
from dstack_tpu.server.routers._common import (
    auth_project,
    auth_user,
    body_dict,
    model_response,
    parse_body,
)
from dstack_tpu.server.services import projects as projects_service
from dstack_tpu.server.services import runs as runs_service

routes = web.RouteTableDef()


@routes.post("/api/runs/list")
async def list_all_runs(request: web.Request) -> web.Response:
    user_row = await auth_user(request)
    db = request.app["db"]
    if user_row["global_role"] == "admin":
        rows = await db.fetchall("SELECT id FROM projects WHERE deleted = 0")
    else:
        rows = await db.fetchall(
            "SELECT p.id FROM projects p JOIN members m ON m.project_id = p.id"
            " WHERE m.user_id = ? AND p.deleted = 0",
            (user_row["id"],),
        )
    runs = await runs_service.list_runs(db, project_ids=[r["id"] for r in rows])
    runs.sort(key=lambda r: r.submitted_at, reverse=True)
    return model_response(runs)


@routes.post("/api/project/{project_name}/configurations/parse")
async def parse_config(request: web.Request) -> web.Response:
    """YAML text -> validated configuration dict. The CLI parses YAML locally;
    the browser SPA has no YAML parser, so run submission from the UI sends
    the pasted text here first (then get_plan/submit with the result)."""
    import json

    import yaml

    from dstack_tpu.core.errors import ConfigurationError, ServerClientError
    from dstack_tpu.core.models.configurations import parse_configuration

    await auth_project(request)
    body = await body_dict(request)
    text = body.get("yaml")
    if not isinstance(text, str) or not text.strip():
        raise ServerClientError("body must carry non-empty `yaml` text")
    try:
        data = yaml.safe_load(text)
    except yaml.YAMLError as e:
        raise ServerClientError(f"invalid YAML: {e}")
    if not isinstance(data, dict):
        raise ServerClientError("configuration must be a YAML mapping")
    try:
        conf = parse_configuration(data)
    except (ConfigurationError, ValueError) as e:
        raise ServerClientError(f"invalid configuration: {e}")
    return web.json_response(json.loads(conf.model_dump_json()))


@routes.post("/api/project/{project_name}/runs/get_plan")
async def get_plan(request: web.Request) -> web.Response:
    user_row, project_row = await auth_project(request)
    body = await body_dict(request)
    run_spec = RunSpec.model_validate(body["run_spec"])
    plan = await runs_service.get_run_plan(request.app["db"], project_row, user_row, run_spec)
    return model_response(plan)


@routes.post("/api/project/{project_name}/runs/apply_plan")
async def apply_plan(request: web.Request) -> web.Response:
    user_row, project_row = await auth_project(request)
    plan_input = await parse_body(request, ApplyRunPlanInput)
    run = await runs_service.submit_run(
        request.app["db"], project_row, user_row, plan_input.run_spec
    )
    return model_response(run)


@routes.post("/api/project/{project_name}/runs/update")
async def update(request: web.Request) -> web.Response:
    user_row, project_row = await auth_project(request)
    body = await body_dict(request)
    run_spec = RunSpec.model_validate(body["run_spec"])
    run = await runs_service.update_run(request.app["db"], project_row, user_row, run_spec)
    return model_response(run)


@routes.post("/api/project/{project_name}/runs/submit")
async def submit(request: web.Request) -> web.Response:
    user_row, project_row = await auth_project(request)
    body = await body_dict(request)
    run_spec = RunSpec.model_validate(body["run_spec"])
    run = await runs_service.submit_run(request.app["db"], project_row, user_row, run_spec)
    return model_response(run)


@routes.post("/api/project/{project_name}/runs/list")
async def list_runs(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    from dstack_tpu.core.errors import ServerClientError

    try:
        limit = int(body.get("limit") or 1000)
    except (TypeError, ValueError):
        raise ServerClientError("limit must be an integer")
    runs = await runs_service.list_runs(
        request.app["db"],
        project_id=project_row["id"],
        only_active=bool(body.get("only_active")),
        limit=max(1, min(limit, 1000)),  # negative LIMIT is unlimited in sqlite
        prev_submitted_at=body.get("prev_submitted_at"),
        prev_run_id=body.get("prev_run_id"),
    )
    return model_response(runs)


@routes.post("/api/project/{project_name}/runs/get")
async def get_run(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    run = await runs_service.get_run(request.app["db"], project_row, body["run_name"])
    return model_response(run)


@routes.post("/api/project/{project_name}/runs/get_events")
async def get_run_events(request: web.Request) -> web.Response:
    """The run's lifecycle timeline (every status transition with timestamp,
    actor, reason, trace id) plus derived phase durations — the API behind
    `dstack-tpu events <run>`."""
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    db = request.app["db"]
    from dstack_tpu.core.errors import ResourceNotExistsError
    from dstack_tpu.server.services import events as events_service

    run_name = body.get("run_name")
    row = await db.fetchone(
        "SELECT id, run_name, status FROM runs WHERE project_id = ? AND run_name = ?"
        " AND deleted = 0",
        (project_row["id"], run_name),
    )
    if row is None:
        raise ResourceNotExistsError(f"run {run_name} not found")
    events = await events_service.list_run_events(db, row["id"])
    return web.json_response(
        {
            "run_name": row["run_name"],
            "status": row["status"],
            "events": events,
            "phases": events_service.compute_phases(events),
        }
    )


@routes.post("/api/project/{project_name}/runs/get_metrics")
async def get_run_metrics(request: web.Request) -> web.Response:
    """Workload telemetry for a run: latest step point (step time / tok/s /
    MFU / loss), serving-engine gauges, recent step series, and the goodput
    ledger — the API behind `dstack-tpu metrics <run>`'s workload columns."""
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    db = request.app["db"]
    from dstack_tpu.core.errors import ResourceNotExistsError
    from dstack_tpu.server.services import metrics as metrics_service

    run_name = body.get("run_name")
    row = await db.fetchone(
        "SELECT id, run_name, status FROM runs WHERE project_id = ? AND run_name = ?"
        " AND deleted = 0",
        (project_row["id"], run_name),
    )
    if row is None:
        raise ResourceNotExistsError(f"run {run_name} not found")
    result = await metrics_service.get_run_workload_metrics(
        db, row["id"], limit=int(body.get("limit") or 50)
    )
    return web.json_response(
        {"run_name": row["run_name"], "status": row["status"], **result}
    )


@routes.post("/api/project/{project_name}/runs/get_traces")
async def get_run_traces(request: web.Request) -> web.Response:
    """Fleet-wide flight-recorder readout for a service run: every running
    replica's GET /debug/traces merged newest-first — the API behind
    `dstack-tpu trace <run>`. Optional request_id / trace_id narrow to one
    request (e.g. the X-Dstack-Trace-Id a slow client response carried)."""
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    db = request.app["db"]
    from dstack_tpu.core.errors import ResourceNotExistsError
    from dstack_tpu.server.services import proxy as proxy_service

    run_name = body.get("run_name")
    row = await db.fetchone(
        "SELECT id, run_name, status FROM runs WHERE project_id = ? AND run_name = ?"
        " AND deleted = 0",
        (project_row["id"], run_name),
    )
    if row is None:
        raise ResourceNotExistsError(f"run {run_name} not found")
    result = await proxy_service.collect_service_traces(
        db,
        project_row["id"],
        row["run_name"],
        request_id=body.get("request_id") or None,
        trace_id=body.get("trace_id") or None,
        limit=int(body.get("limit") or 20),
    )
    return web.json_response({"status": row["status"], **result})


@routes.post("/api/project/{project_name}/runs/profile")
async def profile_run(request: web.Request) -> web.Response:
    """Trigger an on-demand profiler capture in a run's live workload
    (server -> agent control file -> jax.profiler in-process). Returns the
    agent's ack; the `profile_end` mark in get_metrics carries the artifact."""
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    from dstack_tpu.server.services import metrics as metrics_service

    seconds = float(body.get("seconds") or 5.0)
    result = await metrics_service.request_profile(
        request.app["db"], project_row, body.get("run_name"), seconds
    )
    return web.json_response(result)


@routes.post("/api/project/{project_name}/runs/stop")
async def stop_runs(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    await runs_service.stop_runs(
        request.app["db"], project_row, body["runs_names"], abort=body.get("abort_requested", False)
    )
    return model_response(None)


@routes.post("/api/project/{project_name}/runs/delete")
async def delete_runs(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    await runs_service.delete_runs(request.app["db"], project_row, body["runs_names"])
    return model_response(None)
