"""/api/users/* (parity: reference server/routers/users.py)."""

from __future__ import annotations

from aiohttp import web

from dstack_tpu.core.errors import ForbiddenError
from dstack_tpu.core.models.users import GlobalRole
from dstack_tpu.server.routers._common import auth_user, body_dict, model_response
from dstack_tpu.server.security import is_global_admin
from dstack_tpu.server.services import users as users_service

routes = web.RouteTableDef()


@routes.post("/api/users/get_my_user")
async def get_my_user(request: web.Request) -> web.Response:
    user_row = await auth_user(request)
    return model_response(users_service.row_to_user(user_row))


@routes.post("/api/users/list")
async def list_users(request: web.Request) -> web.Response:
    await auth_user(request)
    return model_response(await users_service.list_users(request.app["db"]))


@routes.post("/api/users/create")
async def create_user(request: web.Request) -> web.Response:
    user_row = await auth_user(request)
    if not is_global_admin(user_row):
        raise ForbiddenError("admin required")
    body = await body_dict(request)
    user = await users_service.create_user(
        request.app["db"],
        username=body["username"],
        global_role=GlobalRole(body.get("global_role", "user")),
        email=body.get("email"),
    )
    return model_response(user)


@routes.post("/api/users/update")
async def update_user(request: web.Request) -> web.Response:
    user_row = await auth_user(request)
    if not is_global_admin(user_row):
        raise ForbiddenError("admin required")
    body = await body_dict(request)
    user = await users_service.update_user(
        request.app["db"],
        username=body["username"],
        global_role=GlobalRole(body["global_role"]) if "global_role" in body else None,
        email=body.get("email"),
    )
    return model_response(user)


@routes.post("/api/users/get_user")
async def get_user(request: web.Request) -> web.Response:
    user_row = await auth_user(request)
    if not is_global_admin(user_row):
        raise ForbiddenError("admin required")
    body = await body_dict(request)
    row = await users_service.get_user_by_name(request.app["db"], body["username"])
    return model_response(users_service.row_to_user_with_creds(row))


@routes.post("/api/users/refresh_token")
async def refresh_token(request: web.Request) -> web.Response:
    user_row = await auth_user(request)
    body = await body_dict(request)
    if not is_global_admin(user_row) and user_row["username"] != body["username"]:
        raise ForbiddenError("can only refresh own token")
    return model_response(await users_service.refresh_token(request.app["db"], body["username"]))


@routes.post("/api/users/delete")
async def delete_users(request: web.Request) -> web.Response:
    user_row = await auth_user(request)
    if not is_global_admin(user_row):
        raise ForbiddenError("admin required")
    body = await body_dict(request)
    await users_service.delete_users(request.app["db"], body["users"])
    return model_response(None)
