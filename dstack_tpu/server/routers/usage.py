"""/api/usage/get — the fleet accounting readout (ISSUE 19).

Global route like /api/runs/list: admins see every live project, members see
the projects they belong to. The body optionally narrows to one project by
name and/or a `since` ISO timestamp (compared against the ledger's UTC-hour
buckets).
"""

from __future__ import annotations

from aiohttp import web

from dstack_tpu.core.errors import ResourceNotExistsError
from dstack_tpu.server.routers._common import auth_user, body_dict
from dstack_tpu.server.services import usage as usage_service

routes = web.RouteTableDef()


@routes.post("/api/usage/get")
async def get_usage(request: web.Request) -> web.Response:
    user_row = await auth_user(request)
    body = await body_dict(request)
    db = request.app["db"]
    if user_row["global_role"] == "admin":
        rows = await db.fetchall("SELECT id, name FROM projects WHERE deleted = 0")
    else:
        rows = await db.fetchall(
            "SELECT p.id, p.name FROM projects p JOIN members m ON m.project_id = p.id"
            " WHERE m.user_id = ? AND p.deleted = 0",
            (user_row["id"],),
        )
    project = body.get("project")
    if project:
        rows = [r for r in rows if r["name"] == project]
        if not rows:
            raise ResourceNotExistsError(f"project {project} not found")
    result = await usage_service.get_usage(db, rows, since=body.get("since") or None)
    return web.json_response(result)
