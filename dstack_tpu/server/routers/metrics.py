"""Job metrics API + Prometheus export.

Parity: reference routers/metrics.py (GET job metrics with after/before/limit
windows) and routers/prometheus.py (text exposition gated by
ENABLE_PROMETHEUS_METRICS)."""

from __future__ import annotations

from aiohttp import web

from dstack_tpu.core.errors import ResourceNotExistsError
from dstack_tpu.server import settings
from dstack_tpu.server.routers._common import auth_project, body_dict, model_response, required
from dstack_tpu.server.services import metrics as metrics_service

routes = web.RouteTableDef()


@routes.post("/api/project/{project_name}/metrics/job")
async def get_job_metrics(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    db = request.app["db"]
    run_name = required(body, "run_name")
    replica_num = int(body.get("replica_num") or 0)
    job_num = int(body.get("job_num") or 0)
    row = await db.fetchone(
        "SELECT j.id FROM jobs j JOIN runs r ON r.id = j.run_id"
        " WHERE r.project_id = ? AND r.run_name = ? AND r.deleted = 0"
        "   AND j.replica_num = ? AND j.job_num = ?"
        " ORDER BY j.submission_num DESC LIMIT 1",
        (project_row["id"], run_name, replica_num, job_num),
    )
    if row is None:
        raise ResourceNotExistsError(f"no job {job_num}/{replica_num} for run {run_name}")
    result = await metrics_service.get_job_metrics(
        db,
        row["id"],
        limit=int(body.get("limit") or 100),
        after=body.get("after"),
        before=body.get("before"),
    )
    return model_response(result)


@routes.get("/metrics")
async def prometheus_metrics(request: web.Request) -> web.Response:
    if not settings.ENABLE_PROMETHEUS_METRICS:
        raise web.HTTPNotFound()
    from dstack_tpu.server.services import prometheus

    text = await prometheus.render_metrics(request.app["db"])
    return web.Response(text=text, content_type="text/plain", charset="utf-8")
