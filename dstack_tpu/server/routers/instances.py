"""/api/project/{p}/instances/list (parity: reference instances router)."""

from __future__ import annotations

from aiohttp import web

from dstack_tpu.server.routers._common import auth_project, model_response
from dstack_tpu.server.services import instances as instances_service

routes = web.RouteTableDef()


@routes.post("/api/project/{project_name}/instances/list")
async def list_instances(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    db = request.app["db"]
    rows = await instances_service.list_instances(db, project_row["id"])
    fleet_names = {
        r["id"]: r["name"]
        for r in await db.fetchall(
            "SELECT id, name FROM fleets WHERE project_id = ?", (project_row["id"],)
        )
    }
    return model_response(
        [
            instances_service.row_to_instance(
                r, project_row["name"], fleet_names.get(r["fleet_id"])
            )
            for r in rows
        ]
    )
