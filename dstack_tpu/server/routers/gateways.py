"""Gateways API (parity: reference routers/gateways.py)."""

from __future__ import annotations

from aiohttp import web

from dstack_tpu.core.models.configurations import GatewayConfiguration
from dstack_tpu.server.routers._common import auth_project, body_dict, model_response
from dstack_tpu.server.services import gateways as gateways_service

routes = web.RouteTableDef()


@routes.post("/api/project/{project_name}/gateways/list")
async def list_gateways(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    gateways = await gateways_service.list_gateways(request.app["db"], project_row)
    return model_response(gateways)


@routes.post("/api/project/{project_name}/gateways/create")
async def create_gateway(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    conf = GatewayConfiguration.model_validate(body["configuration"])
    gateway = await gateways_service.create_gateway(request.app["db"], project_row, conf)
    return model_response(gateway)


@routes.post("/api/project/{project_name}/gateways/delete")
async def delete_gateways(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    await gateways_service.delete_gateways(
        request.app["db"], project_row, body.get("names") or []
    )
    return web.json_response({})
