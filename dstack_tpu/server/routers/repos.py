"""/api/project/{p}/repos/* incl. code-blob upload (parity: reference repos router +
code upload, services/repos.py)."""

from __future__ import annotations

from aiohttp import web

from dstack_tpu.server.routers._common import auth_project, body_dict, model_response, required
from dstack_tpu.server.services import repos as repos_service

routes = web.RouteTableDef()


@routes.post("/api/project/{project_name}/repos/init")
async def init_repo(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    return model_response(
        await repos_service.init_repo(
            request.app["db"], project_row, required(body, "repo_name"), body.get("repo_info")
        )
    )


@routes.post("/api/project/{project_name}/repos/list")
async def list_repos(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    return model_response(await repos_service.list_repos(request.app["db"], project_row))


@routes.post("/api/project/{project_name}/repos/{repo_name}/upload_code")
async def upload_code(request: web.Request) -> web.Response:
    """Body is the raw tar.gz bytes; returns {"code_hash": ...}."""
    _, project_row = await auth_project(request)
    blob = await request.read()
    code_hash = await repos_service.upload_code(
        request.app["db"], project_row, request.match_info["repo_name"], blob
    )
    return model_response({"code_hash": code_hash})
