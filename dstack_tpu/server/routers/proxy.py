"""Service proxy routes: /proxy/services/{project}/{run}/...

Parity: reference server/services/proxy routers (service_proxy.py) — the
in-server data plane for `type: service` runs. Auth follows the service's
``auth:`` flag: enabled (default) requires a project token; disabled services
are public through the proxy."""

from __future__ import annotations

from aiohttp import web

from dstack_tpu.server.db import loads
from dstack_tpu.server.routers._common import auth_project
from dstack_tpu.server.services import proxy as proxy_service

routes = web.RouteTableDef()


async def _handle(request: web.Request) -> web.StreamResponse:
    db = request.app["db"]
    project_name = request.match_info["project_name"]
    run_name = request.match_info["run_name"]
    tail = request.match_info.get("tail", "")

    # The route table makes the steady-state data plane DB-free: run row,
    # parsed spec, and resolved replica endpoints all come from one cached
    # entry, invalidated on scheduler state transitions + a short TTL.
    entry = await proxy_service.resolve_route(db, project_name, run_name)
    if not entry.is_service:
        raise web.HTTPBadRequest(text=f"run {run_name} is not a service")
    if entry.auth:
        await auth_project(request)

    return await proxy_service.proxy_request(request, db, entry, tail)


routes.route("*", "/proxy/services/{project_name}/{run_name}/{tail:.*}")(_handle)


@routes.route("*", "/proxy/models/{project_name}/v1/{tail:.*}")
async def model_route(request: web.Request) -> web.StreamResponse:
    """In-server OpenAI-compatible model routing: requests name a model in the
    body; the run whose service registered that model serves it (parity:
    reference gateway/services/registry.py:34-373, in-server flavor)."""
    import json as _json

    from dstack_tpu.core.models.services import ServiceSpec
    from dstack_tpu.server.services import proxy as proxy_service

    db = request.app["db"]
    project_name = request.match_info["project_name"]
    tail = request.match_info.get("tail", "")
    project_row = await db.fetchone(
        "SELECT * FROM projects WHERE name = ? AND deleted = 0", (project_name,)
    )
    if project_row is None:
        raise web.HTTPNotFound(text=f"no project {project_name}")
    await auth_project(request)

    run_rows = await db.fetchall(
        "SELECT * FROM runs WHERE project_id = ? AND deleted = 0"
        " AND service_spec IS NOT NULL AND status IN ('running', 'provisioning')",
        (project_row["id"],),
    )
    models = {}
    for row in run_rows:
        spec = ServiceSpec.model_validate(loads(row["service_spec"]))
        if spec.model is not None:
            models[spec.model.name] = (row, spec.model)

    if request.method == "GET" and tail == "models":
        return web.json_response(
            {
                "object": "list",
                "data": [
                    {"id": name, "object": "model", "owned_by": project_name}
                    for name in sorted(models)
                ],
            }
        )

    body = await request.read()
    try:
        model_name = _json.loads(body).get("model")
    except (ValueError, AttributeError):
        model_name = None
    if not model_name or model_name not in models:
        raise web.HTTPNotFound(text=f"no service serves model {model_name!r}")
    run_row, model = models[model_name]
    prefix = (model.prefix or "/v1").strip("/")
    entry = await proxy_service.resolve_route(db, project_name, run_row["run_name"])
    return await proxy_service.proxy_request(
        request, db, entry, f"{prefix}/{tail}", body=body
    )


@routes.get("/api/project/{project_name}/runs/{run_name}/attach/{port}")
async def attach_ws(request: web.Request) -> web.StreamResponse:
    """TCP-over-WebSocket port forward to a run's worker (services/attach.py)."""
    from dstack_tpu.server.services import attach as attach_service

    db = request.app["db"]
    _, project_row = await auth_project(request)
    run_name = request.match_info["run_name"]
    port = int(request.match_info["port"])
    run_row = await db.fetchone(
        "SELECT * FROM runs WHERE project_id = ? AND run_name = ? AND deleted = 0",
        (project_row["id"], run_name),
    )
    if run_row is None:
        raise web.HTTPNotFound(text=f"no run {run_name}")
    return await attach_service.ws_bridge(request, db, run_row, port)
