"""/api/project/{p}/backends/* (parity: reference server/routers/backends.py)."""

from __future__ import annotations

from aiohttp import web

from dstack_tpu.core.models.backends import BackendConfig
from dstack_tpu.server.routers._common import auth_project, body_dict, model_response, parse_body
from dstack_tpu.server.services import backends as backends_service

routes = web.RouteTableDef()


@routes.post("/api/project/{project_name}/backends/create")
async def create_backend(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request, admin_only=True)
    config = await parse_body(request, BackendConfig)
    await backends_service.create_backend(request.app["db"], project_row, config)
    return model_response(config.masked())


@routes.post("/api/project/{project_name}/backends/list")
async def list_backends(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    configs = await backends_service.list_backends(request.app["db"], project_row)
    return model_response([c.masked() for c in configs])


@routes.post("/api/project/{project_name}/backends/delete")
async def delete_backends(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request, admin_only=True)
    body = await body_dict(request)
    await backends_service.delete_backends(
        request.app["db"], project_row, body["backends_names"]
    )
    return model_response(None)
