"""/api/projects/* (parity: reference server/routers/projects.py)."""

from __future__ import annotations

from aiohttp import web

from dstack_tpu.core.errors import ForbiddenError
from dstack_tpu.server.routers._common import (
    auth_project,
    auth_user,
    body_dict,
    model_response,
)
from dstack_tpu.server.security import is_global_admin
from dstack_tpu.server.services import projects as projects_service

routes = web.RouteTableDef()


@routes.post("/api/projects/list")
async def list_projects(request: web.Request) -> web.Response:
    user_row = await auth_user(request)
    return model_response(await projects_service.list_user_projects(request.app["db"], user_row))


@routes.post("/api/projects/create")
async def create_project(request: web.Request) -> web.Response:
    user_row = await auth_user(request)
    body = await body_dict(request)
    project = await projects_service.create_project(
        request.app["db"], user_row, body["project_name"]
    )
    return model_response(project)


@routes.post("/api/projects/delete")
async def delete_projects(request: web.Request) -> web.Response:
    user_row = await auth_user(request)
    db = request.app["db"]
    body = await body_dict(request)
    for name in body["projects_names"]:
        project_row = await projects_service.get_project_row(db, name)
        if not is_global_admin(user_row) and project_row["owner_id"] != user_row["id"]:
            raise ForbiddenError(f"not the owner of {name}")
    await projects_service.delete_projects(db, body["projects_names"])
    return model_response(None)


@routes.post("/api/projects/{project_name}/get")
async def get_project(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    return model_response(
        await projects_service.get_project(request.app["db"], project_row["name"])
    )


@routes.post("/api/projects/{project_name}/set_members")
async def set_members(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request, admin_only=True)
    body = await body_dict(request)
    project = await projects_service.set_members(
        request.app["db"], project_row["name"], body["members"]
    )
    return model_response(project)
