"""/api/project/{p}/fleets/* (parity: reference server routers fleets)."""

from __future__ import annotations

from aiohttp import web

from dstack_tpu.core.models.fleets import ApplyFleetPlanInput, FleetSpec
from dstack_tpu.server.routers._common import (
    auth_project,
    body_dict,
    model_response,
    parse_body,
    required,
)
from dstack_tpu.server.services import fleets as fleets_service

routes = web.RouteTableDef()


@routes.post("/api/project/{project_name}/fleets/list")
async def list_fleets(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    return model_response(await fleets_service.list_fleets(request.app["db"], project_row))


@routes.post("/api/project/{project_name}/fleets/get")
async def get_fleet(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    return model_response(
        await fleets_service.get_fleet(request.app["db"], project_row, required(body, "name"))
    )


@routes.post("/api/project/{project_name}/fleets/get_plan")
async def get_plan(request: web.Request) -> web.Response:
    user_row, project_row = await auth_project(request)
    body = await body_dict(request)
    spec = FleetSpec.model_validate(required(body, "spec"))
    return model_response(
        await fleets_service.get_plan(request.app["db"], project_row, user_row, spec)
    )


@routes.post("/api/project/{project_name}/fleets/apply_plan")
async def apply_plan(request: web.Request) -> web.Response:
    user_row, project_row = await auth_project(request)
    plan = await parse_body(request, ApplyFleetPlanInput)
    return model_response(
        await fleets_service.apply_plan(request.app["db"], project_row, user_row, plan)
    )


@routes.post("/api/project/{project_name}/fleets/delete")
async def delete(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    await fleets_service.delete_fleets(request.app["db"], project_row, required(body, "names"))
    return model_response(None)
