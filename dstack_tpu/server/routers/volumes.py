"""/api/project/{p}/volumes/* (parity: reference server routers volumes)."""

from __future__ import annotations

from aiohttp import web

from dstack_tpu.core.models.configurations import VolumeConfiguration
from dstack_tpu.server.routers._common import auth_project, body_dict, model_response, required
from dstack_tpu.server.services import volumes as volumes_service

routes = web.RouteTableDef()


@routes.post("/api/project/{project_name}/volumes/list")
async def list_volumes(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    return model_response(await volumes_service.list_volumes(request.app["db"], project_row))


@routes.post("/api/project/{project_name}/volumes/get")
async def get_volume(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    return model_response(
        await volumes_service.get_volume(request.app["db"], project_row, required(body, "name"))
    )


@routes.post("/api/project/{project_name}/volumes/create")
async def create(request: web.Request) -> web.Response:
    user_row, project_row = await auth_project(request)
    body = await body_dict(request)
    conf = VolumeConfiguration.model_validate(required(body, "configuration"))
    return model_response(
        await volumes_service.create_volume(request.app["db"], project_row, user_row, conf)
    )


@routes.post("/api/project/{project_name}/volumes/delete")
async def delete(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    await volumes_service.delete_volumes(request.app["db"], project_row, required(body, "names"))
    return model_response(None)
