"""Router plumbing: auth/dependency helpers, pydantic<->JSON glue, error middleware.

Parity: the FastAPI router/dependency layer of the reference (server/app.py:179-199) —
re-built on aiohttp.web with explicit helpers instead of DI."""

from __future__ import annotations

import json
import logging
from typing import Any, Optional, Type, TypeVar

import pydantic
from aiohttp import web

from dstack_tpu.core.errors import (
    ForbiddenError,
    NotAuthenticatedError,
    ResourceExistsError,
    ResourceNotExistsError,
    ServerClientError,
)
from dstack_tpu.server import security
from dstack_tpu.server.services import projects as projects_service

logger = logging.getLogger(__name__)

M = TypeVar("M", bound=pydantic.BaseModel)

_ERROR_STATUS = {
    NotAuthenticatedError: 401,
    ForbiddenError: 403,
    ResourceNotExistsError: 404,
    ResourceExistsError: 409,
}


@web.middleware
async def error_middleware(request: web.Request, handler):
    from dstack_tpu.core.compatibility import API_VERSION_HEADER, check_client_version

    problem = check_client_version(request.headers.get(API_VERSION_HEADER))
    if problem is not None:
        return web.json_response(
            {"detail": [{"msg": problem, "code": "incompatible_api_version"}]},
            status=400,
        )
    try:
        return await handler(request)
    except web.HTTPException:
        raise
    except ServerClientError as e:
        status = 400
        for cls, code in _ERROR_STATUS.items():
            if isinstance(e, cls):
                status = code
                break
        return web.json_response(
            {"detail": [{"msg": e.msg or str(e), "code": e.code}]}, status=status
        )
    except pydantic.ValidationError as e:
        return web.json_response(
            {"detail": [{"msg": str(e), "code": "validation_error"}]}, status=422
        )
    except Exception:
        logger.exception("unhandled server error: %s %s", request.method, request.path)
        return web.json_response(
            {"detail": [{"msg": "internal server error", "code": "server_error"}]}, status=500
        )


async def parse_body(request: web.Request, model: Type[M]) -> M:
    try:
        raw = await request.read()
        data = json.loads(raw) if raw else {}
    except json.JSONDecodeError:
        raise ServerClientError("invalid JSON body")
    try:
        return model.model_validate(data)
    except pydantic.ValidationError as e:
        raise ServerClientError(f"invalid request: {e}")


async def body_dict(request: web.Request) -> dict:
    try:
        raw = await request.read()
        return json.loads(raw) if raw else {}
    except json.JSONDecodeError:
        raise ServerClientError("invalid JSON body")


def required(body: dict, key: str) -> Any:
    """Fetch a required body field; missing/None becomes a 400, not a KeyError 500."""
    value = body.get(key)
    if value is None:
        raise ServerClientError(f"missing required field `{key}`")
    return value


def model_response(obj: Any, status: int = 200) -> web.Response:
    if obj is None:
        return web.json_response(None, status=status)
    if isinstance(obj, pydantic.BaseModel):
        return web.Response(
            text=obj.model_dump_json(), status=status, content_type="application/json"
        )
    if isinstance(obj, list):
        text = "[" + ",".join(
            o.model_dump_json() if isinstance(o, pydantic.BaseModel) else json.dumps(o)
            for o in obj
        ) + "]"
        return web.Response(text=text, status=status, content_type="application/json")
    return web.json_response(obj, status=status)


async def auth_user(request: web.Request):
    return await security.authenticate(request)


async def auth_project(request: web.Request, admin_only: bool = False):
    """Authenticated user + project from the URL + membership check."""
    user_row = await security.authenticate(request)
    project_name = request.match_info["project_name"]
    db = request.app["db"]
    project_row = await projects_service.get_project_row(db, project_name)
    await security.require_project_access(db, project_row, user_row, admin_only=admin_only)
    return user_row, project_row
