"""/api/project/{p}/offers/list — offer browsing for the CLI `offer` command
(parity: reference CLI `dstack offer` backed by get_offers)."""

from __future__ import annotations

from aiohttp import web

from dstack_tpu.core.models.profiles import Profile
from dstack_tpu.core.models.runs import Requirements
from dstack_tpu.core.models.resources import ResourcesSpec
from dstack_tpu.server.routers._common import auth_project, body_dict, model_response
from dstack_tpu.server.services import offers as offers_service

routes = web.RouteTableDef()


@routes.post("/api/project/{project_name}/offers/list")
async def list_offers(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    resources = ResourcesSpec.model_validate(body.get("resources") or {})
    req = Requirements(
        resources=resources,
        max_price=body.get("max_price"),
        spot=body.get("spot"),
    )
    profile = Profile.model_validate(body.get("profile") or {})
    offers = await offers_service.get_offers_by_requirements(
        request.app["db"], project_row, req, profile
    )
    limit = int(body.get("limit") or 100)
    return model_response(
        {
            "offers": [o.model_dump(mode="json") for o in offers[:limit]],
            "total": len(offers),
        }
    )
