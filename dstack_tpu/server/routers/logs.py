"""/api/project/{p}/logs/poll (parity: reference logs router / services/logs)."""

from __future__ import annotations

import logging

from aiohttp import web

from dstack_tpu.core.errors import ResourceNotExistsError, ServerClientError
from dstack_tpu.core.models.logs import JobSubmissionLogs
from dstack_tpu.server.routers._common import auth_project, body_dict, model_response, required
from dstack_tpu.server.services import logs as logs_service

logger = logging.getLogger(__name__)

routes = web.RouteTableDef()


async def _latest_job_id(db, project_id: str, run_name: str) -> str:
    """The run's replica-0/job-0 latest submission — the default log target
    for both the poll and WS endpoints (they must tail the SAME job)."""
    row = await db.fetchone(
        "SELECT j.id FROM jobs j JOIN runs r ON r.id = j.run_id"
        " WHERE r.project_id = ? AND r.run_name = ? AND r.deleted = 0"
        " ORDER BY j.replica_num, j.job_num, j.submission_num DESC LIMIT 1",
        (project_id, run_name),
    )
    if row is None:
        raise ResourceNotExistsError(f"no jobs for run {run_name}")
    return row["id"]


@routes.post("/api/project/{project_name}/logs/poll")
async def poll_logs(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    db = request.app["db"]
    run_name = required(body, "run_name")
    job_id = body.get("job_id")
    if job_id is None:
        job_id = await _latest_job_id(db, project_row["id"], run_name)
    start_line = int(body.get("start_line") or 0)
    limit = min(int(body.get("limit") or 1000), 10000)
    import asyncio

    # File IO off the event loop: a large log file must not stall the scheduler.
    events = await asyncio.to_thread(
        logs_service.get_log_storage().poll_logs,
        project_row["id"],
        run_name,
        job_id,
        start_line,
        limit,
    )
    return model_response(
        JobSubmissionLogs(logs=events, next_token=str(start_line + len(events)))
    )


@routes.get("/api/project/{project_name}/logs/ws")
async def stream_logs_ws(request: web.Request) -> web.StreamResponse:
    """Live log stream: server pushes new log events over a WebSocket (the
    reference runner exposes logs_ws, runner/api/ws.go:18; here the control
    plane bridges it so the SPA tails without polling). Browser clients
    authenticate via ?token= (see security.get_request_token)."""
    import asyncio
    import json as _json

    _, project_row = await auth_project(request)
    run_name = request.query.get("run_name")
    if not run_name:
        raise ServerClientError("run_name query parameter required")
    db = request.app["db"]
    job_id = await _latest_job_id(db, project_row["id"], run_name)
    try:
        start_line = int(request.query.get("start_line") or 0)
    except ValueError:
        raise ServerClientError("start_line must be an integer")

    ws = web.WebSocketResponse(heartbeat=30)
    await ws.prepare(request)

    async def pump() -> None:
        nonlocal start_line
        storage = logs_service.get_log_storage()
        while True:
            events = await asyncio.to_thread(
                storage.poll_logs, project_row["id"], run_name, job_id,
                start_line, 1000,
            )
            if events:
                start_line += len(events)
                await ws.send_json({
                    "logs": [_json.loads(e.model_dump_json()) for e in events],
                    "next_line": start_line,
                })
            else:
                await asyncio.sleep(0.5)

    task = asyncio.create_task(pump())
    try:
        async for _msg in ws:  # drain until the client closes
            pass
    finally:
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass
        except Exception:
            # An abrupt tab close makes the in-flight send_json raise a
            # connection error; that is a normal end of stream, not a 500.
            logger.debug("log stream for %s ended abruptly", run_name, exc_info=True)
    return ws
