"""/api/project/{p}/logs/poll (parity: reference logs router / services/logs)."""

from __future__ import annotations

from aiohttp import web

from dstack_tpu.core.errors import ResourceNotExistsError
from dstack_tpu.core.models.logs import JobSubmissionLogs
from dstack_tpu.server.routers._common import auth_project, body_dict, model_response, required
from dstack_tpu.server.services import logs as logs_service

routes = web.RouteTableDef()


@routes.post("/api/project/{project_name}/logs/poll")
async def poll_logs(request: web.Request) -> web.Response:
    _, project_row = await auth_project(request)
    body = await body_dict(request)
    db = request.app["db"]
    run_name = required(body, "run_name")
    job_id = body.get("job_id")
    if job_id is None:
        # Default to the latest submission of job (replica 0, num 0).
        row = await db.fetchone(
            "SELECT j.id FROM jobs j JOIN runs r ON r.id = j.run_id"
            " WHERE r.project_id = ? AND r.run_name = ? AND r.deleted = 0"
            " ORDER BY j.replica_num, j.job_num, j.submission_num DESC LIMIT 1",
            (project_row["id"], run_name),
        )
        if row is None:
            raise ResourceNotExistsError(f"no jobs for run {run_name}")
        job_id = row["id"]
    start_line = int(body.get("start_line") or 0)
    limit = min(int(body.get("limit") or 1000), 10000)
    import asyncio

    # File IO off the event loop: a large log file must not stall the scheduler.
    events = await asyncio.to_thread(
        logs_service.get_log_storage().poll_logs,
        project_row["id"],
        run_name,
        job_id,
        start_line,
        limit,
    )
    return model_response(
        JobSubmissionLogs(logs=events, next_token=str(start_line + len(events)))
    )
