"""Env-var driven server settings (parity: reference server/settings.py:1-103)."""

from __future__ import annotations

import os
from pathlib import Path


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.getenv(name)
    if v is None:
        return default
    return v.lower() in ("1", "true", "yes", "on")


SERVER_DIR = Path(os.getenv("DSTACK_TPU_SERVER_DIR", os.path.expanduser("~/.dstack-tpu/server")))
DATA_DIR = SERVER_DIR / "data"
LOGS_DIR = SERVER_DIR / "logs"

# DSTACK_TPU_DB_URL accepts a postgres:// DSN (multi-replica control plane;
# reference server/db.py supports both dialects the same way) or a
# sqlite:///path URL; DSTACK_TPU_DB_PATH remains the plain-path spelling.
DB_PATH = os.getenv(
    "DSTACK_TPU_DB_URL",
    os.getenv("DSTACK_TPU_DB_PATH", str(DATA_DIR / "server.db")),
)

ADMIN_TOKEN = os.getenv("DSTACK_TPU_SERVER_ADMIN_TOKEN")

# At-rest encryption keys, JSON list ordered head-first, e.g.
# '[{"type": "aes", "secret": "<base64 32 bytes>", "name": "k1"}, {"type": "identity"}]'.
# Unset = identity codec (base64 of plaintext — NOT encrypted); see services/encryption.
ENCRYPTION_KEYS = os.getenv("DSTACK_TPU_ENCRYPTION_KEYS")
DEFAULT_PROJECT_NAME = os.getenv("DSTACK_TPU_DEFAULT_PROJECT", "main")

# Background processing knobs (reference background/__init__.py:39-100). The reference
# caps at 150 active jobs/replica with 4s loops; we default to tighter loops (asyncio is
# cheap without APScheduler's executor pools) — see bench: scheduling throughput.
PROCESS_RUNS_INTERVAL = float(os.getenv("DSTACK_TPU_PROCESS_RUNS_INTERVAL", "1.0"))
PROCESS_SUBMITTED_JOBS_INTERVAL = float(os.getenv("DSTACK_TPU_PROCESS_SUBMITTED_JOBS_INTERVAL", "1.0"))
PROCESS_RUNNING_JOBS_INTERVAL = float(os.getenv("DSTACK_TPU_PROCESS_RUNNING_JOBS_INTERVAL", "1.0"))
PROCESS_TERMINATING_JOBS_INTERVAL = float(os.getenv("DSTACK_TPU_PROCESS_TERMINATING_JOBS_INTERVAL", "1.0"))
PROCESS_INSTANCES_INTERVAL = float(os.getenv("DSTACK_TPU_PROCESS_INSTANCES_INTERVAL", "2.0"))
PROCESS_FLEETS_INTERVAL = float(os.getenv("DSTACK_TPU_PROCESS_FLEETS_INTERVAL", "5.0"))
PROCESS_VOLUMES_INTERVAL = float(os.getenv("DSTACK_TPU_PROCESS_VOLUMES_INTERVAL", "5.0"))
PROCESS_GATEWAYS_INTERVAL = float(os.getenv("DSTACK_TPU_PROCESS_GATEWAYS_INTERVAL", "5.0"))
PROCESS_METRICS_INTERVAL = float(os.getenv("DSTACK_TPU_PROCESS_METRICS_INTERVAL", "10.0"))
PROCESS_SERVICES_INTERVAL = float(os.getenv("DSTACK_TPU_PROCESS_SERVICES_INTERVAL", "5.0"))
# The autoscaling decision pass runs tighter than the probe pass: latency
# spikes and scale-from-zero wakeups should not wait out a 5s probe loop.
PROCESS_AUTOSCALER_INTERVAL = float(os.getenv("DSTACK_TPU_PROCESS_AUTOSCALER_INTERVAL", "2.0"))
PROCESS_BATCH_SIZE = int(os.getenv("DSTACK_TPU_PROCESS_BATCH_SIZE", "10"))
METRICS_TTL_SECONDS = int(os.getenv("DSTACK_TPU_METRICS_TTL", "3600"))
# Fleet accounting (services/usage.py): jobs that finished within this many
# seconds stay in the metering scan so their final accrual window (finish
# between two ticks, or a short restart gap) is still folded into the ledger.
USAGE_FINISHED_GRACE = float(os.getenv("DSTACK_TPU_USAGE_FINISHED_GRACE", "300"))

# Concurrent scheduler fan-out: each background pass processes up to this many
# independent runs/gangs at once (bounded asyncio.gather); per-run keyed locks
# (services/locking.py) keep same-run work serialized. 1 restores the old
# strictly-serial passes.
SCHEDULER_CONCURRENCY = int(os.getenv("DSTACK_TPU_SCHEDULER_CONCURRENCY", "16"))

# Offer cache TTL (seconds): identical (project, requirements, profile) offer
# queries within the window reuse the last catalog fan-in instead of re-querying
# every backend (150 identical v5e-8 submissions hit the catalog once). 0
# disables. Invalidated early when a project's backend config changes.
OFFER_CACHE_TTL = float(os.getenv("DSTACK_TPU_OFFER_CACHE_TTL", "30"))

# Service-proxy fast path. Route cache TTL (seconds): the staleness bound for
# cached run-row/spec/replica-endpoint routes when an invalidation hook is
# missed; state transitions (job status, probe flips, scaling, deletion)
# invalidate eagerly, so this is a fallback, not the refresh mechanism. 0
# disables the cache (per-request DB resolution, the pre-fast-path behavior).
PROXY_ROUTE_CACHE_TTL = float(os.getenv("DSTACK_TPU_PROXY_ROUTE_CACHE_TTL", "10"))
# The upstream keep-alive pool's per-replica-host cap lives in
# DSTACK_TPU_PROXY_POOL_SIZE, read directly by core/services/http_forward
# (core must not depend on server settings — the gateway appliance uses it too).

# Cache-aware replica routing (services/routing.py). "prefix" hashes each
# request's leading prompt tokens/bytes onto a rendezvous ring over the ready
# replicas so shared prefixes land on the replica whose KV prefix cache is
# already warm; "round_robin" restores the blind cursor. PREFIX_BLOCK is how
# many leading tokens (or raw prompt bytes) form the routing key — align it
# with the engine's --prefix-block so equal keys mean shareable KV blocks.
# SPILL_QUEUE_DEPTH: when the prefix-preferred replica last reported an engine
# queue depth above this bound, the request spills to the least-loaded ready
# replica instead (cache affinity must not hotspot one replica). STICKY_MAX
# bounds the per-run LRU of memoized bucket->replica assignments.
PROXY_ROUTING_POLICY = os.getenv("DSTACK_TPU_PROXY_ROUTING_POLICY", "prefix")
PROXY_ROUTING_PREFIX_BLOCK = int(os.getenv("DSTACK_TPU_PROXY_ROUTING_PREFIX_BLOCK", "64"))
PROXY_SPILL_QUEUE_DEPTH = float(os.getenv("DSTACK_TPU_PROXY_SPILL_QUEUE_DEPTH", "8"))
PROXY_ROUTING_STICKY_MAX = int(os.getenv("DSTACK_TPU_PROXY_ROUTING_STICKY_MAX", "4096"))

# Scheduler FSM knobs.
MAX_OFFERS_TRIED = int(os.getenv("DSTACK_TPU_MAX_OFFERS_TRIED", "5"))
PROVISIONING_TIMEOUT = float(os.getenv("DSTACK_TPU_PROVISIONING_TIMEOUT", "600"))
RUNNER_DISCONNECT_TIMEOUT = float(os.getenv("DSTACK_TPU_RUNNER_DISCONNECT_TIMEOUT", "120"))
RETRY_BACKOFF_BASE = float(os.getenv("DSTACK_TPU_RETRY_BACKOFF_BASE", "15"))
RETRY_BACKOFF_MAX = float(os.getenv("DSTACK_TPU_RETRY_BACKOFF_MAX", "600"))
TERMINATION_RETRY_WINDOW = float(os.getenv("DSTACK_TPU_TERMINATION_RETRY_WINDOW", "600"))

# Run-ownership leases (services/leases.py): each run-keyed scheduler pass
# claims only runs this replica owns; a lease not renewed within LEASE_TTL is
# reclaimable by any live replica, which reconciles the orphaned run. The TTL
# must comfortably exceed the slowest pass interval (passes renew by claiming).
# DSTACK_TPU_REPLICA_ID pins the replica identity (defaults to host-pid-rand,
# so a restart is a NEW replica and its stale leases age out via the TTL).
RUN_LEASES_ENABLED = _env_bool("DSTACK_TPU_RUN_LEASES", True)
LEASE_TTL = float(os.getenv("DSTACK_TPU_LEASE_TTL", "30"))
REPLICA_ID = os.getenv("DSTACK_TPU_REPLICA_ID")
# Cross-replica notify poll tick: while a notify-registered loop (the
# submitted-jobs pass) sleeps out its interval, it checks the shared
# run_leases notify stamp this often — a submit on another replica is picked
# up next tick instead of next interval. 0 disables the polling (the
# in-process wake() nudge still works).
SCHEDULER_NOTIFY_POLL = float(os.getenv("DSTACK_TPU_SCHEDULER_NOTIFY_POLL", "0.05"))

# Resilience layer (services/resilience.py): per-target circuit breakers over
# the external call families (runner agents, backend Compute, proxy->replica
# forwards). A target opens after BREAKER_THRESHOLD consecutive failures and
# half-opens one probe call after BREAKER_COOLDOWN seconds.
BREAKER_THRESHOLD = int(os.getenv("DSTACK_TPU_BREAKER_THRESHOLD", "5"))
BREAKER_COOLDOWN = float(os.getenv("DSTACK_TPU_BREAKER_COOLDOWN", "30"))
# Runner agent calls: per-request timeout and transport-level retry attempts
# (jittered backoff between attempts; healthcheck and stop never retry).
RUNNER_REQUEST_TIMEOUT = float(os.getenv("DSTACK_TPU_RUNNER_REQUEST_TIMEOUT", "10"))
RUNNER_CALL_ATTEMPTS = int(os.getenv("DSTACK_TPU_RUNNER_CALL_ATTEMPTS", "2"))
# Backend Compute calls: explicit deadline on create_slice (cloud queued
# resources legitimately take a while) and update_provisioning_data polls.
BACKEND_CALL_TIMEOUT = float(os.getenv("DSTACK_TPU_BACKEND_CALL_TIMEOUT", "300"))
BACKEND_POLL_TIMEOUT = float(os.getenv("DSTACK_TPU_BACKEND_POLL_TIMEOUT", "30"))

# Gang health (services/gang_health.py): per-host step-skew analysis joined
# across ALL jobs of a run on every metrics pass. A host whose window-median
# step time exceeds STRAGGLER_K x the gang median for STRAGGLER_WINDOWS
# consecutive passes is flagged (run_event + /metrics gauge); a flagged host
# clears after the same number of windows below the LOWER clear threshold
# (hysteresis — a host flapping around K can't spam events).
GANG_WINDOW_SECONDS = float(os.getenv("DSTACK_TPU_GANG_WINDOW_SECONDS", "120"))
STRAGGLER_K = float(os.getenv("DSTACK_TPU_STRAGGLER_K", "1.5"))
STRAGGLER_CLEAR_K = float(os.getenv("DSTACK_TPU_STRAGGLER_CLEAR_K", "1.2"))
STRAGGLER_WINDOWS = int(os.getenv("DSTACK_TPU_STRAGGLER_WINDOWS", "2"))

LOCAL_BACKEND_ENABLED = _env_bool("DSTACK_TPU_LOCAL_BACKEND_ENABLED", True)
# Container mode the local backend passes to its runner agents (--docker):
# never = host exec (default, no engine dependency), auto/always = container path.
LOCAL_DOCKER_MODE = os.getenv("DSTACK_TPU_LOCAL_DOCKER", "never")

# SSH transport: cloud runner traffic rides ssh -L tunnels (reference tunnel.py).
# Disabled -> direct HTTP (dev). Identity defaults to a server-generated ed25519 key.
SSH_TUNNELS_ENABLED = _env_bool("DSTACK_TPU_SSH_TUNNELS_ENABLED", True)
SSH_IDENTITY_FILE = os.getenv("DSTACK_TPU_SSH_IDENTITY_FILE")
ENABLE_PROMETHEUS_METRICS = _env_bool("DSTACK_TPU_ENABLE_PROMETHEUS_METRICS", True)

# Plan-time registry image introspection (reference services/docker.py:34-70):
# a bad image:/credential fails in the plan instead of after provisioning.
VALIDATE_IMAGES = _env_bool("DSTACK_TPU_VALIDATE_IMAGES", True)

MAX_CODE_SIZE = int(os.getenv("DSTACK_TPU_MAX_CODE_SIZE", str(2 * 1024 * 1024)))  # 2 MiB, ref settings.py:92

SERVER_HOST = os.getenv("DSTACK_TPU_SERVER_HOST", "127.0.0.1")
SERVER_PORT = int(os.getenv("DSTACK_TPU_SERVER_PORT", "3000"))
