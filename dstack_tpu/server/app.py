"""Server application factory + lifespan.

Parity: reference server/app.py (create_app:80, lifespan:96-162: migrate -> config ->
admin -> default project -> background tasks) on aiohttp.web."""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

from aiohttp import web

from dstack_tpu.core.models.users import ProjectRole
from dstack_tpu.server import settings
from dstack_tpu.server.db import Database
from dstack_tpu.server.routers import backends as backends_router
from dstack_tpu.server.routers import fleets as fleets_router
from dstack_tpu.server.routers import instances as instances_router
from dstack_tpu.server.routers import logs as logs_router
from dstack_tpu.server.routers import gateways as gateways_router
from dstack_tpu.server.routers import metrics as metrics_router
from dstack_tpu.server.routers import proxy as proxy_router
from dstack_tpu.server.routers import offers as offers_router
from dstack_tpu.server.routers import projects as projects_router
from dstack_tpu.server.routers import repos as repos_router
from dstack_tpu.server.routers import runs as runs_router
from dstack_tpu.server.routers import secrets as secrets_router
from dstack_tpu.server.routers import usage as usage_router
from dstack_tpu.server.routers import users as users_router
from dstack_tpu.server.routers import volumes as volumes_router
from dstack_tpu.server.routers._common import error_middleware
from dstack_tpu.server.services import projects as projects_service
from dstack_tpu.server.services import users as users_service

logger = logging.getLogger(__name__)


async def _on_startup(app: web.Application) -> None:
    # Error reporting first (reference app.py:81-89 inits Sentry before the
    # rest of the lifespan): startup failures below should be reported too.
    from dstack_tpu.server.services import error_reporting

    error_reporting.setup()
    db: Database = app["db"]
    await db.connect()  # runs migrations
    if settings.ENCRYPTION_KEYS:
        from dstack_tpu.server.services import encryption

        key_specs = json.loads(settings.ENCRYPTION_KEYS)
        encryption.configure_keys(key_specs)
        logger.info("configured %d at-rest encryption key(s)", len(key_specs))
    # Multi-replica HA init: N replicas sharing one postgres database elect a
    # single bootstrapper via an advisory lock (no-op on sqlite; reference
    # server/app.py:109-113 guards the same section the same way).
    async with db.advisory_lock("server-init"):
        admin_row, created = await users_service.get_or_create_admin_user(
            db, token=settings.ADMIN_TOKEN
        )
        app["admin_token"] = admin_row["token"]
        if created:
            logger.info("created admin user")
        # default project
        existing = await db.fetchone(
            "SELECT id FROM projects WHERE name = ? AND deleted = 0",
            (settings.DEFAULT_PROJECT_NAME,),
        )
        if existing is None:
            await projects_service.create_project(db, admin_row, settings.DEFAULT_PROJECT_NAME)
            logger.info("created default project %s", settings.DEFAULT_PROJECT_NAME)
        # Declarative server config: converge projects/backends/plugins to
        # config.yml (reference ServerConfigManager, services/config.py).
        # Inside the init lock: concurrent replicas applying the same config
        # would race on project/backend creation.
        try:
            from dstack_tpu.server.services import config as config_service
            from dstack_tpu.server.services import encryption as encryption_service

            server_config = config_service.load_config(settings.SERVER_DIR)
            env_plugins = os.getenv("DSTACK_TPU_PLUGINS")
            if env_plugins:
                server_config.plugins.extend(
                    p.strip() for p in env_plugins.split(",") if p.strip()
                )
            if (
                server_config.encryption is not None
                and server_config.encryption.keys
                and not settings.ENCRYPTION_KEYS  # env wins over the file
            ):
                encryption_service.configure_keys(server_config.encryption.keys)
            await config_service.apply_config(db, admin_row, server_config)
        except Exception:
            logger.exception("applying server config failed; continuing with DB state")
    # Re-prime the service autoscaler's RPS window from its persisted buckets
    # so a restart doesn't zero a busy service's scaling knowledge.
    try:
        from dstack_tpu.server.services import proxy as proxy_service

        await proxy_service.prime_stats(db)
    except Exception:
        logger.exception("priming service stats failed; starting with an empty window")
    # Crash-safe startup reconciliation: adopt active runs whose lease holder
    # died (or whose lease is ours from a previous incarnation) BEFORE the
    # scheduler loops start — killing a replica mid-provision loses nothing
    # but the interrupted pass (services/leases.py).
    try:
        from dstack_tpu.server.services import leases as leases_service

        adopted = await leases_service.startup_reconcile(db)
        if adopted:
            logger.info(
                "replica %s adopted %d orphaned in-flight run(s) at startup",
                leases_service.replica_id(), adopted,
            )
    except Exception:
        logger.exception("startup lease reconciliation failed; continuing")
    if app["run_background_tasks"]:
        from dstack_tpu.server.background import start_background_tasks

        app["background"] = start_background_tasks(app)


async def _on_cleanup(app: web.Application) -> None:
    bg = app.get("background")
    if bg is not None:
        await bg.stop()
    # Reap every SSH tunnel child; orphaned ssh -N processes outlive us otherwise.
    try:
        from dstack_tpu.server.services.runner import ssh as runner_ssh

        await runner_ssh.close_all_tunnels()
    except Exception:
        logger.exception("closing tunnels during shutdown failed")
    # Drain the proxy's pooled upstream connections (keep-alive sockets would
    # otherwise linger until GC).
    try:
        from dstack_tpu.core.services import http_forward

        await http_forward.close_session()
    except Exception:
        logger.exception("closing the proxy connection pool failed")
    await app["db"].close()


async def healthcheck(request: web.Request) -> web.Response:
    import dstack_tpu

    return web.json_response({"status": "ok", "version": dstack_tpu.__version__})


async def dashboard(request: web.Request) -> web.Response:
    """Admin SPA shell (the reference serves a React SPA from server/statics,
    app.py:292-295; this serves the repo's build-less ES-module equivalent)."""
    from pathlib import Path

    path = Path(__file__).parent / "statics" / "index.html"
    return web.Response(text=path.read_text(), content_type="text/html")


def create_app(
    db_path: Optional[str] = None,
    run_background_tasks: bool = True,
) -> web.Application:
    from dstack_tpu.server.services.request_metrics import request_metrics_middleware

    app = web.Application(
        middlewares=[request_metrics_middleware, error_middleware],
        client_max_size=settings.MAX_CODE_SIZE + 1024**2,
    )
    app["db"] = Database(db_path if db_path is not None else settings.DB_PATH)
    app["run_background_tasks"] = run_background_tasks
    from pathlib import Path

    app.router.add_get("/healthcheck", healthcheck)
    app.router.add_get("/", dashboard)
    app.router.add_static("/statics/", Path(__file__).parent / "statics")
    app.add_routes(users_router.routes)
    app.add_routes(projects_router.routes)
    app.add_routes(runs_router.routes)
    app.add_routes(backends_router.routes)
    app.add_routes(fleets_router.routes)
    app.add_routes(volumes_router.routes)
    app.add_routes(secrets_router.routes)
    app.add_routes(repos_router.routes)
    app.add_routes(offers_router.routes)
    app.add_routes(logs_router.routes)
    app.add_routes(instances_router.routes)
    app.add_routes(metrics_router.routes)
    app.add_routes(usage_router.routes)
    app.add_routes(proxy_router.routes)
    app.add_routes(gateways_router.routes)
    app.on_startup.append(_on_startup)
    app.on_cleanup.append(_on_cleanup)
    return app


def main(host: Optional[str] = None, port: Optional[int] = None) -> None:  # pragma: no cover
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s %(levelname)s %(message)s")
    app = create_app()

    async def _print_token(app_: web.Application) -> None:
        logger.info("admin token: %s", app_["admin_token"])

    app.on_startup.append(_print_token)
    web.run_app(app, host=host or settings.SERVER_HOST, port=port or settings.SERVER_PORT)


if __name__ == "__main__":  # pragma: no cover
    main()
