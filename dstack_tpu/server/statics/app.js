/* dstack-tpu admin SPA — build-less ES module, zero dependencies.
   Parity target: the reference ships a React SPA from server statics
   (ref: src/dstack/_internal/server/app.py:292-295, frontend/src/); this is the
   TPU repo's equivalent over the same REST API the CLI/SDK use. */

const $app = document.getElementById("app");
const LS_TOKEN = "dstack_tpu_token";
const LS_PROJECT = "dstack_tpu_project";

let state = {
  token: localStorage.getItem(LS_TOKEN) || "",
  project: localStorage.getItem(LS_PROJECT) || "main",
  projects: [],
  user: null,
};

/* ---------------- tiny DOM + API helpers ---------------- */

function h(tag, attrs = {}, ...children) {
  const el = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs || {})) {
    if (k === "class") el.className = v;
    else if (k.startsWith("on") && typeof v === "function") el.addEventListener(k.slice(2), v);
    else if (v !== null && v !== undefined) el.setAttribute(k, v);
  }
  for (const c of children.flat(Infinity)) {
    if (c === null || c === undefined || c === false) continue;
    el.append(c.nodeType ? c : document.createTextNode(String(c)));
  }
  return el;
}

class ApiError extends Error {
  constructor(status, detail) { super(detail || `HTTP ${status}`); this.status = status; }
}

async function api(path, body) {
  const resp = await fetch(path, {
    method: "POST",
    headers: {
      "Content-Type": "application/json",
      ...(state.token ? { Authorization: `Bearer ${state.token}` } : {}),
    },
    body: JSON.stringify(body || {}),
  });
  if (resp.status === 401 || resp.status === 403) {
    if (location.hash !== "#/login") { location.hash = "#/login"; }
    throw new ApiError(resp.status, "unauthorized");
  }
  const text = await resp.text();
  let data = null;
  try { data = text ? JSON.parse(text) : null; } catch { data = { raw: text }; }
  if (!resp.ok) throw new ApiError(resp.status, data && (data.detail || data.error) || text);
  return data;
}

const P = () => encodeURIComponent(state.project);

/* ---------------- formatting ---------------- */

function ago(iso) {
  if (!iso) return "—";
  const s = (Date.now() - new Date(iso).getTime()) / 1000;
  if (s < 0) return "now";
  if (s < 60) return `${Math.floor(s)}s ago`;
  if (s < 3600) return `${Math.floor(s / 60)}m ago`;
  if (s < 86400) return `${Math.floor(s / 3600)}h ${Math.floor((s % 3600) / 60)}m ago`;
  return `${Math.floor(s / 86400)}d ago`;
}

function bytes(n) {
  if (n === null || n === undefined) return "—";
  const u = ["B", "KiB", "MiB", "GiB", "TiB"];
  let i = 0;
  while (n >= 1024 && i < u.length - 1) { n /= 1024; i++; }
  return `${n.toFixed(n >= 10 || i === 0 ? 0 : 1)} ${u[i]}`;
}

const money = (x) => (x || x === 0 ? `$${Number(x).toFixed(Number(x) < 10 ? 3 : 2)}` : "—");

/* Status → pill class. Status colors are reserved for state and always carry
   the status text itself (never color alone). */
const STATUS_CLASS = {
  done: "good", running: "active", active: "good", idle: "good",
  submitted: "warn", provisioning: "warn", pulling: "warn", starting: "warn",
  creating: "warn", busy: "active",
  failed: "critical", terminated: "serious", terminating: "warn", aborted: "serious",
};
const pill = (status) =>
  h("span", { class: `pill ${STATUS_CLASS[status] || ""}` }, h("span", { class: "dot" }), status || "—");

function confirmThen(msg, fn) {
  return async (ev) => {
    ev.preventDefault(); ev.stopPropagation();
    if (window.confirm(msg)) { try { await fn(); } catch (e) { alert(e.message); } refresh(); }
  };
}

/* ---------------- sparkline chart (single series, hover layer) ---------------- */

let $tip = null;
function tipShow(x, y, html) {
  if (!$tip) { $tip = h("div", { class: "chart-tip" }); document.body.append($tip); }
  $tip.innerHTML = html;
  $tip.style.left = `${Math.min(x + 12, window.innerWidth - 160)}px`;
  $tip.style.top = `${y + 12}px`;
  $tip.style.display = "block";
}
function tipHide() { if ($tip) $tip.style.display = "none"; }

function sparkline(points, { title, unit = "", fmt = (v) => v.toFixed(1), w = 300, hgt = 64 }) {
  // One series per chart (one axis); the title names the series, so no legend.
  const card = h("div", { class: "chart-card" });
  card.append(h("div", { class: "title" }, title));
  if (!points.length) { card.append(h("div", { class: "muted" }, "no data")); return card; }
  const vals = points.map((p) => p.v);
  const latest = vals[vals.length - 1];
  card.append(h("div", { class: "latest" }, `${fmt(latest)}${unit}`));
  const mn = 0, mx = Math.max(...vals, 1e-9);
  const px = (i) => (points.length === 1 ? w / 2 : (i / (points.length - 1)) * (w - 8) + 4);
  const py = (v) => hgt - 14 - ((v - mn) / (mx - mn || 1)) * (hgt - 22);
  const d = points.map((p, i) => `${i ? "L" : "M"}${px(i).toFixed(1)},${py(p.v).toFixed(1)}`).join("");
  const ns = "http://www.w3.org/2000/svg";
  const svg = document.createElementNS(ns, "svg");
  svg.setAttribute("viewBox", `0 0 ${w} ${hgt}`);
  svg.setAttribute("height", hgt);
  const mk = (tag, attrs) => {
    const e = document.createElementNS(ns, tag);
    for (const [k, v] of Object.entries(attrs)) e.setAttribute(k, v);
    svg.append(e); return e;
  };
  mk("line", { x1: 4, x2: w - 4, y1: py(0), y2: py(0), stroke: "var(--border)", "stroke-width": 1 });
  mk("path", { d, fill: "none", stroke: "var(--series-1)", "stroke-width": 2, "stroke-linejoin": "round" });
  const axisMax = mk("text", { x: 4, y: 10, class: "axis" });
  axisMax.textContent = `${fmt(mx)}${unit}`;
  const cross = mk("line", { y1: 8, y2: hgt - 14, stroke: "var(--text-muted)", "stroke-width": 1, visibility: "hidden" });
  const dot = mk("circle", { r: 3.5, fill: "var(--series-1)", stroke: "var(--surface-1)", "stroke-width": 2, visibility: "hidden" });
  svg.addEventListener("mousemove", (ev) => {
    const rect = svg.getBoundingClientRect();
    const fx = ((ev.clientX - rect.left) / rect.width) * w;
    let best = 0, bd = Infinity;
    points.forEach((p, i) => { const dd = Math.abs(px(i) - fx); if (dd < bd) { bd = dd; best = i; } });
    const p = points[best];
    cross.setAttribute("x1", px(best)); cross.setAttribute("x2", px(best));
    cross.setAttribute("visibility", "visible");
    dot.setAttribute("cx", px(best)); dot.setAttribute("cy", py(p.v));
    dot.setAttribute("visibility", "visible");
    tipShow(ev.clientX, ev.clientY,
      `<b>${fmt(p.v)}${unit}</b><br><span class="muted">${new Date(p.t).toLocaleTimeString()}</span>`);
  });
  svg.addEventListener("mouseleave", () => { cross.setAttribute("visibility", "hidden"); dot.setAttribute("visibility", "hidden"); tipHide(); });
  card.append(svg);
  return card;
}

/* ---------------- layout ---------------- */

const NAV = [
  ["runs", "Runs"], ["fleets", "Fleets"], ["instances", "Instances"],
  ["volumes", "Volumes"], ["gateways", "Gateways"], ["offers", "Offers"],
  ["secrets", "Secrets"],
];

function layout(section, content) {
  const nav = h("nav", {},
    NAV.map(([key, label]) =>
      h("a", { href: `#/p/${P()}/${key}`, class: section === key ? "active" : "" }, label)),
    h("a", { href: "#/projects", class: section === "projects" ? "active" : "" }, "Projects"),
    state.user && state.user.global_role === "admin"
      ? h("a", { href: "#/users", class: section === "users" ? "active" : "" }, "Users") : null,
  );
  const projSel = h("select", {
    onchange: (ev) => {
      state.project = ev.target.value;
      localStorage.setItem(LS_PROJECT, state.project);
      location.hash = `#/p/${P()}/${NAV.some(([k]) => k === section) ? section : "runs"}`;
    },
  }, state.projects.map((p) => h("option", { value: p.project_name, selected: p.project_name === state.project ? "" : null }, p.project_name)));
  return [
    h("header", { class: "top" },
      h("span", { class: "logo" }, h("a", { href: "#/" }, "dstack-tpu")),
      nav,
      h("span", { class: "spacer" }),
      projSel,
      h("button", { class: "small", onclick: () => { localStorage.removeItem(LS_TOKEN); state.token = ""; location.hash = "#/login"; } }, "Sign out"),
    ),
    h("main", {}, content),
  ];
}

function render(...children) {
  $app.replaceChildren(...children.flat(Infinity).filter(Boolean));
}

function table(headers, rows, emptyMsg) {
  if (!rows.length) return h("div", { class: "empty" }, emptyMsg || "nothing here yet");
  return h("table", { class: "list" },
    h("thead", {}, h("tr", {}, headers.map((hd) => h("th", {}, hd)))),
    h("tbody", {}, rows));
}

/* Client-side pagination: `key` keeps the page across auto-refreshes. */
const pageState = {};
function paginated(key, headers, rows, emptyMsg, pageSize = 25) {
  const wrap = h("div", {});
  const draw = () => {
    const total = Math.max(1, Math.ceil(rows.length / pageSize));
    const page = Math.min(pageState[key] || 0, total - 1);
    pageState[key] = page;
    const slice = rows.slice(page * pageSize, (page + 1) * pageSize);
    wrap.replaceChildren(
      table(headers, slice, emptyMsg),
      rows.length > pageSize
        ? h("div", { class: "pager" },
            h("button", { class: "small", disabled: page === 0 ? "" : null, onclick: () => { pageState[key] = page - 1; draw(); } }, "‹ prev"),
            h("span", { class: "muted" }, ` page ${page + 1} / ${total} — ${rows.length} rows `),
            h("button", { class: "small", disabled: page >= total - 1 ? "" : null, onclick: () => { pageState[key] = page + 1; draw(); } }, "next ›"))
        : null,
    );
  };
  draw();
  return wrap;
}

/* ---------------- views ---------------- */

async function viewLogin() {
  stopTimers();
  const input = h("input", { type: "password", placeholder: "admin token", autofocus: "" });
  const err = h("div", { class: "err" });
  const form = h("form", {
    onsubmit: async (ev) => {
      ev.preventDefault();
      state.token = input.value.trim();
      try {
        state.user = await api("/api/users/get_my_user");
        localStorage.setItem(LS_TOKEN, state.token);
        location.hash = "#/";
      } catch (e) { err.textContent = e.status === 401 || e.status === 403 ? "invalid token" : e.message; }
    },
  },
    h("h1", {}, "dstack-tpu"),
    h("div", { class: "muted" }, "Paste the server admin token (printed at server startup) or a user token."),
    input, h("button", {}, "Sign in"), err);
  render(h("div", { class: "login-box" }, form));
}

async function ensureSession() {
  if (!state.token) { location.hash = "#/login"; return false; }
  try {
    if (!state.user) state.user = await api("/api/users/get_my_user");
    state.projects = await api("/api/projects/list");
    if (!state.projects.some((p) => p.project_name === state.project) && state.projects.length) {
      state.project = state.projects[0].project_name;
    }
    return true;
  } catch (e) {
    if (e.status === 401 || e.status === 403) return false;
    throw e;
  }
}

async function viewRuns() {
  const runs = await api(`/api/project/${P()}/runs/list`);
  const rows = runs.map((r) => {
    const name = r.run_spec.run_name;
    const conf = r.run_spec.configuration || {};
    return h("tr", {},
      h("td", {}, h("a", { href: `#/p/${P()}/runs/${encodeURIComponent(name)}` }, name)),
      h("td", {}, conf.type || "task"),
      h("td", {}, pill(r.status)),
      h("td", {}, ago(r.submitted_at)),
      h("td", { class: "num" }, money(r.cost)),
      h("td", {}, h("div", { class: "row-actions" },
        ["done", "failed", "terminated"].includes(r.status)
          ? h("button", { class: "small danger", onclick: confirmThen(`Delete run ${name}?`, () => api(`/api/project/${P()}/runs/delete`, { runs_names: [name] })) }, "delete")
          : h("button", { class: "small", onclick: confirmThen(`Stop run ${name}?`, () => api(`/api/project/${P()}/runs/stop`, { runs_names: [name] })) }, "stop"))),
    );
  });
  render(layout("runs", [
    h("h1", {}, "Runs", h("span", { style: "flex:1" }),
      h("a", { class: "button", href: `#/p/${P()}/submit` }, "Submit run")),
    paginated("runs", ["Name", "Type", "Status", "Submitted", "Cost", ""], rows, "no runs — submit one with `dstack-tpu apply` or the Submit run button"),
  ]));
  autoRefresh(8000);
}

/* Paste YAML -> parse -> plan (offers) -> apply: the UI path of what
   `dstack-tpu apply -f conf.yml` does over the same endpoints. */
async function viewSubmit() {
  const ta = h("textarea", {
    class: "yaml", rows: "14", spellcheck: "false",
    placeholder: "type: task\ncommands:\n  - python train.py\nresources:\n  tpu: v5litepod-8",
  });
  const nameInput = h("input", { placeholder: "run name (optional — auto-generated)" });
  const planBox = h("div", {});
  const err = h("div", { class: "err" });
  let plannedSpec = null;

  // type=button: inside the form these would otherwise ALSO fire the form's
  // onsubmit on every click (Apply would re-plan and drop its plannedSpec).
  const applyBtn = h("button", { type: "button", disabled: "" }, "Apply");
  const planBtn = h("button", { type: "button", class: "small" }, "Plan");

  async function doPlan(ev) {
    ev.preventDefault();
    err.textContent = "";
    planBox.replaceChildren(h("div", { class: "muted" }, "planning…"));
    applyBtn.setAttribute("disabled", "");
    plannedSpec = null;
    try {
      const conf = await api(`/api/project/${P()}/configurations/parse`, { yaml: ta.value });
      const spec = { configuration: conf };
      const name = nameInput.value.trim();
      if (name) spec.run_name = name;
      const plan = await api(`/api/project/${P()}/runs/get_plan`, { run_spec: spec });
      plannedSpec = plan.run_spec || spec;
      const offers = (plan.offers || []).map((o) => h("tr", {},
        h("td", {}, o.slice_name || o.instance?.name || "—"),
        h("td", {}, o.backend),
        h("td", {}, o.region),
        h("td", { class: "num" }, `${money(o.price)}/hr`),
        h("td", {}, o.availability),
      ));
      planBox.replaceChildren(
        h("h2", {}, `Plan: ${plan.action || "create"}${plan.effective_run_name ? ` — ${plan.effective_run_name}` : ""}`),
        plan.total_offers
          ? table(["Slice", "Backend", "Region", "Price", "Availability"], offers)
          : h("div", { class: "err" }, "no offers match this configuration"),
      );
      if (plan.total_offers) applyBtn.removeAttribute("disabled");
    } catch (e) {
      planBox.replaceChildren();
      err.textContent = e.message;
    }
  }

  async function doApply(ev) {
    ev.preventDefault();
    if (!plannedSpec) return;
    err.textContent = "";
    try {
      const run = await api(`/api/project/${P()}/runs/submit`, { run_spec: plannedSpec });
      const name = (run.run_spec && run.run_spec.run_name) || run.run_name;
      location.hash = `#/p/${P()}/runs/${encodeURIComponent(name)}`;
    } catch (e) { err.textContent = e.message; }
  }

  planBtn.addEventListener("click", doPlan);
  applyBtn.addEventListener("click", doApply);
  render(layout("runs", [
    h("h1", {}, h("a", { href: `#/p/${P()}/runs` }, "Runs"), " / submit"),
    h("div", { class: "muted" }, "Paste a run configuration (task / service / dev-environment YAML), plan it, then apply."),
    h("form", { class: "submit-form", onsubmit: doPlan },
      ta, nameInput, h("div", { class: "row-actions" }, planBtn, applyBtn), err),
    planBox,
  ]));
}

async function viewRunDetail(runName) {
  const run = await api(`/api/project/${P()}/runs/get`, { run_name: runName });
  const conf = run.run_spec.configuration || {};
  const jobs = [];
  for (const job of run.jobs || []) {
    const sub = job.job_submissions[job.job_submissions.length - 1];
    if (!sub) continue;
    jobs.push(h("tr", {},
      h("td", { class: "num" }, `${job.job_spec.replica_num ?? 0}/${job.job_spec.job_num ?? 0}`),
      h("td", {}, job.job_spec.job_name || "—"),
      h("td", {}, pill(sub.status)),
      h("td", {}, sub.termination_reason || "—"),
      h("td", { class: "num" }, sub.exit_status ?? "—"),
      h("td", {}, sub.job_provisioning_data ? `${sub.job_provisioning_data.hostname || ""} (${sub.job_provisioning_data.instance_type?.name || "?"})` : "—"),
      h("td", {}, ago(sub.submitted_at)),
    ));
  }
  const actions = h("div", { class: "row-actions" },
    ["done", "failed", "terminated"].includes(run.status)
      ? h("button", { class: "danger", onclick: confirmThen(`Delete run ${runName}?`, async () => { await api(`/api/project/${P()}/runs/delete`, { runs_names: [runName] }); location.hash = `#/p/${P()}/runs`; }) }, "delete")
      : h("button", { class: "danger", onclick: confirmThen(`Stop run ${runName}?`, () => api(`/api/project/${P()}/runs/stop`, { runs_names: [runName] })) }, "stop"));

  const kv = h("dl", { class: "kv" },
    h("dt", {}, "Status"), h("dd", {}, pill(run.status), run.status_message ? ` — ${run.status_message}` : ""),
    h("dt", {}, "Type"), h("dd", {}, conf.type || "task"),
    h("dt", {}, "User"), h("dd", {}, run.user || "—"),
    h("dt", {}, "Submitted"), h("dd", {}, `${new Date(run.submitted_at).toLocaleString()} (${ago(run.submitted_at)})`),
    h("dt", {}, "Cost"), h("dd", {}, money(run.cost)),
    run.error ? h("dt", {}, "Error") : null, run.error ? h("dd", {}, run.error) : null,
    conf.type === "service" ? h("dt", {}, "Endpoint") : null,
    conf.type === "service" ? h("dd", {}, h("code", { class: "inlinecode" }, `/proxy/services/${state.project}/${runName}/`)) : null,
  );

  // Metrics: one small chart per measure (one axis each — never dual-axis).
  const charts = h("div", { class: "charts" });
  (async () => {
    try {
      const m = await api(`/api/project/${P()}/metrics/job`, { run_name: runName, limit: 120 });
      const pts = (m.points || []).slice().reverse();
      if (pts.length) {
        const take = (f) => pts.map((p) => ({ t: p.timestamp, v: f(p) })).filter((p) => p.v !== null && p.v !== undefined);
        charts.append(sparkline(take((p) => p.cpu_usage_percent), { title: "CPU", unit: "%" }));
        charts.append(sparkline(take((p) => p.memory_working_set_bytes / 1024 ** 3), { title: "Memory (working set)", unit: " GiB", fmt: (v) => v.toFixed(2) }));
        const duty = take((p) => p.tpu_duty_cycle_percent);
        if (duty.length) charts.append(sparkline(duty, { title: "TPU duty cycle", unit: "%" }));
        const hbm = take((p) => (p.tpu_hbm_usage_bytes ?? null) === null ? null : p.tpu_hbm_usage_bytes / 1024 ** 3);
        if (hbm.length) charts.append(sparkline(hbm, { title: "TPU HBM", unit: " GiB", fmt: (v) => v.toFixed(1) }));
      }
    } catch { /* metrics are optional (job may not have started) */ }
  })();

  // Live log tail: the server pushes increments over the logs WebSocket
  // (no client polling loop). Falls back to a one-shot REST poll only when
  // the socket cannot be established (e.g. run has no jobs yet).
  const logbox = h("div", { class: "logbox" }, "");
  const follow = h("input", { type: "checkbox", checked: "" });
  let logLine = 0;
  const appendLogs = (evs) => {
    if (!evs.length) return;
    logbox.append(document.createTextNode(evs.map((e) => e.message).join("")));
    if (follow.checked) logbox.scrollTop = logbox.scrollHeight;
  };
  const wsProto = location.protocol === "https:" ? "wss" : "ws";
  const ws = new WebSocket(
    `${wsProto}://${location.host}/api/project/${P()}/logs/ws` +
    `?run_name=${encodeURIComponent(runName)}&token=${encodeURIComponent(state.token)}` +
    `&start_line=${logLine}`);
  ws.onmessage = (ev) => {
    try {
      const batch = JSON.parse(ev.data);
      appendLogs(batch.logs || []);
      logLine = batch.next_line ?? logLine + (batch.logs || []).length;
    } catch { /* ignore malformed frame */ }
  };
  // Fallback ONLY when the socket fails (proxy stripping Upgrade, server
  // restart): resume polling from logLine so nothing duplicates, and keep
  // tailing on a timer like the socket would have.
  let fallback = null;
  ws.onerror = () => {
    if (fallback !== null) return;
    const poll = async () => {
      try {
        const batch = await api(`/api/project/${P()}/logs/poll`, { run_name: runName, start_line: logLine, limit: 1000 });
        const evs = batch.logs || [];
        if (evs.length) { logLine += evs.length; appendLogs(evs); }
      } catch { /* run may have no logs yet */ }
    };
    poll();
    fallback = setInterval(poll, 2000);
    timers.push(fallback);
  };
  sockets.push(ws);

  render(layout("runs", [
    h("h1", {}, h("a", { href: `#/p/${P()}/runs` }, "Runs"), " / ", runName, h("span", { class: "spacer", style: "flex:1" }), actions),
    kv,
    h("h2", {}, "Jobs"),
    table(["Replica/Job", "Name", "Status", "Termination", "Exit", "Instance", "Submitted"], jobs),
    h("h2", {}, "Metrics"),
    charts,
    h("h2", {}, "Logs"),
    h("div", { class: "log-controls" }, h("label", {}, follow, " follow")),
    logbox,
  ]));
  // No full-view auto-refresh here: it would reset the log scroll. Logs poll on
  // their own timer; status/jobs update on manual navigation or reload.
}

async function viewFleets() {
  const fleets = await api(`/api/project/${P()}/fleets/list`);
  const rows = fleets.map((f) => h("tr", {},
    h("td", {}, h("a", { href: `#/p/${P()}/fleets/${encodeURIComponent(f.name)}` }, f.name)),
    h("td", {}, pill(f.status)),
    h("td", { class: "num" }, (f.instances || []).length),
    h("td", {}, ago(f.created_at)),
    h("td", {}, h("div", { class: "row-actions" },
      h("button", { class: "small danger", onclick: confirmThen(`Delete fleet ${f.name}?`, () => api(`/api/project/${P()}/fleets/delete`, { names: [f.name] })) }, "delete"))),
  ));
  render(layout("fleets", [h("h1", {}, "Fleets"), table(["Name", "Status", "Instances", "Created", ""], rows)]));
  autoRefresh(8000);
}

async function viewFleetDetail(name) {
  const f = await api(`/api/project/${P()}/fleets/get`, { name });
  const rows = (f.instances || []).map((i) => h("tr", {},
    h("td", { class: "num" }, i.instance_num),
    h("td", {}, i.name || "—"),
    h("td", {}, pill(i.status)),
    h("td", {}, i.instance_type?.name || "—"),
    h("td", {}, i.hostname || "—"),
    h("td", { class: "num" }, i.price ? `${money(i.price)}/hr` : "—"),
  ));
  render(layout("fleets", [
    h("h1", {}, h("a", { href: `#/p/${P()}/fleets` }, "Fleets"), " / ", name),
    h("dl", { class: "kv" },
      h("dt", {}, "Status"), h("dd", {}, pill(f.status)),
      h("dt", {}, "Created"), h("dd", {}, ago(f.created_at))),
    h("h2", {}, "Instances"),
    table(["#", "Name", "Status", "Type", "Hostname", "Price"], rows),
  ]));
  autoRefresh(15000);
}

async function viewInstances() {
  const instances = await api(`/api/project/${P()}/instances/list`);
  const rows = instances.map((i) => h("tr", {},
    h("td", {}, i.name || i.id),
    h("td", {}, pill(i.status)),
    h("td", {}, i.instance_type?.name || "—"),
    h("td", {}, i.hostname || "—"),
    h("td", {}, i.fleet_name || "—"),
    h("td", { class: "num" }, i.price ? `${money(i.price)}/hr` : "—"),
    h("td", {}, ago(i.created)),
  ));
  render(layout("instances", [h("h1", {}, "Instances"), paginated("instances", ["Name", "Status", "Type", "Hostname", "Fleet", "Price", "Created"], rows)]));
  autoRefresh(8000);
}

async function viewVolumes() {
  const volumes = await api(`/api/project/${P()}/volumes/list`);
  const rows = volumes.map((v) => h("tr", {},
    h("td", {}, v.name),
    h("td", {}, pill(v.status)),
    h("td", {}, v.configuration?.backend || "—"),
    h("td", {}, v.configuration?.region || "—"),
    h("td", { class: "num" }, v.configuration?.size ? `${v.configuration.size} GB` : "—"),
    h("td", { class: "num" }, (v.attachments || []).length),
    h("td", {}, ago(v.created_at)),
    h("td", {}, h("div", { class: "row-actions" },
      h("button", { class: "small danger", onclick: confirmThen(`Delete volume ${v.name}?`, () => api(`/api/project/${P()}/volumes/delete`, { names: [v.name] })) }, "delete"))),
  ));
  render(layout("volumes", [h("h1", {}, "Volumes"), table(["Name", "Status", "Backend", "Region", "Size", "Attached", "Created", ""], rows)]));
  autoRefresh(10000);
}

async function viewGateways() {
  const gws = await api(`/api/project/${P()}/gateways/list`);
  const rows = gws.map((g) => h("tr", {},
    h("td", {}, g.name),
    h("td", {}, pill(g.status)),
    h("td", {}, g.configuration?.backend || "—"),
    h("td", {}, g.configuration?.region || "—"),
    h("td", {}, g.ip_address || "—"),
    h("td", {}, g.configuration?.domain || "—"),
    h("td", {}, h("div", { class: "row-actions" },
      h("button", { class: "small danger", onclick: confirmThen(`Delete gateway ${g.name}?`, () => api(`/api/project/${P()}/gateways/delete`, { names: [g.name] })) }, "delete"))),
  ));
  const name = h("input", { placeholder: "name" });
  const backend = h("input", { placeholder: "backend (e.g. gcp)", value: "gcp" });
  const region = h("input", { placeholder: "region" });
  const domain = h("input", { placeholder: "domain (optional)" });
  const createForm = h("form", {
    class: "inline",
    onsubmit: async (ev) => {
      ev.preventDefault();
      try {
        await api(`/api/project/${P()}/gateways/create`, {
          configuration: {
            name: name.value.trim(), backend: backend.value.trim(),
            region: region.value.trim(), ...(domain.value.trim() ? { domain: domain.value.trim() } : {}),
          },
        });
        refresh();
      } catch (e) { alert(e.message); }
    },
  }, name, backend, region, domain, h("button", {}, "Create gateway"));
  render(layout("gateways", [h("h1", {}, "Gateways"), createForm, table(["Name", "Status", "Backend", "Region", "IP", "Domain", ""], rows)]));
  autoRefresh(10000);
}

async function viewOffers() {
  const resp = await api(`/api/project/${P()}/offers/list`, { limit: 200 });
  const rows = (resp.offers || []).map((o) => h("tr", {},
    h("td", {}, o.slice_name || o.instance?.name || "—"),
    h("td", {}, o.backend),
    h("td", {}, o.region),
    h("td", { class: "num" }, `${money(o.price)}/hr`),
    h("td", {}, o.availability),
    h("td", {}, o.spot ? "spot" : "on-demand"),
  ));
  render(layout("offers", [
    h("h1", {}, "Offers"),
    h("div", { class: "muted" }, "TPU slice offers across configured backends, cheapest first."),
    paginated("offers", ["Slice", "Backend", "Region", "Price", "Availability", "Tier"], rows),
  ]));
}

async function viewSecrets() {
  const secrets = await api(`/api/project/${P()}/secrets/list`);
  const rows = secrets.map((s) => h("tr", {},
    h("td", {}, h("code", { class: "inlinecode" }, s)),
    h("td", {}, h("div", { class: "row-actions" },
      h("button", { class: "small danger", onclick: confirmThen(`Delete secret ${s}?`, () => api(`/api/project/${P()}/secrets/delete`, { names: [s] })) }, "delete"))),
  ));
  const name = h("input", { placeholder: "NAME" });
  const value = h("input", { placeholder: "value", type: "password" });
  const form = h("form", {
    class: "inline",
    onsubmit: async (ev) => {
      ev.preventDefault();
      try { await api(`/api/project/${P()}/secrets/set`, { name: name.value.trim(), value: value.value }); refresh(); }
      catch (e) { alert(e.message); }
    },
  }, name, value, h("button", {}, "Set secret"));
  render(layout("secrets", [h("h1", {}, "Secrets"), form, table(["Name", ""], rows, "no secrets")]));
}

async function viewProjects() {
  const projects = await api("/api/projects/list");
  const rows = projects.map((p) => h("tr", {},
    h("td", {}, p.project_name),
    h("td", {}, p.owner?.username || "—"),
    h("td", { class: "num" }, (p.members || []).length),
    h("td", {}, h("div", { class: "row-actions" },
      h("button", { class: "small", onclick: () => { state.project = p.project_name; localStorage.setItem(LS_PROJECT, state.project); location.hash = `#/p/${P()}/runs`; } }, "open"),
      h("button", { class: "small danger", onclick: confirmThen(`Delete project ${p.project_name}?`, () => api("/api/projects/delete", { projects_names: [p.project_name] })) }, "delete"))),
  ));
  const name = h("input", { placeholder: "project name" });
  const form = h("form", {
    class: "inline",
    onsubmit: async (ev) => {
      ev.preventDefault();
      try { await api("/api/projects/create", { project_name: name.value.trim() }); refresh(); }
      catch (e) { alert(e.message); }
    },
  }, name, h("button", {}, "Create project"));
  render(layout("projects", [h("h1", {}, "Projects"), form, table(["Name", "Owner", "Members", ""], rows)]));
}

async function viewUsers() {
  const users = await api("/api/users/list");
  const rows = users.map((u) => h("tr", {},
    h("td", {}, u.username),
    h("td", {}, u.global_role),
    h("td", {}, u.email || "—"),
    h("td", {}, h("div", { class: "row-actions" },
      h("button", {
        class: "small",
        onclick: async () => {
          const r = await api("/api/users/refresh_token", { username: u.username });
          window.prompt(`New token for ${u.username}:`, r.creds?.token || r.token || "");
        },
      }, "new token"),
      h("button", { class: "small danger", onclick: confirmThen(`Delete user ${u.username}?`, () => api("/api/users/delete", { users: [u.username] })) }, "delete"))),
  ));
  const name = h("input", { placeholder: "username" });
  const role = h("select", {}, h("option", {}, "user"), h("option", {}, "admin"));
  const form = h("form", {
    class: "inline",
    onsubmit: async (ev) => {
      ev.preventDefault();
      try {
        const u = await api("/api/users/create", { username: name.value.trim(), global_role: role.value });
        window.prompt(`Token for ${u.username}:`, u.creds?.token || "");
        refresh();
      } catch (e) { alert(e.message); }
    },
  }, name, role, h("button", {}, "Create user"));
  render(layout("users", [h("h1", {}, "Users"), form, table(["Username", "Role", "Email", ""], rows)]));
}

/* ---------------- router ---------------- */

let timers = [];
let sockets = [];
function stopTimers() {
  timers.forEach(clearInterval); timers = [];
  sockets.forEach((s) => { try { s.close(); } catch { /* already closed */ } });
  sockets = [];
}
function autoRefresh(ms) {
  // Periodic re-render of the current (list) view.
  timers.push(setInterval(() => { route(true); }, ms));
}
function refresh() { route(true); }

let routing = false;
async function route(isRefresh = false) {
  if (routing) return; routing = true;
  try {
    const hash = location.hash || "#/";
    const parts = hash.slice(2).split("/").map(decodeURIComponent).filter((x) => x !== "");
    stopTimers();
    if (parts[0] === "login") return void await viewLogin();
    if (!(await ensureSession())) return;
    if (parts[0] === "projects") return void await viewProjects();
    if (parts[0] === "users") return void await viewUsers();
    if (parts[0] === "p" && parts.length >= 3) {
      state.project = parts[1];
      localStorage.setItem(LS_PROJECT, state.project);
      const section = parts[2];
      if (section === "runs" && parts[3]) return void await viewRunDetail(parts[3]);
      const views = {
        runs: viewRuns, submit: viewSubmit,
        fleets: parts[3] ? () => viewFleetDetail(parts[3]) : viewFleets,
        instances: viewInstances, volumes: viewVolumes, gateways: viewGateways,
        offers: viewOffers, secrets: viewSecrets,
      };
      if (views[section]) return void await views[section]();
    }
    location.hash = `#/p/${P()}/runs`;
  } catch (e) {
    if (!(e instanceof ApiError && (e.status === 401 || e.status === 403))) {
      render(layout("", [h("div", { class: "error-banner" }, `error: ${e.message}`)]));
    }
  } finally { routing = false; }
}

window.addEventListener("hashchange", () => route(false));
route(false);
