"""Model configurations for the Llama-style workload."""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"  # compute/activation dtype
    param_dtype: str = "float32"  # master weights
    remat: bool = True  # rematerialize each block on the backward pass
    # "full" recomputes everything; "dots" saves MXU outputs and recomputes only
    # elementwise ops (less recompute, more HBM).
    remat_policy: str = "full"
    # Attention core: "blockwise" (online-softmax scan; O(block) memory, long-seq),
    # "plain" (materialize [T,S] scores; fastest via XLA fusion when T is moderate).
    # Ring attention over `sp` always uses the blockwise accumulator.
    attn_impl: str = "blockwise"
    # Cross-entropy: chunk the vocab projection over the sequence so [B,T,V] fp32
    # logits are never fully materialized (0 = off). Trades ~2*d*V flops/token of
    # recompute for ~2 * B*T*V*4 bytes of HBM.
    loss_chunk: int = 0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def num_params(self) -> int:
        """Parameter count (embeddings counted once; lm head untied)."""
        d, v = self.d_model, self.vocab_size
        attn = d * self.n_heads * self.head_dim + 2 * d * self.n_kv_heads * self.head_dim
        attn += self.n_heads * self.head_dim * d
        mlp = 3 * d * self.d_ff
        per_layer = attn + mlp + 2 * d  # + norms
        return v * d + self.n_layers * per_layer + d + d * v

    def flops_per_token(self, seq_len: int, causal: bool = True) -> float:
        """Training FLOPs per token: 6*N plus attention score FLOPs. The
        full-window QK^T+AV term is 12*L*T*d per token (fwd+bwd); with causal
        masking only half the score matrix is computed, so the honest count —
        matching what a flash kernel actually executes — is 6*L*T*d. MFU
        numbers in bench.py use the causal (conservative) count."""
        attn = 12.0 * self.n_layers * seq_len * self.d_model
        if causal:
            attn /= 2.0
        return 6.0 * self.num_params() + attn


# Presets. llama3_8b mirrors the reference north-star workload (BASELINE.json:
# "MaxText Llama-3-8B ... on v5p-16").
PRESETS = {
    "test": LlamaConfig(
        vocab_size=4096, d_model=256, n_layers=4, n_heads=8, n_kv_heads=4, d_ff=688,
        max_seq_len=2048, param_dtype="float32",
    ),
    "llama3_1b": LlamaConfig(
        vocab_size=32000, d_model=2048, n_layers=16, n_heads=16, n_kv_heads=8, d_ff=5504,
        max_seq_len=8192,
    ),
    "llama3_8b": LlamaConfig(
        vocab_size=128256, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8, d_ff=14336,
        max_seq_len=8192,
    ),
    # Single-v5e-chip bench geometry (~670M params): wide-not-deep so the MLP
    # matmuls hit the MXU's efficient K,N>=2048 regime (measured 191 vs 178
    # TFLOP/s for d=1536/ff=4096 shapes — BASELINE.md round-3 sweep). Flash
    # attention + chunked CE keep HBM under the 16 GB chip limit at batch 24.
    "v5e_bench": LlamaConfig(
        vocab_size=32000, d_model=2048, n_layers=8, n_heads=16, n_kv_heads=16,
        d_ff=8192, max_seq_len=2048, remat=True, remat_policy="full",
        attn_impl="flash", loss_chunk=256,
    ),
    # GPT-2-124M geometry (BASELINE north-star "GPT-2 125M single-node CPU
    # task"): d=768/L=12/h=12, vocab padded to a 128 multiple for clean tiling.
    "gpt2_125m": LlamaConfig(
        vocab_size=50304, d_model=768, n_layers=12, n_heads=12, n_kv_heads=12,
        d_ff=3072, max_seq_len=1024, loss_chunk=256,
    ),
}


def get_config(name: str, **overrides) -> LlamaConfig:
    cfg = PRESETS[name]
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg
