"""Model configurations for the Llama-style workload."""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"  # compute/activation dtype
    param_dtype: str = "float32"  # master weights
    remat: bool = True  # rematerialize each block on the backward pass
    # "full" recomputes everything; "dots" saves MXU outputs and recomputes only
    # elementwise ops (less recompute, more HBM).
    remat_policy: str = "full"
    # Attention core (see attention.attention_core for the dispatch):
    #   "auto"      — public Pallas kernel on a meshless TPU, blockwise else;
    #   "xla"/"blockwise" — online-softmax scan (O(block) memory, long-seq);
    #   "flash"     — the in-repo Pallas kernel (kernels/flash.py): compiled
    #                 on TPU, interpreted on CPU so tests run the real kernel;
    #   "flash_tpu" — the public jax.experimental.pallas.ops TPU kernel;
    #   "splash"    — block-SPARSE flash (kernels/splash.py): skips fully-
    #                 masked q/kv block pairs (causal + attn_window local
    #                 band + optional document masks) — the long-context
    #                 kernel;
    #   "plain"     — materialize [T,S] scores (fastest for moderate T).
    # Ring attention over `sp` always uses the blockwise accumulator.
    attn_impl: str = "blockwise"
    # Local-attention window in tokens (0 = full causal): with attn_impl=
    # splash each query attends to the last `attn_window` positions only and
    # the kernel skips KV blocks outside the band — O(T·W) instead of
    # O(T²/2) score work.
    attn_window: int = 0
    # Matmul precision: "none" (bf16/fp32 per dtype), "int8", or "fp8"
    # (e4m3, v5p+ only — validate_config gates it) — dynamically quantized
    # dot with fp32 accumulation and straight-through gradients
    # (workloads/quantize.py). Serving quantizes weights only.
    quant: str = "none"
    # Collective-matmul overlap for the TP down-projections: decompose the
    # local matmul into a ppermute ring so the tp all-reduce hides under MXU
    # compute (kernels/collective.py). No-op when tp == 1.
    tp_overlap: bool = False
    # Collective-matmul overlap for the FSDP all-gather of column-parallel
    # weights (wq/wk/wv/w_gate/w_up): rotate weight shards around the
    # (dp, fsdp) ring, each hop's chunk matmul hiding the next transfer,
    # instead of XLA's monolithic gather-on-use (kernels/collective.py
    # allgather_matmul). No-op when dp*fsdp == 1. The lm_head is excluded:
    # its [D, V] gather amortizes over one call per step, not per layer.
    fsdp_overlap: bool = False
    # Cross-entropy: chunk the vocab projection over the sequence so [B,T,V] fp32
    # logits are never fully materialized (0 = off). Trades ~2*d*V flops/token of
    # recompute for ~2 * B*T*V*4 bytes of HBM.
    loss_chunk: int = 0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def num_params(self) -> int:
        """Parameter count (embeddings counted once; lm head untied)."""
        d, v = self.d_model, self.vocab_size
        attn = d * self.n_heads * self.head_dim + 2 * d * self.n_kv_heads * self.head_dim
        attn += self.n_heads * self.head_dim * d
        mlp = 3 * d * self.d_ff
        per_layer = attn + mlp + 2 * d  # + norms
        return v * d + self.n_layers * per_layer + d + d * v

    def flops_per_token(self, seq_len: int, causal: bool = True) -> float:
        """Training FLOPs per token: 6*N plus attention score FLOPs. The
        full-window QK^T+AV term is 12*L*T*d per token (fwd+bwd); with causal
        masking only half the score matrix is computed, so the honest count —
        matching what a flash kernel actually executes — is 6*L*T*d. MFU
        numbers in bench.py use the causal (conservative) count."""
        attn = 12.0 * self.n_layers * seq_len * self.d_model
        if causal:
            attn /= 2.0
        return 6.0 * self.num_params() + attn


# Presets. llama3_8b mirrors the reference north-star workload (BASELINE.json:
# "MaxText Llama-3-8B ... on v5p-16").
PRESETS = {
    "test": LlamaConfig(
        vocab_size=4096, d_model=256, n_layers=4, n_heads=8, n_kv_heads=4, d_ff=688,
        max_seq_len=2048, param_dtype="float32",
    ),
    "llama3_1b": LlamaConfig(
        vocab_size=32000, d_model=2048, n_layers=16, n_heads=16, n_kv_heads=8, d_ff=5504,
        max_seq_len=8192,
    ),
    "llama3_8b": LlamaConfig(
        vocab_size=128256, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8, d_ff=14336,
        max_seq_len=8192,
    ),
    # Single-v5e-chip bench geometry (~670M params): wide-not-deep so the MLP
    # matmuls hit the MXU's efficient K,N>=2048 regime (measured 191 vs 178
    # TFLOP/s for d=1536/ff=4096 shapes — BASELINE.md round-3 sweep). Flash
    # attention + chunked CE keep HBM under the 16 GB chip limit at batch 24.
    "v5e_bench": LlamaConfig(
        vocab_size=32000, d_model=2048, n_layers=8, n_heads=16, n_kv_heads=16,
        d_ff=8192, max_seq_len=2048, remat=True, remat_policy="full",
        attn_impl="auto", loss_chunk=256,
    ),
    # GPT-2-124M geometry (BASELINE north-star "GPT-2 125M single-node CPU
    # task"): d=768/L=12/h=12, vocab padded to a 128 multiple for clean tiling.
    "gpt2_125m": LlamaConfig(
        vocab_size=50304, d_model=768, n_layers=12, n_heads=12, n_kv_heads=12,
        d_ff=3072, max_seq_len=1024, loss_chunk=256,
    ),
}


def get_config(name: str, **overrides) -> LlamaConfig:
    cfg = PRESETS[name]
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg


ATTN_IMPLS = ("auto", "xla", "blockwise", "plain", "flash", "flash_tpu",
              "splash")


def validate_config(
    cfg: LlamaConfig,
    mesh=None,
    batch: Optional[int] = None,
    seq: Optional[int] = None,
) -> None:
    """Loud trace-time/CLI validation of the perf-dispatch flags.

    The model-side dispatchers fall back silently where a combination merely
    degrades (e.g. flash under a mesh whose tp doesn't divide the KV heads);
    an *explicitly requested* invalid combination at the CLI is a config
    error and must fail before compile, not quietly run the slow path."""
    from dstack_tpu.workloads.quantize import check_quant

    if cfg.attn_impl not in ATTN_IMPLS:
        raise ValueError(
            f"unknown attn_impl {cfg.attn_impl!r}; expected one of {ATTN_IMPLS}"
        )
    check_quant(cfg.quant)
    if cfg.quant == "fp8":
        from dstack_tpu.workloads.kernels.platform import (
            chip_generation,
            supports_fp8,
        )

        gen = chip_generation()
        if not supports_fp8(gen):
            raise ValueError(
                f"quant=fp8 needs a chip generation with a native fp8 MXU "
                f"path (v5p+); this host is {gen} where fp8 operands upcast "
                f"in hardware — no throughput win, only precision loss. Use "
                f"quant=int8 here, or submit to a v5p/v6e pool"
            )
    if cfg.attn_window:
        if cfg.attn_window < 0:
            raise ValueError(f"attn_window must be >= 0, got {cfg.attn_window}")
        if cfg.attn_impl != "splash":
            raise ValueError(
                f"attn_window={cfg.attn_window} only applies to attn_impl="
                f"splash (the block-sparse kernel that skips out-of-window "
                f"blocks); attn_impl={cfg.attn_impl!r} would silently ignore "
                f"the window"
            )
    sp = mesh.shape.get("sp", 1) if mesh is not None else 1
    tp = mesh.shape.get("tp", 1) if mesh is not None else 1
    if cfg.attn_impl == "flash_tpu" and mesh is not None:
        # attention_core only routes to the public kernel on a MESHLESS TPU
        # (a Pallas call has no SPMD rule); under any mesh — and train always
        # builds one — the request would silently run blockwise.
        raise ValueError(
            "attn_impl=flash_tpu only runs meshless (the public kernel has "
            "no sharding rule) and would silently fall back to blockwise "
            "under a device mesh; use attn_impl=flash (the in-repo sharded "
            "kernel) or attn_impl=auto"
        )
    if cfg.attn_impl in ("flash", "flash_tpu", "splash"):
        if sp > 1:
            raise ValueError(
                f"attn_impl={cfg.attn_impl!r} does not compose with sequence "
                f"parallelism (sp={sp} runs ring attention, whose rotating KV "
                f"chunks don't meet the kernel's block-divisibility contract);"
                f" use attn_impl=xla or sp=1"
            )
        if seq:
            # Each impl has its own block menu: the public kernel only takes
            # 512/256/128 (attention._flash_block) while the in-repo kernel
            # goes down to 8 — validating flash_tpu with the in-repo picker
            # would pass seqs (e.g. 576) the public kernel then silently
            # degrades to blockwise on.
            if cfg.attn_impl == "flash_tpu":
                from dstack_tpu.workloads.attention import _flash_block as _pick
            else:
                from dstack_tpu.workloads.kernels import pick_flash_block as _pick

            if _pick(seq // sp) is None:
                raise ValueError(
                    f"attn_impl={cfg.attn_impl!r} needs a block-divisible "
                    f"sequence length; seq={seq} has no power-of-two block "
                    f"(pad the sequence or use attn_impl=xla)"
                )
        if (cfg.attn_impl in ("flash", "splash") and tp > 1
                and cfg.n_kv_heads % tp):
            raise ValueError(
                f"attn_impl={cfg.attn_impl} shards heads over tp={tp}, which "
                f"must divide n_kv_heads={cfg.n_kv_heads} (whole GQA groups "
                f"per shard); adjust the mesh or use attn_impl=xla"
            )
    if cfg.fsdp_overlap and mesh is not None:
        from dstack_tpu.workloads.kernels.collective import can_fsdp_overlap

        if not can_fsdp_overlap(mesh, cfg.d_model):
            data = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
            raise ValueError(
                f"fsdp_overlap rotates weight shards around the dp*fsdp="
                f"{data} ring, which needs dp*fsdp > 1 and d_model="
                f"{cfg.d_model} divisible by it; adjust the mesh or drop "
                f"--fsdp-overlap"
            )
    if cfg.tp_overlap and tp > 1 and batch and seq:
        from dstack_tpu.workloads.kernels.collective import can_overlap

        if not can_overlap(mesh, batch, seq):
            raise ValueError(
                f"tp_overlap needs the per-device row count (batch x seq "
                f"after dp/fsdp/sp sharding) to split into tp={tp} ring "
                f"chunks; batch={batch} seq={seq} mesh={dict(mesh.shape)} "
                f"doesn't — grow the batch or drop --tp-overlap"
            )
