"""Attention: blockwise (flash-style) core + ring attention for sequence parallelism.

TPU-first: the blockwise core keeps the score matrix at [*, Tq, block] so long
sequences never materialize T² scores in HBM; ring attention rotates KV chunks around
the ``sp`` mesh axis with ``jax.lax.ppermute`` (ICI neighbor hops) while accumulating
the same online softmax — the classic ring-attention construction, expressed with XLA
collectives rather than raw RDMA.

Parity note: the reference delegates long-context parallelism to the workload
(SURVEY §2.6 "Long context / seq parallelism: absent"); here it ships in-framework.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """GQA: repeat KV heads to match query heads. [B,S,Kh,D] -> [B,S,Kh*n_rep,D]."""
    if n_rep == 1:
        return k
    b, s, kh, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, kh, n_rep, d)).reshape(b, s, kh * n_rep, d)


def _attn_state_init(q: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    b, t, h, d = q.shape
    o = jnp.zeros((b, t, h, d), jnp.float32)
    l = jnp.zeros((b, t, h), jnp.float32)
    m = jnp.full((b, t, h), NEG_INF, jnp.float32)
    return o, l, m


def _attn_block_accum(
    state: Tuple[jax.Array, jax.Array, jax.Array],
    q: jax.Array,  # [B,Tq,H,D]
    k: jax.Array,  # [B,S,H,D] (kv heads already repeated)
    v: jax.Array,
    q_positions: jax.Array,   # [Tq] global positions
    kv_positions: jax.Array,  # [S] global positions
    causal: bool,
    kv_valid: Optional[jax.Array] = None,  # [S] bool; False = padded key, never attended
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Online-softmax accumulation of one KV block into the running (o, l, m) state."""
    o, l, m = state
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    s = jnp.einsum("bthd,bshd->bths", q, k, preferred_element_type=jnp.float32) * scale
    mask = None  # [Tq, S]; padding is masked independently of causality
    if causal:
        mask = kv_positions[None, :] <= q_positions[:, None]
    if kv_valid is not None:
        valid = jnp.broadcast_to(kv_valid[None, :], (q_positions.shape[0], kv_valid.shape[0]))
        mask = valid if mask is None else (mask & valid)
    if mask is not None:
        s = jnp.where(mask[None, :, None, :], s, NEG_INF)
    m_block = jnp.max(s, axis=-1)  # [B,Tq,H]
    m_new = jnp.maximum(m, m_block)
    # Guard against all-masked blocks (m_new == NEG_INF): exp(NEG_INF - NEG_INF) = 1
    # would poison l; clamp the correction instead.
    safe_m_new = jnp.where(m_new == NEG_INF, 0.0, m_new)
    corr = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - safe_m_new))
    p = jnp.exp(s - safe_m_new[..., None])  # [B,Tq,H,S]
    if mask is not None:
        p = jnp.where(mask[None, :, None, :], p, 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bths,bshd->bthd", p, v.astype(jnp.float32))
    o_new = o * corr[..., None] + pv
    return o_new, l_new, m_new


def _finalize(state) -> jax.Array:
    o, l, _ = state
    return o / jnp.maximum(l, 1e-20)[..., None]


def flash_available(mesh: Optional[Mesh] = None) -> bool:
    """True when the Pallas TPU flash kernel can run (Mosaic needs a real TPU).

    Checks the devices the computation will actually land on: the mesh's when
    sharded, else the configured default device (tests pin ``jax_default_device``
    to CPU while the TPU plugin still owns ``jax.devices()[0]``)."""
    try:
        if mesh is not None:
            return mesh.devices.flat[0].platform == "tpu"
        dev = jax.config.jax_default_device or jax.devices()[0]
        return getattr(dev, "platform", None) == "tpu"
    except Exception:
        return False


def _flash_block(seq_len: int) -> Optional[int]:
    """Largest of (512, 256, 128) that divides seq_len; None when none does
    (the Pallas kernel requires seq_len % block == 0)."""
    for b in (512, 256, 128):
        if seq_len >= b and seq_len % b == 0:
            return b
    return None


def flash_attention_tpu(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
) -> jax.Array:
    """Pallas/Mosaic fused flash attention (public JAX kernel), tuned for v5e.

    q [B,T,H,D]; k,v [B,S,Kh,D]; returns [B,T,H,D] in q.dtype. Scores never touch
    HBM — measured on v5e at T=2048: 1.05 ms fwd / 7.5 ms fwd+bwd per layer vs
    ~13/~37 ms for the materializing XLA path (see BASELINE.md round-3 sweep).
    512-sized blocks beat the kernel defaults ~6x on the forward pass.
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention as _pallas_flash,
    )

    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    t, s_len, d = q.shape[1], k.shape[1], q.shape[3]
    bq = _flash_block(t)
    bk = _flash_block(s_len)
    if bq is None or bk is None:
        # Kernel requires seq % block == 0; odd lengths take the padding-capable
        # blockwise path instead of crashing at trace time.
        return blockwise_attention(q, k, v, causal=causal)
    block_sizes = BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk, block_q_dkv=bq,
        block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq,
    )
    # kernel layout is [B, H, T, D]
    qh = q.swapaxes(1, 2)
    kh = k.swapaxes(1, 2)
    vh = v.swapaxes(1, 2)
    o = _pallas_flash(
        qh, kh, vh,
        causal=causal,
        sm_scale=float(1.0 / (d ** 0.5)),
        block_sizes=block_sizes,
    )
    return o.swapaxes(1, 2)


def plain_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
) -> jax.Array:
    """Fully-materialized attention. q [B,T,H,D]; k,v [B,S,Kh,D]; returns fp32
    [B,T,H,D]. Scores are [B,H,T,S] — fine for moderate T where XLA's fused
    softmax beats the blockwise scan on the MXU; use blockwise/ring for long S."""
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    t, s_len = q.shape[1], k.shape[1]
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    s = jnp.einsum("bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.arange(s_len)[None, :] <= jnp.arange(t)[:, None]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", p, v, preferred_element_type=jnp.float32)


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    q_offset=0,
    kv_offset=0,
    block_size: int = 512,
) -> jax.Array:
    """Memory-efficient causal attention. q [B,T,H,D]; k,v [B,S,Kh,D]; returns fp32
    [B,T,H,D]. Scans KV in blocks with an online softmax (flash-attention recurrence);
    XLA keeps each block in VMEM on TPU."""
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    b, s_len, h, d = k.shape
    t = q.shape[1]
    q_pos = q_offset + jnp.arange(t)
    state = _attn_state_init(q)

    if s_len <= block_size:
        kv_pos = kv_offset + jnp.arange(s_len)
        state = _attn_block_accum(state, q, k, v, q_pos, kv_pos, causal)
        return _finalize(state)

    # Pad S to a block multiple; padded keys are masked out by position (> any q pos).
    n_blocks = -(-s_len // block_size)
    pad = n_blocks * block_size - s_len
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    k = k.reshape(b, n_blocks, block_size, h, d)
    v = v.reshape(b, n_blocks, block_size, h, d)

    def body(state, inputs):
        k_blk, v_blk, blk_idx = inputs
        rel_pos = blk_idx * block_size + jnp.arange(block_size)
        kv_pos = kv_offset + rel_pos
        kv_valid = rel_pos < s_len  # mask the padded tail regardless of causality
        return (
            _attn_block_accum(state, q, k_blk, v_blk, q_pos, kv_pos, causal, kv_valid),
            None,
        )

    k_scan = jnp.moveaxis(k, 1, 0)  # [n_blocks, B, block, H, D]
    v_scan = jnp.moveaxis(v, 1, 0)
    state, _ = jax.lax.scan(body, state, (k_scan, v_scan, jnp.arange(n_blocks)))
    return _finalize(state)


def paged_decode_attention(
    q: jax.Array,        # [S, H, D] — ONE query per decode slot
    k_pages: jax.Array,  # [N, page, Kh, D] — one layer's page pool
    v_pages: jax.Array,
    page_table: jax.Array,  # [S, P] int32 page ids into the pool
    kv_lens: jax.Array,     # [S] valid KV length per slot (past + current token)
) -> jax.Array:
    """Single-query attention over a paged KV cache (the decode half of a
    continuous-batching engine; serve.py). Each slot gathers its own pages —
    sequences share the pool but never each other's pages — then runs a
    masked softmax over its valid prefix. Returns fp32 [S, H, D].

    Slots with kv_lens == 0 (inactive) produce finite garbage (uniform
    weights over masked scores), never NaN; the engine discards those rows.
    """
    s, p = page_table.shape
    n, page, kh, d = k_pages.shape
    k = k_pages[page_table].reshape(s, p * page, kh, d)
    v = v_pages[page_table].reshape(s, p * page, kh, d)
    n_rep = q.shape[1] // kh
    if n_rep > 1:
        k = jnp.broadcast_to(
            k[:, :, :, None, :], (s, p * page, kh, n_rep, d)
        ).reshape(s, p * page, kh * n_rep, d)
        v = jnp.broadcast_to(
            v[:, :, :, None, :], (s, p * page, kh, n_rep, d)
        ).reshape(s, p * page, kh * n_rep, d)
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    scores = jnp.einsum("shd,sthd->sht", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    valid = jnp.arange(p * page)[None, :] < kv_lens[:, None]  # [S, T]
    scores = jnp.where(valid[:, None, :], scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("sht,sthd->shd", weights, v.astype(jnp.float32))


def paged_chunk_attention(
    q: jax.Array,        # [S, C, H, D] — C chunk queries per decode slot
    k_pages: jax.Array,  # [N, page, Kh, D] — one layer's page pool
    v_pages: jax.Array,
    page_table: jax.Array,  # [S, P] int32 page ids into the pool
    starts: jax.Array,      # [S] absolute position of each slot's first query
) -> jax.Array:
    """Multi-query paged attention: the chunked-prefill / speculative-verify
    generalization of ``paged_decode_attention`` (which is the C == 1 special
    case with ``starts = kv_lens - 1``). Query i of slot s sits at absolute
    position ``starts[s] + i`` and attends causally over the slot's paged
    prefix INCLUDING the chunk itself — the chunk's K/V must already be
    scattered into the pages before this runs. Returns fp32 [S, C, H, D].

    Positions beyond each query's own (unwritten page slots, other slots'
    stale data behind zero-padded table entries) are masked by causality
    alone: every position <= starts[s] + i is valid written KV for slot s by
    the engine's append-only write discipline. Padded batch rows (starts 0,
    zeroed table rows) produce finite garbage the engine discards.
    """
    s, c, h, d = q.shape
    n, page, kh, _ = k_pages.shape
    p = page_table.shape[1]
    k = k_pages[page_table].reshape(s, p * page, kh, d)
    v = v_pages[page_table].reshape(s, p * page, kh, d)
    n_rep = h // kh
    if n_rep > 1:
        k = jnp.broadcast_to(
            k[:, :, :, None, :], (s, p * page, kh, n_rep, d)
        ).reshape(s, p * page, kh * n_rep, d)
        v = jnp.broadcast_to(
            v[:, :, :, None, :], (s, p * page, kh, n_rep, d)
        ).reshape(s, p * page, kh * n_rep, d)
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    scores = jnp.einsum(
        "schd,sthd->scht", q, k, preferred_element_type=jnp.float32
    ) * scale
    q_pos = starts[:, None] + jnp.arange(c)[None, :]  # [S, C]
    valid = jnp.arange(p * page)[None, None, :] <= q_pos[:, :, None]  # [S, C, T]
    scores = jnp.where(valid[:, :, None, :], scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("scht,sthd->schd", weights, v.astype(jnp.float32))


def attention_core(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,  # [B, T, Kh, D]
    v: jax.Array,
    attn_impl: str,
    mesh: Optional[Mesh] = None,
    *,
    causal: bool = True,
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
    window: int = 0,
    doc_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """The one attention dispatch the model/MoE/pipeline forwards share.

    Precedence: sequence parallelism (sp > 1) always runs ring attention —
    it's the only core that understands rotating KV chunks. Otherwise
    ``attn_impl`` picks the core:

    - ``auto``: the public Pallas TPU kernel when it can run (real TPU, no
      mesh — it has no SPMD rule), blockwise everywhere else. The safe
      default.
    - ``flash``: the in-repo Pallas kernel (kernels/flash.py) — compiled on
      TPU, interpreted elsewhere, shard_map'd over (batch, tp) under a mesh.
      Falls back to blockwise when the sequence isn't block-divisible or tp
      doesn't divide the KV heads (config.validate_config raises loudly for
      CLI-requested combos; mid-model we degrade instead of crashing).
    - ``splash``: the block-SPARSE kernel (kernels/splash.py): causal +
      ``window`` local band + optional ``doc_ids`` same-document masks, with
      fully-masked q/kv block pairs skipped in the grid. Degrades to the
      masked materializing reference (``splash_reference``) on shapes the
      kernel can't tile — the only core that honors window/doc masks.
    - ``flash_tpu``: the public kernel explicitly (meshless TPU only).
    - ``xla``/``blockwise``: the online-softmax scan; ``plain``: materialized
      scores.
    """
    from dstack_tpu.workloads import kernels

    if mesh is not None and mesh.shape.get("sp", 1) > 1:
        return ring_attention(q, k, v, mesh, causal=causal,
                              batch_axes=batch_axes)
    impl = attn_impl
    if impl == "auto":
        impl = "flash_tpu" if (mesh is None and flash_available()) else "blockwise"
    if impl == "splash":
        from dstack_tpu.workloads.kernels import splash as splash_lib

        t, s_len = q.shape[1], k.shape[1]
        if (kernels.pick_flash_block(t) is None
                or kernels.pick_flash_block(s_len) is None):
            return splash_lib.splash_reference(
                q, k, v, causal=causal, window=window, doc_ids=doc_ids
            )
        if mesh is not None:
            tp = mesh.shape.get("tp", 1)
            data = 1
            for a in batch_axes:
                data *= mesh.shape.get(a, 1)
            if q.shape[0] % data or q.shape[2] % tp or k.shape[2] % tp:
                return splash_lib.splash_reference(
                    q, k, v, causal=causal, window=window, doc_ids=doc_ids
                )
            return splash_lib.splash_attention_sharded(
                q, k, v, mesh, causal=causal, window=window, doc_ids=doc_ids,
                batch_axes=batch_axes,
            )
        return splash_lib.splash_attention(
            q, k, v, causal=causal, window=window, doc_ids=doc_ids
        )
    if impl == "flash":
        t, s_len = q.shape[1], k.shape[1]
        if (kernels.pick_flash_block(t) is None
                or kernels.pick_flash_block(s_len) is None):
            return blockwise_attention(q, k, v, causal=causal)
        if mesh is not None:
            tp = mesh.shape.get("tp", 1)
            data = 1
            for a in batch_axes:
                data *= mesh.shape.get(a, 1)
            # shard_map needs whole shards: batch over the data axes, whole
            # GQA groups over tp — ragged shapes degrade like odd seq does.
            if q.shape[0] % data or q.shape[2] % tp or k.shape[2] % tp:
                return blockwise_attention(q, k, v, causal=causal)
            return kernels.flash_attention_sharded(
                q, k, v, mesh, causal=causal, batch_axes=batch_axes
            )
        return kernels.flash_attention(q, k, v, causal=causal)
    if impl == "flash_tpu":
        if mesh is None and flash_available():
            return flash_attention_tpu(q, k, v, causal=causal)
        return blockwise_attention(q, k, v, causal=causal)
    if impl == "plain":
        return plain_attention(q, k, v, causal=causal)
    return blockwise_attention(q, k, v, causal=causal)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    block_size: int = 512,
    batch_axes=("dp", "fsdp"),
) -> jax.Array:
    """Sequence-parallel attention over the ``sp`` mesh axis.

    q/k/v are globally [B,T,H|Kh,D] with T sharded over sp. Each device holds one
    contiguous sequence chunk; KV chunks rotate around the sp ring (ppermute), each
    step accumulating into the same online-softmax state the blockwise core uses.
    Communication rides ICI neighbor links; compute overlaps with the next hop under
    XLA's async collectives."""
    sp_size = mesh.shape["sp"]
    if sp_size == 1:
        return blockwise_attention(q, k, v, causal=causal, block_size=block_size)

    qspec = P(batch_axes, "sp", "tp", None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(qspec, qspec, qspec),
        out_specs=qspec,
        check_rep=False,
    )
    def _ring(q_loc, k_loc, v_loc):
        t_local = q_loc.shape[1]
        my_chunk = jax.lax.axis_index("sp")
        n_rep = q_loc.shape[2] // k_loc.shape[2]
        k_rep = _repeat_kv(k_loc, n_rep)
        v_rep = _repeat_kv(v_loc, n_rep)
        q_pos = my_chunk * t_local + jnp.arange(t_local)
        state = _attn_state_init(q_loc)
        perm = [(i, (i + 1) % sp_size) for i in range(sp_size)]

        def step(s, carry):
            state, k_cur, v_cur = carry
            src_chunk = (my_chunk - s) % sp_size
            kv_pos = src_chunk * t_local + jnp.arange(t_local)
            state = _attn_block_accum(state, q_loc, k_cur, v_cur, q_pos, kv_pos, causal)
            k_nxt = jax.lax.ppermute(k_cur, "sp", perm)
            v_nxt = jax.lax.ppermute(v_cur, "sp", perm)
            return state, k_nxt, v_nxt

        carry = (state, k_rep, v_rep)
        carry = jax.lax.fori_loop(0, sp_size, step, carry)
        state = carry[0]
        return _finalize(state).astype(q_loc.dtype)

    return _ring(q, k, v)
