"""Distributed async checkpointing: the elastic-training half of ROADMAP item 3.

Design (the same double-buffering idiom as ``data.py``'s Prefetcher, pointed
the other way — host->storage instead of host->device):

- **Per-host shards.** Each process saves only the *addressable* pieces of
  every ``jax.Array`` in the state tree — shard ``(index, data)`` pairs keyed
  by the leaf's tree path — into its own ``shard-<process>.npz``. No host ever
  materializes a peer's bytes; a pod-scale checkpoint is N parallel local
  writes to shared storage. Process 0 additionally writes ``manifest.json``
  (step, mesh shape, data-source offset, the full leaf schema) and each host
  drops a ``_COMMIT-<process>`` marker **after** its shard is durable, so a
  checkpoint is readable iff every host finished — a killed-mid-write step
  directory is simply ignored by ``latest_step()``.

- **Async, double-buffered.** ``save()`` blocks the train thread only for the
  device->host copy (plus draining the *previous* save, if storage is slower
  than the checkpoint cadence — the depth-1 bound is what stops unwritten
  host buffers pinning RAM). The storage write runs on a background thread.
  Telemetry marks bracket exactly the blocking window
  (``checkpoint_start``/``checkpoint_end`` with the measured ``blocked_s``),
  which is what lets the server's goodput ledger attribute checkpoint stalls
  to a ``checkpoint_s`` bucket instead of lumping them into ``other_s``; the
  writer emits ``checkpoint_saved`` when the bytes are durable.

- **Elastic restore.** Shards carry their *global* index, so ``restore()``
  rebuilds each leaf's full host array from whatever shard files exist and
  re-shards it onto the template's (possibly different) mesh via the leaf's
  own ``NamedSharding`` — the existing ``sharding.py`` rules, applied by
  ``jax.device_put``. A run checkpointed on dp2/fsdp4 resumes on dp4/fsdp2
  (or a different slice count) with bit-identical state; the manifest's
  ``data_offset`` seeks the input pipeline so no batch replays or skips.

Failure contract: a checkpoint that cannot be written degrades (counted +
``checkpoint_error`` mark), never kills the step loop; a checkpoint that
cannot be *read* raises — resuming from garbage is worse than failing loudly.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from dstack_tpu.workloads import telemetry as telemetry_lib

_STEP_DIR_RE = re.compile(r"^step-(\d+)$")


def _step_dir(step: int) -> str:
    return f"step-{step:08d}"


def leaf_entries(tree) -> List[Tuple[str, Any]]:
    """Stable ``(key, leaf)`` pairs for any pytree (dict / dataclass / optax
    state), keyed by the jax tree path so save and restore agree on names."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def _index_key(index, shape) -> str:
    """Serialize a shard's global index (tuple of slices) as ``a:b,c:d``."""
    parts = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        parts.append(f"{start}:{stop}")
    return ",".join(parts)


def _parse_index(key: str) -> Tuple[slice, ...]:
    if not key:
        return ()
    return tuple(
        slice(int(a), int(b)) for a, b in (p.split(":") for p in key.split(","))
    )


def _savable(arr: np.ndarray) -> np.ndarray:
    """np.savez round-trips only builtin dtypes; extension dtypes (ml_dtypes
    bfloat16/fp8) come back as raw void. Store them as a same-width unsigned
    view — restore views back per the manifest's recorded dtype."""
    if arr.dtype.isbuiltin == 1:
        return arr
    u = np.dtype(f"u{arr.dtype.itemsize}")
    return arr.reshape(1).view(u).reshape(arr.shape) if arr.ndim == 0 else arr.view(u)


def _host_shards(leaf) -> List[Tuple[str, np.ndarray]]:
    """Device->host copy of this process's unique shards of one array.

    Replicated placements (e.g. norms sharded ``P(None)``) appear once per
    device with the same global index — dedupe by index so the file holds one
    copy, not one per replica."""
    if not isinstance(leaf, jax.Array):
        arr = np.asarray(leaf)
        return [(_index_key((), arr.shape), _savable(arr))]
    shape = leaf.shape
    out, seen = [], set()
    for shard in leaf.addressable_shards:
        key = _index_key(shard.index, shape)
        if key in seen:
            continue
        seen.add(key)
        out.append((key, _savable(np.asarray(shard.data))))
    return out


class CheckpointManager:
    """Async per-host checkpointing for a pytree of (sharded) jax.Arrays.

    ``save()`` is called from the train loop; ``restore()`` at startup. One
    manager per process; ``directory`` must be shared (or gathered) storage
    for multi-host restore."""

    def __init__(
        self,
        directory: str,
        keep: int = 2,
        telemetry: Optional[Any] = None,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
    ) -> None:
        self.directory = directory
        self.keep = max(1, keep)
        self.process_index = (
            jax.process_index() if process_index is None else process_index
        )
        self.process_count = (
            jax.process_count() if process_count is None else process_count
        )
        self._telemetry = telemetry if telemetry is not None else telemetry_lib.get_emitter()
        self.save_errors = 0
        self.last_error: Optional[BaseException] = None
        self.saves = 0
        self._pending: Optional[threading.Event] = None
        self._closed = False
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)

    # -- writing -----------------------------------------------------------

    def save(
        self,
        step: int,
        state,
        data_offset: Optional[int] = None,
        mesh_shape: Optional[Dict[str, int]] = None,
        extra: Optional[dict] = None,
        block: bool = False,
    ) -> None:
        """Snapshot ``state`` at ``step``. Blocks only for the device->host
        copy (and for the previous save's write, if still in flight); the
        storage write happens on a background thread. Never raises — a failed
        write is counted and marked, not fatal to training."""
        if self._closed:
            return
        t0 = time.perf_counter()
        self._telemetry.mark("checkpoint_start", step=step)
        try:
            # Double-buffer bound: at most one host snapshot awaiting write.
            self.wait()
            entries = leaf_entries(state)
            payload: Dict[str, np.ndarray] = {}
            leaves: List[dict] = []
            for i, (key, leaf) in enumerate(entries):
                arr_shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
                arr_dtype = str(np.dtype(getattr(leaf, "dtype", np.asarray(leaf).dtype)))
                leaves.append({"key": key, "shape": list(arr_shape), "dtype": arr_dtype})
                for idx_key, arr in _host_shards(leaf):
                    payload[f"{i}@{idx_key}"] = arr
            manifest = {
                "step": int(step),
                "process_count": self.process_count,
                "mesh": dict(mesh_shape) if mesh_shape else None,
                "data_offset": int(data_offset) if data_offset is not None else None,
                "leaves": leaves,
                "extra": extra or {},
            }
        except BaseException as e:  # noqa: BLE001 — never kill the train step
            self.save_errors += 1
            self.last_error = e
            self._telemetry.mark("checkpoint_error", step=step, error=str(e)[:200])
            # Close the bracket: a dangling checkpoint_start would bill
            # everything to the window edge as checkpoint_s in the ledger.
            self._telemetry.mark(
                "checkpoint_end", step=step,
                blocked_s=round(time.perf_counter() - t0, 6), failed=True,
            )
            return
        blocked = time.perf_counter() - t0
        done = threading.Event()
        self._pending = done
        thread = threading.Thread(
            target=self._write,
            args=(step, payload, manifest, done),
            name="checkpoint-write",
            daemon=True,
        )
        thread.start()
        self._telemetry.mark(
            "checkpoint_end", step=step, blocked_s=round(blocked, 6)
        )
        if block:
            self.wait()

    def _write(self, step: int, payload, manifest, done: threading.Event) -> None:
        t0 = time.perf_counter()
        path = os.path.join(self.directory, _step_dir(step))
        try:
            os.makedirs(path, exist_ok=True)
            shard = os.path.join(path, f"shard-{self.process_index:05d}.npz")
            tmp = shard + ".tmp"
            with open(tmp, "wb") as f:
                np.savez(f, **payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, shard)
            if self.process_index == 0:
                mtmp = os.path.join(path, "manifest.json.tmp")
                with open(mtmp, "w", encoding="utf-8") as f:
                    json.dump(manifest, f)
                os.replace(mtmp, os.path.join(path, "manifest.json"))
            # Commit marker LAST: readers treat the step as complete only when
            # every process's marker exists.
            with open(
                os.path.join(path, f"_COMMIT-{self.process_index:05d}"), "w"
            ) as f:
                f.write(str(step))
            self.saves += 1
            self._telemetry.mark(
                "checkpoint_saved",
                step=step,
                write_s=round(time.perf_counter() - t0, 6),
                path=path,
            )
            self._prune()
        except BaseException as e:  # noqa: BLE001
            self.save_errors += 1
            self.last_error = e
            self._telemetry.mark("checkpoint_error", step=step, error=str(e)[:200])
        finally:
            done.set()

    def _prune(self) -> None:
        if self.process_index != 0:
            return
        steps = self.complete_steps()
        for step in steps[: -self.keep]:
            shutil.rmtree(
                os.path.join(self.directory, _step_dir(step)), ignore_errors=True
            )

    def wait(self, timeout: float = 600.0) -> bool:
        """Drain the in-flight write (True = nothing pending or it finished)."""
        pending = self._pending
        if pending is None:
            return True
        ok = pending.wait(timeout)
        if ok:
            self._pending = None
        return ok

    def close(self, timeout: float = 600.0) -> None:
        """Drain pending writes; further saves become no-ops. Idempotent."""
        self.wait(timeout)
        self._closed = True

    # -- reading -----------------------------------------------------------

    def complete_steps(self) -> List[int]:
        """Ascending steps whose every per-host commit marker exists."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            m = _STEP_DIR_RE.match(name)
            if not m:
                continue
            path = os.path.join(self.directory, name)
            manifest = self._read_manifest(path)
            if manifest is None:
                continue
            n = int(manifest.get("process_count") or 1)
            if all(
                os.path.exists(os.path.join(path, f"_COMMIT-{p:05d}"))
                for p in range(n)
            ):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.complete_steps()
        return steps[-1] if steps else None

    @staticmethod
    def _read_manifest(path: str) -> Optional[dict]:
        try:
            with open(os.path.join(path, "manifest.json"), "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def read_manifest(self, step: Optional[int] = None) -> dict:
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no complete checkpoint in {self.directory}")
        manifest = self._read_manifest(os.path.join(self.directory, _step_dir(step)))
        if manifest is None:
            raise FileNotFoundError(
                f"checkpoint {_step_dir(step)} has no readable manifest"
            )
        return manifest

    def restore(self, template, step: Optional[int] = None):
        """Load a checkpoint into ``template``'s structure and shardings.

        ``template`` is a pytree shaped like the saved state — typically a
        freshly initialized TrainState on the *current* mesh; each restored
        leaf is ``device_put`` with the template leaf's sharding, which is
        where elastic re-sharding happens (the global host array is rebuilt
        from the shard files, then split per the NEW topology's rules).
        Returns ``(state, manifest)``. Raises on any mismatch or missing
        shard coverage — a partial restore must never silently train on."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no complete checkpoint in {self.directory}")
        path = os.path.join(self.directory, _step_dir(step))
        manifest = self.read_manifest(step)
        leaves = manifest["leaves"]

        entries = leaf_entries(template)
        if [k for k, _ in entries] != [l["key"] for l in leaves]:
            raise ValueError(
                f"checkpoint structure mismatch: saved "
                f"{[l['key'] for l in leaves]} vs template {[k for k, _ in entries]}"
            )
        for (key, leaf), meta in zip(entries, leaves):
            shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
            if list(shape) != list(meta["shape"]):
                raise ValueError(
                    f"{key}: checkpoint shape {meta['shape']} != template {list(shape)}"
                    f" — the model/optimizer config changed under the checkpoint"
                )

        host = self._load_host_arrays(path, leaves, set(range(len(leaves))))
        restored = [
            self._materialize(leaf, host[i])
            for i, (key, leaf) in enumerate(entries)
        ]
        treedef = jax.tree_util.tree_structure(template)
        return jax.tree_util.tree_unflatten(treedef, restored), manifest

    def restore_subtree(self, template, step: Optional[int] = None,
                        prefix: str = ""):
        """Restore only the leaves of a checkpoint matching ``template``'s
        keys — the train->serve handoff: a checkpoint holds a whole TrainState
        but the engine wants just ``.params``, without paying to read (or
        materialize) the optimizer moments.

        Each template key is matched against the manifest as ``prefix + key``
        first, then — when that misses — as a unique suffix, so a bare params
        dict restores from both a params-only checkpoint and a full TrainState
        one (``prefix=".params"``). An ambiguous suffix (the adam mu/nu trees
        mirror the param keys exactly) raises with the candidate prefixes
        rather than guessing. Only matched leaves' shard bytes are loaded.

        Template leaves may be ``jax.ShapeDtypeStruct`` (optionally carrying a
        ``NamedSharding``): no template arrays ever exist on device, each
        restored host array is ``device_put`` straight into its serve-mesh
        layout — the no-double-copy restore path.
        Returns ``(tree, manifest)``."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no complete checkpoint in {self.directory}")
        path = os.path.join(self.directory, _step_dir(step))
        manifest = self.read_manifest(step)
        leaves = manifest["leaves"]
        saved_keys = [l["key"] for l in leaves]
        by_key = {k: i for i, k in enumerate(saved_keys)}

        entries = leaf_entries(template)
        picked: List[int] = []
        for key, leaf in entries:
            i = by_key.get(prefix + key)
            if i is None:
                matches = [
                    j for j, sk in enumerate(saved_keys) if sk.endswith(key)
                ]
                if not matches:
                    raise ValueError(
                        f"checkpoint {_step_dir(step)} has no leaf matching"
                        f" {prefix + key!r} (or suffix {key!r})"
                    )
                if len(matches) > 1:
                    prefixes = sorted(saved_keys[j][: -len(key)] for j in matches)
                    raise ValueError(
                        f"{key!r} is ambiguous in {_step_dir(step)}: matches"
                        f" under prefixes {prefixes} — pass prefix= to pick one"
                    )
                i = matches[0]
            meta = leaves[i]
            shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
            if list(shape) != list(meta["shape"]):
                raise ValueError(
                    f"{key}: checkpoint shape {meta['shape']} != template"
                    f" {list(shape)} — the model config changed under the"
                    f" checkpoint"
                )
            picked.append(i)

        host = self._load_host_arrays(path, leaves, set(picked))
        restored = [
            self._materialize(leaf, host[i])
            for (key, leaf), i in zip(entries, picked)
        ]
        treedef = jax.tree_util.tree_structure(template)
        return jax.tree_util.tree_unflatten(treedef, restored), manifest

    @staticmethod
    def _load_host_arrays(path: str, leaves: List[dict], wanted) -> Dict[int, np.ndarray]:
        """Rebuild the global host array of every leaf index in ``wanted``
        from the step directory's shard files; unwanted leaves' bytes are
        skipped (npz members are lazily decompressed, so a subtree restore
        reads only its own leaves). Raises on incomplete shard coverage."""
        host: Dict[int, np.ndarray] = {
            i: np.zeros(tuple(leaves[i]["shape"]), np.dtype(leaves[i]["dtype"]))
            for i in wanted
        }
        covered = {i: 0 for i in wanted}
        seen: Dict[int, set] = {i: set() for i in wanted}
        shard_files = sorted(
            os.path.join(path, n)
            for n in os.listdir(path)
            if n.startswith("shard-") and n.endswith(".npz")
        )
        for fname in shard_files:
            with np.load(fname) as z:
                for zkey in z.files:
                    leaf_s, _, idx_key = zkey.partition("@")
                    i = int(leaf_s)
                    if i not in host or idx_key in seen[i]:
                        continue  # unwanted, or replicated across hosts
                    seen[i].add(idx_key)
                    index = _parse_index(idx_key)
                    piece = z[zkey]
                    want_dtype = host[i].dtype
                    if piece.dtype != want_dtype:
                        if piece.dtype.itemsize != want_dtype.itemsize:
                            raise ValueError(
                                f"{leaves[i]['key']}: shard dtype {piece.dtype}"
                                f" incompatible with manifest {want_dtype}"
                            )
                        # Extension dtypes were stored as same-width uints
                        # (np.savez can't round-trip bfloat16/fp8).
                        piece = (
                            piece.reshape(1).view(want_dtype).reshape(piece.shape)
                            if piece.ndim == 0
                            else piece.view(want_dtype)
                        )
                    if index:
                        host[i][index] = piece
                        covered[i] += int(piece.size)
                    else:
                        host[i] = piece.reshape(host[i].shape).astype(host[i].dtype)
                        covered[i] += int(piece.size)
        for i in wanted:
            meta = leaves[i]
            want = int(np.prod(meta["shape"])) if meta["shape"] else 1
            if covered[i] < want:
                raise ValueError(
                    f"{meta['key']}: shard files cover {covered[i]}/{want}"
                    f" elements — a host's shard file is missing"
                )
        return host

    @staticmethod
    def _materialize(leaf, arr: np.ndarray):
        """Place one restored host array per its template leaf: device_put
        into a NamedSharding (elastic re-shard — works for live jax.Arrays AND
        ShapeDtypeStruct templates carrying a sharding), plain jnp for
        unsharded device leaves (scalars stay UNcommitted, like fresh init —
        a device_put would pin them to one device and clash with the sharded
        params inside a jitted step), numpy passthrough otherwise."""
        from jax.sharding import NamedSharding

        sharding = getattr(leaf, "sharding", None)
        if isinstance(sharding, NamedSharding):
            return jax.device_put(arr.astype(leaf.dtype), sharding)
        if isinstance(leaf, (jax.Array, jax.ShapeDtypeStruct)):
            import jax.numpy as jnp

            return jnp.asarray(arr, dtype=leaf.dtype)
        return arr
