"""Input pipeline: batch sources, per-host sharding, and async prefetch.

The host side of the overlapped training loop. Three pieces:

- **Batch sources** yield host-local numpy (tokens, targets) pairs — synthetic
  (seeded, cheap) or token-file-backed (a flat binary of token ids, the
  standard packed-corpus format).
- **Per-host sharded batch construction**: on a multihost mesh each process
  materializes only its `global_batch / process_count` rows and the global
  jax.Array is assembled from the local shards — no host ever touches the
  full batch (Podracer-style host->device feeding).
- **Prefetcher**: a configurable-depth double buffer that issues
  `jax.device_put` for batch N+1 (and beyond, up to `depth`) on a background
  thread while step N runs on the device, so host->HBM transfer disappears
  from the step's critical path. `device_put` is async on TPU — the thread
  only *enqueues* transfers; depth bounds how much HBM staged batches pin.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

Batch = Tuple[np.ndarray, np.ndarray]  # (tokens, targets), each [local_B, T]


def host_shard(global_batch: int, process_index: int, process_count: int) -> Tuple[int, int]:
    """(row_offset, rows) of this host's contiguous slice of the global batch."""
    if global_batch % process_count != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by {process_count} hosts"
        )
    rows = global_batch // process_count
    return process_index * rows, rows


def synthetic_batches(
    vocab_size: int,
    global_batch: int,
    seq: int,
    seed: int = 0,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
    start_batch: int = 0,
) -> Iterator[Batch]:
    """Endless stream of random-token batches; each host draws only its own
    rows. The stream is *seekable*: batch b is generated from its own
    (seed, process_index, b)-seeded generator, so a resumed run passing
    ``start_batch`` (the checkpoint manifest's data offset) sees exactly the
    batches an uninterrupted run would — per-batch seeding costs nothing and
    is what makes O(1) seek possible (a sequential generator would need to
    draw-and-discard its way back to the offset)."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    _, rows = host_shard(global_batch, pi, pc)
    b = start_batch
    while True:
        rng = np.random.default_rng((seed, pi, b))
        tokens = rng.integers(0, vocab_size, (rows, seq), dtype=np.int32)
        yield tokens, tokens
        b += 1


def token_file_batches(
    path: str,
    global_batch: int,
    seq: int,
    dtype: str = "uint16",
    loop: bool = True,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
    start_batch: int = 0,
) -> Iterator[Batch]:
    """Batches from a flat binary file of token ids (np.memmap — the file is
    never loaded whole). Windows of seq+1 tokens give (tokens, next-token
    targets). Hosts stride the corpus disjointly: window w belongs to the host
    where (w // rows_per_host) % process_count lands, so a pass covers the file
    once across the fleet. ``start_batch`` seeks: batch b always maps to the
    same file windows ((b mod batches_per_pass) * global_batch), so a resumed
    run neither replays nor skips data."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    _, rows = host_shard(global_batch, pi, pc)
    data = np.memmap(path, dtype=np.dtype(dtype), mode="r")
    window = seq + 1
    n_windows = len(data) // window
    if n_windows < global_batch:
        raise ValueError(
            f"{path}: {len(data)} tokens = {n_windows} windows of {window}; "
            f"need at least {global_batch} for one global batch"
        )
    per_pass = n_windows // global_batch
    b = start_batch
    while True:
        # Each global batch consumes `global_batch` consecutive windows; this
        # host takes the `rows` of them at offset process_index * rows.
        if not loop and b >= per_pass:
            return
        start = (b % per_pass) * global_batch + pi * rows
        idx = np.arange(start, start + rows) * window
        chunk = np.stack([data[i : i + window] for i in idx]).astype(np.int32)
        yield chunk[:, :-1], chunk[:, 1:]
        b += 1


def make_global_array(
    local: np.ndarray, sharding: NamedSharding, global_batch: int
) -> jax.Array:
    """One global [global_batch, ...] jax.Array from this host's local rows.

    Multihost: `jax.make_array_from_process_local_data` places each host's
    rows onto its own devices — no cross-host gather. Single process: a plain
    sharded device_put (local IS global)."""
    if jax.process_count() > 1:
        global_shape = (global_batch,) + local.shape[1:]
        return jax.make_array_from_process_local_data(sharding, local, global_shape)
    return jax.device_put(local, sharding)


def sharded_batches(
    source: Iterator[Batch],
    mesh: Mesh,
    spec,
    global_batch: int,
) -> Iterator[Tuple[jax.Array, jax.Array]]:
    """Map a host-local numpy batch stream to globally-sharded device arrays."""
    sharding = NamedSharding(mesh, spec)
    for tokens, targets in source:
        yield (
            make_global_array(tokens, sharding, global_batch),
            make_global_array(targets, sharding, global_batch),
        )


class Prefetcher:
    """Depth-bounded async prefetch over any iterator.

    A daemon thread pulls items from `it` (each pull typically enqueues a
    host->device transfer via `sharded_batches`) and parks them in a queue of
    size `depth`; `__next__` pops the oldest. While the consumer runs step N
    on-device, the thread is already staging batches N+1..N+depth, so the
    transfer for the next step overlaps the current step's compute.

    depth=0 is a synchronous passthrough (no thread — the legacy feed).
    Exceptions in the source re-raise in the consumer; `close()` (or source
    exhaustion) shuts the thread down. Iteration order is always preserved.
    """

    _DONE = object()

    def __init__(self, it: Iterator, depth: int = 2):
        if depth < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {depth}")
        self.depth = depth
        self._it = it
        self._closed = False
        if depth == 0:
            self._q = None
            self._thread = None
            return
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._thread = threading.Thread(target=self._fill, daemon=True)
        self._thread.start()

    def _fill(self) -> None:
        try:
            for item in self._it:
                if self._closed:
                    return
                while not self._closed:
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._closed:
                    return
            self._push(self._DONE)
        except BaseException as e:  # noqa: BLE001 — relayed to the consumer
            self._push(e)

    def _push(self, item) -> None:
        while not self._closed:
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self):
        return self

    def __next__(self):
        if self.depth == 0:
            return next(self._it)
        if self._closed:
            raise StopIteration
        item = self._q.get()
        if item is self._DONE:
            self._closed = True
            raise StopIteration
        if isinstance(item, BaseException):
            self._closed = True
            raise item
        return item

    def close(self) -> None:
        self._closed = True
        if self._thread is not None:
            # Drain so a blocked put() observes _closed and exits.
            while True:
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
            self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def input_pipeline(
    mesh: Mesh,
    spec,
    global_batch: int,
    seq: int,
    vocab_size: int,
    data_path: Optional[str] = None,
    prefetch: int = 2,
    seed: int = 0,
    start_batch: int = 0,
) -> Prefetcher:
    """The train entrypoint's one-call feed: pick the source (token file or
    synthetic), shard per host, wrap in the prefetcher. ``start_batch`` seeks
    both sources to the checkpoint manifest's data offset on resume (one
    global batch is consumed per optimizer step, so offset == step)."""
    if data_path:
        source: Iterator[Batch] = token_file_batches(
            data_path, global_batch, seq, start_batch=start_batch
        )
    else:
        source = synthetic_batches(
            vocab_size, global_batch, seq, seed=seed, start_batch=start_batch
        )
    return Prefetcher(sharded_batches(source, mesh, spec, global_batch), depth=prefetch)
